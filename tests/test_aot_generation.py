"""AOT generation serving (VERDICT r4 Next #5): the whole KV-cached
greedy decode compiled into ONE executable artifact
(transformer.save_compiled_generator) must emit the SAME token ids the
committed generation golden pins (tests/golden/transformer_greedy.npz)
— from Python via load_compiled_inference_model, and from C++ via the
ptpu_aot_generator main (no Python tracing at serve time)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid

HERE = os.path.dirname(os.path.abspath(__file__))
GOLDEN = os.path.join(HERE, "golden", "transformer_greedy.npz")

BS, SEQ, VOCAB = 2, 10, 50
N_LAYER, N_HEAD, D_MODEL, D_INNER = 1, 2, 32, 64


def _trained_scope_and_artifact(tmp_path):
    """Same recipe as the generation golden: deterministic params on the
    bs2/seq10/vocab50 model, then export the AOT generator."""
    from paddle_tpu import unique_name
    from paddle_tpu.models import transformer
    from paddle_tpu.testing import set_deterministic_params

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        transformer.build(
            src_vocab_size=VOCAB, trg_vocab_size=VOCAB, max_length=SEQ,
            n_layer=N_LAYER, n_head=N_HEAD, d_model=D_MODEL,
            d_inner=D_INNER, dropout=0.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    set_deterministic_params(main, fluid.global_scope())
    path = str(tmp_path / "aot_gen")
    transformer.save_compiled_generator(
        path, batch_size=BS, src_vocab_size=VOCAB, trg_vocab_size=VOCAB,
        max_length=SEQ, n_layer=N_LAYER, n_head=N_HEAD,
        d_model=D_MODEL, d_inner=D_INNER, eos_id=0)
    return path


def _golden():
    assert os.path.exists(GOLDEN), (
        "missing committed generation golden %s" % GOLDEN)
    g = np.load(GOLDEN)
    return g["src"], g["src_len"], g["tokens"]


def test_aot_generator_matches_generation_golden(tmp_path):
    src, src_len, want = _golden()
    with fluid.scope_guard(fluid.executor.Scope()):
        path = _trained_scope_and_artifact(tmp_path)
    model = fluid.io.load_compiled_inference_model(path)
    (tokens,) = model.run({"src_word": src, "src_len": src_len})
    np.testing.assert_array_equal(
        np.asarray(tokens), want.astype(np.int32),
        err_msg="AOT generator token stream diverged from the "
                "committed generation golden")


def test_aot_generator_cpp_main_matches_golden(tmp_path):
    """The C++ serving main: load the artifact, decode, dump tokens —
    the pinned ids with no Python tracing in the serve path."""
    sys.path.insert(0, HERE)  # tests/ dir, where test_cpp_predictor lives
    from test_cpp_predictor import _demo_binary

    binary = _demo_binary("ptpu_aot_generator")
    if binary is None:
        pytest.skip("cmake/ninja or embeddable Python unavailable")
    src, src_len, want = _golden()
    with fluid.scope_guard(fluid.executor.Scope()):
        path = _trained_scope_and_artifact(tmp_path)
    np.save(str(tmp_path / "src.npy"), src.astype(np.int32))
    np.save(str(tmp_path / "src_len.npy"), src_len.astype(np.int32))
    outp = str(tmp_path / "tokens.npy")
    import sysconfig

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(HERE), sysconfig.get_paths()["purelib"]]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    res = subprocess.run(
        [binary, path, str(tmp_path / "src.npy"),
         str(tmp_path / "src_len.npy"), outp],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr + res.stdout
    assert "ok aot tokens" in res.stdout
    got = np.load(outp)
    np.testing.assert_array_equal(
        got, want.astype(np.int32),
        err_msg="C++ AOT generator diverged from the committed golden")


def test_aot_generator_exports_for_tpu(tmp_path):
    """Cross-platform: a CPU build host must be able to emit a
    TPU-target generation artifact — the kernel selection keys on the
    export platform, so this runs the full Mosaic lowering of the
    cached-decode attention path in CI (same gate class as
    tests/test_tpu_lowering.py)."""
    import json

    from paddle_tpu.models import transformer

    with fluid.scope_guard(fluid.executor.Scope()):
        from paddle_tpu import unique_name
        from paddle_tpu.testing import set_deterministic_params

        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 13
        with fluid.program_guard(main, startup):
            transformer.build(
                src_vocab_size=VOCAB, trg_vocab_size=VOCAB,
                max_length=SEQ, n_layer=N_LAYER, n_head=N_HEAD,
                d_model=D_MODEL, d_inner=D_INNER, dropout=0.0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        set_deterministic_params(main, fluid.global_scope())
        path = str(tmp_path / "aot_tpu")
        transformer.save_compiled_generator(
            path, batch_size=BS, src_vocab_size=VOCAB,
            trg_vocab_size=VOCAB, max_length=SEQ, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=D_INNER, eos_id=0,
            platforms=("tpu",))
    meta = json.load(open(path + "/__compiled__.json"))
    assert meta["platforms"] == ["tpu"]
    # multi-platform stays rejected (kernel selection is platform-keyed)
    with pytest.raises(ValueError, match="platform-keyed"):
        transformer.save_compiled_generator(
            str(tmp_path / "nope"), batch_size=BS, src_vocab_size=VOCAB,
            trg_vocab_size=VOCAB, max_length=SEQ, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=D_INNER,
            platforms=("cpu", "tpu"))
