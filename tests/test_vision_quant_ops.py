"""Vision / quantization / misc op-wave tests: affine_channel, spatial
transformer (affine_grid + grid_sampler), index pooling + unpool, spp,
multiplex, bilinear_tensor_product, conv_shift, mean_iou,
positive_negative_pair, modified_huber_loss, lod_reset, hash, fill,
*_batch_size_like, conv3d_transpose, fake quant/dequant.

Reference test strategy parity: python/paddle/fluid/tests/unittests/
test_{affine_channel,grid_sampler,pool_max,unpool,spp,multiplex,...}_op.py
— numpy oracles + analytic-vs-numeric gradients via the OpTest harness.
"""

import numpy as np
import pytest

import paddle_tpu as fluid

from op_test import OpTest


def _run_program(build, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=list(fetches))


# -- affine_channel ---------------------------------------------------------

class TestAffineChannel(OpTest):
    op_type = "affine_channel"

    def setup(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 4, 5).astype("float32")
        scale = rng.randn(3).astype("float32")
        bias = rng.randn(3).astype("float32")
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        out = x * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
        self.outputs = {"Out": out}


def test_affine_channel_output_and_grad():
    t = TestAffineChannel()
    t.check_output()
    t2 = TestAffineChannel()
    t2.check_grad(["X", "Scale", "Bias"], "Out")


def test_affine_channel_nhwc():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 5, 3).astype("float32")
    scale = rng.randn(3).astype("float32")
    bias = rng.randn(3).astype("float32")
    t = TestAffineChannel()
    t.setup = lambda: None
    t.inputs = {"X": x, "Scale": scale, "Bias": bias}
    t.attrs = {"data_layout": "NHWC"}
    t.outputs = {"Out": x * scale + bias}
    t.check_output()


# -- spatial transformer ----------------------------------------------------

def _np_affine_grid(theta, h, w):
    xs = np.linspace(-1, 1, w)
    ys = np.linspace(-1, 1, h)
    xg, yg = np.meshgrid(xs, ys)
    base = np.stack([xg, yg, np.ones_like(xg)], axis=-1)  # [H,W,3]
    return np.einsum("hwc,nkc->nhwk", base, theta).astype("float32")


def test_affine_grid_matches_numpy():
    theta = np.random.RandomState(2).randn(2, 2, 3).astype("float32")
    t = OpTest()
    t.op_type = "affine_grid"
    t.inputs = {"Theta": theta}
    t.attrs = {"output_shape": [2, 3, 4, 5]}
    t.outputs = {"Output": _np_affine_grid(theta, 4, 5)}
    t.check_output()
    t2 = OpTest()
    t2.op_type = "affine_grid"
    t2.inputs = {"Theta": theta}
    t2.attrs = {"output_shape": [2, 3, 4, 5]}
    t2.outputs = {"Output": _np_affine_grid(theta, 4, 5)}
    t2.check_grad(["Theta"], "Output")


def test_grid_sampler_identity_roundtrip():
    """Identity theta -> grid samples every pixel exactly."""
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 5, 6).astype("float32")
    theta = np.tile(
        np.asarray([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]], "float32"), (2, 1, 1)
    )

    def build():
        xv = fluid.layers.data("x", [3, 5, 6])
        tv = fluid.layers.data("theta", [2, 3])
        grid = fluid.layers.affine_grid(tv, out_shape=[2, 3, 5, 6])
        out = fluid.layers.grid_sampler(xv, grid)
        return (out,)

    (out,) = _run_program(build, {"x": x, "theta": theta})
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-4, atol=1e-4)


def test_grid_sampler_grad():
    rng = np.random.RandomState(4)
    x = rng.randn(1, 2, 4, 4).astype("float32")
    # keep sample points away from the integer lattice so the bilinear
    # surface is smooth in the finite-difference neighborhood
    grid = rng.uniform(-0.85, 0.85, (1, 3, 3, 2)).astype("float32")
    t = OpTest()
    t.op_type = "grid_sampler"
    t.inputs = {"X": x, "Grid": grid}
    gx = (grid[..., 0] + 1) * 1.5
    gy = (grid[..., 1] + 1) * 1.5
    exp = np.zeros((1, 2, 3, 3), "float32")
    for i in range(3):
        for j in range(3):
            xx, yy = gx[0, i, j], gy[0, i, j]
            x0, y0 = int(np.floor(xx)), int(np.floor(yy))
            acc = np.zeros(2)
            for dy in (0, 1):
                for dx in (0, 1):
                    cx, cy = x0 + dx, y0 + dy
                    if 0 <= cx <= 3 and 0 <= cy <= 3:
                        wgt = (1 - abs(xx - cx)) * (1 - abs(yy - cy))
                        acc += wgt * x[0, :, cy, cx]
            exp[0, :, i, j] = acc
    t.outputs = {"Output": exp}
    t.check_output(atol=1e-4)
    t2 = OpTest()
    t2.op_type = "grid_sampler"
    t2.inputs = {"X": x, "Grid": grid}
    t2.outputs = {"Output": exp}
    t2.check_grad(["X", "Grid"], "Output", max_relative_error=2e-2)


# -- index pooling / unpool / spp ------------------------------------------

def _np_max_pool_with_index(x, k, s, p):
    n, c, h, w = x.shape
    oh = (h + 2 * p[0] - k[0]) // s[0] + 1
    ow = (w + 2 * p[1] - k[1]) // s[1] + 1
    xo = np.full((n, c, h + 2 * p[0], w + 2 * p[1]), -np.inf, x.dtype)
    xo[:, :, p[0]:p[0] + h, p[1]:p[1] + w] = x
    out = np.zeros((n, c, oh, ow), x.dtype)
    mask = np.zeros((n, c, oh, ow), np.int64)
    for i in range(oh):
        for j in range(ow):
            win = xo[:, :, i * s[0]:i * s[0] + k[0],
                     j * s[1]:j * s[1] + k[1]].reshape(n, c, -1)
            out[:, :, i, j] = win.max(-1)
            loc = win.argmax(-1)
            hh = i * s[0] - p[0] + loc // k[1]
            ww = j * s[1] - p[1] + loc % k[1]
            mask[:, :, i, j] = hh * w + ww
    return out, mask


def test_max_pool2d_with_index_matches_numpy():
    x = np.random.RandomState(5).randn(2, 3, 6, 8).astype("float32")
    eo, em = _np_max_pool_with_index(x, [2, 3], [2, 2], [1, 1])
    t = OpTest()
    t.op_type = "max_pool2d_with_index"
    t.inputs = {"X": x}
    t.attrs = {"ksize": [2, 3], "strides": [2, 2], "paddings": [1, 1]}
    t.outputs = {"Out": eo, "Mask": em.astype("int32")}
    t.check_output()


def test_max_pool2d_with_index_grad():
    # well-separated values -> unique argmax -> smooth in the fd window
    x = (np.arange(16, dtype="float32").reshape(1, 1, 4, 4) * 7.3) % 11.0
    eo, em = _np_max_pool_with_index(x, [2, 2], [2, 2], [0, 0])
    t = OpTest()
    t.op_type = "max_pool2d_with_index"
    t.inputs = {"X": x}
    t.attrs = {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
    t.outputs = {"Out": eo, "Mask": em.astype("int32")}
    t.check_grad(["X"], "Out")


def test_max_pool3d_with_index():
    x = np.random.RandomState(6).randn(1, 2, 4, 4, 4).astype("float32")
    exp = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    t = OpTest()
    t.op_type = "max_pool3d_with_index"
    t.inputs = {"X": x}
    t.attrs = {"ksize": [2, 2, 2], "strides": [2, 2, 2],
               "paddings": [0, 0, 0]}
    t.outputs = {"Out": exp}
    t.check_output(no_check_set=("Mask",))


def test_unpool_roundtrip_and_grad():
    x = np.random.RandomState(7).randn(1, 2, 4, 4).astype("float32")
    pooled, mask = _np_max_pool_with_index(x, [2, 2], [2, 2], [0, 0])
    exp = np.zeros((1, 2, 4, 4), "float32")
    for c in range(2):
        flat = exp[0, c].ravel()
        flat[mask[0, c].ravel()] = pooled[0, c].ravel()
    t = OpTest()
    t.op_type = "unpool"
    t.inputs = {"X": pooled, "Indices": mask.astype("int32")}
    t.attrs = {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
    t.outputs = {"Out": exp}
    t.check_output()
    t2 = OpTest()
    t2.op_type = "unpool"
    t2.inputs = {"X": pooled, "Indices": mask.astype("int32")}
    t2.attrs = {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
    t2.outputs = {"Out": exp}
    t2.check_grad(["X"], "Out")


@pytest.mark.parametrize("ptype", ["max", "avg"])
def test_spp_matches_manual(ptype):
    x = np.random.RandomState(8).randn(2, 3, 4, 4).astype("float32")
    # level 0: global 1x1; level 1: 2x2 bins of 2x2 windows
    red = np.max if ptype == "max" else np.mean
    lvl0 = red(x, axis=(2, 3)).reshape(2, 3)
    lvl1 = np.zeros((2, 3, 2, 2), "float32")
    for i in range(2):
        for j in range(2):
            lvl1[:, :, i, j] = red(
                x[:, :, 2 * i:2 * i + 2, 2 * j:2 * j + 2], axis=(2, 3))
    exp = np.concatenate([lvl0, lvl1.reshape(2, -1)], axis=1)
    t = OpTest()
    t.op_type = "spp"
    t.inputs = {"X": x}
    t.attrs = {"pyramid_height": 2, "pooling_type": ptype}
    t.outputs = {"Out": exp}
    t.check_output()


# -- multiplex / bilinear / conv_shift -------------------------------------

def test_multiplex_selects_rows():
    rng = np.random.RandomState(9)
    xs = [rng.randn(4, 5).astype("float32") for _ in range(3)]
    ids = np.asarray([[2], [0], [1], [2]], "int32")
    exp = np.stack([xs[int(ids[b, 0])][b] for b in range(4)])
    t = OpTest()
    t.op_type = "multiplex"
    t.inputs = {"Ids": ids, "X": [("x%d" % i, x) for i, x in enumerate(xs)]}
    t.outputs = {"Out": exp}
    t.check_output()
    t2 = OpTest()
    t2.op_type = "multiplex"
    t2.inputs = {"Ids": ids, "X": [("x%d" % i, x) for i, x in enumerate(xs)]}
    t2.outputs = {"Out": exp}
    t2.check_grad(["x0", "x1", "x2"], "Out")


def test_bilinear_tensor_product():
    rng = np.random.RandomState(10)
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 5).astype("float32")
    w = rng.randn(6, 4, 5).astype("float32")
    b = rng.randn(1, 6).astype("float32")
    exp = np.einsum("bm,kmn,bn->bk", x, w, y) + b
    t = OpTest()
    t.op_type = "bilinear_tensor_product"
    t.inputs = {"X": x, "Y": y, "Weight": w, "Bias": b}
    t.outputs = {"Out": exp}
    t.check_output(atol=1e-4)
    t2 = OpTest()
    t2.op_type = "bilinear_tensor_product"
    t2.inputs = {"X": x, "Y": y, "Weight": w, "Bias": b}
    t2.outputs = {"Out": exp}
    t2.check_grad(["X", "Y", "Weight", "Bias"], "Out",
                  max_relative_error=1e-2)


def test_conv_shift_circular():
    rng = np.random.RandomState(11)
    x = rng.randn(3, 7).astype("float32")
    y = rng.randn(3, 3).astype("float32")
    exp = np.zeros_like(x)
    m, n = 7, 3
    for b in range(3):
        for i in range(m):
            for j in range(n):
                exp[b, i] += x[b, (i + j - (n - 1) // 2) % m] * y[b, j]
    t = OpTest()
    t.op_type = "conv_shift"
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": exp}
    t.check_output()
    t2 = OpTest()
    t2.op_type = "conv_shift"
    t2.inputs = {"X": x, "Y": y}
    t2.outputs = {"Out": exp}
    t2.check_grad(["X", "Y"], "Out")


# -- metrics ---------------------------------------------------------------

def test_mean_iou_confusion():
    pred = np.asarray([0, 1, 2, 2, 1, 0, 1], "int32")
    label = np.asarray([0, 1, 1, 2, 2, 0, 1], "int32")
    ncls = 3
    correct = np.zeros(ncls, np.int64)
    wrong = np.zeros(ncls, np.int64)
    for p, l in zip(pred, label):
        if p == l:
            correct[l] += 1
        else:
            wrong[l] += 1
            wrong[p] += 1
    union = correct + wrong
    iou = np.where(union > 0, correct / np.maximum(union, 1), 0.0)
    mean = iou[union > 0].mean()
    t = OpTest()
    t.op_type = "mean_iou"
    t.inputs = {"Predictions": pred, "Labels": label}
    t.attrs = {"num_classes": ncls}
    t.outputs = {
        "OutMeanIou": np.asarray([mean], "float32"),
        "OutWrong": wrong.astype("int32"),
        "OutCorrect": correct.astype("int32"),
    }
    t.check_output()


def test_positive_negative_pair_counts():
    score = np.asarray(
        [[0.9], [0.5], [0.7], [0.2], [0.2]], "float32")
    label = np.asarray([[1.0], [0.0], [1.0], [0.0], [1.0]], "float32")
    query = np.asarray([[1], [1], [1], [2], [2]], "int64")
    # brute force with the reference's tie quirk (tie -> neutral AND
    # negative)
    pos = neg = neu = 0.0
    rows = list(range(5))
    for a in rows:
        for b in rows:
            if a >= b or query[a, 0] != query[b, 0]:
                continue
            if label[a, 0] == label[b, 0]:
                continue
            sd = score[a, 0] - score[b, 0]
            ld = label[a, 0] - label[b, 0]
            if sd == 0:
                neu += 1
            if sd * ld > 0:
                pos += 1
            else:
                neg += 1
    t = OpTest()
    t.op_type = "positive_negative_pair"
    t.inputs = {"Score": score, "Label": label, "QueryID": query}
    t.outputs = {
        "PositivePair": np.asarray([pos], "float32"),
        "NegativePair": np.asarray([neg], "float32"),
        "NeutralPair": np.asarray([neu], "float32"),
    }
    t.check_output()
    assert pos == 2.0 and neg == 1.0 and neu == 1.0


def test_positive_negative_pair_accumulates_and_weights():
    score = np.asarray([[0.3], [0.6]], "float32")
    label = np.asarray([[1.0], [0.0]], "float32")
    query = np.asarray([[7], [7]], "int64")
    weight = np.asarray([[2.0], [4.0]], "float32")
    t = OpTest()
    t.op_type = "positive_negative_pair"
    t.inputs = {
        "Score": score, "Label": label, "QueryID": query,
        "Weight": weight,
        "AccumulatePositivePair": np.asarray([10.0], "float32"),
        "AccumulateNegativePair": np.asarray([20.0], "float32"),
        "AccumulateNeutralPair": np.asarray([30.0], "float32"),
    }
    # one discordant pair, weight (2+4)/2 = 3 -> negative
    t.outputs = {
        "PositivePair": np.asarray([10.0], "float32"),
        "NegativePair": np.asarray([23.0], "float32"),
        "NeutralPair": np.asarray([30.0], "float32"),
    }
    t.check_output()


# -- losses ----------------------------------------------------------------

def test_modified_huber_loss():
    x = np.asarray([[2.0], [0.5], [-0.5], [-3.0]], "float32")
    y = np.asarray([[1.0], [1.0], [1.0], [1.0]], "float32")
    z = (2 * y - 1) * x
    exp = np.where(z >= -1, np.maximum(1 - z, 0) ** 2, -4 * z)
    t = OpTest()
    t.op_type = "modified_huber_loss"
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": exp.astype("float32")}
    t.check_output(no_check_set=("IntermediateVal",))
    t2 = OpTest()
    t2.op_type = "modified_huber_loss"
    # keep away from the z = -1 and z = 1 kinks for the fd check
    t2.inputs = {"X": np.asarray([[2.2], [0.4], [-0.3], [-3.1]], "float32"),
                 "Y": y}
    t2.outputs = {"Out": exp.astype("float32")}
    t2.check_grad(["X"], "Out")


# -- tensor utilities -------------------------------------------------------

def test_fill_and_batch_size_like():
    def build():
        x = fluid.layers.data("x", [5], dtype="float32")
        filled = fluid.layers.fill(
            shape=[2, 3], value=[1, 2, 3, 4, 5, 6], dtype="float32")
        g = fluid.layers.gaussian_random_batch_size_like(
            x, shape=[-1, 16], mean=0.0, std=1.0)
        u = fluid.layers.uniform_random_batch_size_like(
            x, shape=[-1, 8], min=-2.0, max=2.0)
        return filled, g, u

    f, g, u = _run_program(
        build, {"x": np.zeros((6, 5), "float32")})
    np.testing.assert_allclose(
        np.asarray(f), np.arange(1, 7, dtype="float32").reshape(2, 3))
    assert np.asarray(g).shape == (6, 16)
    assert np.asarray(u).shape == (6, 8)
    assert np.abs(np.asarray(u)).max() <= 2.0


def test_hash_deterministic_in_range():
    ids = np.asarray([[3], [3], [77], [123456]], "int64")

    def build():
        x = fluid.layers.data("x", [1], dtype="int64")
        return (fluid.layers.hash(x, hash_size=1000, num_hash=4),)

    (h1,) = _run_program(build, {"x": ids})
    (h2,) = _run_program(build, {"x": ids})
    h1 = np.asarray(h1)
    assert h1.shape == (4, 4, 1)
    assert (h1 >= 0).all() and (h1 < 1000).all()
    np.testing.assert_array_equal(h1, np.asarray(h2))  # deterministic
    np.testing.assert_array_equal(h1[0], h1[1])  # same id -> same hashes
    assert not (h1[0] == h1[2]).all()  # different ids differ somewhere
    assert len(np.unique(h1[3])) > 1  # slots use different seeds


def test_lod_reset_rechunks():
    x = np.arange(12, dtype="float32").reshape(2, 3, 2)  # 6 rows of dim 2

    def build():
        xv = fluid.layers.data("x", [3, 2])
        out, length = fluid.layers.lod_reset(xv, target_lod=[0, 2, 6])
        return out, length

    out, length = _run_program(build, {"x": x})
    out = np.asarray(out)
    assert out.shape == (2, 4, 2)
    flat = x.reshape(6, 2)
    np.testing.assert_allclose(out[0, :2], flat[0:2])
    np.testing.assert_allclose(out[0, 2:], 0.0)
    np.testing.assert_allclose(out[1], flat[2:6])
    np.testing.assert_array_equal(np.asarray(length).ravel(), [2, 4])


# -- conv3d_transpose -------------------------------------------------------

def test_conv3d_transpose_matches_loop():
    rng = np.random.RandomState(12)
    x = rng.randn(1, 2, 3, 3, 3).astype("float32")
    w = rng.randn(2, 3, 2, 2, 2).astype("float32")  # [in_c, out_c, kd,kh,kw]
    stride, pad = 2, 0
    od = (3 - 1) * stride + 2
    exp = np.zeros((1, 3, od, od, od), "float32")
    for ic in range(2):
        for d in range(3):
            for h in range(3):
                for ww_ in range(3):
                    exp[0, :, d * stride:d * stride + 2,
                        h * stride:h * stride + 2,
                        ww_ * stride:ww_ * stride + 2] += (
                        x[0, ic, d, h, ww_] * w[ic]
                    )
    t = OpTest()
    t.op_type = "conv3d_transpose"
    t.inputs = {"Input": x, "Filter": w}
    t.attrs = {"strides": [2, 2, 2], "paddings": [0, 0, 0]}
    t.outputs = {"Output": exp}
    t.check_output(atol=1e-4)


# -- quantization -----------------------------------------------------------

def test_fake_quantize_abs_max():
    x = np.asarray([[0.5, -1.0], [0.25, 0.75]], "float32")
    scale = 1.0
    exp = np.round(np.clip(x / scale, -1, 1) * 127)
    t = OpTest()
    t.op_type = "fake_quantize_abs_max"
    t.inputs = {"X": x}
    t.outputs = {"Out": exp, "OutScale": np.asarray([scale], "float32")}
    t.check_output()


def test_fake_quantize_range_abs_max_train_vs_test():
    x = np.asarray([[2.0, -4.0]], "float32")
    in_scale = np.asarray([3.0], "float32")
    # train: scale grows to batch abs-max
    t = OpTest()
    t.op_type = "fake_quantize_range_abs_max"
    t.inputs = {"X": x, "InScale": in_scale}
    t.attrs = {"is_test": False}
    t.outputs = {
        "Out": np.round(np.clip(x / 4.0, -1, 1) * 127),
        "OutScale": np.asarray([4.0], "float32"),
    }
    t.check_output()
    # test: stored scale wins, saturating the -4
    t2 = OpTest()
    t2.op_type = "fake_quantize_range_abs_max"
    t2.inputs = {"X": x, "InScale": in_scale}
    t2.attrs = {"is_test": True}
    t2.outputs = {
        "Out": np.round(np.clip(x / 3.0, -1, 1) * 127),
        "OutScale": np.asarray([3.0], "float32"),
    }
    t2.check_output()


def test_fake_dequantize_max_abs():
    x = np.asarray([[127.0, -64.0]], "float32")
    scale = np.asarray([2.0], "float32")
    t = OpTest()
    t.op_type = "fake_dequantize_max_abs"
    t.inputs = {"X": x, "Scale": scale}
    t.attrs = {"max_range": 127.0}
    t.outputs = {"Out": x * 2.0 / 127.0}
    t.check_output()


def test_fake_quantize_straight_through_gradient():
    """The vjp through fake_quantize must be the straight-through
    estimator: d(out)/d(x) = 127/scale (never zero despite round)."""
    x = np.asarray([[0.5, -0.25], [0.125, -1.0]], "float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [2], stop_gradient=False)
        out, _scale = fluid.layers.fake_quantize_abs_max(xv)
        loss = fluid.layers.reduce_sum(out)
        grads = fluid.calc_gradient(loss, [xv])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (g,) = exe.run(main, feed={"x": x}, fetch_list=grads)
    np.testing.assert_allclose(
        np.asarray(g), np.full_like(x, 127.0), rtol=1e-5)


def test_conv2d_transpose_output_size():
    """output_size disambiguates the stride-ambiguous output shape by
    extra high-side padding (conv_transpose_op.cc InferShape role)."""
    x = np.random.RandomState(13).randn(1, 2, 5, 5).astype("float32")

    def build():
        xv = fluid.layers.data("x", [2, 5, 5])
        out = fluid.layers.conv2d_transpose(
            xv, num_filters=3, filter_size=3, stride=2, padding=1,
            output_size=[10, 10])
        return (out,)

    (out,) = _run_program(build, {"x": x})
    assert np.asarray(out).shape == (1, 3, 10, 10)  # default would be 9x9


def test_affine_channel_default_params():
    """scale/bias default to created parameters (identity at init)."""
    x = np.random.RandomState(14).randn(2, 3, 4, 4).astype("float32")

    def build():
        xv = fluid.layers.data("x", [3, 4, 4])
        return (fluid.layers.affine_channel(xv),)

    (out,) = _run_program(build, {"x": x})
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-6)


def test_fake_quantize_range_clipped_gradient_passes_through():
    """Clipped elements keep the straight-through gradient (the reference
    grad kernel is an unconditional pass-through)."""
    x = np.asarray([[0.5, 9.0]], "float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [2], stop_gradient=False)
        from paddle_tpu.layer_helper import LayerHelper
        helper = LayerHelper("fake_quantize_range_abs_max")
        out = helper.create_variable_for_type_inference("float32")
        scale_out = helper.create_variable_for_type_inference("float32")
        in_scale = fluid.layers.fill(shape=[1], value=[1.0], dtype="float32")
        helper.append_op(
            type="fake_quantize_range_abs_max",
            inputs={"X": [xv], "InScale": [in_scale]},
            outputs={"Out": [out], "OutScale": [scale_out]},
            attrs={"is_test": True},
        )
        loss = fluid.layers.reduce_sum(out)
        grads = fluid.calc_gradient(loss, [xv])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (g,) = exe.run(main, feed={"x": x}, fetch_list=grads)
    np.testing.assert_allclose(np.asarray(g), [[127.0, 127.0]], rtol=1e-5)


def test_spp_avg_exclusive_on_nondivisible():
    """Edge bins on non-divisible inputs average over real elements only
    (reference AvgPool clips the window; padding must not deflate)."""
    x = np.ones((1, 1, 5, 5), "float32")
    t = OpTest()
    t.op_type = "spp"
    t.inputs = {"X": x}
    t.attrs = {"pyramid_height": 2, "pooling_type": "avg"}
    # all-ones input: every bin's exclusive average is exactly 1
    t.outputs = {"Out": np.ones((1, 5), "float32")}
    t.check_output()
