"""ParallelExecutor / GSPMD data-parallel tests on the 8-device virtual CPU
mesh (reference: test_parallel_executor_mnist.py + TestDistBase loss-parity
pattern, SURVEY.md §4: dist tests via multi-device CPU XLA)."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor


def _build_mlp_program(seed=123):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[32], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=64, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label)
        )
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 4, n).astype("int64")
    centers = rng.randn(4, 32).astype("float32")
    x = centers[labels] + 0.3 * rng.randn(n, 32).astype("float32")
    return x, labels.reshape(-1, 1)


def _run_single(steps=8):
    main, startup, loss = _build_mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x, y = _data()
    losses = []
    for i in range(steps):
        lv, = exe.run(
            main,
            feed={"x": x[i * 32 : (i + 1) * 32], "label": y[i * 32 : (i + 1) * 32]},
            fetch_list=[loss],
        )
        losses.append(float(lv[0]))
    return losses


def _run_parallel(steps=8, reduce_strategy=None):
    main, startup, loss = _build_mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bs = BuildStrategy()
    if reduce_strategy is not None:
        bs.reduce_strategy = reduce_strategy
    pe = ParallelExecutor(
        loss_name=loss.name, main_program=main, build_strategy=bs, use_tpu=False
    )
    assert pe.device_count == 8
    x, y = _data()
    losses = []
    for i in range(steps):
        lv, = pe.run(
            fetch_list=[loss],
            feed={"x": x[i * 32 : (i + 1) * 32], "label": y[i * 32 : (i + 1) * 32]},
        )
        losses.append(float(lv[0]))
    return losses


def test_parallel_matches_single_allreduce():
    single = _run_single()
    par = _run_parallel()
    np.testing.assert_allclose(single, par, atol=1e-4, rtol=1e-4)


def test_parallel_matches_single_reduce_strategy():
    single = _run_single()
    par = _run_parallel(reduce_strategy=BuildStrategy.ReduceStrategy.Reduce)
    np.testing.assert_allclose(single, par, atol=1e-4, rtol=1e-4)


def test_feeds_are_sharded_over_mesh():
    main, startup, loss = _build_mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = ParallelExecutor(loss_name=loss.name, main_program=main, use_tpu=False)
    x, y = _data(64)
    pe.run(fetch_list=[loss], feed={"x": x, "label": y})
    # after a run, persistable state lives as committed GSPMD arrays
    w = fluid.global_scope().get_value("fc_0.w_0")
    assert isinstance(w, jax.Array)
    assert len(w.sharding.device_set) == 8


def test_per_device_feed_list():
    main, startup, loss = _build_mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = ParallelExecutor(loss_name=loss.name, main_program=main, use_tpu=False)
    x, y = _data(64)
    feeds = [
        {"x": x[i * 8 : (i + 1) * 8], "label": y[i * 8 : (i + 1) * 8]}
        for i in range(8)
    ]
    lv, = pe.run(fetch_list=[loss], feed=feeds)
    assert np.isfinite(float(lv[0]))


def _build_tp_block_program(seed=31):
    """The driver dryrun's Megatron TP block (shared builder, so the
    dryrun and this parity test always validate the same graph)."""
    import __graft_entry__

    return __graft_entry__.build_tp_block_program(seed=seed, nclass=4)


def _tp_data(n=32, seed=5):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8, 16).astype("float32"),
            rng.randint(0, 4, (n, 1)).astype("int64"))


def test_tensor_parallel_matches_single():
    """TP-sharded training (2-way model axis x 4-way data) must track the
    single-device run step for step: sharding is a layout, not a math
    change (the TestDistBase loss-parity pattern applied to TP)."""
    import __graft_entry__
    from paddle_tpu.parallel.mesh import build_mesh

    # single-device baseline
    main, startup, loss = _build_tp_block_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x, y = _tp_data()
    single = []
    for i in range(4):
        lv, = exe.run(main, feed={"x": x[i*8:(i+1)*8],
                                  "label": y[i*8:(i+1)*8]},
                      fetch_list=[loss])
        single.append(float(np.asarray(lv).ravel()[0]))

    # TP + DP run
    main, startup, loss = _build_tp_block_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bs = BuildStrategy()
    bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
    pe = ParallelExecutor(
        loss_name=loss.name, main_program=main, build_strategy=bs,
        use_tpu=False,
        sharding_overrides=__graft_entry__.TP_OVERRIDES,
    )
    pe.mesh = build_mesh(num_devices=8, data=4, model=2)
    par = []
    for i in range(4):
        lv, = pe.run(fetch_list=[loss],
                     feed={"x": x[i*8:(i+1)*8], "label": y[i*8:(i+1)*8]})
        par.append(float(np.asarray(lv).ravel()[0]))
    np.testing.assert_allclose(single, par, atol=1e-4, rtol=1e-4)

    # weights actually span the model axis, and their Adam moments follow
    for name, dim in (("tp_qkv.w", 1), ("tp_ffn1.w", 1), ("tp_ffn2.w", 0)):
        w = fluid.global_scope().get_value(name)
        assert w.sharding.spec[dim] == "model", (name, w.sharding.spec)
    scope_names = fluid.global_scope().local_var_names()
    moments = [n for n in scope_names
               if n.startswith("tp_qkv.w_moment1")]
    assert moments, "no adam moment found for tp_qkv.w"
    # the single-device baseline left a same-prefixed moment in the shared
    # global scope; the PE run's copy must carry the inherited TP layout
    specs = []
    for name in moments:
        m = fluid.global_scope().get_value(name)
        spec = getattr(m.sharding, "spec", None)
        specs.append(spec)
    assert any(spec is not None and spec[1] == "model" for spec in specs), specs


def test_uneven_last_batch_parity():
    """Reference DataBalanceOpHandle capability
    (framework/details/data_balance_op_handle.cc): a global batch not
    divisible by the data axis still runs — the ShardingPolicy feed
    fallback replicates it (jax rejects uneven NamedShardings), the
    logical batch (and thus the mean loss) is unchanged — and must
    match the single-device executor exactly."""
    import __graft_entry__
    devices = jax.devices()
    __graft_entry__._dryrun_uneven_batch(len(devices), devices)


def test_dryrun_multichip_16_devices():
    """VERDICT r4 Next #7: the full dryrun chain (dp/ZeRO, TP, ring
    attention, GPipe, program pipeline, EP, composed dp*tp*pp, uneven
    batch) at 16 virtual devices. dryrun_multichip re-execs itself in a
    subprocess with the right XLA flags, so the suite's 8-device mesh
    is untouched."""
    import __graft_entry__
    __graft_entry__.dryrun_multichip(16)


@pytest.mark.slow
def test_rebuilt_executor_reuses_shared_gspmd_executable():
    """The elastic runtime tears a ParallelExecutor down and rebuilds it
    per membership generation; a rebuild over the SAME devices / program
    / policy inputs must reuse the process-global compiled executable —
    a 2 -> 1 -> 2 fleet reshape pays two compiles, not three."""
    from paddle_tpu.core import exec_cache

    main, startup, loss = _build_mlp_program(seed=321)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "x": np.random.RandomState(0).rand(8, 32).astype("float32"),
        "label": np.zeros((8, 1), "int64"),
    }

    def build():
        return ParallelExecutor(
            loss_name=loss.name, main_program=main, use_tpu=False,
            num_devices=2)

    pe1 = build()
    pe1.run(fetch_list=[loss], feed=feed)
    misses_after_first = exec_cache.stats()["trace_cache_misses"]
    pe2 = build()  # fresh instance, same mesh devices + policy inputs
    out2 = pe2.run(fetch_list=[loss], feed=feed)
    assert exec_cache.stats()["trace_cache_misses"] == misses_after_first, (
        "a rebuilt ParallelExecutor re-traced an executable the shared "
        "registry already held")
    assert np.isfinite(np.asarray(out2[0])).all()
    # a different world size is a different executable, never aliased
    pe3 = ParallelExecutor(
        loss_name=loss.name, main_program=main, use_tpu=False,
        num_devices=1)
    pe3.run(fetch_list=[loss], feed=feed)
    assert exec_cache.stats()["trace_cache_misses"] == misses_after_first + 1
