"""Gradient merge (accumulation) parity tests.

Reference capability: multi_batch_merge_pass
(paddle/fluid/framework/ir/multi_batch_merge_pass.cc). The contract under
test: training with batch size N for T steps follows the SAME parameter
trajectory as training with batch size N/K for K*T runs under
``rewrite_program_gradient_merge(k_steps=K, avg=True)``.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.transpiler import rewrite_program_gradient_merge


def _build(optimizer_fn, seed=123):
    from paddle_tpu import unique_name

    unique_name.switch()  # same param names across rebuilt programs
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=10, act="tanh")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        optimizer_fn().minimize(loss)
    return main, startup, loss


def _data(n=64, seed=7):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 6).astype("float32")
    w = rng.randn(6, 1).astype("float32")
    y = (x @ w + 0.1 * rng.randn(n, 1)).astype("float32")
    return x, y


def _params(exe_scope, main):
    out = {}
    for p in main.global_block().all_parameters():
        out[p.name] = np.asarray(exe_scope.find_var(p.name).value)
    return out


def _run_trajectory(optimizer_fn, k_steps, big_bs=16, n_big_steps=6):
    """Train; return final params. k_steps=1 trains on full batches;
    k_steps>1 feeds each big batch as k_steps microbatches under the
    gradient-merge rewrite."""
    main, startup, loss = _build(optimizer_fn)
    if k_steps > 1:
        rewrite_program_gradient_merge(main, startup, k_steps=k_steps,
                                       avg=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        x, y = _data(big_bs * n_big_steps)
        micro = big_bs // k_steps
        for s in range(n_big_steps):
            xb = x[s * big_bs:(s + 1) * big_bs]
            yb = y[s * big_bs:(s + 1) * big_bs]
            for m in range(k_steps):
                exe.run(main,
                        feed={"x": xb[m * micro:(m + 1) * micro],
                              "y": yb[m * micro:(m + 1) * micro]},
                        fetch_list=[loss])
        return _params(fluid.executor.global_scope(), main)


@pytest.mark.parametrize("opt_fn", [
    lambda: fluid.optimizer.SGD(learning_rate=0.05),
    lambda: fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9),
    lambda: fluid.optimizer.Adam(learning_rate=0.01),
], ids=["sgd", "momentum", "adam"])
def test_merged_matches_full_batch(opt_fn):
    full = _run_trajectory(opt_fn, k_steps=1)
    merged = _run_trajectory(opt_fn, k_steps=4)
    assert set(full) == set(merged)
    for name in full:
        np.testing.assert_allclose(
            merged[name], full[name], rtol=2e-4, atol=2e-5,
            err_msg="param %s diverged under gradient merge" % name)


def test_state_frozen_between_boundaries():
    """Params must NOT move on non-boundary microbatch runs."""
    main, startup, loss = _build(
        lambda: fluid.optimizer.SGD(learning_rate=0.1))
    rewrite_program_gradient_merge(main, startup, k_steps=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        x, y = _data(12)
        scope = fluid.executor.global_scope()
        p0 = _params(scope, main)
        exe.run(main, feed={"x": x[:4], "y": y[:4]}, fetch_list=[loss])
        p1 = _params(scope, main)
        for name in p0:
            np.testing.assert_array_equal(p0[name], p1[name])
        exe.run(main, feed={"x": x[4:8], "y": y[4:8]}, fetch_list=[loss])
        exe.run(main, feed={"x": x[8:], "y": y[8:]}, fetch_list=[loss])
        p3 = _params(scope, main)
        moved = any(
            not np.array_equal(p0[name], p3[name]) for name in p0)
        assert moved, "no parameter moved after the boundary step"


def test_lr_schedule_advances_per_merged_step():
    """A decaying schedule must step once per K microbatches, matching the
    unmerged program's per-step decay."""
    def build(k):
        from paddle_tpu import unique_name

        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 5
        startup.random_seed = 5
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[6], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1, act=None)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            lr = fluid.layers.exponential_decay(
                learning_rate=0.1, decay_steps=1, decay_rate=0.5,
                staircase=True)
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
        if k > 1:
            rewrite_program_gradient_merge(main, startup, k_steps=k)
        return main, startup, loss

    results = {}
    for k in (1, 2):
        main, startup, loss = build(k)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.executor.Scope()):
            exe.run(startup)
            x, yv = _data(8, seed=3)
            for s in range(2 * k):  # 2 merged steps for either k
                exe.run(main, feed={"x": x[:4], "y": yv[:4]},
                        fetch_list=[loss])
            results[k] = _params(fluid.executor.global_scope(), main)
    # k=1 ran 2 steps; k=2 ran 4 microbatches = 2 merged steps on the
    # same (repeated) batch -> identical decay count and trajectory
    for name in results[1]:
        np.testing.assert_allclose(results[2][name], results[1][name],
                                   rtol=2e-5, atol=1e-6)


def test_rejects_bad_k_and_missing_optimizer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        fluid.layers.fc(input=x, size=1)
    with pytest.raises(ValueError):
        rewrite_program_gradient_merge(main, startup, k_steps=0)
    with pytest.raises(ValueError):
        rewrite_program_gradient_merge(main, startup, k_steps=2)


def test_rejects_double_transpile():
    main, startup, _ = _build(lambda: fluid.optimizer.SGD(learning_rate=0.1))
    rewrite_program_gradient_merge(main, startup, k_steps=2)
    with pytest.raises(ValueError, match="already"):
        rewrite_program_gradient_merge(main, startup, k_steps=2)


def test_gradient_merge_composes_with_data_parallel():
    """Gradient merge under ParallelExecutor: K microbatches accumulated
    across an 8-device DP mesh follow the single-device merged
    trajectory (multi_batch_merge_pass + multi-device, the reference's
    large-batch recipe)."""
    opt_fn = lambda: fluid.optimizer.Momentum(learning_rate=0.05,
                                              momentum=0.9)
    single = _run_trajectory(opt_fn, k_steps=4)

    main, startup, loss = _build(opt_fn)
    rewrite_program_gradient_merge(main, startup, k_steps=4, avg=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        pe = fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                                    num_devices=8)
        x, y = _data(16 * 6)
        micro = 16 // 4
        for s in range(6):
            xb, yb = x[s * 16:(s + 1) * 16], y[s * 16:(s + 1) * 16]
            for m in range(4):
                pe.run(feed={"x": xb[m * micro:(m + 1) * micro],
                             "y": yb[m * micro:(m + 1) * micro]},
                       fetch_list=[loss.name])
        dp = _params(fluid.executor.global_scope(), main)
    for name in single:
        np.testing.assert_allclose(
            dp[name], single[name], rtol=2e-4, atol=2e-5,
            err_msg="param %s diverged under DP gradient merge" % name)
