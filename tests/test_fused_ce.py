"""fused_label_smooth_ce: the MFU lever-#1 op (docs/MFU_PLAN.md) must be
algebraically identical to the composed head it replaces
(softmax_with_cross_entropy + log_softmax smoothing term,
models/transformer.py), in loss AND in gradients."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags


def _build_head(fused, eps, n, v, seed):
    # reset the name counter so both engines' programs name the fc
    # params identically (head_fc.w_0) regardless of build order
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, size=v, name="head_fc")
        if fused:
            cost = fluid.layers.fused_label_smooth_ce(
                logits, label, epsilon=eps)
        else:
            cost = fluid.layers.softmax_with_cross_entropy(logits, label)
            if eps:
                neg_sum_logp = fluid.layers.scale(
                    fluid.layers.reduce_sum(
                        fluid.layers.log_softmax(logits), dim=-1,
                        keep_dim=True),
                    scale=-1.0)
                cost = fluid.layers.elementwise_add(
                    fluid.layers.scale(cost, scale=1.0 - eps),
                    fluid.layers.scale(neg_sum_logp, scale=eps / v))
        loss = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    return main, startup, loss


def _run_steps(fused, eps, steps=3, n=6, v=11, seed=3):
    rng = np.random.RandomState(7)
    xs = rng.randn(steps, n, 4).astype("float32")
    ys = rng.randint(0, v, (steps, n, 1)).astype("int64")
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, loss = _build_head(fused, eps, n, v, seed)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for i in range(steps):
            (lv,) = exe.run(main, feed={"x": xs[i], "label": ys[i]},
                            fetch_list=[loss])
            losses.append(float(np.ravel(lv)[0]))
        w = np.asarray(fluid.executor.global_scope()
                       .find_var("head_fc.w_0").value)
    return losses, w


@pytest.mark.parametrize("eps", [0.0, 0.1])
def test_fused_matches_composed_head(eps):
    """Same seeds, same feeds: per-step losses identical (the loss
    values drive nothing, so equality at step k also proves the
    gradient/update parity of steps < k) and final weights identical."""
    l_ref, w_ref = _run_steps(fused=False, eps=eps)
    l_fused, w_fused = _run_steps(fused=True, eps=eps)
    np.testing.assert_allclose(l_fused, l_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_fused, w_ref, rtol=1e-4, atol=1e-5,
                               err_msg="weight trajectories diverged — "
                                       "fused backward is not the "
                                       "composed head's gradient")


def test_fused_ce_grad_formula():
    """Direct check of dL/dx = softmax - eps/V - (1-eps)*onehot against
    numeric differentiation through the op's own lowering."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.loss_ops import _lower_fused_label_smooth_ce

    rng = np.random.RandomState(0)
    x = rng.randn(5, 9).astype("float32")
    lbl = rng.randint(0, 9, (5, 1)).astype("int64")
    eps = 0.1

    def f(xx):
        out = _lower_fused_label_smooth_ce(
            None, {"Logits": [xx], "Label": [jnp.asarray(lbl)]},
            {"epsilon": eps})
        return jnp.sum(out["Loss"])

    got = jax.grad(f)(jnp.asarray(x))
    # analytic expectation
    e = np.exp(x - x.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    onehot = np.eye(9)[lbl[:, 0]]
    want = sm - eps / 9 - (1 - eps) * onehot
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_fused_ce_flag_switches_transformer_head():
    from paddle_tpu.models import transformer

    def ops_of(prog):
        return {op.type for op in prog.global_block().ops}

    old = flags.get("fused_ce")
    try:
        flags.set_flag("fused_ce", True)
        with fluid.scope_guard(fluid.executor.Scope()):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                transformer.build(src_vocab_size=40, trg_vocab_size=40,
                                  max_length=8, n_layer=1, n_head=2,
                                  d_model=16, d_inner=32, dropout=0.0)
            assert "fused_label_smooth_ce" in ops_of(main)
            assert "log_softmax" not in ops_of(main)
    finally:
        flags.set_flag("fused_ce", old)


def test_fused_ce_bf16_logits_stay_bf16():
    """Under AMP the fused op must accept bf16 logits without a
    blacklist upcast: the [N, V] softmax/grad tensors are the lever."""
    import jax.numpy as jnp
    from paddle_tpu.ops.loss_ops import _lower_fused_label_smooth_ce

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 33).astype("float32")).astype(jnp.bfloat16)
    lbl = jnp.asarray(rng.randint(0, 33, (4, 1)))
    out = _lower_fused_label_smooth_ce(
        None, {"Logits": [x], "Label": [lbl]}, {"epsilon": 0.1})
    loss = np.asarray(out["Loss"]).astype("float64")
    # f32 reference on the same (bf16-rounded) logits
    xf = np.asarray(x.astype(jnp.float32)).astype("float64")
    m = xf.max(-1, keepdims=True)
    lse = m + np.log(np.exp(xf - m).sum(-1, keepdims=True))
    xy = np.take_along_axis(xf, np.asarray(lbl), axis=-1)
    want = lse - 0.9 * xy - (0.1 / 33) * xf.sum(-1, keepdims=True)
    np.testing.assert_allclose(loss, want, rtol=2e-2, atol=2e-2)
    assert out["Loss"].dtype == jnp.float32


def test_fused_ce_full_transformer_trajectory():
    """End to end on the real model: transformer.build under
    FLAGS_fused_ce must produce the same 3-step loss trajectory as the
    composed head (same seeds, same feeds) — pins the model wiring, not
    just the op."""
    from paddle_tpu.models import transformer

    def run(fused):
        old = flags.get("fused_ce")
        flags.set_flag("fused_ce", fused)
        try:
            fluid.unique_name.switch()
            with fluid.scope_guard(fluid.executor.Scope()):
                main, startup = fluid.Program(), fluid.Program()
                main.random_seed = startup.random_seed = 11
                with fluid.program_guard(main, startup):
                    loss, feeds, _ = transformer.build(
                        src_vocab_size=60, trg_vocab_size=60,
                        max_length=8, n_layer=1, n_head=2, d_model=16,
                        d_inner=32, dropout=0.0)
                    fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
                # the flag must actually switch the head, or the A/B
                # below compares the composed head against itself
                has_fused = any(op.type == "fused_label_smooth_ce"
                                for op in main.global_block().ops)
                assert has_fused == fused, (
                    "FLAGS_fused_ce plumbing broken: fused=%r but "
                    "program has_fused=%r" % (fused, has_fused))
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rng = np.random.RandomState(3)
                losses = []
                for _ in range(3):
                    feed = {
                        "src_word": rng.randint(1, 60, (2, 8)).astype("int64"),
                        "src_len": np.full((2, 1), 8, "int64"),
                        "trg_word": rng.randint(1, 60, (2, 8)).astype("int64"),
                        "trg_len": np.full((2, 1), 8, "int64"),
                        "label": rng.randint(1, 60, (2, 8)).astype("int64"),
                    }
                    (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                    losses.append(float(np.ravel(lv)[0]))
            return losses
        finally:
            flags.set_flag("fused_ce", old)

    ref = run(False)
    fused = run(True)
    np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-6,
                               err_msg="full-model fused-CE trajectory "
                                       "diverged from the composed head")
    assert ref[-1] < ref[0], "training did not reduce the loss"
