"""Predictor API tests (inference/api/api_impl_tester.cc role): config ->
predictor -> run parity with the training executor, Clone() multithreaded
serving, and the C++ reference interpreter cross-check of the XLA path."""

import threading

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference import NativeConfig, create_paddle_predictor


def _train_and_save(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[12], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=24, act="relu")
        pred = fluid.layers.fc(input=h, size=3, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=y)
        )
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    rng = np.random.RandomState(0)
    base = rng.randn(3, 12).astype("float32")
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(40):
            lbl = rng.randint(0, 3, 32)
            xb = base[lbl] + 0.2 * rng.randn(32, 12).astype("float32")
            exe.run(main, feed={"x": xb, "y": lbl.reshape(-1, 1)},
                    fetch_list=[loss])
        path = str(tmp_path / "model")
        fluid.io.save_inference_model(path, ["x"], [pred], exe,
                                      main_program=main)
        xb = base[[0, 1, 2]] + 0.1
        (want,) = exe.run(
            main, feed={"x": xb, "y": np.zeros((3, 1), "int64")},
            fetch_list=[pred],
        )
    return path, xb, np.asarray(want)


def test_predictor_matches_executor(tmp_path):
    path, xb, want = _train_and_save(tmp_path)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False)
    )
    (got,) = predictor.run({"x": xb})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # Positional input form.
    (got2,) = predictor.run([xb])
    np.testing.assert_allclose(got2, want, rtol=1e-5, atol=1e-6)


def test_predictor_clone_multithreaded(tmp_path):
    path, xb, want = _train_and_save(tmp_path)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False)
    )
    results = {}

    def serve(tid):
        p = predictor.clone()
        for _ in range(5):
            (out,) = p.run({"x": xb})
            results.setdefault(tid, []).append(out)

    threads = [threading.Thread(target=serve, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 4
    for outs in results.values():
        for out in outs:
            np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_cpp_reference_interpreter_matches_xla(tmp_path):
    from paddle_tpu import native

    if not native.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    path, xb, want = _train_and_save(tmp_path)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False)
    )
    got = predictor.run_native_reference({"x": xb})
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_analysis_predictor_fuses_and_matches(tmp_path):
    """AnalysisConfig runs the inference pass pipeline over the loaded
    program (analysis_predictor.cc role): fc chains collapse to fc ops,
    outputs identical to the un-optimized NativeConfig path."""
    from paddle_tpu.inference import AnalysisConfig

    path, xb, want = _train_and_save(tmp_path)
    analysis = create_paddle_predictor(
        AnalysisConfig(model_dir=path, use_tpu=False))
    (got,) = analysis.run({"x": xb})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    types = [op.type for op in analysis._program.global_block().ops]
    assert "fc" in types and "mul" not in types

    # ir_optim off degrades to the native path (no fusion)
    plain = create_paddle_predictor(
        AnalysisConfig(model_dir=path, use_tpu=False, ir_optim=False))
    (got2,) = plain.run({"x": xb})
    np.testing.assert_allclose(got2, want, rtol=1e-5, atol=1e-6)
    assert "mul" in [op.type for op in plain._program.global_block().ops]

    # clone shares the optimized program + weights
    (got3,) = analysis.clone().run({"x": xb})
    np.testing.assert_allclose(got3, want, rtol=1e-5, atol=1e-6)
