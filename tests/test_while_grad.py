"""Gradient through While (VERDICT r1 #4; reference while_op.cc:50-72
StepScopes backward). With ``max_iterations`` set, the while lowering is a
masked bounded lax.scan, so the synthesized ``while_grad`` differentiates
it like any other op; unbounded While stays forward-only."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import backward


def _build_while_loss(max_iterations, iters=3, n=4):
    """loss = mean(sum_{t<iters} x*w) -> dloss/dw = iters * x / n."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[n], append_batch_size=False)
        w = fluid.layers.create_parameter([n], "float32", name="w_while")
        acc = fluid.layers.fill_constant([n], "float32", 0.0)
        i = fluid.layers.fill_constant([1], "int64", 0)
        limit = fluid.layers.fill_constant([1], "int64", iters)
        cond = fluid.layers.less_than(i, limit)
        loop = fluid.layers.While(cond, max_iterations=max_iterations)
        with loop.block():
            step = fluid.layers.elementwise_mul(x, w)
            acc2 = fluid.layers.elementwise_add(acc, step)
            fluid.layers.assign(acc2, acc)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        loss = fluid.layers.mean(acc)
    return main, startup, loss, x, w


def test_while_scan_forward_matches_unbounded():
    outs = {}
    for max_iters in (0, 8):  # 0 = lax.while_loop path, 8 = masked scan
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = fluid.layers.fill_constant([1], "int64", 0)
            limit = fluid.layers.fill_constant([1], "int64", 5)
            acc = fluid.layers.fill_constant([1], "float32", 0.0)
            cond = fluid.layers.less_than(i, limit)
            loop = fluid.layers.While(cond, max_iterations=max_iters)
            with loop.block():
                acc2 = fluid.layers.elementwise_add(
                    acc, fluid.layers.cast(i, "float32")
                )
                fluid.layers.assign(acc2, acc)
                fluid.layers.increment(i, value=1, in_place=True)
                fluid.layers.less_than(i, limit, cond=cond)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        av, = exe.run(main, fetch_list=[acc])
        outs[max_iters] = float(np.ravel(av)[0])
    assert outs[0] == outs[8] == sum(range(5))


def test_while_grad_matches_analytic():
    iters, n = 3, 4
    main, startup, loss, x, w = _build_while_loss(
        max_iterations=6, iters=iters, n=n
    )
    with fluid.program_guard(main, startup):
        grads = backward.calc_gradient([loss], [w])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.array([0.5, -1.0, 2.0, 3.0], np.float32)
    gw, = exe.run(main, feed={"x": xv}, fetch_list=[grads[0]])
    np.testing.assert_allclose(
        np.asarray(gw), iters * xv / n, rtol=1e-5,
        err_msg="analytic while grad mismatch",
    )


def test_while_grad_matches_numeric():
    main, startup, loss, x, w = _build_while_loss(max_iterations=6)
    with fluid.program_guard(main, startup):
        grads = backward.calc_gradient([loss], [w])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.array([1.0, 0.25, -0.5, 2.0], np.float32)
    gw, = exe.run(main, feed={"x": xv}, fetch_list=[grads[0]])
    gw = np.asarray(gw)

    scope = fluid.global_scope()
    base_w = np.asarray(scope.get_value(w.name)).copy()
    eps = 1e-3
    numeric = np.zeros_like(base_w)
    for j in range(base_w.size):
        for sign in (+1, -1):
            pert = base_w.copy()
            pert[j] += sign * eps
            scope.set_value(w.name, pert)
            lv, = exe.run(main, feed={"x": xv}, fetch_list=[loss])
            numeric[j] += sign * float(np.ravel(lv)[0])
        numeric[j] /= 2 * eps
    scope.set_value(w.name, base_w)
    np.testing.assert_allclose(gw, numeric, rtol=1e-2, atol=1e-4)


def test_training_through_while_converges():
    """A seq-model-free regression: fit targets through a While-unrolled
    accumulation; SGD on the loop-captured parameter must reduce loss."""
    n = 4
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[n], append_batch_size=False)
        t = fluid.layers.data(name="t", shape=[n], append_batch_size=False)
        w = fluid.layers.create_parameter([n], "float32", name="w_fit")
        acc = fluid.layers.fill_constant([n], "float32", 0.0)
        i = fluid.layers.fill_constant([1], "int64", 0)
        limit = fluid.layers.fill_constant([1], "int64", 4)
        cond = fluid.layers.less_than(i, limit)
        loop = fluid.layers.While(cond, max_iterations=5)
        with loop.block():
            acc2 = fluid.layers.elementwise_add(
                acc, fluid.layers.elementwise_mul(x, w)
            )
            fluid.layers.assign(acc2, acc)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        diff = fluid.layers.elementwise_sub(acc, t)
        loss = fluid.layers.mean(fluid.layers.elementwise_mul(diff, diff))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.array([1.0, 2.0, -1.0, 0.5], np.float32)
    tv = np.array([2.0, -4.0, 1.0, 3.0], np.float32)
    losses = []
    for _ in range(40):
        lv, = exe.run(main, feed={"x": xv, "t": tv}, fetch_list=[loss])
        losses.append(float(np.ravel(lv)[0]))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])


def test_while_grad_with_tensor_array_carry():
    """A While whose carries include a tensor array next to the
    differentiable float carry: the backward engine's missing-grad
    pre-fill must skip the array carry (zeros_like over a (buffer, size)
    tensor-array rep would crash) while the float carry still trains."""
    iters, n = 3, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[n], append_batch_size=False)
        w = fluid.layers.create_parameter([n], "float32", name="w_arr")
        acc = fluid.layers.fill_constant([n], "float32", 0.0)
        i = fluid.layers.fill_constant([1], "int64", 0)
        limit = fluid.layers.fill_constant([1], "int64", iters)
        trace = fluid.layers.array_write(acc, i)  # seed the array carry
        cond = fluid.layers.less_than(i, limit)
        loop = fluid.layers.While(cond, max_iterations=8)
        with loop.block():
            step = fluid.layers.elementwise_mul(x, w)
            acc2 = fluid.layers.elementwise_add(acc, step)
            fluid.layers.assign(acc2, acc)
            fluid.layers.array_write(acc2, i, array=trace)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        loss = fluid.layers.mean(acc)
        grads = backward.append_backward(loss)
    gmap = dict((p.name, g) for p, g in grads)
    (gvar,) = [g for name, g in gmap.items() if name.startswith("w_arr")]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.arange(1.0, n + 1, dtype="float32")
    (gw,) = exe.run(main, feed={"x": xv}, fetch_list=[gvar])
    np.testing.assert_allclose(np.asarray(gw), iters * xv / n, rtol=1e-5)
