"""Multi-process tensor-parallel x data-parallel trainer (the multi-host
leg of the Megatron-style TP design): 2 jax.distributed processes x 4
virtual CPU devices = a (data=2, model=4) global mesh whose DATA axis
crosses the process boundary — grad all-reduces ride the inter-process
link (the DCN stand-in), TP collectives stay intra-process (the ICI
stand-in), exactly how a real multi-host TP topology lays out.

Spawned by test_dist_multiproc.py with the PADDLE_* env cluster surface;
MODEL_AXIS devices per process must equal the local device count. The
single-process parity reference runs the SAME program over the same
(2, 4) mesh built from 8 local devices (no process boundary).
"""

import json
import os
import sys

GLOBAL_BATCH = 16
STEPS = 4
MODEL_AXIS = 4


def run_tp_trainer(num_trainers, trainer_id):
    import numpy as np

    import paddle_tpu as fluid
    import __graft_entry__ as graft
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    seq, nclass, d_model = 8, 8, 16
    main, startup, loss = graft.build_tp_block_program(
        seq=seq, nclass=nclass, d_model=d_model)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    import jax

    devices = jax.devices()  # global list: all processes' devices
    if jax.local_device_count() != 8 // num_trainers or len(devices) != 8:
        raise RuntimeError(
            "TP parity needs %d local devices (8 global), found %d local / "
            "%d global — was XLA_FLAGS=--xla_force_host_platform_device_"
            "count overridden?"
            % (8 // num_trainers, jax.local_device_count(), len(devices)))
    bs_strategy = BuildStrategy()
    if os.environ.get("DIST_REDUCE", "reduce") == "reduce":
        bs_strategy.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
    pe = ParallelExecutor(
        loss_name=loss.name,
        main_program=main,
        build_strategy=bs_strategy,
        use_tpu=False,
        sharding_overrides=graft.TP_OVERRIDES,
        num_trainers=num_trainers,
        trainer_id=trainer_id,
    )
    pe.mesh = build_mesh(
        num_devices=len(devices),
        data=len(devices) // MODEL_AXIS,
        model=MODEL_AXIS,
        devices=devices,
    )

    shard = GLOBAL_BATCH // num_trainers
    lo, hi = trainer_id * shard, (trainer_id + 1) * shard
    rng_feeds = []
    for step in range(STEPS):
        rng = np.random.RandomState(300 + step)
        rng_feeds.append({
            "x": rng.randn(GLOBAL_BATCH, seq, d_model).astype(np.float32),
            "label": rng.randint(0, nclass,
                                 (GLOBAL_BATCH, 1)).astype(np.int64),
        })
    losses = []
    for step in range(STEPS):
        feed = {k: v[lo:hi] for k, v in rng_feeds[step].items()}
        lv, = pe.run(fetch_list=[loss], feed=feed)
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    return losses


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nprocs = int(os.environ["PADDLE_TRAINERS_NUM"])
    coord = os.environ["PADDLE_COORDINATOR"]
    out_file = os.environ["DIST_OUT_FILE"]
    local_devices = 8 // nprocs
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=%d" % local_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.parallel.mesh import init_distributed

    if nprocs > 1:
        init_distributed(
            coordinator_address=coord, num_processes=nprocs, process_id=rank)
    losses = run_tp_trainer(nprocs, rank)
    with open(out_file, "w") as f:
        json.dump({"rank": rank, "losses": losses}, f)
    print("tp trainer %d done: %s" % (rank, losses), flush=True)


if __name__ == "__main__":
    sys.exit(main())
