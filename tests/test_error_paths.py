"""User-error paths must fail with pointed messages, not XLA tracebacks
(enforce.h role: errors carry op/var context a user can act on)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_run_before_startup_names_the_variable():
    main, startup, loss = _program()
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": np.zeros((2, 4), "float32"), "y": np.zeros((2, 1), "float32")}
    with fluid.scope_guard(fluid.executor.Scope()):
        with pytest.raises(Exception, match="[Uu]ninitialized|not.*initialized"):
            exe.run(main, feed=feed, fetch_list=[loss])


def test_missing_feed_is_reported():
    main, startup, loss = _program()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        with pytest.raises(Exception,
                           match="uninitialized variable 'x'"):
            exe.run(main, feed={"y": np.zeros((2, 1), "float32")},
                    fetch_list=[loss])


def test_unknown_fetch_name_is_reported():
    main, startup, loss = _program()
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": np.zeros((2, 4), "float32"), "y": np.zeros((2, 1), "float32")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        with pytest.raises(Exception, match="no_such_var"):
            exe.run(main, feed=feed, fetch_list=["no_such_var"])


def test_unknown_op_type_is_reported_at_append():
    # fails at graph-BUILD time, naming the op (OpRegistry::CreateOp role)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        block = main.current_block()
        out = block.create_var(name="o", dtype="float32", shape=None)
        with pytest.raises(KeyError, match="definitely_not_an_op"):
            block.append_op("definitely_not_an_op",
                            inputs={"X": [x.name]},
                            outputs={"Out": [out.name]})


def test_shape_mismatch_across_cached_runs_recompiles_not_crashes():
    """Feeding a different batch size must hit a fresh executable, not a
    stale shape (program cache keyed on feed shapes)."""
    main, startup, loss = _program()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        for bs in (2, 5, 2):
            feed = {"x": np.zeros((bs, 4), "float32"),
                    "y": np.zeros((bs, 1), "float32")}
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.ravel(lv)).all()
