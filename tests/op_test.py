"""OpTest harness: single-op programs checked for output correctness and
analytic-vs-numeric gradients.

Reference parity: python/paddle/fluid/tests/unittests/op_test.py:131
(OpTest base), :43 (get_numeric_gradient), :400 (check_grad). Builds a
one-op program from numpy inputs, runs it through the XLA executor, and
compares ``calc_gradient`` results against central finite differences.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import framework
from paddle_tpu.core import op_registry


class OpTest(object):
    """Usage: configure self.op_type / self.inputs / self.outputs /
    self.attrs then call check_output() / check_grad([...], 'Out')."""

    op_type = None
    inputs = None
    outputs = None
    attrs = None

    def setup(self):
        pass

    # -- program construction ----------------------------------------------
    def _build(self):
        self.setup()
        main = fluid.Program()
        startup = fluid.Program()
        self._feed = {}
        self._out_vars = {}
        with fluid.program_guard(main, startup):
            block = main.global_block()
            op_inputs = {}
            for slot, value in (self.inputs or {}).items():
                pairs = value if isinstance(value, list) else [(slot, value)]
                names = []
                for sub_name, arr in pairs:
                    arr = np.asarray(arr)
                    block.create_var(
                        name=sub_name,
                        shape=arr.shape,
                        dtype=str(arr.dtype),
                        stop_gradient=False,
                    )
                    self._feed[sub_name] = arr
                    names.append(sub_name)
                op_inputs[slot] = names
            op_outputs = {}
            opdef = op_registry.get_op_def(self.op_type)
            for slot in opdef.output_slots():
                spec = (self.outputs or {}).get(slot)
                if spec is None and slot not in (self.outputs or {}):
                    continue
                if isinstance(spec, list):
                    names = [n for n, _ in spec]
                else:
                    names = [slot]
                for n in names:
                    v = block.create_var(name=n, shape=None, dtype="float32")
                    self._out_vars[n] = v
                op_outputs[slot] = names
            block.append_op(
                type=self.op_type,
                inputs=op_inputs,
                outputs=op_outputs,
                attrs=dict(self.attrs or {}),
            )
        self._main = main
        return main

    def _expected(self):
        exp = {}
        for slot, spec in (self.outputs or {}).items():
            if isinstance(spec, list):
                for n, arr in spec:
                    exp[n] = np.asarray(arr)
            else:
                exp[slot] = np.asarray(spec)
        return exp

    def check_output(self, atol=1e-5, rtol=1e-4, no_check_set=()):
        main = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        expected = self._expected()
        names = [n for n in expected if n not in no_check_set]
        got = exe.run(main, feed=self._feed, fetch_list=names)
        for n, g in zip(names, got):
            e = expected[n]
            np.testing.assert_allclose(
                np.asarray(g, np.float64),
                np.asarray(e, np.float64),
                atol=atol,
                rtol=rtol,
                err_msg="op %s output %s mismatch" % (self.op_type, n),
            )

    # -- gradient checking --------------------------------------------------
    def check_grad(
        self,
        inputs_to_check,
        output_name,
        max_relative_error=5e-3,
        delta=5e-3,
        no_grad_set=None,
    ):
        main = self._build()
        block = main.global_block()
        # Random-projection loss sum(out * R): well-conditioned for ops whose
        # plain output-sum gradient degenerates (batch_norm, softmax).
        ref_shape = self._expected()[output_name].shape
        proj = (
            np.random.RandomState(0)
            .uniform(0.5, 1.5, ref_shape)
            .astype("float32")
        )
        with fluid.program_guard(main):
            out_var = block.var(output_name)
            proj_var = fluid.layers.assign_numpy(proj)
            proj_var.stop_gradient = True
            loss = fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(out_var, proj_var)
            )
            grads = fluid.calc_gradient(
                loss,
                [block.var(n) for n in inputs_to_check],
                no_grad_set=no_grad_set,
            )
        exe = fluid.Executor(fluid.CPUPlace())
        analytic = exe.run(main, feed=self._feed, fetch_list=grads)

        for name, a_grad in zip(inputs_to_check, analytic):
            n_grad = self._numeric_grad(name, output_name, delta, proj)
            a = np.asarray(a_grad, np.float64)
            b = np.asarray(n_grad, np.float64)
            abs_a = np.maximum(np.abs(a), np.abs(b))
            abs_a[abs_a < 1e-3] = 1.0
            rel = np.abs(a - b) / abs_a
            assert rel.max() <= max_relative_error, (
                "op %s grad wrt %s: max rel error %g (analytic vs numeric)\n"
                "analytic:\n%s\nnumeric:\n%s"
                % (self.op_type, name, rel.max(), a, b)
            )

    def _numeric_grad(self, input_name, output_name, delta, proj):
        """Central finite differences of sum(output * proj) wrt input."""
        exe = fluid.Executor(fluid.CPUPlace())
        projd = np.asarray(proj, np.float64)

        def f(feed):
            (out,) = exe.run(self._main, feed=feed, fetch_list=[output_name])
            return float(np.sum(np.asarray(out, np.float64) * projd))

        base = {k: np.array(v) for k, v in self._feed.items()}
        x = base[input_name].astype(np.float64)
        grad = np.zeros_like(x, np.float64)
        flat = x.reshape(-1)
        gflat = grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + delta
            feed = dict(base)
            feed[input_name] = x.astype(base[input_name].dtype)
            fp = f(feed)
            flat[i] = orig - delta
            feed[input_name] = x.astype(base[input_name].dtype)
            fm = f(feed)
            flat[i] = orig
            gflat[i] = (fp - fm) / (2 * delta)
        return grad


def make_op_test(op_type, inputs, outputs, attrs=None):
    """One-line OpTest construction for sweep-style tests."""
    t = OpTest()
    t.op_type = op_type
    t.inputs = inputs
    t.outputs = outputs
    t.attrs = dict(attrs or {})
    return t


def make_grad_test(op_type, inputs, out_shapes, attrs=None):
    """Grad-only variant: outputs need correct SHAPES, not values
    (check_grad uses the expected array only for the random projection)."""
    return make_op_test(
        op_type, inputs,
        {k: np.zeros(v, "float32") for k, v in out_shapes.items()},
        attrs)
