"""Stacked-LSTM model convergence (benchmark/fluid stacked_dynamic_lstm
recipe on synthetic separable sentiment data)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import stacked_lstm


def _synthetic_sentiment(n, seq_len, dict_size, rng):
    """Class 0 draws tokens from the low half of the vocab, class 1 from
    the high half — linearly separable through the embedding."""
    words = np.zeros((n, seq_len), "int64")
    lens = rng.randint(seq_len // 2, seq_len + 1, size=n).astype("int64")
    labels = rng.randint(0, 2, size=(n, 1)).astype("int64")
    for i in range(n):
        lo, hi = (2, dict_size // 2) if labels[i, 0] == 0 else (
            dict_size // 2, dict_size - 1
        )
        words[i, : lens[i]] = rng.randint(lo, hi, size=lens[i])
    return words, lens.reshape(-1, 1), labels


def test_stacked_lstm_converges():
    seq_len, dict_size = 16, 200
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        loss, feeds, extras = stacked_lstm.build(
            seq_len=seq_len,
            dict_size=dict_size,
            emb_dim=16,
            hid_dim=16,
            stacked_num=2,
        )
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    bs = 32
    first = None
    accs = []
    for step in range(30):
        words, lens, labels = _synthetic_sentiment(
            bs, seq_len, dict_size, rng
        )
        lv, acc = exe.run(
            main,
            feed={"words": words, "length": lens, "label": labels},
            fetch_list=[loss, extras["accuracy"]],
        )
        if first is None:
            first = float(np.asarray(lv).ravel()[0])
        accs.append(float(np.asarray(acc).ravel()[0]))
    last = float(np.asarray(lv).ravel()[0])
    assert np.isfinite(last)
    assert last < first * 0.6, (first, last)
    assert np.mean(accs[-5:]) > 0.8, accs
