"""Router tier: fleet-grade serving (serving/router.py) — one address
over N frontends with prefix-affinity routing, degradation-aware
shedding, heartbeat-leased membership, and zero-loss live session
migration (planned drain + failover from a banked snapshot).

Covers, in order: consistent-ring stability under membership change
(the property ``prefix_hit_rate`` survives scale-out by), the TLS/auth
front door (typed non-retriable ``AuthError``), lease-lapse eviction,
client address rotation, the degradation-aware pick policy, affinity
routing + the ``router.route`` chaos site (an injected fault re-routes
— never surfaces), unary round-robin with degraded shedding, and the
two migration legs against an UNINTERRUPTED oracle: planned drain
mid-stream (snapshot -> ship -> restore -> sever -> re-attach splice,
banked results reclaimable via ``take_result`` through the router) and
failover (frozen + severed victim, restore of its last banked
snapshot on the survivor) — both bit-identical under a top-k sampler
(sampling keys are (seed, slot, position)), zero duplicated and zero
lost tokens, pools conserved on every teardown. The client-side
(rid, seq) splice is covered against a direct frontend too (a
connection blip with ``resume=True``).

Geometry is IDENTICAL to test_frontend.py so the jax executables are
shared through the exec cache across the tier-1 run.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.distributed.master import (
    AuthError,
    JsonLineClient,
    close_json_server,
)
from paddle_tpu.executor import global_scope
from paddle_tpu.resilience import chaos
from paddle_tpu.serving.client import ServingClient, StreamBrokenError
from paddle_tpu.serving.server import ServingError
from paddle_tpu.serving.frontend import ServingFrontend
from paddle_tpu.serving.generation import Sampler, SlotDecodeSession
from paddle_tpu.serving.router import (
    ConsistentRing,
    RouterMember,
    ServingRouter,
)
from paddle_tpu.serving.snapshot import DecodeSnapshotManager

VOCAB, SEQ, D, S = 24, 8, 32, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)

# source row 5 decodes the full SEQ-1 tokens without an early EOS
# (seeded model + seeded sampler make this stable) — the migration
# legs need a generation long enough to interrupt
LONG_SRC = 5


@pytest.fixture(autouse=True)
def _clean_chaos_and_flags():
    yield
    chaos.disable()
    flags.set_flag("dispatch_retries", 0)


@pytest.fixture(scope="module")
def trained():
    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 41
    startup.random_seed = 41
    scope = global_scope()
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(7)
    src = rng.randint(3, VOCAB, (8, SEQ)).astype("int64")
    return {"exe": exe, "scope": scope, "src": src}


def _paged(trained, **kw):
    args = dict(num_slots=S, max_length=SEQ, d_model=D, paged=True,
                page_size=4, steps=2, num_groups=2,
                prefix_cache_pages=8,
                sampler=Sampler(strategy="top_k", top_k=4,
                                temperature=0.9, seed=11),
                scope=trained["scope"].new_scope())
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


def _expected_tokens(oracle, src, src_len=SEQ):
    """The oracle's generated token list: everything after bos up to
    and including the first eos (or the full row)."""
    row = oracle.generate(np.asarray(src)[None, :], [src_len])[0]
    out = []
    for t in row[1:]:
        out.append(int(t))
        if t == 2:
            break
    return out


def _stream_tokens(events):
    toks = []
    for e in events:
        if e["event"] == "tokens":
            toks.extend(int(t) for t in e["tokens"])
    return toks


class _StubFrontend(object):
    """Just enough frontend surface for RouterMember registration."""

    address = ("127.0.0.1", 9)
    _snap_mgr = None


# ---------------------------------------------------------------------------
# the ring: affinity stability under membership change
# ---------------------------------------------------------------------------

def test_ring_affinity_stable_under_membership_change():
    keys = ["req-%d" % i for i in range(300)]
    r3 = ConsistentRing(["a", "b", "c"])
    r4 = ConsistentRing(["a", "b", "c", "d"])
    moved = 0
    for k in keys:
        if r4.pick(k) != r3.pick(k):
            # the consistent-hash contract: a key's owner changes ONLY
            # to the new member — never between survivors
            assert r4.pick(k) == "d", k
            moved += 1
    # ~1/4 of the keyspace moves on 3 -> 4; far from all of it
    assert 0 < moved < len(keys) // 2
    rm = ConsistentRing(["a", "b", "c"])
    rm.remove("b")
    for k in keys:
        if r3.pick(k) != "b":
            assert rm.pick(k) == r3.pick(k), k
        else:
            assert rm.pick(k) in ("a", "c")
    # skip walks clockwise past excluded members, never returns them
    for k in keys[:50]:
        owner = r3.pick(k)
        assert r3.pick(k, skip={owner}) != owner
    assert r3.pick("x", skip={"a", "b", "c"}) is None


# ---------------------------------------------------------------------------
# membership: auth front door, lease lapse, stub members
# ---------------------------------------------------------------------------

def test_auth_front_door_typed_reject_and_member_registration():
    with ServingRouter(lease_s=5.0, health_poll_s=0,
                       auth_token="sesame") as r:
        bad = JsonLineClient(r.address)
        with pytest.raises(AuthError):
            bad._call(method="status")
        bad.close()
        # a wrong token is the same typed, non-retriable reject
        wrong = JsonLineClient(r.address, auth_token="open")
        with pytest.raises(AuthError):
            wrong._call(method="status")
        wrong.close()
        m = RouterMember(_StubFrontend(), r.address,
                         auth_token="sesame")
        try:
            assert m.worker_id in r.stats()["frontends"]
        finally:
            m.close()


def test_lease_lapse_evicts_and_runs_failover():
    with ServingRouter(lease_s=0.3, health_poll_s=0) as r:
        # heartbeat far slower than the lease: the member lapses
        m = RouterMember(_StubFrontend(), r.address, heartbeat_s=30.0)
        wid = m.worker_id
        assert wid in r.stats()["frontends"]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if wid not in r.stats()["frontends"]:
                break
            time.sleep(0.05)
        st = r.stats()
        assert wid not in st["frontends"]
        # the eviction hook ran the failover; a stub banks no snapshot
        # and owned no streams, so it is a counted no-op
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not st["failovers"]:
            time.sleep(0.05)
            st = r.stats()
        assert st["failovers"] == 1 and st["lost_streams"] == 0
        m.close(leave=False)


def test_client_rotates_across_dead_addresses():
    with ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        # first address refuses connections: the client must rotate to
        # the live router and answer
        cl = ServingClient([("127.0.0.1", 1), r.address])
        st = cl._request(method="stats")
        assert st["ok"] and "frontends" in st["stats"]
        cl.close()


def test_degradation_aware_pick_policy():
    with ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(_StubFrontend(), r.address)
        m2 = RouterMember(_StubFrontend(), r.address)
        w1, w2 = m1.worker_id, m2.worker_id
        try:
            # shed members stop receiving NEW admissions while a
            # healthy peer exists — for every key
            r._mark_degraded(w1, "shed")
            assert all(r._pick_stream("k%d" % i, set()) == w2
                       for i in range(20))
            # every member degraded: fall back to live (the fleet's
            # typed degradation answer beats a router error)
            r._mark_degraded(w2, "brownout")
            assert r._pick_stream("k", set()) in (w1, w2)
            # draining members are excluded even when the alternative
            # is degraded
            with r._mu:
                r._draining.add(w2)
            assert r._pick_stream("k", set()) == w1
            # nothing routable at all
            with r._mu:
                r._draining.add(w1)
            assert r._pick_stream("k", set()) is None
        finally:
            m1.close()
            m2.close()


# ---------------------------------------------------------------------------
# routing: affinity + chaos re-route, unary round-robin
# ---------------------------------------------------------------------------

def test_generate_affinity_and_route_fault_rerouted(trained):
    src = trained["src"]
    s1, s2, oracle = _paged(trained), _paged(trained), _paged(trained)
    pfx = [int(t) for t in src[0][:5]]
    with ServingFrontend(session=s1) as fe1, \
            ServingFrontend(session=s2) as fe2, \
            ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        m2 = RouterMember(fe2, r.address)
        cl = ServingClient(r.address)
        try:
            want = oracle.generate_best_of(src[0], 1, src_len=SEQ,
                                           prefix_tokens=pfx)
            # the same (src, prefix) twice: the affinity key pins both
            # admissions to ONE member, so the second rides its warm
            # prefix cache — hit rate survives the fleet
            got1 = cl.generate_full(src[0], src_len=SEQ,
                                    prefix_tokens=pfx)
            got2 = cl.generate_full(src[0], src_len=SEQ,
                                    prefix_tokens=pfx)
            assert np.array_equal(got1, want)
            assert np.array_equal(got2, want)
            stats = [s.prefix_cache_stats() for s in (s1, s2)]
            landed = [st for st in stats if st["lookups"]]
            assert len(landed) == 1, stats
            assert landed[0]["lookups"] >= 2 and landed[0]["hits"] >= 1
            # an injected route fault re-routes to the other member —
            # the client never sees it, tokens stay oracle-exact
            # (identical (seed, slot, position) keys on either member)
            chaos.configure("io@site=router.route,n=1")
            got = cl.generate_full(src[1], src_len=5)
            assert chaos.fires("router.route") == 1
            want1 = oracle.generate(src[1][None, :], [5])
            assert np.array_equal(got[0], want1[0])
        finally:
            cl.close()
            m1.close()
            m2.close()


def test_predict_round_robin_and_degraded_shed(trained):
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import loadgen
    from paddle_tpu.serving.server import BatchingServer
    import tempfile

    model_dir = tempfile.mkdtemp(prefix="router_demo_")
    loadgen.build_demo_model(model_dir, train_steps=5)
    pred = create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))
    sv1 = BatchingServer(pred, max_batch=8, workers=1,
                         batch_linger_s=0.002)
    sv2 = BatchingServer(pred, max_batch=8, workers=1,
                         batch_linger_s=0.002)
    with sv1, sv2, ServingFrontend(server=sv1) as fe1, \
            ServingFrontend(server=sv2) as fe2, \
            ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        m2 = RouterMember(fe2, r.address)
        cl = ServingClient(r.address)
        try:
            reqs = loadgen.demo_requests(4, seed=5)
            for req in reqs:
                got = cl.predict(req)
                want = sv1.run_reference(req)
                assert all(np.array_equal(g, w)
                           for g, w in zip(got, want))
            n1 = fe1.stats()["requests"]["predict"]["ok"]
            n2 = fe2.stats()["requests"]["predict"]["ok"]
            assert n1 >= 1 and n2 >= 1 and n1 + n2 == 4
            # a degraded member sheds NEW unary admissions to its peer
            r._mark_degraded(m1.worker_id, "shed")
            for req in loadgen.demo_requests(2, seed=9):
                cl.predict(req)
            assert fe1.stats()["requests"]["predict"]["ok"] == n1
            assert fe2.stats()["requests"]["predict"]["ok"] == n2 + 2
        finally:
            cl.close()
            m1.close()
            m2.close()


# ---------------------------------------------------------------------------
# migration: planned drain + failover, bit-exact vs the oracle
# ---------------------------------------------------------------------------

def test_drain_midstream_bit_exact_and_banked_reclaim(
        trained, tmp_path):
    src = trained["src"]
    s1, s2, oracle = _paged(trained), _paged(trained), _paged(trained)
    exp = _expected_tokens(oracle, src[LONG_SRC])
    exp_banked = oracle.generate(src[6][None, :], [SEQ])[0]
    fe1 = ServingFrontend(
        session=s1, snapshot_manager=DecodeSnapshotManager(
            s1, str(tmp_path / "snapA"), interval_steps=1))
    fe2 = ServingFrontend(
        session=s2, snapshot_manager=DecodeSnapshotManager(
            s2, str(tmp_path / "snapB"), interval_steps=1))
    with fe1, fe2, ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)  # registered first: the
        cl = ServingClient(r.address)      # stream lands on fe1
        try:
            # a headless request banks its result on the victim — the
            # migration must carry the bank (enqueue at the worker's
            # quiesce point; direct session calls race the step loop)
            rid_banked = fe1._decode.call(
                lambda: s1.enqueue(src[6], SEQ))
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if rid_banked in s1._results:
                    break
                time.sleep(0.02)
            assert rid_banked in s1._results
            # slow each decode dispatch so the drain lands MID-stream
            chaos.configure("slow@site=serve.dispatch,p=1.0,secs=0.15")
            gen = cl.generate(src[LONG_SRC], src_len=SEQ)
            events = []
            while True:
                ev = next(gen)
                events.append(ev)
                if ev["event"] == "tokens":
                    break
            m2 = RouterMember(fe2, r.address)
            cl2 = ServingClient(r.address)
            res = cl2._request(method="drain", worker_id=m1.worker_id)
            assert res["ok"] and res["target"] == m2.worker_id
            # the drain caught the generation LIVE and the bank rode
            # along
            assert res["live"], res
            assert rid_banked in res["banked"]
            events.extend(gen)
            chaos.disable()
            # spliced stream: bit-identical to the uninterrupted
            # oracle, no duplicated and no dropped tokens
            assert _stream_tokens(events) == exp
            st = r.stats()
            assert st["migrations"] == 1 and st["lost_streams"] == 0
            assert st["migration_seconds"]
            # the banked result is claimable THROUGH the router, off
            # the migration target
            got_banked = cl2.take_result(rid_banked)
            assert np.array_equal(got_banked, exp_banked)
            # the drained member is pinned out of routing even though
            # its heartbeats re-register it under the same id
            n_before = len(s1._results)
            got_after = cl2.generate_full(src[1], src_len=5)
            want_after = oracle.generate(src[1][None, :], [5])
            assert np.array_equal(got_after[0], want_after[0])
            assert len(s1._results) == n_before
            assert not s1.active_slots and not s1.pending_requests
            # teardown conservation on both pools
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not (
                    s1.pool_conserved and s2.pool_conserved
                    and not s2.active_slots):
                time.sleep(0.02)
            assert s1.pool_conserved and s2.pool_conserved
            cl2.close()
            m2.close()
        finally:
            chaos.disable()
            cl.close()
            m1.close()


def test_failover_restores_banked_snapshot_bit_exact(
        trained, tmp_path):
    src = trained["src"]
    s1, s2, oracle = _paged(trained), _paged(trained), _paged(trained)
    exp = _expected_tokens(oracle, src[LONG_SRC])
    fe1 = ServingFrontend(
        session=s1, snapshot_manager=DecodeSnapshotManager(
            s1, str(tmp_path / "snapA"), interval_steps=1))
    fe2 = ServingFrontend(
        session=s2, snapshot_manager=DecodeSnapshotManager(
            s2, str(tmp_path / "snapB"), interval_steps=1))
    unfreeze = threading.Event()
    with fe2, ServingRouter(lease_s=1.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        cl = ServingClient(r.address)
        try:
            chaos.configure("slow@site=serve.dispatch,p=1.0,secs=0.15")
            gen = cl.generate(src[LONG_SRC], src_len=SEQ)
            events, ntok = [], 0
            while ntok < 2:
                ev = next(gen)
                events.append(ev)
                if ev["event"] == "tokens":
                    ntok += len(ev["tokens"])
            m2 = RouterMember(fe2, r.address)
            # "kill" fe1 without a subprocess: freeze its decode loop
            # at the next quiesce point (no further snapshots — like a
            # SIGKILL, the last BANKED snapshot is the failover basis),
            # stop its heartbeats, sever its server
            with pytest.raises(TimeoutError):
                fe1._decode.call(lambda: unfreeze.wait(30.0),
                                 timeout=0.0)
            m1._stop.set()
            close_json_server(fe1._json_server)
            fe1._json_server = None
            t0 = time.monotonic()
            events.extend(gen)
            chaos.disable()
            # the severed relay + failed probe detect the death FAST —
            # well inside the migration budget, no lease wait needed
            assert time.monotonic() - t0 < 30.0
            assert _stream_tokens(events) == exp
            st = r.stats()
            assert st["failovers"] == 1 and st["migrations"] == 1
            assert st["lost_streams"] == 0
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not (
                    s2.pool_conserved and not s2.active_slots):
                time.sleep(0.02)
            assert s2.pool_conserved
            m2.close()
        finally:
            chaos.disable()
            unfreeze.set()
            cl.close()
            m1.close(leave=False)
            fe1.close()


# ---------------------------------------------------------------------------
# client-side splice: resume=True, attached DIRECTLY to the victim
# ---------------------------------------------------------------------------

def test_client_resume_rotates_to_router_after_victim_death(
        trained, tmp_path):
    """A client streaming directly from a frontend (router only in its
    fallback address list) survives that frontend's death: the sever
    triggers the resume path, the client rotates to the router, and
    the router — seeing a rid it never relayed, owned by an
    unreachable member — runs the failover, restores the banked
    snapshot on the survivor, and re-drives the attach. The client's
    own (rid, seq) splice trims the replay."""
    src = trained["src"]
    s1, s2, oracle = _paged(trained), _paged(trained), _paged(trained)
    exp = _expected_tokens(oracle, src[LONG_SRC])
    fe1 = ServingFrontend(
        session=s1, snapshot_manager=DecodeSnapshotManager(
            s1, str(tmp_path / "snapA"), interval_steps=1))
    fe2 = ServingFrontend(
        session=s2, snapshot_manager=DecodeSnapshotManager(
            s2, str(tmp_path / "snapB"), interval_steps=1))
    unfreeze = threading.Event()
    with fe2, ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        m2 = RouterMember(fe2, r.address)
        cl = ServingClient([fe1.address, r.address])
        try:
            chaos.configure("slow@site=serve.dispatch,p=1.0,secs=0.15")
            gen = cl.generate(src[LONG_SRC], src_len=SEQ, resume=True)
            events, ntok = [], 0
            while ntok < 2:
                ev = next(gen)
                events.append(ev)
                if ev["event"] == "tokens":
                    ntok += len(ev["tokens"])
            # kill the victim under its direct client
            with pytest.raises(TimeoutError):
                fe1._decode.call(lambda: unfreeze.wait(30.0),
                                 timeout=0.0)
            m1._stop.set()
            close_json_server(fe1._json_server)
            fe1._json_server = None
            events.extend(gen)
            chaos.disable()
            assert _stream_tokens(events) == exp
            st = r.stats()
            assert st["failovers"] == 1 and st["lost_streams"] == 0
            m2.close()
        finally:
            chaos.disable()
            unfreeze.set()
            cl.close()
            m1.close(leave=False)
            fe1.close()


# ---------------------------------------------------------------------------
# rid namespaces: per-member ids must never cross-resolve
# ---------------------------------------------------------------------------

def test_take_result_rid_collision_resolves_to_minting_member(
        trained, tmp_path):
    """Two frontends mint the SAME rid number for different requests
    (rids are per-member namespaces counting from 0). The router's
    composite "wid:rid" handle claims exactly the minting member's
    result; a bare ambiguous rid is a typed miss (None) — it must
    never pop another member's bank."""
    src = trained["src"]
    s1, s2, oracle = _paged(trained), _paged(trained), _paged(trained)
    exp6 = oracle.generate(src[6][None, :], [SEQ])[0]
    exp7 = oracle.generate(src[7][None, :], [SEQ])[0]
    fe1, fe2 = ServingFrontend(session=s1), ServingFrontend(session=s2)
    with fe1, fe2, ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        m2 = RouterMember(fe2, r.address)
        cl = ServingClient(r.address)
        try:
            rid1 = fe1._decode.call(lambda: s1.enqueue(src[6], SEQ))
            rid2 = fe2._decode.call(lambda: s2.enqueue(src[7], SEQ))
            # the collision premise: independent namespaces, same number
            assert rid1 == rid2
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and not (
                    rid1 in s1._results and rid2 in s2._results):
                time.sleep(0.02)
            assert rid1 in s1._results and rid2 in s2._results
            # a BARE rid with two live members is ambiguous: typed
            # miss, both banks untouched
            assert cl.take_result(rid1) is None
            assert rid1 in s1._results and rid2 in s2._results
            # composite handles resolve to exactly their namespace
            got1 = cl.take_result("%s:%d" % (m1.worker_id, rid1))
            assert np.array_equal(got1, exp6)
            assert rid2 in s2._results  # fe2's bank survived the claim
            got2 = cl.take_result("%s:%d" % (m2.worker_id, rid2))
            assert np.array_equal(got2, exp7)
        finally:
            cl.close()
            m1.close()
            m2.close()


def test_drain_failure_rolls_back_routing_pin(trained, tmp_path):
    """A drain that cannot land (here: no surviving target) raises its
    typed error AND unpins the victim — one transient failure must not
    remove a healthy frontend from routing forever."""
    src = trained["src"]
    s1, oracle = _paged(trained), _paged(trained)
    fe1 = ServingFrontend(
        session=s1, snapshot_manager=DecodeSnapshotManager(
            s1, str(tmp_path / "snapA"), interval_steps=1))
    with fe1, ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        cl = ServingClient(r.address)
        try:
            with pytest.raises(ServingError):
                cl._request(method="drain", worker_id=m1.worker_id)
            st = r.stats()
            assert st["frontends"][m1.worker_id]["draining"] is False
            # the member still serves: the failed drain left no pin
            got = cl.generate_full(src[1], src_len=5)
            want = oracle.generate(src[1][None, :], [5])
            assert np.array_equal(got[0], want[0])
        finally:
            cl.close()
            m1.close()


# ---------------------------------------------------------------------------
# relay discipline: in-band cancel while the upstream is producing,
# typed loss for rid-less (group) streams
# ---------------------------------------------------------------------------

def test_inband_cancel_propagates_while_upstream_producing(trained):
    """The relay polls the downstream on EVERY event, so a mid-stream
    cancel reaches the member while tokens are still flowing — the
    generation is torn down instead of running to completion."""
    src = trained["src"]
    s1 = _paged(trained)
    fe1 = ServingFrontend(session=s1)
    with fe1, ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        cl = ServingClient(r.address)
        try:
            chaos.configure("slow@site=serve.dispatch,p=1.0,secs=0.3")
            gen = cl.generate(src[LONG_SRC], src_len=SEQ)
            while next(gen)["event"] != "tokens":
                pass
            gen.close()  # sends the in-band cancel and drains the ack
            chaos.disable()
            # the frontend saw the teardown mid-flight: its generate
            # stream must NOT have completed normally
            deadline = time.monotonic() + 10.0
            outcomes = {}
            while time.monotonic() < deadline:
                outcomes = fe1.stats()["requests"].get("generate", {})
                if outcomes and not s1.active_slots:
                    break
                time.sleep(0.05)
            assert outcomes.get("ok", 0) == 0, outcomes
            assert not s1.active_slots
            assert s1.pool_conserved
        finally:
            chaos.disable()
            cl.close()
            m1.close()


def test_group_stream_sever_after_delivery_is_typed_loss(trained):
    """Fork-group streams carry no rid (the frontend attaches no id to
    their events), so a sever after delivery cannot re-attach: the
    router must answer with a TYPED StreamBrokenError and count the
    lost stream — never an untyped internal error."""
    src = trained["src"]
    s1 = _paged(trained)
    fe1 = ServingFrontend(session=s1)
    with ServingRouter(lease_s=5.0, health_poll_s=0) as r:
        m1 = RouterMember(fe1, r.address)
        cl = ServingClient(r.address)
        try:
            chaos.configure("slow@site=serve.dispatch,p=1.0,secs=0.3")
            gen = cl.generate(src[LONG_SRC], src_len=SEQ, n=2)
            while next(gen)["event"] != "tokens":
                pass
            # kill the member's server under the live relay
            close_json_server(fe1._json_server)
            fe1._json_server = None
            with pytest.raises(StreamBrokenError):
                for _ in gen:
                    pass
            chaos.disable()
            assert r.stats()["lost_streams"] == 1
        finally:
            chaos.disable()
            cl.close()
            m1.close(leave=False)
            fe1.close()


# ---------------------------------------------------------------------------
# resumed events carry bos (the router's synthesized-admission basis)
# ---------------------------------------------------------------------------

def test_resumed_events_carry_bos(trained):
    """Every ``resumed`` variant must carry ``bos`` — the router
    synthesizes an admission from it when a stream fails over before
    its admission event reached the client; a missing field silently
    corrupted non-zero-bos sessions' first prefix token."""
    src = trained["src"]
    s1 = _paged(trained)
    fe1 = ServingFrontend(session=s1)
    with fe1:
        # banked: a headless request finishes into the result bank
        rid = fe1._decode.call(lambda: s1.enqueue(src[6], SEQ))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and rid not in s1._results:
            time.sleep(0.02)
        assert rid in s1._results
        cl = ServingClient(fe1.address)
        try:
            cl._send_line({"method": "attach", "id": int(rid)})
            first = cl._recv_line()
            assert first["event"] == "resumed" and first["finished"]
            assert first["bos"] == int(s1._bos)
            assert cl._recv_line()["event"] == "end"
            # live: attach to a mid-flight headless generation
            chaos.configure("slow@site=serve.dispatch,p=1.0,secs=0.2")
            rid2 = fe1._decode.call(lambda: s1.enqueue(src[5], SEQ))
            deadline = time.monotonic() + 30.0
            while (time.monotonic() < deadline
                    and rid2 not in s1._owner.values()):
                time.sleep(0.02)
            assert rid2 in s1._owner.values()
            cl2 = ServingClient(fe1.address)
            cl2._send_line({"method": "attach", "id": int(rid2)})
            first2 = cl2._recv_line()
            assert first2["event"] == "resumed"
            assert not first2["finished"]
            assert first2["bos"] == int(s1._bos)
            cl2.close()  # disconnect cancels the attached generation
            chaos.disable()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and s1.active_slots:
                time.sleep(0.02)
            assert s1.pool_conserved
        finally:
            chaos.disable()
            cl.close()
