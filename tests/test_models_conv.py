"""Book-style convergence tests for the conv model zoo on tiny synthetic
data (tests/book/test_{recognize_digits,image_classification}.py parity)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import mnist, resnet, vgg


def _synthetic_images(n, shape, classes, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n).astype("int64")
    base = rng.randn(classes, *shape).astype("float32")
    x = base[labels] + 0.25 * rng.randn(n, *shape).astype("float32")
    return x, labels.reshape(-1, 1)


def _train(build_fn, kwargs, n=64, bs=16, steps=25, lr=0.001, classes=4,
           optimizer=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, feeds, extras = build_fn(**kwargs)
        opt = optimizer or fluid.optimizer.Adam(learning_rate=lr)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    shape = tuple(int(d) for d in feeds[0].shape[1:])
    x, y = _synthetic_images(n, shape, classes)
    losses = []
    for step in range(steps):
        i = (step * bs) % n
        lv, = exe.run(
            main,
            feed={feeds[0].name: x[i : i + bs], feeds[1].name: y[i : i + bs]},
            fetch_list=[loss],
        )
        losses.append(float(lv[0]))
        assert np.isfinite(losses[-1]), "loss diverged at step %d" % step
    return losses


def test_mnist_conv_converges():
    losses = _train(
        mnist.build,
        {"img_shape": (1, 28, 28), "class_num": 4},
        steps=30,
    )
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.6, losses


def test_resnet_cifar_converges():
    losses = _train(
        resnet.build,
        {"img_shape": (3, 16, 16), "class_num": 4, "depth": 8,
         "variant": "cifar10"},
        steps=30,
    )
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_vgg_builds_and_steps():
    # Full VGG-16 is heavy for CI; 32x32 input, few steps, finite loss.
    losses = _train(
        vgg.build,
        {"img_shape": (3, 32, 32), "class_num": 4},
        n=16,
        bs=8,
        steps=4,
    )
    assert all(np.isfinite(losses))


def test_resnet50_imagenet_builds():
    """ResNet-50 graph builds and infers shapes (train step exercised in
    bench.py on real hardware; too heavy for unit CI)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, feeds, extras = resnet.build(
            img_shape=(3, 64, 64), class_num=10, depth=50
        )
        fluid.optimizer.Momentum(0.1, 0.9).minimize(loss)
    n_params = len(main.global_block().all_parameters())
    assert n_params > 100  # 53 convs + BN scales/biases
    assert loss.shape == (1,)


def test_se_resnext_builds_and_steps():
    from paddle_tpu.models import se_resnext

    losses = _train(
        se_resnext.build,
        {"img_shape": (3, 32, 32), "class_num": 4, "depth": 50},
        n=8,
        bs=4,
        steps=3,
    )
    assert all(np.isfinite(losses))


def test_googlenet_builds_and_steps():
    from paddle_tpu.models import googlenet

    losses = _train(
        googlenet.build,
        {"img_shape": (3, 64, 64), "class_num": 4},
        n=8,
        bs=4,
        steps=3,
    )
    assert all(np.isfinite(losses))


def test_alexnet_converges():
    from paddle_tpu.models import alexnet

    losses = _train(
        alexnet.build,
        {"img_shape": (3, 63, 63), "class_num": 4},
        n=32,
        bs=8,
        steps=20,
    )
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_conv_nhwc_flag_parity():
    """FLAGS_conv_nhwc (the MFU layout experiment) must be a pure layout
    change: identical losses, forward and backward, vs the NCHW default."""
    from paddle_tpu import flags, unique_name

    def run():
        unique_name.switch()
        np.random.seed(0)
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 21
        startup.random_seed = 21
        with fluid.program_guard(main, startup):
            loss, feeds, _ = mnist.build(class_num=4)
            fluid.optimizer.Momentum(learning_rate=0.01,
                                     momentum=0.9).minimize(loss)
        with fluid.scope_guard(fluid.executor.Scope()):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            shape = tuple(int(d) for d in feeds[0].shape[1:])
            x, y = _synthetic_images(32, shape, 4)
            out = []
            for step in range(6):
                lv, = exe.run(
                    main,
                    feed={feeds[0].name: x[:16], feeds[1].name: y[:16]},
                    fetch_list=[loss])
                out.append(float(lv[0]))
            return out

    base = run()
    flags.set_flag("conv_nhwc", True)
    try:
        nhwc = run()
    finally:
        flags.set_flag("conv_nhwc", False)
    np.testing.assert_allclose(nhwc, base, rtol=1e-5, atol=1e-6)
