"""contrib decoder DSL (beam_search_decoder.py parity): one StateCell
definition drives BOTH the TrainingDecoder (scan-based teacher-forced
decode) and the BeamSearchDecoder (dense-lattice generation)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib import (
    BeamSearchDecoder,
    InitState,
    StateCell,
    TrainingDecoder,
)

V, D, H = 20, 8, 12
END_ID = 1


def _make_cell(encoder_state):
    cell = StateCell(inputs={"x": None},
                     states={"h": InitState(init=encoder_state)},
                     out_state="h")

    @cell.state_updater
    def updater(c):
        h = c.get_state("h")
        x = c.get_input("x")
        # concat + one named weight (a multi-input fc would need one
        # ParamAttr per input to keep names unique)
        xh = fluid.layers.concat([x, h], axis=1)
        c.set_state("h", fluid.layers.fc(
            input=xh, size=H, act="tanh",
            param_attr=fluid.ParamAttr(name="cell_fc.w"),
            bias_attr=fluid.ParamAttr(name="cell_fc.b")))

    return cell


def _training_program():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 31
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src", shape=[H], dtype="float32")
        trg = fluid.layers.data(name="trg", shape=[5], dtype="int64")
        label = fluid.layers.data(name="label", shape=[5], dtype="int64")
        trg_emb = fluid.layers.embedding(
            trg, size=[V, D],
            param_attr=fluid.ParamAttr(name="word_emb"))

        cell = _make_cell(src)
        decoder = TrainingDecoder(cell)
        with decoder.block():
            w = decoder.step_input(trg_emb)
            decoder.state_cell.compute_state(inputs={"x": w})
            score = fluid.layers.fc(
                input=decoder.state_cell.get_state("h"), size=V,
                param_attr=fluid.ParamAttr(name="beam_score_fc.w"),
                bias_attr=fluid.ParamAttr(name="beam_score_fc.b"))
            decoder.state_cell.update_states()
            decoder.output(score)
        scores = decoder()  # [B, T, V]
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(
                scores, fluid.layers.unsqueeze(label, axes=[2])))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def test_training_decoder_learns():
    main, startup, loss = _training_program()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(2)
    srcs = rng.randn(8, H).astype("float32")
    seqs = rng.randint(2, V, (8, 6)).astype("int64")
    feed = {"src": srcs, "trg": seqs[:, :5], "label": seqs[:, 1:]}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        losses = [float(np.ravel(exe.run(main, feed=feed,
                                         fetch_list=[loss])[0])[0])
                  for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_beam_search_decoder_generates_with_shared_cell():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        # static batch for the beam lattice (append_batch_size=False)
        src4 = fluid.layers.data(name="src", shape=[4, H],
                                 dtype="float32", append_batch_size=False)
        ids4 = fluid.layers.data(name="init_ids", shape=[4, 1],
                                 dtype="int64", append_batch_size=False)
        init_scores = fluid.layers.data(name="init_scores", shape=[4, 1],
                                        dtype="float32",
                                        append_batch_size=False)

        cell = _make_cell(src4)
        decoder = BeamSearchDecoder(
            cell, init_ids=ids4, init_scores=init_scores,
            target_dict_dim=V, word_dim=D, max_len=7, beam_size=3,
            end_id=END_ID)
        sent_ids, sent_scores = decoder.decode()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(4)
    feed = {
        "src": rng.randn(4, H).astype("float32"),
        "init_ids": np.zeros((4, 1), "int64"),
        "init_scores": np.zeros((4, 1), "float32"),
    }
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        ids, scores = exe.run(main, feed=feed,
                              fetch_list=[sent_ids, sent_scores])
    ids, scores = np.asarray(ids), np.asarray(scores)
    assert ids.shape[:2] == (4, 3) and ids.shape[2] <= 7
    assert ((ids >= 0) & (ids < V)).all()
    # beams are score-ordered best-first per batch row
    final = scores.reshape(4, 3, -1)[:, :, -1]
    assert (np.diff(final, axis=1) <= 1e-6).all()
    # scores ACCUMULATE (log-probs sum over steps): totals are not the
    # single-step values a degenerate non-accumulating loop would give
    assert (final < -1e-3).all()
    # and the K beams per row are genuinely distinct hypotheses
    for b in range(4):
        rows = {tuple(ids[b, k]) for k in range(3)}
        assert len(rows) > 1, ids[b]


def test_beam_decoder_rejects_dynamic_batch():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src", shape=[H], dtype="float32")
        ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
        cell = _make_cell(src)
        dec = BeamSearchDecoder(cell, init_ids=ids, init_scores=ids,
                                target_dict_dim=V, word_dim=D)
        with pytest.raises(ValueError, match="static batch"):
            dec.decode()


def test_state_cell_validates():
    with pytest.raises(ValueError, match="out_state"):
        StateCell(inputs={}, states={"h": InitState(
            init=fluid.layers.fill_constant([2, 3], "float32", 0.0))},
            out_state="missing")
    with pytest.raises(ValueError, match="InitState"):
        StateCell(inputs={}, states={"h": 3}, out_state="h")


def test_cell_released_when_updater_raises_mid_build():
    """A failing user updater must not permanently lock the StateCell:
    a corrected decoder can be built from the same cell afterwards."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src4 = fluid.layers.data(name="src", shape=[4, H],
                                 dtype="float32", append_batch_size=False)
        ids4 = fluid.layers.data(name="ids", shape=[4, 1], dtype="int64",
                                 append_batch_size=False)
        scores4 = fluid.layers.data(name="sc", shape=[4, 1],
                                    dtype="float32",
                                    append_batch_size=False)
        cell = StateCell(inputs={"x": None},
                         states={"h": InitState(init=src4)},
                         out_state="h")

        calls = {"n": 0}

        @cell.state_updater
        def updater(c):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom in user updater")
            xh = fluid.layers.concat([c.get_input("x"),
                                      c.get_state("h")], axis=1)
            c.set_state("h", fluid.layers.fc(
                xh, size=H, act="tanh",
                param_attr=fluid.ParamAttr(name="cell2.w"),
                bias_attr=fluid.ParamAttr(name="cell2.b")))

        bad = BeamSearchDecoder(cell, init_ids=ids4, init_scores=scores4,
                                target_dict_dim=V, word_dim=D, max_len=3,
                                beam_size=2, end_id=END_ID)
        with pytest.raises(RuntimeError, match="boom"):
            bad.decode()
        # the cell is free again: a corrected decoder builds fine
        cell._set_raw_state("h", src4)  # restore the pre-lattice state
        good = BeamSearchDecoder(cell, init_ids=ids4, init_scores=scores4,
                                 target_dict_dim=V, word_dim=D, max_len=3,
                                 beam_size=2, end_id=END_ID,
                                 emb_param_name="word_emb2",
                                 score_param_name="score2")
        sent_ids, _ = good.decode()
        assert sent_ids is not None
