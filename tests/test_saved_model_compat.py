"""Serialized-format compatibility pin: a COMMITTED saved-model dir
(tests/golden/mnist_saved_model/: PTPB `__model__`, `.npy` params,
`io_pin.npz` inputs + expected outputs) must keep loading and serving on
every engine — the format-level half of the golden regressions
(test_golden_cpp.py pins numerics over rebuilt programs; this pins the
BYTES ON DISK: a PTPB schema change, a var-file naming change, or a
loader regression breaks here first, before any user's saved model does).

Reference analog: paddle/fluid/inference/tests/api/ keeps serving
models serialized by older producers.
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native

MODEL_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "golden", "mnist_saved_model")


def _pin():
    pin = np.load(os.path.join(MODEL_DIR, "io_pin.npz"))
    feed = {k[len("feed_"):]: pin[k] for k in pin.files
            if k.startswith("feed_")}
    return feed, pin["expected"]


def test_committed_saved_model_serves_via_executor():
    feed, expected = _pin()
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        program, feed_names, fetch_vars = fluid.io.load_inference_model(
            MODEL_DIR, exe)
        assert sorted(feed_names) == sorted(feed)
        (got,) = exe.run(program, feed=feed, fetch_list=fetch_vars)
    np.testing.assert_allclose(np.asarray(got), expected,
                               rtol=2e-4, atol=2e-5,
                               err_msg="the committed saved model no "
                                       "longer reproduces its pin")


def test_committed_saved_model_serves_via_cpp():
    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor

    feed, expected = _pin()
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=MODEL_DIR, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(np.asarray(got), expected,
                               rtol=1e-3, atol=1e-4)
