"""Fused Pallas LSTM kernel tests (kernels/lstm_cell.py): interpret-mode
parity with the XLA scan reference for values and gradients, padding /
peephole / masking / reverse variants, and the FLAGS_use_pallas_lstm
routing of the dynamic_lstm op.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.kernels.lstm_cell import fused_lstm, lstm_reference


def _inputs(b=3, t=5, d=8, seed=0, with_peep=True, with_mask=True):
    rng = np.random.RandomState(seed)
    xw = jnp.asarray(rng.randn(b, t, 4 * d).astype("float32") * 0.4)
    wh = jnp.asarray(rng.randn(d, 4 * d).astype("float32") * 0.3)
    bias = jnp.asarray(rng.randn(4 * d).astype("float32") * 0.1)
    peep = (tuple(jnp.asarray(rng.randn(d).astype("float32") * 0.1)
                  for _ in range(3)) if with_peep else None)
    if with_mask:
        lens = rng.randint(1, t + 1, b)
        mask = jnp.asarray(
            (np.arange(t)[None, :] < lens[:, None]).astype("float32"))
    else:
        mask = None
    return xw, wh, bias, peep, mask


@pytest.mark.parametrize("with_peep,with_mask", [
    (True, True), (False, False), (True, False), (False, True)])
def test_fused_lstm_matches_reference(with_peep, with_mask):
    xw, wh, bias, peep, mask = _inputs(with_peep=with_peep,
                                       with_mask=with_mask)
    d = wh.shape[0]
    h0 = jnp.zeros((xw.shape[0], d))
    ref = lstm_reference(xw, wh, bias, peep, h0, h0, mask)
    got = fused_lstm(xw, wh, bias, peephole=peep, mask=mask,
                     force_pallas=True)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(ref[1]),
                               atol=1e-5)


def test_fused_lstm_gradients_match_reference():
    xw, wh, bias, peep, mask = _inputs(seed=2)
    d = wh.shape[0]
    h0 = jnp.zeros((xw.shape[0], d))

    def loss_pal(xw, wh, bias):
        h, c = fused_lstm(xw, wh, bias, peephole=peep, mask=mask,
                          force_pallas=True)
        return jnp.sum(h ** 2) + jnp.sum(c)

    def loss_ref(xw, wh, bias):
        h, c = lstm_reference(xw, wh, bias, peep, h0, h0, mask)
        return jnp.sum(h ** 2) + jnp.sum(c)

    gp = jax.grad(loss_pal, argnums=(0, 1, 2))(xw, wh, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(xw, wh, bias)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_fused_lstm_batch_padding_path():
    # batch bigger than one block multiple exercises the pad/unpad path
    xw, wh, bias, _, _ = _inputs(b=5, t=3, seed=3, with_peep=False,
                                 with_mask=False)
    d = wh.shape[0]
    h0 = jnp.zeros((5, d))
    ref = lstm_reference(xw, wh, bias, None, h0, h0, None)
    got = fused_lstm(xw, wh, bias, force_pallas=True)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               atol=1e-5)


def test_fused_lstm_validates():
    xw, wh, bias, _, _ = _inputs(with_peep=False, with_mask=False)
    with pytest.raises(ValueError, match="activation"):
        fused_lstm(xw, wh, bias, gate_act="softsign")
    with pytest.raises(ValueError, match="4\\*D"):
        fused_lstm(xw[:, :, :-4], wh, bias)


def test_dynamic_lstm_flag_routes_to_fused_path():
    """FLAGS_use_pallas_lstm=1 must produce the same training results as
    the scan path (on CPU the fused entry point falls back to the same
    reference math; the routing itself is what's exercised)."""
    def run(flag):
        flags.set_flag("use_pallas_lstm", flag)
        try:
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = 11
            startup.random_seed = 11
            with fluid.program_guard(main, startup):
                words = fluid.layers.data("w", [6], dtype="int64")
                length = fluid.layers.data("len", [1], dtype="int64")
                label = fluid.layers.data("y", [1], dtype="int64")
                emb = fluid.layers.embedding(words, size=[30, 8])
                proj = fluid.layers.fc(emb, size=4 * 8, num_flatten_dims=2)
                hid, _ = fluid.layers.dynamic_lstm(proj, size=4 * 8,
                                                   length=length)
                pooled = fluid.layers.sequence_pool(hid, "max",
                                                    length=length)
                loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
                    fluid.layers.fc(pooled, 3), label))
                fluid.optimizer.SGD(0.1).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(1)
            out = []
            for _ in range(5):
                feed = {
                    "w": rng.randint(0, 30, (4, 6)).astype("int64"),
                    "len": rng.randint(1, 7, (4, 1)).astype("int64"),
                    "y": rng.randint(0, 3, (4, 1)).astype("int64"),
                }
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                out.append(float(np.asarray(lv).ravel()[0]))
            return out
        finally:
            flags.set_flag("use_pallas_lstm", False)

    base = run(False)
    fused = run(True)
    np.testing.assert_allclose(base, fused, rtol=1e-5, atol=1e-6)


def test_flag_toggle_recompiles_cached_program():
    """Toggling FLAGS_use_pallas_lstm between runs of the SAME program on
    the SAME executor must recompile (the executable cache is keyed on
    trace-time flags)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4, 4 * 4])
        hid, _ = fluid.layers.dynamic_lstm(x, size=4 * 4)
        out = fluid.layers.reduce_sum(hid)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.random.RandomState(0).randn(2, 4, 16)
            .astype("float32")}
    flags.set_flag("use_pallas_lstm", False)
    try:
        (a,) = exe.run(main, feed=feed, fetch_list=[out])
        n_cached = len(exe._cache)
        flags.set_flag("use_pallas_lstm", True)
        (b,) = exe.run(main, feed=feed, fetch_list=[out])
        assert len(exe._cache) == n_cached + 1, "flag flip did not recompile"
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)
    finally:
        flags.set_flag("use_pallas_lstm", False)


# -- GRU sibling kernel (kernels/gru_cell.py) -------------------------------

def _gru_inputs(b=3, t=5, d=8, seed=4, with_mask=True):
    rng = np.random.RandomState(seed)
    xw = jnp.asarray(rng.randn(b, t, 3 * d).astype("float32") * 0.4)
    wg = jnp.asarray(rng.randn(d, 2 * d).astype("float32") * 0.3)
    wc = jnp.asarray(rng.randn(d, d).astype("float32") * 0.3)
    bias = jnp.asarray(rng.randn(3 * d).astype("float32") * 0.1)
    if with_mask:
        lens = rng.randint(1, t + 1, b)
        mask = jnp.asarray(
            (np.arange(t)[None, :] < lens[:, None]).astype("float32"))
    else:
        mask = None
    return xw, wg, wc, bias, mask


@pytest.mark.parametrize("with_mask", [True, False])
def test_fused_gru_matches_reference(with_mask):
    from paddle_tpu.kernels.gru_cell import fused_gru, gru_reference

    xw, wg, wc, bias, mask = _gru_inputs(with_mask=with_mask)
    h0 = jnp.zeros((xw.shape[0], wc.shape[0]))
    ref = gru_reference(xw, wg, wc, bias, h0, mask)
    got = fused_gru(xw, wg, wc, bias, mask=mask, force_pallas=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_fused_gru_gradients_match_reference():
    from paddle_tpu.kernels.gru_cell import fused_gru, gru_reference

    xw, wg, wc, bias, mask = _gru_inputs(seed=6)
    h0 = jnp.zeros((xw.shape[0], wc.shape[0]))

    def loss_pal(xw, wg, wc, bias):
        return jnp.sum(fused_gru(xw, wg, wc, bias, mask=mask,
                                 force_pallas=True) ** 2)

    def loss_ref(xw, wg, wc, bias):
        return jnp.sum(gru_reference(xw, wg, wc, bias, h0, mask) ** 2)

    gp = jax.grad(loss_pal, argnums=(0, 1, 2, 3))(xw, wg, wc, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(xw, wg, wc, bias)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_dynamic_gru_flag_parity():
    """FLAGS_use_pallas_gru routing reproduces the scan-path training."""
    def run(flag):
        flags.set_flag("use_pallas_gru", flag)
        try:
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = 13
            startup.random_seed = 13
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", [5, 3 * 6])
                length = fluid.layers.data("len", [1], dtype="int64")
                hid = fluid.layers.dynamic_gru(x, size=6, length=length)
                out = fluid.layers.reduce_sum(hid)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(2)
            feed = {"x": rng.randn(3, 5, 18).astype("float32"),
                    "len": np.asarray([[5], [2], [4]], "int64")}
            (v,) = exe.run(main, feed=feed, fetch_list=[out])
            return float(np.asarray(v).ravel()[0])
        finally:
            flags.set_flag("use_pallas_gru", False)

    np.testing.assert_allclose(run(False), run(True), rtol=1e-5)
