"""Native (C++) elastic master: protocol parity with the Python
MasterService, elastic lease-timeout/failure semantics, and CROSS-LANGUAGE
snapshot recovery (either implementation resumes the other's snapshot).

Reference parity: go/master/service.go + go/cmd/master, rebuilt as the
C++ coordination service SURVEY.md §2.9 item 12 calls for. The Python
MasterClient/task_reader from paddle_tpu.distributed drive the binary
unchanged — the wire protocol is shared.
"""

import json
import os
import subprocess
import time

import pytest

from paddle_tpu.distributed.master import (
    MasterClient,
    MasterService,
    task_reader,
)


class _NativeMaster(object):
    """Context manager: spawn ptpu_master, parse its bound port. Skips
    the calling test when the native toolchain is unavailable (lazy: the
    cmake build runs at most once, at first use, not at collection)."""

    def __init__(self, *args):
        from tests.conftest import build_native_binary

        binary = build_native_binary("ptpu_master")
        if binary is None:
            pytest.skip("native toolchain unavailable")
        self.proc = subprocess.Popen(
            [binary] + [str(a) for a in args],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        line = self.proc.stdout.readline().decode()
        assert line.startswith("LISTENING "), line
        self.port = int(line.split()[1])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.proc.terminate()
        self.proc.wait(timeout=10)


def test_protocol_parity_full_epoch():
    chunks = ["part-%03d" % i for i in range(10)]
    with _NativeMaster("--chunks_per_task", 3) as m:
        client = MasterClient(("127.0.0.1", m.port))
        assert client.set_dataset(chunks)
        # 10 chunks / 3 per task = 4 tasks
        st = client.status()
        assert st["todo"] == 4 and st["cur_pass"] == 0

        seen = []
        loaded = task_reader(client, lambda c: iter([c]))
        for sample in loaded():
            seen.append(sample)
        assert sorted(seen) == chunks  # one full pass, every chunk once
        assert client.status()["cur_pass"] == 1  # rolled to the next pass

        # second epoch redispatches everything
        seen2 = sorted(loaded())
        assert seen2 == chunks
        client.close()


def test_unicode_chunk_descriptors_round_trip():
    """Chunk descriptors are opaque: non-ASCII (incl. astral plane, which
    Python json.dumps ships as \\u-surrogate pairs) must round-trip
    through the C++ master byte-exactly."""
    chunks = ["データ/part-0", "shards/\U0001F600.rec", {"file": "naïve.txt",
                                                        "offset": 42}]
    with _NativeMaster() as m:
        client = MasterClient(("127.0.0.1", m.port))
        client.set_dataset(chunks)
        got = []
        while True:
            task = client.get_task(sync_pass=False)
            if task is None:
                break
            got.extend(task.chunks)
            client.task_finished(task.task_id)
        assert sorted(got, key=str) == sorted(chunks, key=str)
        client.close()


def test_lease_timeout_requeues_and_failure_max_discards():
    with _NativeMaster("--timeout_s", 0.3, "--failure_max", 2) as m:
        client = MasterClient(("127.0.0.1", m.port))
        client.set_dataset(["only-chunk"])

        # lease and abandon: the lease must expire back to todo
        t1 = client.get_task()
        assert t1 is not None and t1.epoch == 1
        deadline = time.time() + 5.0
        while client.status()["todo"] == 0 and time.time() < deadline:
            time.sleep(0.05)
        assert client.status()["todo"] == 1  # requeued (num_failures=1)

        # fail it once more explicitly: reaches failure_max -> discarded
        t2 = client.get_task()
        assert t2.num_failures == 1
        assert client.task_failed(t2.task_id, t2.epoch)
        st = client.status()
        assert st["failed"] == 1 and st["todo"] == 0

        # stale failure reports (old epoch) are rejected
        client2 = MasterClient(("127.0.0.1", m.port))
        assert not client2.task_failed(t2.task_id, epoch=0)
        client.close()
        client2.close()


def test_concurrent_workers_each_chunk_exactly_once():
    """8 worker threads hammering one C++ master: across a pass every
    chunk is dispatched exactly once (no double-lease, no loss) — the
    mutex discipline in master.h under real connection concurrency."""
    import threading

    chunks = list(range(64))
    with _NativeMaster("--chunks_per_task", 2, "--timeout_s", 30.0) as m:
        boot = MasterClient(("127.0.0.1", m.port))
        boot.set_dataset(chunks)
        seen = []
        seen_lock = threading.Lock()
        errors = []

        def worker():
            try:
                client = MasterClient(("127.0.0.1", m.port))
                while True:
                    task = client.get_task(sync_pass=False)
                    if task is None:
                        break
                    with seen_lock:
                        seen.extend(task.chunks)
                    client.task_finished(task.task_id)
                client.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert sorted(seen) == chunks  # exactly once each
        assert boot.status()["cur_pass"] == 1
        boot.close()


def test_native_master_recovers_python_snapshot(tmp_path):
    """A Python-master snapshot restarts under the C++ master: pending
    tasks go back to todo, pass counter and chunks carry over."""
    snap = str(tmp_path / "master.snap")
    py = MasterService(chunks_per_task=2, timeout_s=30.0, snapshot_path=snap)
    py.set_dataset(list(range(8)))  # 4 tasks
    t, err = py.get_task(0)
    assert err is None
    py.task_finished(t.task_id)
    t2, _ = py.get_task(0)  # leave one leased ("crash" with it pending)
    assert t2 is not None
    py.close()
    assert os.path.exists(snap)

    with _NativeMaster("--snapshot", snap, "--timeout_s", 30.0) as m:
        client = MasterClient(("127.0.0.1", m.port))
        st = client.status()
        # 2 untouched todo + 1 recovered-from-pending; 1 done
        assert st == {"todo": 3, "pending": 0, "done": 1, "failed": 0,
                      "cur_pass": 0}
        got = []
        while True:
            task = client.get_task(sync_pass=False)  # one pass only
            if task is None:
                break
            got.extend(task.chunks)
            client.task_finished(task.task_id)
        # task (0,1) was finished pre-crash; leased (2,3) was recovered
        assert sorted(got) == [2, 3, 4, 5, 6, 7]
        client.close()


def test_python_master_recovers_native_snapshot(tmp_path):
    """And the reverse: the C++ master's snapshot file loads into the
    Python MasterService (same schema both ways)."""
    snap = str(tmp_path / "native.snap")
    with _NativeMaster("--snapshot", snap, "--chunks_per_task", 1,
                       "--timeout_s", 30.0) as m:
        client = MasterClient(("127.0.0.1", m.port))
        client.set_dataset(["a", "b", "c"])
        t = client.get_task()
        client.task_finished(t.task_id)
        client.close()
    # binary got SIGTERM -> flushed its snapshot on Close
    assert os.path.exists(snap)
    with open(snap) as f:
        state = json.load(f)
    assert state["cur_pass"] == 0 and len(state["done"]) == 1

    py = MasterService(chunks_per_task=1, snapshot_path=snap)
    assert py.status() == {"todo": 2, "pending": 0, "done": 1, "failed": 0,
                           "cur_pass": 0}
    remaining = []
    while True:
        task, err = py.get_task(0)
        if err:
            break
        remaining.extend(task.chunks)
        py.task_finished(task.task_id)
    assert sorted(remaining) == ["b", "c"]
    py.close()
