"""Multi-process data-parallel trainer (test_dist_base.py model-file
pattern, e.g. /root/reference/python/paddle/fluid/tests/unittests/
dist_mnist.py): run the same MLP either single-process (the parity
reference) or as one of N jax.distributed trainer processes.

As a script (spawned by test_dist_multiproc.py), env carries the cluster
config — PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_COORDINATOR,
DIST_OUT_FILE — mirroring the reference's PADDLE_* env cluster surface.
"""

import json
import os
import sys

GLOBAL_BATCH = 16
STEPS = 5
SEED = 23


def _env_int(name, default):
    return int(os.environ.get(name, default))


def build_model(fluid):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = SEED
    startup.random_seed = SEED
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[12], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=24, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y)
        )
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    return main, startup, loss


def global_batch(step):
    import numpy as np

    rng = np.random.RandomState(100 + step)
    return (
        rng.rand(GLOBAL_BATCH, 12).astype(np.float32),
        rng.randint(0, 4, (GLOBAL_BATCH, 1)).astype(np.int64),
    )


def run_trainer(num_trainers, trainer_id, reduce_strategy="all_reduce"):
    """Train STEPS steps; returns the per-step loss list. In multi-trainer
    mode feeds only this trainer's batch shard."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    main, startup, loss = build_model(fluid)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    bs = BuildStrategy()
    if reduce_strategy == "reduce":
        bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
    pe = ParallelExecutor(
        loss_name=loss.name,
        main_program=main,
        build_strategy=bs,
        use_tpu=False,
        num_trainers=num_trainers,
        trainer_id=trainer_id,
    )
    shard = GLOBAL_BATCH // num_trainers
    lo, hi = trainer_id * shard, (trainer_id + 1) * shard
    losses = []
    steps = _env_int("DIST_STEPS", STEPS)
    die_at = _env_int("DIST_DIE_AT_STEP", -1)
    for step in range(steps):
        if step == die_at:
            # simulate a worker host dying mid-training (failure-path
            # test): hard exit, no cleanup, like a kill -9
            print("trainer %d dying at step %d" % (trainer_id, step),
                  flush=True)
            os._exit(42)
        xs, ys = global_batch(step % STEPS)
        lv, = pe.run(fetch_list=[loss], feed={"x": xs[lo:hi], "y": ys[lo:hi]})
        losses.append(float(np.ravel(lv)[0]))
    return losses


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nprocs = int(os.environ["PADDLE_TRAINERS_NUM"])
    coord = os.environ["PADDLE_COORDINATOR"]
    out_file = os.environ["DIST_OUT_FILE"]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=%d" % (
        8 // nprocs
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.parallel.mesh import init_distributed

    init_distributed(
        coordinator_address=coord, num_processes=nprocs, process_id=rank
    )
    losses = run_trainer(nprocs, rank,
                         os.environ.get("DIST_REDUCE", "all_reduce"))
    with open(out_file, "w") as f:
        json.dump({"rank": rank, "losses": losses}, f)
    print("trainer %d done: %s" % (rank, losses), flush=True)


if __name__ == "__main__":
    sys.exit(main())
