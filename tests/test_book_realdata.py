"""Book tests on the REAL dataset pipeline (file -> parser -> reader ->
train -> convergence threshold), with small cached fixtures in each
dataset's native on-disk format (IDX gzip for mnist, whitespace table for
uci_housing, aclImdb tar.gz for imdb).

Reference model: python/paddle/fluid/tests/book/test_recognize_digits.py,
test_fit_a_line.py, test_understand_sentiment.py — those assert
convergence on real downloaded data. This rig has no network egress, so
the fixtures are written into DATA_HOME in the real formats and
PADDLE_TPU_DATASET=real makes any silent synthetic fallback an ERROR:
what trains here went through the same bytes-on-disk parse path real
downloads use. (tests/test_book.py keeps the fast synthetic path.)
"""

import hashlib
import io
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.dataset as ds
from paddle_tpu.dataset import common


def _md5(path):
    h = hashlib.md5()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


# --- fixtures in real on-disk formats ------------------------------------
# the MNIST IDX writer is shared with tools/convergence_run.py (the
# on-chip convergence proof) via paddle_tpu.dataset.fixtures so the
# recipe cannot drift between the test and the hardware artifact
from paddle_tpu.dataset.fixtures import (  # noqa: E402
    write_mnist_idx_fixture as _write_mnist_fixture,
)


def _write_housing_fixture(path, n=320, seed=4):
    """Whitespace-separated table, 13 features + price, linear relation."""
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(44).randn(13, 1)
    feats = rng.randn(n, 13)
    price = feats @ w + 0.05 * rng.randn(n, 1) + 22.0
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for row in np.hstack([feats, price]):
            f.write(" ".join("%.6f" % v for v in row) + "\n")
    return path


_POS_WORDS = ("great", "wonderful", "loved", "excellent", "superb")
_NEG_WORDS = ("awful", "terrible", "hated", "boring", "worst")
_FILLER = ("the", "movie", "film", "plot", "actor", "scene", "it", "was")


def _write_imdb_fixture(path, n_per_class=60, seed=6):
    """aclImdb_v1-layout tar.gz with sentiment-indicative documents."""
    rng = np.random.RandomState(seed)

    def doc(words):
        toks = [rng.choice(_FILLER) for _ in range(20)]
        toks += [rng.choice(words) for _ in range(6)]
        rng.shuffle(toks)
        return " ".join(toks)

    os.makedirs(os.path.dirname(path), exist_ok=True)
    with tarfile.open(path, "w:gz") as tf:
        for split in ("train", "test"):
            for cls, words in (("pos", _POS_WORDS), ("neg", _NEG_WORDS)):
                for i in range(n_per_class):
                    data = doc(words).encode()
                    info = tarfile.TarInfo(
                        "aclImdb/%s/%s/%d_7.txt" % (split, cls, i))
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))
    return path


@pytest.fixture()
def real_data_home(tmp_path, monkeypatch):
    """DATA_HOME populated with real-format fixtures; md5 pins repointed
    at them; synthetic fallback turned into a hard error."""
    home = str(tmp_path / "data")
    monkeypatch.setattr(common, "DATA_HOME", home)
    monkeypatch.setenv("PADDLE_TPU_DATASET", "real")

    tr_img, tr_lbl = _write_mnist_fixture(
        os.path.join(home, "mnist"), 512, seed=1, prefix="fix-train")
    te_img, te_lbl = _write_mnist_fixture(
        os.path.join(home, "mnist"), 128, seed=2, prefix="fix-test")
    os.replace(tr_img, os.path.join(home, "mnist", ds.mnist.TRAIN_IMAGE[0]))
    os.replace(tr_lbl, os.path.join(home, "mnist", ds.mnist.TRAIN_LABEL[0]))
    os.replace(te_img, os.path.join(home, "mnist", ds.mnist.TEST_IMAGE[0]))
    os.replace(te_lbl, os.path.join(home, "mnist", ds.mnist.TEST_LABEL[0]))
    for attr in ("TRAIN_IMAGE", "TRAIN_LABEL", "TEST_IMAGE", "TEST_LABEL"):
        fname = getattr(ds.mnist, attr)[0]
        monkeypatch.setattr(
            ds.mnist, attr,
            (fname, _md5(os.path.join(home, "mnist", fname))))

    housing = _write_housing_fixture(
        os.path.join(home, "uci_housing", "housing.data"))
    monkeypatch.setattr(ds.uci_housing, "MD5", _md5(housing))

    imdb_tar = _write_imdb_fixture(
        os.path.join(home, "imdb", ds.imdb.URL.split("/")[-1]))
    monkeypatch.setattr(ds.imdb, "MD5", _md5(imdb_tar))
    return home


def _batches(reader, batch_size):
    buf = []
    for sample in reader():
        buf.append(sample)
        if len(buf) == batch_size:
            yield buf
            buf = []


def test_recognize_digits_real_pipeline(real_data_home):
    samples = list(ds.mnist.train()())
    assert len(samples) == 512  # the fixture, not the synthetic fallback
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 1
        startup.random_seed = 1
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[784], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1], dtype="int64")
            h = fluid.layers.fc(input=img, size=64, act="relu")
            logits = fluid.layers.fc(input=h, size=10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(input=logits, label=label)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for epoch in range(4):
            for batch in _batches(ds.mnist.train(), 64):
                feed = {
                    "img": np.stack([s[0] for s in batch]),
                    "label": np.asarray(
                        [[s[1]] for s in batch], "int64"),
                }
                lv, av = exe.run(main, feed=feed, fetch_list=[loss, acc])
        assert float(lv[0]) < 0.35, float(lv[0])
        assert float(av[0]) > 0.9, float(av[0])


def test_fit_a_line_real_pipeline(real_data_home):
    feats, target = zip(*list(ds.uci_housing.train()()))
    assert len(feats) == 256  # 0.8 * 320 fixture rows
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 2
        startup.random_seed = 2
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[13], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs = np.stack(feats)
        ys = np.stack(target)
        for epoch in range(60):
            for i in range(0, len(xs), 32):
                lv, = exe.run(
                    main,
                    feed={"x": xs[i:i + 32], "y": ys[i:i + 32]},
                    fetch_list=[loss])
        assert float(lv[0]) < 1.0, float(lv[0])


def test_understand_sentiment_real_pipeline(real_data_home):
    word_idx = ds.imdb.word_dict()
    # real vocabulary from the tarball, not the synthetic w%d dictionary
    assert "great" in word_idx and "awful" in word_idx
    vocab = len(word_idx)
    samples = list(ds.imdb.train(word_idx)())
    assert len(samples) == 120
    seq = 32

    def pad(doc):
        ids = (doc[:seq] + [word_idx["<unk>"]] * seq)[:seq]
        return ids

    xs = np.asarray([pad(d) for d, _ in samples], "int64")
    ys = np.asarray([[l] for _, l in samples], "int64")
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 3
        startup.random_seed = 3
        with fluid.program_guard(main, startup):
            words = fluid.layers.data(name="words", shape=[seq],
                                      dtype="int64")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            emb = fluid.layers.embedding(input=words, size=[vocab, 16])
            bow = fluid.layers.reduce_mean(emb, dim=1)
            h = fluid.layers.fc(input=bow, size=16, act="relu")
            logits = fluid.layers.fc(input=h, size=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(input=logits, label=label)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        order = np.random.RandomState(0).permutation(len(xs))
        xs, ys = xs[order], ys[order]
        for epoch in range(15):
            for i in range(0, len(xs), 40):
                lv, av = exe.run(
                    main,
                    feed={"words": xs[i:i + 40], "label": ys[i:i + 40]},
                    fetch_list=[loss, acc])
        assert float(av[0]) > 0.8, float(av[0])
        assert float(lv[0]) < 0.5, float(lv[0])
