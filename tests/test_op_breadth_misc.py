"""Breadth sweep part 2: optimizer update rules, RNN units, random ops,
tensor-array ops, interpolation/conv variants, and detection/metric
utilities that previously had no dedicated test.

Optimizer mirrors are written from the reference update rules
(operators/{adadelta,adagrad,adamax,decayed_adagrad,ftrl,rmsprop,
proximal_adagrad,proximal_gd,lars_momentum}_op.cc), evaluated in numpy
float64 and compared against the op output after one step.
"""

import numpy as np
import pytest

import paddle_tpu as fluid

from op_test import make_grad_test as _shapes, make_op_test as _t


def _run(op_type, inputs, fetch, attrs=None):
    """Build a one-op program and fetch the named outputs."""
    t = _shapes(op_type, inputs, {k: (1,) for k in fetch}, attrs)
    main = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    return [np.asarray(v) for v in
            exe.run(main, feed=t._feed, fetch_list=list(fetch))]


_RNG = np.random.RandomState


def _opt_inputs(rng, extra=()):
    ins = {
        "Param": rng.randn(3, 4).astype("float32"),
        "Grad": rng.randn(3, 4).astype("float32"),
        "LearningRate": np.asarray([0.05], "float32"),
    }
    for slot in extra:
        ins[slot] = np.abs(rng.randn(3, 4)).astype("float32") * 0.1
    return ins


def test_adadelta_update():
    rng = _RNG(50)
    ins = _opt_inputs(rng, ["AvgSquaredGrad", "AvgSquaredUpdate"])
    del ins["LearningRate"]  # adadelta_op.cc has no LR input
    p, g = ins["Param"].astype("float64"), ins["Grad"].astype("float64")
    asg, asu = (ins["AvgSquaredGrad"].astype("float64"),
                ins["AvgSquaredUpdate"].astype("float64"))
    rho, eps = 0.95, 1e-6
    asg_o = rho * asg + (1 - rho) * g * g
    upd = -np.sqrt((asu + eps) / (asg_o + eps)) * g
    asu_o = rho * asu + (1 - rho) * upd * upd
    _t("adadelta", ins,
       {"ParamOut": p + upd, "AvgSquaredGradOut": asg_o,
        "AvgSquaredUpdateOut": asu_o},
       {"rho": rho, "epsilon": eps}).check_output()


def test_adagrad_update():
    rng = _RNG(51)
    ins = _opt_inputs(rng, ["Moment"])
    p, g, m = (ins[k].astype("float64") for k in ("Param", "Grad", "Moment"))
    lr, eps = 0.05, 1e-6
    m_o = m + g * g
    _t("adagrad", ins,
       {"ParamOut": p - lr * g / (np.sqrt(m_o) + eps), "MomentOut": m_o},
       {"epsilon": eps}).check_output()


def test_adamax_update():
    rng = _RNG(52)
    ins = _opt_inputs(rng, ["Moment", "InfNorm"])
    ins["Beta1Pow"] = np.asarray([0.9], "float32")
    p, g, m, inf = (ins[k].astype("float64")
                    for k in ("Param", "Grad", "Moment", "InfNorm"))
    lr, b1, b2, eps = 0.05, 0.9, 0.999, 1e-8
    m_o = b1 * m + (1 - b1) * g
    inf_o = np.maximum(b2 * inf, np.abs(g))
    lr_t = lr / (1 - 0.9)
    _t("adamax", ins,
       {"ParamOut": p - lr_t * m_o / (inf_o + eps),
        "MomentOut": m_o, "InfNormOut": inf_o},
       {"beta1": b1, "beta2": b2, "epsilon": eps}).check_output()


def test_decayed_adagrad_update():
    rng = _RNG(53)
    ins = _opt_inputs(rng, ["Moment"])
    p, g, m = (ins[k].astype("float64") for k in ("Param", "Grad", "Moment"))
    lr, decay, eps = 0.05, 0.95, 1e-6
    m_o = decay * m + (1 - decay) * g * g
    _t("decayed_adagrad", ins,
       {"ParamOut": p - lr * g / (np.sqrt(m_o) + eps), "MomentOut": m_o},
       {"decay": decay, "epsilon": eps}).check_output()


def test_ftrl_update():
    rng = _RNG(54)
    ins = _opt_inputs(rng, ["SquaredAccumulator", "LinearAccumulator"])
    p, g = ins["Param"].astype("float64"), ins["Grad"].astype("float64")
    sq = ins["SquaredAccumulator"].astype("float64")
    lin = ins["LinearAccumulator"].astype("float64")
    lr, l1, l2, power = 0.05, 0.1, 0.1, -0.5
    new_sq = sq + g * g
    sigma = (new_sq ** -power - sq ** -power) / lr
    lin_o = lin + g - sigma * p
    x = l1 * np.sign(lin_o) - lin_o
    y = new_sq ** -power / lr + 2 * l2
    p_o = np.where(np.abs(lin_o) > l1, x / y, 0.0)
    _t("ftrl", ins,
       {"ParamOut": p_o, "SquaredAccumOut": new_sq, "LinearAccumOut": lin_o},
       {"l1": l1, "l2": l2, "lr_power": power}).check_output()


@pytest.mark.parametrize("centered", [False, True], ids=["plain", "centered"])
def test_rmsprop_update(centered):
    rng = _RNG(55)
    ins = _opt_inputs(rng, ["MeanSquare", "MeanGrad", "Moment"])
    p, g = ins["Param"].astype("float64"), ins["Grad"].astype("float64")
    ms = ins["MeanSquare"].astype("float64")
    mg = ins["MeanGrad"].astype("float64")
    mom = ins["Moment"].astype("float64")
    lr, rho, eps, mu = 0.05, 0.9, 1e-10, 0.9
    ms_o = rho * ms + (1 - rho) * g * g
    outs = {"MeanSquareOut": ms_o}
    if centered:
        mg_o = rho * mg + (1 - rho) * g
        denom = ms_o - mg_o * mg_o + eps
        outs["MeanGradOut"] = mg_o
    else:
        denom = ms_o + eps
    mom_o = mu * mom + lr * g / np.sqrt(denom)
    outs.update({"ParamOut": p - mom_o, "MomentOut": mom_o})
    _t("rmsprop", ins, outs,
       {"decay": rho, "epsilon": eps, "momentum": mu,
        "centered": centered}).check_output()


def test_proximal_adagrad_update():
    rng = _RNG(56)
    ins = _opt_inputs(rng, ["Moment"])
    p, g, m = (ins[k].astype("float64") for k in ("Param", "Grad", "Moment"))
    lr, l1, l2 = 0.05, 0.1, 0.05
    m_o = m + g * g
    lr_t = lr / np.sqrt(m_o)
    prox = p - lr_t * g
    p_o = np.sign(prox) * np.maximum(np.abs(prox) - lr_t * l1, 0) / \
        (1 + lr_t * l2)
    _t("proximal_adagrad", ins, {"ParamOut": p_o, "MomentOut": m_o},
       {"l1": l1, "l2": l2}).check_output()


def test_proximal_gd_update():
    rng = _RNG(57)
    ins = _opt_inputs(rng)
    p, g = ins["Param"].astype("float64"), ins["Grad"].astype("float64")
    lr, l1, l2 = 0.05, 0.1, 0.05
    prox = p - lr * g
    p_o = np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0) / \
        (1 + lr * l2)
    _t("proximal_gd", ins, {"ParamOut": p_o},
       {"l1": l1, "l2": l2}).check_output()


def test_lars_momentum_update():
    rng = _RNG(58)
    ins = _opt_inputs(rng, ["Velocity"])
    p, g, v = (ins[k].astype("float64")
               for k in ("Param", "Grad", "Velocity"))
    lr, mu, coeff, wd = 0.05, 0.9, 0.001, 0.0005
    p_n = np.sqrt(np.sum(p * p))
    g_n = np.sqrt(np.sum(g * g))
    local_lr = lr * coeff * p_n / (g_n + wd * p_n + 1e-12)
    v_o = mu * v + local_lr * (g + wd * p)
    _t("lars_momentum", ins, {"ParamOut": p - v_o, "VelocityOut": v_o},
       {"mu": mu, "lars_coeff": coeff,
        "lars_weight_decay": wd}).check_output()


# --- RNN building blocks -------------------------------------------------
def test_lstm_unit_output_and_grad():
    rng = _RNG(60)
    B, D = 3, 4
    x = rng.randn(B, 4 * D).astype("float32")
    c_prev = rng.randn(B, D).astype("float32")
    fb = 1.0
    x64, c64 = x.astype("float64"), c_prev.astype("float64")

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    i = sig(x64[:, :D])
    f = sig(x64[:, D:2 * D] + fb)
    g = np.tanh(x64[:, 2 * D:3 * D])
    o = sig(x64[:, 3 * D:])
    c = f * c64 + i * g
    h = o * np.tanh(c)
    t = _t("lstm_unit", {"X": x, "C_prev": c_prev}, {"C": c, "H": h},
           {"forget_bias": fb})
    t.check_output()
    _shapes("lstm_unit", {"X": x, "C_prev": c_prev},
            {"C": (B, D), "H": (B, D)},
            {"forget_bias": fb}).check_grad(["X", "C_prev"], "H")


def test_gru_unit_output_and_grad():
    rng = _RNG(61)
    B, D = 3, 4
    x = rng.randn(B, 3 * D).astype("float32")
    h_prev = rng.randn(B, D).astype("float32")
    w = (0.5 * rng.randn(D, 3 * D)).astype("float32")
    bias = (0.1 * rng.randn(1, 3 * D)).astype("float32")
    x64, h64, w64, b64 = (a.astype("float64") for a in (x, h_prev, w,
                                                        bias.ravel()))

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    g = x64[:, :2 * D] + h64 @ w64[:, :2 * D] + b64[:2 * D]
    u = sig(g[:, :D])
    r = sig(g[:, D:])
    c = np.tanh(x64[:, 2 * D:] + (r * h64) @ w64[:, 2 * D:] + b64[2 * D:])
    h = u * h64 + (1 - u) * c
    t = _t("gru_unit",
           {"Input": x, "HiddenPrev": h_prev, "Weight": w, "Bias": bias},
           {"Hidden": h})
    t.check_output()
    _shapes("gru_unit",
            {"Input": x, "HiddenPrev": h_prev, "Weight": w, "Bias": bias},
            {"Hidden": (B, D)}).check_grad(
        ["Input", "HiddenPrev", "Weight"], "Hidden",
        max_relative_error=1e-2)


def test_dynamic_lstmp_shapes_and_grad():
    rng = _RNG(62)
    B, T, D, P = 2, 5, 4, 3
    ins = {
        "Input": rng.randn(B, T, 4 * D).astype("float32") * 0.5,
        "Weight": (0.3 * rng.randn(P, 4 * D)).astype("float32"),
        "ProjWeight": (0.5 * rng.randn(D, P)).astype("float32"),
        "Bias": (0.1 * rng.randn(1, 4 * D)).astype("float32"),
        "Length": np.asarray([T, T - 2], "int32"),
    }
    t = _shapes("dynamic_lstmp", ins,
                {"Projection": (B, T, P), "Cell": (B, T, D)},
                {"use_peepholes": False})
    (proj,) = _run("dynamic_lstmp", ins, ["Projection"],
                   {"use_peepholes": False})
    assert proj.shape == (B, T, P) and np.isfinite(proj).all()
    # padded steps beyond Length carry state: projection frozen after t=3
    np.testing.assert_allclose(proj[1, T - 2], proj[1, T - 1], rtol=1e-5)
    # fd through the T-step recurrence accumulates cancellation noise
    t.check_grad(["Input", "Weight", "ProjWeight"], "Projection",
                 max_relative_error=3e-2)


def test_hierarchical_sigmoid_grad():
    rng = _RNG(63)
    B, D, K = 4, 5, 4
    ins = {
        "X": rng.randn(B, D).astype("float32"),
        "W": (0.5 * rng.randn(K - 1, D)).astype("float32"),
        "Label": rng.randint(0, K, (B, 1)).astype("int64"),
        "Bias": (0.1 * rng.randn(1, K - 1)).astype("float32"),
    }
    t = _shapes("hierarchical_sigmoid", ins, {"Out": (B, 1)},
                {"num_classes": K})
    t.check_grad(["X", "W"], "Out", max_relative_error=1e-2)


# --- random ops ----------------------------------------------------------
def test_gaussian_random_statistics():
    (out,) = _run("gaussian_random", {}, ["Out"],
                  {"shape": [200, 100], "mean": 1.0, "std": 2.0, "seed": 7,
                   "dtype": "float32"})
    assert out.shape == (200, 100)
    assert abs(out.mean() - 1.0) < 0.05
    assert abs(out.std() - 2.0) < 0.05


def test_uniform_random_statistics():
    (out,) = _run("uniform_random", {}, ["Out"],
                  {"shape": [200, 100], "min": -2.0, "max": 4.0, "seed": 7,
                   "dtype": "float32"})
    assert out.shape == (200, 100)
    assert out.min() >= -2.0 and out.max() <= 4.0
    assert abs(out.mean() - 1.0) < 0.1


def test_truncated_gaussian_random_statistics():
    (out,) = _run("truncated_gaussian_random", {}, ["Out"],
                  {"shape": [200, 100], "mean": 0.0, "std": 1.0, "seed": 7,
                   "dtype": "float32"})
    # truncated at two standard deviations (reference
    # truncated_gaussian_random_op.cc contract)
    assert np.abs(out).max() <= 2.0 + 1e-5
    assert abs(out.mean()) < 0.05


def test_sampling_id_distribution():
    rng = _RNG(64)
    probs = np.tile(np.asarray([[0.7, 0.2, 0.1, 0.0]], "float32"),
                    (512, 1))
    (ids,) = _run("sampling_id", {"X": probs}, ["Out"], {"seed": 9})
    assert ids.shape[0] == 512
    assert set(np.unique(ids)) <= {0, 1, 2}
    frac0 = float(np.mean(ids == 0))
    assert 0.6 < frac0 < 0.8  # matches the 0.7 row mass


# --- tensor arrays -------------------------------------------------------
def test_tensor_array_write_read_length():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        i0 = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        i1 = fluid.layers.fill_constant(shape=[1], dtype="int64", value=1)
        arr = fluid.layers.array_write(x, i0)
        fluid.layers.array_write(x * 2.0, i1, array=arr)
        n = fluid.layers.array_length(arr)
        back = fluid.layers.array_read(arr, i1)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = _RNG(65).randn(2, 4).astype("float32")
    n_v, back_v = exe.run(main, feed={"x": xv}, fetch_list=[n, back])
    assert int(np.ravel(n_v)[0]) == 2
    np.testing.assert_allclose(back_v, xv * 2.0, rtol=1e-6)


def test_lod_tensor_to_array_round_trip():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3, 4], lod_level=1)
        lens = fluid.layers.data("x_len", [1], dtype="int64")
        table = fluid.layers.lod_rank_table(lengths=lens)
        arr = fluid.layers.lod_tensor_to_array(x, table)
        back = fluid.layers.array_to_lod_tensor(arr, table)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = _RNG(66).randn(2, 3, 4).astype("float32")
    lv = np.asarray([[3], [1]], "int64")
    (out,) = exe.run(main, feed={"x": xv, "x_len": lv}, fetch_list=[back])
    np.testing.assert_allclose(out, xv, rtol=1e-6)


# --- sequence ------------------------------------------------------------
def test_sequence_pad_output_and_grad():
    rng = _RNG(67)
    B, T, D, PT = 2, 3, 4, 5
    x = rng.randn(B, T, D).astype("float32")
    lens = np.asarray([3, 1], "int64")
    pad = np.asarray([0.25], "float32")
    expect = np.full((B, PT, D), 0.25, "float32")
    for b in range(B):
        expect[b, :lens[b]] = x[b, :lens[b]]
    t = _t("sequence_pad", {"X": x, "PadValue": pad, "Length": lens},
           {"Out": expect}, {"padded_length": PT})
    t.check_output()
    _shapes("sequence_pad", {"X": x, "PadValue": pad, "Length": lens},
            {"Out": (B, PT, D)}, {"padded_length": PT}).check_grad(
        ["X"], "Out")


def test_sequence_reverse_output_and_grad():
    rng = _RNG(68)
    x = rng.randn(2, 4, 3).astype("float32")
    lens = np.asarray([4, 2], "int64")
    expect = x.copy()
    expect[0] = x[0, ::-1]
    expect[1, :2] = x[1, 1::-1]
    t = _t("sequence_reverse", {"X": x, "Length": lens}, {"Y": expect})
    t.check_output()
    _shapes("sequence_reverse", {"X": x, "Length": lens},
            {"Y": (2, 4, 3)}).check_grad(["X"], "Y")


def test_sequence_scatter_output_and_grad():
    rng = _RNG(69)
    x = rng.randn(2, 5).astype("float32")
    ids = np.asarray([[0, 3], [1, 4]], "int32")
    upd = rng.randn(2, 2).astype("float32")
    expect = x.copy()
    for b in range(2):
        for k in range(2):
            expect[b, ids[b, k]] += upd[b, k]
    t = _t("sequence_scatter", {"X": x, "Ids": ids, "Updates": upd},
           {"Out": expect})
    t.check_output()
    _shapes("sequence_scatter", {"X": x, "Ids": ids, "Updates": upd},
            {"Out": (2, 5)}).check_grad(["X", "Updates"], "Out")


# --- interpolation / conv variants --------------------------------------
def test_nearest_interp_output_and_grad():
    rng = _RNG(70)
    x = rng.randn(1, 2, 3, 3).astype("float32")
    t = _shapes("nearest_interp", {"X": x}, {"Out": (1, 2, 6, 6)},
                {"out_h": 6, "out_w": 6})
    (out,) = _run("nearest_interp", {"X": x}, ["Out"],
                  {"out_h": 6, "out_w": 6})
    assert out.shape == (1, 2, 6, 6)
    # every output value is one of the input values (nearest semantics)
    assert np.isin(np.round(out, 5), np.round(x, 5)).all()
    t.check_grad(["X"], "Out")


def test_bilinear_interp_output_and_grad():
    rng = _RNG(71)
    x = rng.randn(1, 2, 3, 3).astype("float32")
    t = _shapes("bilinear_interp", {"X": x}, {"Out": (1, 2, 6, 6)},
                {"out_h": 6, "out_w": 6})
    (out,) = _run("bilinear_interp", {"X": x}, ["Out"],
                  {"out_h": 6, "out_w": 6})
    assert out.shape == (1, 2, 6, 6)
    # interpolation stays inside the input's range
    assert out.min() >= x.min() - 1e-5 and out.max() <= x.max() + 1e-5
    t.check_grad(["X"], "Out", max_relative_error=1e-2)


def test_conv3d_grad():
    rng = _RNG(72)
    x = rng.randn(1, 2, 4, 4, 4).astype("float32")
    w = (0.3 * rng.randn(3, 2, 2, 2, 2)).astype("float32")
    t = _shapes("conv3d", {"Input": x, "Filter": w},
                {"Output": (1, 3, 3, 3, 3)},
                {"strides": [1, 1, 1], "paddings": [0, 0, 0],
                 "dilations": [1, 1, 1], "groups": 1})
    t.check_grad(["Input", "Filter"], "Output", max_relative_error=2e-2)


def test_depthwise_conv2d_output_and_grad():
    rng = _RNG(73)
    x = rng.randn(1, 3, 5, 5).astype("float32")
    w = (0.3 * rng.randn(3, 1, 3, 3)).astype("float32")
    attrs = {"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
             "groups": 3}
    expect = np.zeros((1, 3, 3, 3), "float64")
    for c in range(3):
        for i in range(3):
            for j in range(3):
                expect[0, c, i, j] = np.sum(
                    x[0, c, i:i + 3, j:j + 3].astype("float64")
                    * w[c, 0].astype("float64"))
    t = _t("depthwise_conv2d", {"Input": x, "Filter": w},
           {"Output": expect}, attrs)
    t.check_output(atol=1e-4, rtol=1e-3)
    _shapes("depthwise_conv2d", {"Input": x, "Filter": w},
            {"Output": (1, 3, 3, 3)}, attrs).check_grad(
        ["Input", "Filter"], "Output", max_relative_error=1e-2)


# --- detection / metric utilities ---------------------------------------
def test_iou_similarity_output():
    x = np.asarray([[0, 0, 2, 2], [1, 1, 3, 3]], "float32")
    y = np.asarray([[0, 0, 2, 2], [2, 2, 4, 4], [10, 10, 11, 11]],
                   "float32")

    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    expect = np.asarray([[iou(a, b) for b in y] for a in x], "float32")
    _t("iou_similarity", {"X": x, "Y": y}, {"Out": expect}).check_output()


def test_box_coder_encode_output():
    prior = np.asarray([[0, 0, 2, 2], [1, 1, 4, 5]], "float32")
    pvar = np.tile(np.asarray([[0.1, 0.1, 0.2, 0.2]], "float32"), (2, 1))
    target = np.asarray([[0, 0, 2, 2], [0.5, 0.5, 3, 3.5]], "float32")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    tw = target[:, 2] - target[:, 0]
    th = target[:, 3] - target[:, 1]
    tcx = target[:, 0] + tw / 2
    tcy = target[:, 1] + th / 2
    expect = np.stack([
        (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0],
        (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1],
        np.log(tw[:, None] / pw[None, :]) / pvar[None, :, 2],
        np.log(th[:, None] / ph[None, :]) / pvar[None, :, 3],
    ], axis=-1).astype("float32")
    _t("box_coder",
       {"PriorBox": prior, "PriorBoxVar": pvar, "TargetBox": target},
       {"OutputBox": expect},
       {"code_type": "encode_center_size"}).check_output(
        atol=1e-5, rtol=1e-4)


def test_ctc_align_output():
    # path [blank a a blank b b] -> [a b]; merge_repeated + blank removal
    x = np.asarray([[0, 1, 1, 0, 2, 2], [3, 3, 0, 0, 0, 1]], "int32")
    lens = np.asarray([6, 3], "int32")
    out, n = _run("ctc_align", {"Input": x, "InputLength": lens},
                  ["Output", "OutputLength"], {"blank": 0})
    n = np.ravel(n)
    assert list(out[0][:n[0]]) == [1, 2]
    assert list(out[1][:n[1]]) == [3]  # steps past InputLength ignored
    assert (out[0][n[0]:] == 0).all()


def test_auc_perfect_separation():
    n_t = 200
    preds = np.asarray([[0.1, 0.9]] * 8 + [[0.9, 0.1]] * 8, "float32")
    labels = np.asarray([[1]] * 8 + [[0]] * 8, "int64")
    zeros = np.zeros((n_t,), "int64")
    auc, sp, sn = _run(
        "auc",
        {"Predict": preds, "Label": labels, "StatPos": zeros,
         "StatNeg": zeros},
        ["AUC", "StatPosOut", "StatNegOut"],
        {"curve": "ROC", "num_thresholds": n_t})
    assert float(np.ravel(auc)[0]) > 0.99
    assert int(sp.sum()) == 8 and int(sn.sum()) == 8


def test_prior_box_output_shapes_and_ranges():
    feat = np.zeros((1, 4, 2, 2), "float32")
    img = np.zeros((1, 3, 8, 8), "float32")
    boxes, variances = _run(
        "prior_box", {"Input": feat, "Image": img}, ["Boxes", "Variances"],
        {"min_sizes": [4.0], "max_sizes": [], "aspect_ratios": [1.0],
         "variances": [0.1, 0.1, 0.2, 0.2], "flip": False, "clip": True,
         "step_w": 0.0, "step_h": 0.0, "offset": 0.5})
    assert boxes.shape[-1] == 4 and variances.shape[-1] == 4
    assert boxes.min() >= 0.0 and boxes.max() <= 1.0  # clip=True
    # centers sit at (i + 0.5) * step / img: distinct per cell
    flat = boxes.reshape(-1, 4)
    assert len({tuple(np.round(r, 4)) for r in flat}) == flat.shape[0]


def test_attention_lstm_outputs_and_grad():
    rng = _RNG(74)
    B, T, S, D, C, M = 2, 4, 5, 3, 5, 4
    ins = {
        "X": rng.randn(B, T, M).astype("float32") * 0.3,
        "EncoderVec": rng.randn(B, S, C).astype("float32"),
        "EncoderProj": rng.randn(B, S, D).astype("float32"),
        "H0": np.zeros((B, D), "float32"),
        "C0": np.zeros((B, D), "float32"),
        "StateProjW": (0.3 * rng.randn(D, D)).astype("float32"),
        "AttnW": (0.3 * rng.randn(2 * D, 1)).astype("float32"),
        "CellW": (0.3 * rng.randn(D + C + M, 4 * D)).astype("float32"),
        "CellB": np.zeros((1, 4 * D), "float32"),
        "EncoderLen": np.asarray([S, S - 2], "int32"),
    }
    hid, attn = _run("attention_lstm", ins, ["Hidden", "AttentionWeight"])
    assert hid.shape == (B, T, D) and np.isfinite(hid).all()
    assert attn.shape == (B, T, S)
    # attention over padded encoder steps is masked out, rows sum to 1
    np.testing.assert_allclose(attn.sum(-1), np.ones((B, T)), rtol=1e-5)
    assert np.abs(attn[1, :, S - 2:]).max() < 1e-6
    _shapes("attention_lstm", ins,
            {"Hidden": (B, T, D)}).check_grad(
        ["X", "CellW", "StateProjW"], "Hidden", max_relative_error=2e-2)


def test_attention_lstm_beam_decode_smoke():
    rng = _RNG(75)
    B, S, D, C, V, M, K, T = 2, 5, 3, 5, 11, 4, 3, 6
    ins = {
        "EncoderVec": rng.randn(B, S, C).astype("float32"),
        "EncoderProj": rng.randn(B, S, D).astype("float32"),
        "H0": np.zeros((B, D), "float32"),
        "StateProjW": (0.3 * rng.randn(D, D)).astype("float32"),
        "AttnW": (0.3 * rng.randn(2 * D, 1)).astype("float32"),
        "CellW": (0.3 * rng.randn(D + C + M, 4 * D)).astype("float32"),
        "CellB": np.zeros((1, 4 * D), "float32"),
        "Embedding": rng.randn(V, M).astype("float32"),
        "OutW": (0.3 * rng.randn(D, V)).astype("float32"),
        "OutB": np.zeros((1, V), "float32"),
        "EncoderLen": np.asarray([S, S - 2], "int32"),
    }
    ids, scores = _run(
        "attention_lstm_beam_decode", ins,
        ["SentenceIds", "SentenceScores"],
        {"beam_size": K, "max_len": T, "start_id": 1, "end_id": 2})
    assert ids.shape == (B, K, T)
    assert scores.shape == (B, K)
    assert ((ids >= 0) & (ids < V)).all()
    # beams come back best-first: scores sorted descending per batch row
    assert (np.diff(scores, axis=1) <= 1e-6).all()


def test_transformer_smoothed_loss_matches_explicit_soft_label():
    """The factored label-smoothing head in models/transformer.py must be
    numerically identical to the explicit one_hot -> label_smooth ->
    soft-label CE chain it replaces."""
    rng = _RNG(76)
    N, V, eps = 6, 7, 0.1
    logits_v = rng.randn(N, V).astype("float32")
    label_v = rng.randint(0, V, (N, 1)).astype("int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        logits = fluid.layers.data("logits", [V])
        label = fluid.layers.data("label", [1], dtype="int64")
        # explicit soft-label chain
        soft = fluid.layers.label_smooth(
            fluid.layers.one_hot(label, depth=V), epsilon=eps)
        explicit = fluid.layers.softmax_with_cross_entropy(
            logits, soft, soft_label=True)
        # factored form (models/transformer.py head)
        hard = fluid.layers.softmax_with_cross_entropy(logits, label)
        neg_sum_logp = fluid.layers.scale(
            fluid.layers.reduce_sum(
                fluid.layers.log_softmax(logits), dim=-1, keep_dim=True),
            scale=-1.0)
        factored = fluid.layers.elementwise_add(
            fluid.layers.scale(hard, scale=1.0 - eps),
            fluid.layers.scale(neg_sum_logp, scale=eps / V))
    exe = fluid.Executor(fluid.CPUPlace())
    e_v, f_v = exe.run(main, feed={"logits": logits_v, "label": label_v},
                       fetch_list=[explicit, factored])
    np.testing.assert_allclose(np.asarray(f_v), np.asarray(e_v),
                               rtol=1e-5, atol=1e-6)


def test_lod_tensor_to_array_round_trip_trains():
    """Gradients must flow through the array round trip: a parameter
    feeding lod_tensor_to_array -> array_to_lod_tensor -> loss trains
    (the op pair's grads are each other)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3, 4], lod_level=1)
        lens = fluid.layers.data("lens", [1], dtype="int64")
        w = fluid.layers.create_parameter([4], "float32", name="w_rt")
        scaled = fluid.layers.elementwise_mul(x, w, axis=-1)
        table = fluid.layers.lod_rank_table(lengths=lens)
        arr = fluid.layers.lod_tensor_to_array(scaled, table)
        back = fluid.layers.array_to_lod_tensor(arr, table)
        loss = fluid.layers.reduce_mean(back)
        from paddle_tpu import backward as bw
        grads = bw.append_backward(loss)
    (gvar,) = [g for p, g in grads if p.name.startswith("w_rt")]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = _RNG(77).randn(2, 3, 4).astype("float32")
    lv = np.asarray([[3], [2]], "int64")
    (gw,) = exe.run(main, feed={"x": xv, "lens": lv}, fetch_list=[gvar])
    # d(mean(x*w))/dw_j = sum over (b, t) of x[b, t, j] / (B*T*D)
    np.testing.assert_allclose(
        np.asarray(gw), xv.sum(axis=(0, 1)) / xv.size, rtol=1e-5)


def test_attention_lstm_zero_length_row_zero_context():
    """ADVICE r4: a row with EncoderLen==0 must yield ZERO attention
    weights (and thus zero context), not uniform attention over
    padding. The C++ interpreter mirrors this (covered by the
    differential fuzz harness for nonzero lengths; this pins the
    zero-length corner on the XLA engine)."""
    rng = _RNG(75)
    B, T, S, D, C, M = 2, 3, 4, 3, 4, 3
    ins = {
        "X": rng.randn(B, T, M).astype("float32") * 0.3,
        "EncoderVec": rng.randn(B, S, C).astype("float32"),
        "EncoderProj": rng.randn(B, S, D).astype("float32"),
        "H0": np.zeros((B, D), "float32"),
        "C0": np.zeros((B, D), "float32"),
        "StateProjW": (0.3 * rng.randn(D, D)).astype("float32"),
        "AttnW": (0.3 * rng.randn(2 * D, 1)).astype("float32"),
        "CellW": (0.3 * rng.randn(D + C + M, 4 * D)).astype("float32"),
        "CellB": np.zeros((1, 4 * D), "float32"),
        "EncoderLen": np.asarray([S, 0], "int32"),
    }
    hid, attn = _run("attention_lstm", ins, ["Hidden", "AttentionWeight"])
    np.testing.assert_allclose(attn[0].sum(-1), np.ones(T), rtol=1e-5)
    assert np.abs(attn[1]).max() == 0.0, "zero-length row must have zero weights"
    assert np.isfinite(hid).all()


def test_lrn_even_n_reference_window():
    """ADVICE r4: for even n the reference window is start=-(n-1)/2 —
    biased toward HIGHER channels. n=4 at channel c must average
    squares over [c-1, c+2], not [c-2, c+1]."""
    rng = _RNG(40)
    x = rng.randn(1, 6, 2, 2).astype("float32")
    n, k, alpha, beta = 4, 2.0, 0.5, 0.75
    (out,) = _run("lrn", {"X": x}, ["Out"],
                  {"n": n, "k": k, "alpha": alpha, "beta": beta})
    sq = x ** 2
    want = np.empty_like(x)
    C = x.shape[1]
    lo_off = (n - 1) // 2
    for c in range(C):
        lo, hi = max(0, c - lo_off), min(C - 1, c + (n - 1 - lo_off))
        acc = sq[:, lo:hi + 1].sum(axis=1)
        want[:, c] = x[:, c] / (k + alpha * acc) ** beta
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
