"""Ring attention + Ulysses sequence parallelism on the 8-device CPU mesh
(SURVEY.md §4: dist-parity tests via multi-device CPU XLA)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.kernels.flash_attention import flash_attention_reference
from paddle_tpu.parallel.ring_attention import (
    ring_attention,
    ulysses_attention,
)


def _mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs %d devices" % n)
    return Mesh(np.asarray(devs[:n]), ("data",))


def _qkv(B=2, H=4, T=32, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(B, H, T, d).astype("float32")),
        jnp.asarray(rng.randn(B, H, T, d).astype("float32")),
        jnp.asarray(rng.randn(B, H, T, d).astype("float32")),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_single_device(causal):
    mesh = _mesh()
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, axis_name="data", causal=causal)
    expect = flash_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_single_device(causal):
    mesh = _mesh()
    q, k, v = _qkv(H=8)
    out = ulysses_attention(q, k, v, mesh, axis_name="data", causal=causal)
    expect = flash_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5
    )


def test_ring_attention_grads_match():
    """Ring attention is reverse-differentiable (training path)."""
    mesh = _mesh()
    q, k, v = _qkv(T=16)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh, axis_name="data", causal=True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            flash_attention_reference(q, k, v, causal=True) ** 2
        )

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, ge):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5
        )


def test_ring_attention_under_jit_with_sharded_inputs():
    """Compiles inside jit with inputs already placed on the mesh — the
    production path (sequence sharded across ICI)."""
    mesh = _mesh()
    q, k, v = _qkv(T=64)
    sh = NamedSharding(mesh, P(None, None, "data", None))
    q = jax.device_put(q, sh)
    k = jax.device_put(k, sh)
    v = jax.device_put(v, sh)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name="data", causal=True)

    out = f(q, k, v)
    assert out.sharding.is_equivalent_to(sh, 4)
    expect = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_impl_matches_single_device(causal):
    """The Pallas-per-block ring engine (impl="flash", interpret mode on
    CPU): forward matches full attention, diagonal peel + rotated-block
    keep/drop included."""
    mesh = _mesh()
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, axis_name="data", causal=causal,
                         impl="flash")
    expect = flash_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5
    )


def test_ring_flash_impl_grads_match():
    """Gradients flow through the ring-level custom_vjp (backward is the
    XLA reference ring) and match single-device attention grads."""
    import jax

    mesh = _mesh()
    q, k, v = _qkv()

    def loss_ring(q_, k_, v_):
        return jnp.sum(ring_attention(
            q_, k_, v_, mesh, axis_name="data", causal=True,
            impl="flash") ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(flash_attention_reference(
            q_, k_, v_, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
