"""Aux-subsystem tests: flags-from-env, check_nan_inf, memory_optimize
(remat), debugger dumps, profiler chrome trace (SURVEY.md §5 parity)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags


def _simple_program(lr=0.05, seed=3):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=32, act="tanh")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y)
        )
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, loss


def test_flags_env_parsing(monkeypatch):
    monkeypatch.setenv("FLAGS_check_nan_inf", "true")
    monkeypatch.setenv("FLAGS_eager_delete_tensor_gb", "2.5")
    monkeypatch.setenv("FLAGS_rpc_deadline", "1234")
    flags.refresh_from_env()
    try:
        assert flags.get("check_nan_inf") is True
        assert flags.get("eager_delete_tensor_gb") == 2.5
        assert flags.get("rpc_deadline") == 1234
        with pytest.raises(KeyError):
            flags.get("no_such_flag")
    finally:
        monkeypatch.delenv("FLAGS_check_nan_inf")
        monkeypatch.delenv("FLAGS_eager_delete_tensor_gb")
        monkeypatch.delenv("FLAGS_rpc_deadline")
        flags.refresh_from_env()
    assert flags.get("check_nan_inf") is False


def test_check_nan_inf_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        out = fluid.layers.log(x)  # log of a negative -> NaN
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bad = np.array([[-1.0, 1.0, 2.0, 3.0]], "float32")
    # Without the flag: NaN flows through silently (reference default).
    (res,) = exe.run(main, feed={"x": bad}, fetch_list=[out])
    assert np.isnan(np.asarray(res)).any()
    flags.set_flag("check_nan_inf", True)
    try:
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            exe.run(main, feed={"x": bad}, fetch_list=[out])
    finally:
        flags.set_flag("check_nan_inf", False)


def test_memory_optimize_remat_preserves_numerics():
    rng = np.random.RandomState(0)
    data = [
        (
            rng.randn(16, 16).astype("float32"),
            rng.randn(16, 1).astype("float32"),
        )
        for _ in range(5)
    ]

    def run(optimized):
        with fluid.unique_name.guard():
            main, startup, loss = _simple_program()
        if optimized:
            n = fluid.memory_optimize(main, print_log=False)
            assert n > 0
            assert fluid.transpiler.release_memory(main) == 0
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu.core.scope import Scope

        with fluid.scope_guard(Scope()):
            exe.run(startup)
            losses = []
            for xb, yb in data:
                (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv).ravel()[0]))
        return losses

    base = run(optimized=False)
    remat = run(optimized=True)
    np.testing.assert_allclose(remat, base, rtol=1e-5, atol=1e-7)


def test_debugger_dumps(tmp_path):
    main, startup, loss = _simple_program()
    code = fluid.debugger.program_to_code(main)
    assert "mul(" in code and "sgd(" in code
    assert "param fc_" in code
    dot_path = str(tmp_path / "prog.dot")
    dot = fluid.debugger.draw_block_graphviz(
        main.global_block(), highlights=[loss.name], path=dot_path
    )
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert os.path.exists(dot_path)
    assert loss.name.replace(".", "_") in dot  # highlighted node present


def test_profiler_report_and_chrome_trace(tmp_path, capsys):
    main, startup, loss = _simple_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    trace_path = str(tmp_path / "trace.json")
    rng = np.random.RandomState(1)
    # print_report=True: the report routes through logging by default so
    # pytest stays quiet; the stdout table is the opt-in escape hatch
    with fluid.profiler.profiler(profile_path=trace_path, print_report=True):
        for _ in range(3):
            with fluid.profiler.RecordEvent("train_step"):
                exe.run(
                    main,
                    feed={
                        "x": rng.randn(8, 16).astype("float32"),
                        "y": rng.randn(8, 1).astype("float32"),
                    },
                    fetch_list=[loss],
                )
    out = capsys.readouterr().out
    assert "Profiling Report" in out and "train_step" in out
    with open(trace_path) as f:
        trace = json.load(f)
    steps = [e for e in trace["traceEvents"] if e["name"] == "train_step"]
    assert len(steps) == 3
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in steps)


def test_weighted_average():
    from paddle_tpu.average import WeightedAverage

    wa = WeightedAverage()
    wa.add(2.0, 3)
    wa.add(np.array([4.0]), 1)
    assert wa.eval() == pytest.approx((2.0 * 3 + 4.0) / 4)
    wa.reset()
    with pytest.raises(ValueError):
        wa.eval()
    # element-wise matrix averaging, as upstream average.py supports
    wa.add(np.array([1.0, 3.0]), 1.0)
    wa.add(np.array([3.0, 5.0]), 3.0)
    np.testing.assert_allclose(wa.eval(), [2.5, 4.5])


def test_create_random_int_lodtensor():
    import paddle_tpu as fluid

    t = fluid.create_random_int_lodtensor(
        [[2, 3]], base_shape=[4], low=1, high=9)
    assert t.numpy().shape == (5, 4)
    assert t.recursive_sequence_lengths() == [[2, 3]]
    arr = t.numpy()
    assert arr.min() >= 1 and arr.max() <= 9
    assert arr.dtype == np.int64


def test_contrib_memory_usage_and_op_freq():
    """contrib utilities: memory band estimate + op frequency report
    (contrib/memory_usage_calc.py, contrib/op_frequence.py roles)."""
    from paddle_tpu import contrib

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [256])
        h = fluid.layers.fc(x, 128, act="relu")
        h = fluid.layers.fc(h, 128, act="relu")
        loss = fluid.layers.mean(h)
    low, high, unit = contrib.memory_usage(main, batch_size=64)
    assert 0 < low < high and unit in ("B", "KB", "MB", "GB")
    # doubling the batch cannot shrink the estimate
    low2, high2, unit2 = contrib.memory_usage(main, batch_size=128)
    bytes_for = {"B": 1, "KB": 2**10, "MB": 2**20, "GB": 2**30}
    assert high2 * bytes_for[unit2] > high * bytes_for[unit]

    uni, pairs = contrib.op_freq_statis(main)
    assert uni["mul"] == 2 and uni["relu"] == 2
    assert pairs.get("elementwise_add->relu") == 2  # fc bias -> act chain
    import pytest as _pytest
    with _pytest.raises(TypeError):
        contrib.memory_usage("not a program", 4)


def test_get_parameter_value():
    """io.get_parameter_value(_by_name): scope-backed parameter reads
    (io.py:818/:848 parity) including the not-initialized error."""
    import numpy as np
    import pytest
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        pred = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    param = main.global_block().all_parameters()[0]
    with fluid.scope_guard(fluid.executor.Scope()):
        with pytest.raises(RuntimeError, match="startup"):
            fluid.io.get_parameter_value(param, exe)
        exe.run(startup)
        v = fluid.io.get_parameter_value(param, exe)
        assert v.shape == (3, 2)
        v2 = fluid.io.get_parameter_value_by_name(param.name, exe,
                                                  program=main)
        np.testing.assert_array_equal(v, v2)
    with pytest.raises(AssertionError, match="not a Parameter"):
        fluid.io.get_parameter_value(x, exe)
