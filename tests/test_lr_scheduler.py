"""LR schedule tests against closed-form numpy oracles.

Reference: tests/unittests/test_learning_rate_scheduler.py — each decay's
fetched value at step t must match the python formula; schedules run as
in-graph ops over the @LR_DECAY_COUNTER@ persistable.
"""

import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.layers import learning_rate_scheduler as lrs


def _run_schedule(build_fn, steps=8):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lr = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    values = []
    for _ in range(steps):
        (v,) = exe.run(main, feed={}, fetch_list=[lr])
        values.append(float(np.ravel(np.asarray(v))[0]))
    return values


@pytest.mark.parametrize("staircase", [False, True])
def test_exponential_decay(staircase):
    got = _run_schedule(
        lambda: lrs.exponential_decay(0.1, 3, 0.5, staircase=staircase))
    for t, v in enumerate(got):
        div = t / 3.0
        if staircase:
            div = math.floor(div)
        assert v == pytest.approx(0.1 * 0.5 ** div, rel=1e-5)


def test_natural_exp_and_inverse_time_decay():
    got = _run_schedule(lambda: lrs.natural_exp_decay(0.1, 2, 0.9))
    for t, v in enumerate(got):
        assert v == pytest.approx(0.1 * math.exp(-0.9 * t / 2.0), rel=1e-5)
    got = _run_schedule(lambda: lrs.inverse_time_decay(0.1, 2, 0.5))
    for t, v in enumerate(got):
        assert v == pytest.approx(0.1 / (1 + 0.5 * t / 2.0), rel=1e-5)


@pytest.mark.parametrize("cycle", [False, True])
def test_polynomial_decay(cycle):
    lr0, end, k, p = 0.1, 0.01, 4, 2.0
    got = _run_schedule(
        lambda: lrs.polynomial_decay(lr0, k, end, power=p, cycle=cycle),
        steps=10)
    for t, v in enumerate(got):
        if cycle:
            div = max(1.0, math.ceil(t / float(k)))
            frac = t / (div * k)
        else:
            frac = min(float(t), float(k)) / k
        expect = (lr0 - end) * (1 - frac) ** p + end
        assert v == pytest.approx(expect, rel=1e-4), t


def test_piecewise_decay():
    got = _run_schedule(
        lambda: lrs.piecewise_decay([2, 5], [0.1, 0.05, 0.01]), steps=8)
    expect = [0.1, 0.1, 0.05, 0.05, 0.05, 0.01, 0.01, 0.01]
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_noam_and_cosine_decay():
    d_model, warm = 64, 4
    got = _run_schedule(lambda: lrs.noam_decay(d_model, warm), steps=8)
    for t, v in enumerate(got):
        if t == 0:
            continue  # 0**-0.5 -> inf; min picks the warmup branch
        expect = d_model ** -0.5 * min(t ** -0.5, t * warm ** -1.5)
        assert v == pytest.approx(expect, rel=1e-5)
    got = _run_schedule(lambda: lrs.cosine_decay(0.1, 2, 4), steps=8)
    for t, v in enumerate(got):
        epoch = math.floor(t / 2.0)
        expect = 0.1 * (math.cos(epoch * math.pi / 4.0) + 1) / 2
        assert v == pytest.approx(expect, rel=1e-5)


def test_scheduler_drives_optimizer():
    """The schedule actually changes the applied step size."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [2], stop_gradient=False)
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        lr = lrs.piecewise_decay([2], [0.5, 0.0])  # step 0-1 lr .5, then 0
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((4, 2), "float32"), "y": np.ones((4, 1), "float32")}
    w_name = [n for n in fluid.global_scope().local_var_names()
              if n.endswith("w_0")][0]
    exe.run(main, feed=feed, fetch_list=[loss])
    exe.run(main, feed=feed, fetch_list=[loss])
    w_after_2 = np.array(fluid.global_scope().get_value(w_name))
    exe.run(main, feed=feed, fetch_list=[loss])
    w_after_3 = np.array(fluid.global_scope().get_value(w_name))
    # lr dropped to 0 at step 2 -> weights frozen from then on
    np.testing.assert_allclose(w_after_3, w_after_2, rtol=0, atol=0)
