"""Front-end layer-surface completion tests: Print, crop, sum,
random_crop, dice_loss, image_resize_short, autoincreased_step_counter,
sequence_expand, load, append_LARS export.

Reference parity: python/paddle/fluid/layers __all__ (the API surface the
golden API.spec test locks); semantics from layers/nn.py + the op kernels.
"""

import os

import numpy as np

import paddle_tpu as fluid


def _run(build, feed=None, steps=1):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = None
    for _ in range(steps):
        out = exe.run(main, feed=feed or {}, fetch_list=list(fetches))
    return out


def test_crop_and_sum_layers():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)

    def build():
        xv = fluid.layers.data("x", [3, 4])
        c = fluid.layers.crop(xv, shape=[1, 2, 3], offsets=[1, 1, 0])
        s = fluid.layers.sum([xv, xv, xv])
        return c, s

    c, s = _run(build, {"x": x})
    np.testing.assert_allclose(np.asarray(c), x[1:2, 1:3, 0:3])
    np.testing.assert_allclose(np.asarray(s), 3 * x)


def test_print_layer_passthrough(capfd):
    x = np.asarray([[1.5, 2.5]], "float32")

    def build():
        xv = fluid.layers.data("x", [2])
        out = fluid.layers.Print(xv, message="dbg")
        return (fluid.layers.scale(out, scale=2.0),)

    (out,) = _run(build, {"x": x})
    np.testing.assert_allclose(np.asarray(out), 2 * x)


def test_random_crop_layer():
    x = np.random.RandomState(0).rand(4, 3, 8, 8).astype("float32")

    def build():
        xv = fluid.layers.data("x", [3, 8, 8])
        return (fluid.layers.random_crop(xv, shape=[3, 5, 5]),)

    (out,) = _run(build, {"x": x})
    out = np.asarray(out)
    assert out.shape == (4, 3, 5, 5)
    # crop content must exist inside the source image
    found = False
    for i in range(4):
        for j in range(4):
            if np.allclose(out[0, :, :, :], x[0, :, i:i + 5, j:j + 5]):
                found = True
    assert found


def test_dice_loss_perfect_and_disjoint():
    # perfect overlap -> ~0; disjoint -> ~1
    a = np.zeros((2, 4), "float32")
    a[:, :2] = 1.0

    def build():
        p = fluid.layers.data("p", [4])
        l = fluid.layers.data("l", [4])
        return (fluid.layers.dice_loss(p, l),)

    (perfect,) = _run(build, {"p": a, "l": a})
    assert abs(float(np.asarray(perfect).ravel()[0])) < 1e-4
    b = 1.0 - a
    (disjoint,) = _run(build, {"p": a, "l": b})
    assert abs(float(np.asarray(disjoint).ravel()[0]) - 1.0) < 1e-4


def test_image_resize_short_keeps_aspect():
    x = np.random.RandomState(1).rand(1, 3, 6, 12).astype("float32")

    def build():
        xv = fluid.layers.data("x", [3, 6, 12])
        return (fluid.layers.image_resize_short(xv, 3),)

    (out,) = _run(build, {"x": x})
    assert np.asarray(out).shape == (1, 3, 3, 6)


def test_autoincreased_step_counter():
    def build():
        step = fluid.layers.autoincreased_step_counter(begin=1)
        return (step,)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        (step,) = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    vals = [float(np.asarray(exe.run(main, fetch_list=[step])[0]).ravel()[0])
            for _ in range(3)]
    assert vals == [1.0, 2.0, 3.0], vals


def test_sequence_expand_repeats_rows():
    x = np.asarray([[1.0, 2.0], [3.0, 4.0]], "float32")  # [2, d]
    y = np.zeros((2, 3, 1), "float32")  # ref: max_len 3

    def build():
        xv = fluid.layers.data("x", [2], append_batch_size=True)
        yv = fluid.layers.data("y", [3, 1])
        return (fluid.layers.sequence_expand(xv, yv),)

    (out,) = _run(build, {"x": x, "y": y})
    exp = np.repeat(x, 3, axis=0)
    np.testing.assert_allclose(np.asarray(out), exp)


def test_load_layer_roundtrip(tmp_path):
    val = np.arange(6, dtype="float32").reshape(2, 3)
    path = os.path.join(str(tmp_path), "w.npy")
    np.save(path, val)

    def build():
        w = fluid.layers.load(path)
        return (fluid.layers.scale(w, scale=1.0),)

    (out,) = _run(build)
    np.testing.assert_allclose(np.asarray(out), val)


def test_append_lars_exported():
    assert callable(fluid.layers.append_LARS)


def test_dice_loss_int_class_labels_one_hot():
    """Integer labels are one-hot encoded over the last dim (reference
    dice_loss contract), not cast to float indices."""
    probs = np.asarray([[0.9, 0.05, 0.05], [0.1, 0.8, 0.1]], "float32")
    labs = np.asarray([[0], [1]], "int64")

    def build():
        p = fluid.layers.data("p", [3])
        l = fluid.layers.data("l", [1], dtype="int64")
        return (fluid.layers.dice_loss(p, l),)

    (v,) = _run(build, {"p": probs, "l": labs})
    oh = np.eye(3)[labs[:, 0]]
    inse = (probs * oh).sum(-1)
    den = probs.sum(-1) + oh.sum(-1)
    exp = (1 - 2 * inse / (den + 1e-5)).mean()
    np.testing.assert_allclose(float(np.asarray(v).ravel()[0]), exp,
                               rtol=1e-5)


def test_random_crop_seed_deterministic():
    def crop_once():
        def build():
            xv = fluid.layers.data("x", [1, 6, 6])
            return (fluid.layers.random_crop(xv, shape=[1, 3, 3], seed=42),)

        x = np.arange(36, dtype="float32").reshape(1, 1, 6, 6)
        return np.asarray(_run(build, {"x": x})[0])

    np.testing.assert_array_equal(crop_once(), crop_once())


def test_step_counter_shared_single_increment():
    """Two call sites share ONE +1 per run (reference is-new-var guard)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        c1 = fluid.layers.autoincreased_step_counter(begin=1)
        c2 = fluid.layers.autoincreased_step_counter(begin=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for expect in (1.0, 2.0):
        v1, v2 = exe.run(main, fetch_list=[c1, c2])
        assert float(np.asarray(v1).ravel()[0]) == expect
        assert float(np.asarray(v2).ravel()[0]) == expect


def test_load_layer_dtype_cast(tmp_path):
    path = os.path.join(str(tmp_path), "v.npy")
    np.save(path, np.asarray([1, 2, 3], np.int32))

    def build():
        return (fluid.layers.load(path, dtype="float32"),)

    (v,) = _run(build)
    assert np.asarray(v).dtype == np.float32


def test_save_op_writes_during_execution(tmp_path):
    """The in-graph save op persists a mid-program value at execution
    time (save_op.cc role), round-tripping through layers.load."""
    from paddle_tpu.layer_helper import LayerHelper

    path = os.path.join(str(tmp_path), "ckpt", "h.npy")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3])
        h = fluid.layers.scale(x, scale=2.0)
        helper = LayerHelper("save")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="save", inputs={"X": [h]},
                         outputs={"Out": [out]},
                         attrs={"file_path": path})
        final = fluid.layers.scale(out, scale=3.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.asarray([[1.0, 2.0, 3.0]], "float32")
    (fv,) = exe.run(main, feed={"x": xv}, fetch_list=[final])
    np.testing.assert_allclose(np.asarray(fv), 6 * xv)
    saved = np.load(path)
    np.testing.assert_allclose(saved, 2 * xv)

    # reload in a fresh program through layers.load
    p2, s2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(p2, s2):
        w = fluid.layers.load(path)
    e2 = fluid.Executor(fluid.CPUPlace())
    e2.run(s2)
    (wv,) = e2.run(p2, fetch_list=[w])
    np.testing.assert_allclose(np.asarray(wv), 2 * xv)


def test_save_op_passes_gradients_through(tmp_path):
    """save is identity in the dataflow: training THROUGH a save op must
    converge (its grad is an assign — the io_callback has no JVP rule)."""
    from paddle_tpu.layer_helper import LayerHelper

    path = os.path.join(str(tmp_path), "h.npy")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        y = fluid.layers.data("y", [1])
        h = fluid.layers.fc(x, 16, act="relu")
        helper = LayerHelper("save")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="save", inputs={"X": [h]},
                         outputs={"Out": [out]},
                         attrs={"file_path": path})
        pred = fluid.layers.fc(out, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    w = rng.randn(8, 1).astype("float32")
    losses = []
    for _ in range(20):
        xb = rng.randn(16, 8).astype("float32")
        (lv,) = exe.run(main, feed={"x": xb, "y": xb @ w},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.6
    assert os.path.exists(path)


def test_save_combine_load_combine_roundtrip(tmp_path):
    """save_combine bundles several mid-graph values into one archive at
    execution time; load_combine restores them positionally."""
    from paddle_tpu.layer_helper import LayerHelper

    path = os.path.join(str(tmp_path), "bundle")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        a = fluid.layers.scale(x, scale=2.0)
        b = fluid.layers.scale(x, scale=-1.0)
        helper = LayerHelper("save_combine")
        oa = helper.create_variable_for_type_inference("float32")
        ob = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="save_combine",
                         inputs={"X": [a, b]},
                         outputs={"Out": [oa, ob]},
                         attrs={"file_path": path})
        total = fluid.layers.elementwise_add(oa, ob)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.asarray([[1.0, 2.0, 3.0, 4.0]], "float32")
    (tv,) = exe.run(main, feed={"x": xv}, fetch_list=[total])
    np.testing.assert_allclose(np.asarray(tv), xv)  # 2x + (-x) = x

    p2, s2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(p2, s2):
        helper = LayerHelper("load_combine")
        ra = helper.create_variable_for_type_inference("float32")
        rb = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="load_combine",
                         outputs={"Out": [ra, rb]},
                         attrs={"file_path": path})
    e2 = fluid.Executor(fluid.CPUPlace())
    e2.run(s2)
    va, vb = e2.run(p2, fetch_list=[ra, rb])
    np.testing.assert_allclose(np.asarray(va), 2 * xv)
    np.testing.assert_allclose(np.asarray(vb), -xv)


def test_save_combine_partial_gradient_path(tmp_path):
    """Only ONE bundled output feeds the loss: the other entry's input
    grad must come back as zeros (not vanish — the dup-grad sum reads
    every declared contribution)."""
    from paddle_tpu.layer_helper import LayerHelper

    path = os.path.join(str(tmp_path), "state")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 2
    startup.random_seed = 2
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6])
        y = fluid.layers.data("y", [1])
        h1 = fluid.layers.fc(x, 12, act="relu")
        h2 = fluid.layers.fc(h1, 12, act="relu")
        helper = LayerHelper("save_combine")
        o1 = helper.create_variable_for_type_inference("float32")
        o2 = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="save_combine", inputs={"X": [h1, h2]},
                         outputs={"Out": [o1, o2]},
                         attrs={"file_path": path})
        pred = fluid.layers.fc(o2, 1)  # o1 is checkpoint-only
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(1)
    w = rng.randn(6, 1).astype("float32")
    losses = []
    for _ in range(20):
        xb = rng.randn(8, 6).astype("float32")
        (lv,) = exe.run(main, feed={"x": xb, "y": xb @ w},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.6
    assert os.path.exists(path + ".npz")


def test_lod_rank_table_and_reorder():
    """lod_rank_table (desc-stable rank over lengths) + batch reorder,
    with the gradient scattering back through the permutation."""
    import numpy as np
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        x.stop_gradient = False
        lens = fluid.layers.data(name="lens", shape=[1], dtype="int64")
        table = fluid.layers.lod_rank_table(lengths=lens)
        y = fluid.layers.reorder_lod_tensor_by_rank(x, table)
        loss = fluid.layers.reduce_sum(
            y * fluid.layers.assign(
                np.arange(12, dtype="float32").reshape(4, 3)))
        fluid.backward.append_backward(loss)
        xg = main.block(0).vars["x@GRAD"]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.arange(12, dtype="float32").reshape(4, 3)
    lv = np.array([[2], [5], [5], [1]], dtype="int64")
    idx, slen, yv, gv = exe.run(
        main, feed={"x": xv, "lens": lv},
        fetch_list=[table.index, table.length, y, xg])
    # desc by length, ties stable: lens [2,5,5,1] -> order [1,2,0,3]
    np.testing.assert_array_equal(np.ravel(idx), [1, 2, 0, 3])
    np.testing.assert_array_equal(np.ravel(slen), [5, 5, 2, 1])
    np.testing.assert_allclose(yv, xv[[1, 2, 0, 3]])
    # dL/dx permutes the weight matrix back through the gather
    w = np.arange(12, dtype="float32").reshape(4, 3)
    want = np.empty_like(w)
    want[[1, 2, 0, 3]] = w
    np.testing.assert_allclose(gv, want)


def test_data_feeder_parallel_and_decorate_reader():
    """feed_parallel + decorate_reader (reference DataFeeder API): batch
    split across places, trained through ParallelExecutor's per-device
    feed-list form."""
    import numpy as np
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    feeder = fluid.DataFeeder(feed_list=[x, y], place=fluid.CPUPlace())

    rng = np.random.RandomState(0)
    def batch_reader():
        for _ in range(3):
            yield [(rng.rand(4).astype("float32"),
                    rng.rand(1).astype("float32")) for _ in range(16)]

    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        pe = fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                                    num_devices=8)
        seen = 0
        for feed_list in feeder.decorate_reader(
                batch_reader, multi_devices=True, num_places=8)():
            assert isinstance(feed_list, list) and len(feed_list) == 8
            assert feed_list[0]["x"].shape == (2, 4)
            lv, = pe.run(feed=feed_list, fetch_list=[loss.name])
            assert np.isfinite(np.ravel(np.asarray(lv))).all()
            seen += 1
        assert seen == 3

    # feed_parallel: explicit per-place iterables
    samples = [[(rng.rand(4).astype("float32"),
                 rng.rand(1).astype("float32"))] for _ in range(8)]
    dicts = feeder.feed_parallel(samples, num_places=8)
    assert len(dicts) == 8 and dicts[0]["x"].shape == (1, 4)

    # indivisible batch without drop_last raises
    def bad_reader():
        yield [(rng.rand(4).astype("float32"),
                rng.rand(1).astype("float32")) for _ in range(5)]
    import pytest
    with pytest.raises(ValueError, match="not divisible"):
        list(feeder.decorate_reader(bad_reader, multi_devices=True,
                                    num_places=8, drop_last=False)())
    # with drop_last a sub-device-count batch is skipped whole...
    assert list(feeder.decorate_reader(bad_reader, multi_devices=True,
                                       num_places=8)()) == []

    # ...while a larger indivisible batch only loses remainder samples
    def uneven_reader():
        yield [(rng.rand(4).astype("float32"),
                rng.rand(1).astype("float32")) for _ in range(10)]
    (dicts2,) = list(feeder.decorate_reader(uneven_reader,
                                            multi_devices=True,
                                            num_places=8)())
    assert len(dicts2) == 8 and dicts2[0]["x"].shape == (1, 4)
