"""Executable-cache correctness: structural fingerprints, the process-
global registry, the persistent on-disk layers, and async dispatch.

Satellite coverage from the compile-tax PR: every trace flag toggle
recompiles, program mutation recompiles, structurally identical programs
share one executable, a corrupted on-disk entry degrades to a fresh
compile (asserted through the exec_cache stats counters), and
run_async(...).result() matches run(...) bit-for-bit. The cross-PROCESS
warm start is proven by tools/run_ci.sh `warm` (tools/warm_start_smoke.py);
here the same disk layers are exercised in-process by purging the
in-memory registries between runs.
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, unique_name
from paddle_tpu.core import exec_cache
from paddle_tpu.core.fingerprint import (
    TRACE_FLAGS,
    program_fingerprint,
)
import paddle_tpu.executor as executor_mod


def _build_mlp():
    unique_name.switch({})
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6])
        hid = fluid.layers.fc(x, size=8, act="relu")
        out = fluid.layers.reduce_sum(fluid.layers.fc(hid, size=2))
    return main, startup, out


def _feed(bs=3):
    return {"x": np.arange(bs * 6, dtype="float32").reshape(bs, 6) / 10.0}


def _trace_misses():
    return exec_cache.stats()["trace_cache_misses"]


# -- fingerprint scheme ------------------------------------------------------

def test_fingerprint_stable_and_memoized():
    main, _, _ = _build_mlp()
    fp1 = program_fingerprint(main)
    fp2 = program_fingerprint(main)
    assert fp1 == fp2
    # memo is version-keyed: no structural change, no re-hash needed
    assert main._fingerprint_memo[0] == main._version


def test_fingerprint_identical_builds_match():
    m1, _, _ = _build_mlp()
    m2, _, _ = _build_mlp()
    assert m1 is not m2
    assert program_fingerprint(m1) == program_fingerprint(m2)


def test_fingerprint_changes_on_mutation():
    main, _, _ = _build_mlp()
    fp = program_fingerprint(main)
    op = main.global_block().ops[-1]
    op.set_attr("some_knob", 42)  # bumps _version through the framework API
    assert program_fingerprint(main) != fp


def test_fingerprint_differs_for_different_programs():
    m1, _, _ = _build_mlp()
    unique_name.switch({})
    m2, s2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(m2, s2):
        x = fluid.layers.data("x", [6])
        fluid.layers.reduce_sum(fluid.layers.fc(x, size=8))
    assert program_fingerprint(m1) != program_fingerprint(m2)


# -- in-memory executable sharing -------------------------------------------

def test_identical_programs_share_one_executable():
    m1, s1, o1 = _build_mlp()
    m2, _, o2 = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(s1)
    r1 = exe.run(m1, feed=_feed(), fetch_list=[o1])
    misses = _trace_misses()
    # same structure, same scope signature -> ZERO new traces, on either
    # the same executor or a brand-new instance
    r1b = exe.run(m2, feed=_feed(), fetch_list=[o2])
    exe2 = fluid.Executor(fluid.CPUPlace())
    r2 = exe2.run(m2, feed=_feed(), fetch_list=[o2])
    assert _trace_misses() == misses
    np.testing.assert_array_equal(np.asarray(r1[0]), np.asarray(r1b[0]))
    np.testing.assert_array_equal(np.asarray(r1[0]), np.asarray(r2[0]))


def test_program_mutation_recompiles():
    main, startup, out = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[out])
    misses = _trace_misses()
    with fluid.program_guard(main, startup):
        out2 = fluid.layers.scale(out, scale=2.0)  # graph surgery
    exe.run(main, feed=_feed(), fetch_list=[out2])
    assert _trace_misses() == misses + 1


def test_each_trace_flag_toggle_recompiles():
    main, startup, out = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[out])
    for name in TRACE_FLAGS:
        old = flags.get(name)
        flip = {"attention_impl": "reference",
                "flash_backward": "reference"}.get(name, True)
        assert flip != old, "flag %s: test flip value equals default" % name
        misses = _trace_misses()
        flags.set_flag(name, flip)
        try:
            exe.run(main, feed=_feed(), fetch_list=[out])
            assert _trace_misses() == misses + 1, (
                "toggling %s did not recompile" % name)
            # ...and toggling BACK is a pure cache hit, not a re-trace
            flags.set_flag(name, old)
            exe.run(main, feed=_feed(), fetch_list=[out])
            assert _trace_misses() == misses + 1
        finally:
            flags.set_flag(name, old)


def test_use_program_cache_false_retraces_without_evicting_others():
    main, startup, out = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[out])
    misses = _trace_misses()
    exe.run(main, feed=_feed(), fetch_list=[out], use_program_cache=False)
    assert _trace_misses() == misses + 1  # this run really re-traced
    # ...but the registry still serves everyone else (bypass, not purge)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(main, feed=_feed(), fetch_list=[out])
    assert _trace_misses() == misses + 1


# -- async dispatch ----------------------------------------------------------

def test_run_async_matches_run_bit_for_bit():
    main, startup, out = _build_mlp()
    main.random_seed = 5  # deterministic step keys across the two runs
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (sync_out,) = exe.run(main, feed=_feed(), fetch_list=[out])
    handle = exe.run_async(main, feed=_feed(), fetch_list=[out])
    assert handle.fetch_names == [out.name]
    arrays = handle.arrays()
    assert len(arrays) == 1  # live device arrays, no host materialization
    handle.block_until_ready()
    assert handle.done()
    (async_out,) = handle.result()
    np.testing.assert_array_equal(np.asarray(sync_out), async_out)
    assert handle.result() is handle.result()  # memoized


def test_run_async_nan_check_survives_back_to_back_donation():
    """The deferred nan scan must be DISPATCHED at run_async time: a
    later step donates the very state buffers being checked, so a scan
    started lazily at .result() would read deleted arrays."""
    unique_name.switch({})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=1))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    flags.set_flag("check_nan_inf", True)
    try:
        h1 = exe.run_async(main, feed=_feed(), fetch_list=[loss])
        h2 = exe.run_async(main, feed=_feed(), fetch_list=[loss])
        (l1,) = h1.result()  # h2's dispatch donated h1's checked state
        (l2,) = h2.result()
        assert np.isfinite(l1).all() and np.isfinite(l2).all()
    finally:
        flags.set_flag("check_nan_inf", False)


def test_run_async_nan_failure_raises_on_every_result_call():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("xr", [4])
        out = fluid.layers.log(x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bad = np.array([[-1.0, 1.0, 2.0, 3.0]], "float32")
    flags.set_flag("check_nan_inf", True)
    try:
        handle = exe.run_async(main, feed={"xr": bad}, fetch_list=[out])
        for _ in range(2):  # a retry must NOT silently return the NaNs
            with pytest.raises(RuntimeError, match="NaN/Inf"):
                handle.result()
    finally:
        flags.set_flag("check_nan_inf", False)


def test_run_async_defers_nan_check_to_result():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        out = fluid.layers.log(x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bad = np.array([[-1.0, 1.0, 2.0, 3.0]], "float32")
    flags.set_flag("check_nan_inf", True)
    try:
        handle = exe.run_async(main, feed={"x": bad}, fetch_list=[out])
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            handle.result()
    finally:
        flags.set_flag("check_nan_inf", False)


def test_predictor_clone_shares_executable(tmp_path):
    main, startup, out = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(
            str(tmp_path / "model"), ["x"], [out], exe, main_program=main)
    config = fluid.inference.NativeConfig(
        model_dir=str(tmp_path / "model"), use_tpu=False)
    pred = fluid.inference.create_paddle_predictor(config)
    r1 = pred.run([_feed()["x"]])
    misses = _trace_misses()
    clone = pred.clone()
    r2 = clone.run([_feed()["x"]])
    assert _trace_misses() == misses, "Clone() recompiled the model"
    np.testing.assert_array_equal(r1[0], r2[0])
    h = clone.run_async([_feed()["x"]])
    np.testing.assert_array_equal(r1[0], h.result()[0])


# -- persistent on-disk layers ----------------------------------------------

def _purge_in_memory():
    """Simulate a fresh process: drop every in-memory executable handle so
    the next run can only be served by the on-disk layers."""
    executor_mod._shared_executables.clear()
    exec_cache._reset_jax_cache()


@pytest.fixture
def cache_dir(tmp_path):
    d = str(tmp_path / "exec_cache")
    old = flags.get("exec_cache_dir")
    flags.set_flag("exec_cache_dir", d)
    exec_cache.configure()
    # executables compiled by EARLIER tests (while persistence was off)
    # share the same structural keys; drop them so this test's cold run
    # actually compiles and persists
    _purge_in_memory()
    try:
        yield d
    finally:
        flags.set_flag("exec_cache_dir", old)
        exec_cache.configure()  # re-disable persistence for later tests


def test_warm_start_loads_aot_image(cache_dir):
    main, startup, out = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (cold,) = exe.run(main, feed=_feed(), fetch_list=[out])
    aot_dir = os.path.join(cache_dir, "aot")
    assert os.listdir(aot_dir), "no AOT images written"
    _purge_in_memory()
    before = exec_cache.stats()["aot_hits"]
    m2, _, o2 = _build_mlp()
    exe2 = fluid.Executor(fluid.CPUPlace())
    (warm,) = exe2.run(m2, feed=_feed(), fetch_list=[o2])
    assert exec_cache.stats()["aot_hits"] > before, (
        "warm run did not deserialize the stored executable")
    # params untouched between runs -> identical math through the image
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(warm))


def test_corrupted_cache_entry_degrades_to_fresh_compile(cache_dir):
    main, startup, out = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (good,) = exe.run(main, feed=_feed(), fetch_list=[out])
    # trash EVERY on-disk entry in both layers
    for sub in ("aot", "xla"):
        root = os.path.join(cache_dir, sub)
        for dirpath, _, files in os.walk(root):
            for f in files:
                with open(os.path.join(dirpath, f), "wb") as fh:
                    fh.write(b"corrupt garbage, not an executable")
    _purge_in_memory()
    errors_before = exec_cache.stats()["aot_errors"]
    m2, _, o2 = _build_mlp()
    exe2 = fluid.Executor(fluid.CPUPlace())
    (recovered,) = exe2.run(m2, feed=_feed(), fetch_list=[o2])  # must not crash
    st = exec_cache.stats()
    assert st["aot_errors"] > errors_before, (
        "corrupt AOT image was not detected")
    np.testing.assert_array_equal(np.asarray(good), np.asarray(recovered))
    # the bad entries were QUARANTINED (kept for autopsy, never re-read)
    # and replaced by fresh ones on the way
    aot = os.path.join(cache_dir, "aot")
    for f in os.listdir(aot):
        path = os.path.join(aot, f)
        if not os.path.isfile(path):
            continue  # the quarantine subdir itself
        with open(path, "rb") as fh:
            assert fh.read(32) != b"corrupt garbage, not an executa"
    qdir = os.path.join(aot, "quarantine")
    assert os.path.isdir(qdir) and os.listdir(qdir), (
        "corrupt entries should be moved to quarantine/, not deleted")
    for f in os.listdir(qdir):
        with open(os.path.join(qdir, f), "rb") as fh:
            assert fh.read(32).startswith(b"corrupt garbage")


def test_cache_stats_exported_through_profiler(cache_dir):
    main, startup, out = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[out])
    st = fluid.profiler.exec_cache_stats()
    assert st["enabled"] and st["cache_dir"] == os.path.abspath(cache_dir)
    for k in ("fresh_compiles", "persistent_hits", "persistent_misses",
              "aot_hits", "aot_misses", "aot_errors",
              "compile_seconds_cold", "compile_seconds_warm"):
        assert k in st
    assert st["compile_seconds_cold"] + st["compile_seconds_warm"] >= 0
