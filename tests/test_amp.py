"""bf16 mixed-precision (AMP) rewrite + in-graph random reader tests.

Covers the fp16-transpiler-equivalent capability
(paddle/contrib/float16/float16_transpiler.py) redesigned for bf16
training, and the synthetic reader op
(operators/reader/create_random_data_generator_op.cc capability).
"""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.amp import apply_amp_casts
from paddle_tpu.transpiler import amp_guard, rewrite_program_amp


def _mlp_program(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y)
        )
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


class TestAmpCasts:
    def test_white_op_casts_f32_down(self):
        ins = {"X": [jnp.ones((2, 3), jnp.float32)],
               "Y": [jnp.ones((3, 4), jnp.float32)]}
        out = apply_amp_casts("mul", ins, "bfloat16")
        assert out["X"][0].dtype == jnp.bfloat16
        assert out["Y"][0].dtype == jnp.bfloat16

    def test_grad_op_follows_forward_class(self):
        ins = {"X": [jnp.ones((2, 3), jnp.float32)]}
        out = apply_amp_casts("conv2d_grad", ins, "bfloat16")
        assert out["X"][0].dtype == jnp.bfloat16

    def test_black_op_casts_up(self):
        ins = {"X": [jnp.ones((2, 3), jnp.bfloat16)]}
        out = apply_amp_casts("mean", ins, "bfloat16")
        assert out["X"][0].dtype == jnp.float32

    def test_neutral_op_untouched(self):
        ins = {"X": [jnp.ones((2, 3), jnp.bfloat16)]}
        out = apply_amp_casts("relu", ins, "bfloat16")
        assert out["X"][0].dtype == jnp.bfloat16

    def test_int_inputs_never_cast(self):
        ins = {"Label": [jnp.ones((2, 1), jnp.int32)]}
        out = apply_amp_casts("cross_entropy", ins, "bfloat16")
        assert out["Label"][0].dtype == jnp.int32


class TestAmpTraining:
    def test_amp_training_converges_and_masters_stay_f32(self):
        main, startup, loss = _mlp_program()
        rewrite_program_amp(main, "bfloat16")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        x = rng.rand(32, 16).astype(np.float32)
        y = rng.randint(0, 4, (32, 1)).astype(np.int64)
        losses = []
        for _ in range(30):
            lv, = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
            losses.append(float(np.ravel(lv)[0]))
        assert losses[-1] < losses[0] * 0.8, losses[::10]
        for name in fluid.global_scope().local_var_names():
            if name.endswith(".w_0") or name.endswith(".b_0"):
                assert fluid.global_scope().get_value(name).dtype == \
                    jnp.float32, name

    def test_amp_matches_f32_loss_roughly(self):
        results = {}
        for amp in (False, True):
            main, startup, loss = _mlp_program(seed=11)
            if amp:
                rewrite_program_amp(main, "bfloat16")
            from paddle_tpu.core.scope import Scope

            exe = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(Scope()):
                exe.run(startup)
                rng = np.random.RandomState(1)
                x = rng.rand(16, 16).astype(np.float32)
                y = rng.randint(0, 4, (16, 1)).astype(np.int64)
                for _ in range(5):
                    lv, = exe.run(
                        main, feed={"x": x, "y": y}, fetch_list=[loss]
                    )
                results[amp] = float(np.ravel(lv)[0])
        # bf16 has ~3 decimal digits; trajectories stay close over 5 steps.
        assert abs(results[True] - results[False]) < 0.05, results

    def test_amp_guard_restores(self):
        main, _, _ = _mlp_program()
        assert main._amp_dtype is None
        with amp_guard(main, "bfloat16"):
            assert main._amp_dtype == "bfloat16"
        assert main._amp_dtype is None

    def test_rejects_bad_dtype(self):
        main, _, _ = _mlp_program()
        with pytest.raises(ValueError):
            rewrite_program_amp(main, "int8")


class TestRandomDataGenerator:
    def test_shapes_dtypes_and_freshness(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            pixel, label = fluid.layers.random_data_generator(
                shapes=[[4, 3, 8, 8], [4, 1]],
                dtypes=["float32", "int64"],
                int_high=9,
            )
            psum = fluid.layers.reduce_sum(pixel)
        exe = fluid.Executor(fluid.CPUPlace())
        a1, l1, s1 = exe.run(main, fetch_list=[pixel, label, psum])
        a2, l2, s2 = exe.run(main, fetch_list=[pixel, label, psum])
        assert a1.shape == (4, 3, 8, 8) and l1.shape == (4, 1)
        assert np.issubdtype(l1.dtype, np.integer)
        assert l1.min() >= 0 and l1.max() <= 9
        assert a1.min() >= 0.0 and a1.max() < 1.0
        # fresh draw every step
        assert float(np.ravel(s1)[0]) != float(np.ravel(s2)[0])

    def test_rejects_dynamic_shape(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with pytest.raises(ValueError):
                fluid.layers.random_data_generator(
                    shapes=[[-1, 3]], dtypes=["float32"]
                )

    def test_trains_resnet_block_no_feed(self):
        from paddle_tpu.models import resnet

        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 5
        startup.random_seed = 5
        with fluid.program_guard(main, startup):
            pixel, label = fluid.layers.random_data_generator(
                shapes=[[4, 3, 16, 16], [4, 1]],
                dtypes=["float32", "int64"],
                int_high=9,
            )
            pred = resnet.resnet_cifar10(pixel, 10, depth=8)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(input=pred, label=label)
            )
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(3):
            lv, = exe.run(main, feed={}, fetch_list=[loss])
        assert np.isfinite(float(np.ravel(lv)[0]))
