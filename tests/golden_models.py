"""The golden-regression model registry (shared by tools/make_goldens.py
and tests/test_golden_cpp.py).

Each entry builds a model's serving slice at a small, C++-interpreter-
friendly shape and supplies a seeded feed. Parameters are materialized
deterministically (paddle_tpu.testing.set_deterministic_params), so
(model code, param recipe, feed) fully determine the expected output —
which is what tests/golden/<name>.npz pins.
"""

import numpy as np

import paddle_tpu as fluid


def _img_feed(name, shape, seed):
    rng = np.random.RandomState(seed)
    return {name: rng.rand(*shape).astype("float32")}


def _mnist():
    from paddle_tpu.models import mnist

    _, feeds, outs = mnist.build()
    return ["pixel"], outs["predict"], _img_feed("pixel", (2, 1, 28, 28), 31)


def _resnet_cifar10():
    from paddle_tpu.models import resnet

    _, feeds, outs = resnet.build(img_shape=(3, 32, 32), class_num=10,
                                  variant="cifar10", depth=20)
    return ["pixel"], outs["predict"], _img_feed("pixel", (2, 3, 32, 32), 32)


def _vgg():
    from paddle_tpu.models import vgg

    _, feeds, outs = vgg.build(img_shape=(3, 32, 32), class_num=10)
    return ["pixel"], outs["predict"], _img_feed("pixel", (1, 3, 32, 32), 33)


def _googlenet():
    from paddle_tpu.models import googlenet

    _, feeds, outs = googlenet.build(img_shape=(3, 96, 96), class_num=10)
    return ["pixel"], outs["predict"], _img_feed("pixel", (1, 3, 96, 96), 34)


def _se_resnext():
    from paddle_tpu.models import se_resnext

    _, feeds, outs = se_resnext.build(img_shape=(3, 64, 64), class_num=10)
    return ["pixel"], outs["predict"], _img_feed("pixel", (1, 3, 64, 64), 35)


def _alexnet():
    from paddle_tpu.models import alexnet

    _, feeds, outs = alexnet.build(img_shape=(3, 224, 224), class_num=10)
    return ["pixel"], outs["predict"], _img_feed(
        "pixel", (1, 3, 224, 224), 36)


def _stacked_lstm():
    from paddle_tpu.models import stacked_lstm

    _, feeds, outs = stacked_lstm.build()
    rng = np.random.RandomState(37)
    names = [getattr(f, "name", f) for f in feeds]
    data_name, len_name = names[0], names[1]
    feed = {
        data_name: rng.randint(0, 100, (2, 16)).astype("int64"),
        len_name: np.asarray([[16], [9]], "int64"),
    }
    return [data_name, len_name], outs["predict"], feed


def _transformer():
    from paddle_tpu.models import transformer

    bs, seq, vocab = 2, 8, 60
    _, feeds, outs = transformer.build(
        src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq,
        n_layer=1, n_head=2, d_model=32, d_inner=64, dropout=0.0)
    rng = np.random.RandomState(38)
    feed = {
        "src_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "src_len": np.asarray([[seq], [seq - 3]], "int64"),
        "trg_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "trg_len": np.asarray([[seq], [seq - 2]], "int64"),
    }
    return (["src_word", "src_len", "trg_word", "trg_len"],
            outs["logits"], feed)


def _machine_translation():
    from paddle_tpu.models import machine_translation as mt

    bs, ts, tt = 2, 6, 5
    avg_cost, feeds, _ = mt.build(
        src_vocab=40, tgt_vocab=30, src_seq_len=ts, tgt_seq_len=tt,
        emb_dim=8, encoder_size=8, decoder_size=8)
    rng = np.random.RandomState(39)
    mask = np.ones((bs, tt), "float32")
    mask[1, 3:] = 0.0
    feed = {
        "source_sequence": rng.randint(1, 40, (bs, ts)).astype("int64"),
        "source_length": np.asarray([[ts], [ts - 2]], "int64"),
        "target_sequence": rng.randint(1, 30, (bs, tt)).astype("int64"),
        "label": rng.randint(1, 30, (bs, tt)).astype("int64"),
        "label_mask": mask,
    }
    return (["source_sequence", "source_length", "target_sequence",
             "label", "label_mask"], avg_cost, feed)


GOLDEN_MODELS = {
    "mnist": _mnist,
    "resnet_cifar10": _resnet_cifar10,
    "vgg16": _vgg,
    "googlenet": _googlenet,
    "se_resnext50": _se_resnext,
    "alexnet": _alexnet,
    "stacked_lstm": _stacked_lstm,
    "transformer": _transformer,
    "machine_translation": _machine_translation,
}


def build_golden(name):
    """Build ``name``'s serving slice with deterministic params in the
    CURRENT scope (callers wrap in their own
    ``fluid.scope_guard(Scope())`` to avoid leaking params process-wide).
    Returns (pruned_program, feed_names, fetch_var, feed, exe)."""
    from paddle_tpu.io import prune_program
    from paddle_tpu.testing import set_deterministic_params
    from paddle_tpu import unique_name

    # param seeds derive from variable NAMES: reset the unique-name
    # counters so the names (hence the seeds) are identical no matter
    # what was built earlier in the process
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        feed_names, fetch, feed = GOLDEN_MODELS[name]()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    set_deterministic_params(main, fluid.global_scope())
    pruned = prune_program(main.clone(for_test=True), feed_names,
                           [fetch.name])
    return pruned, feed_names, fetch, feed, exe


def _ssd():
    from paddle_tpu.models import ssd

    _, feeds, outs = ssd.build(img_shape=(3, 96, 96), class_num=4)
    rng = np.random.RandomState(40)
    feed = {"image": rng.rand(1, 3, 96, 96).astype("float32")}
    # pin the DENSE location head, not nmsed_out: NMS is a thresholded
    # top-k selection, so a near-tie flip would reorder rows wholesale
    # and break allclose without any real numerics regression
    return ["image"], outs["mbox_locs"], feed


def _switch_transformer():
    from paddle_tpu.models import switch_transformer

    bs, seq = 2, 12
    _, feeds, outs = switch_transformer.build(
        vocab_size=80, max_length=seq, n_layer=2, n_head=2, d_model=32,
        d_inner=64, num_experts=2, moe_every=2, dropout=0.0)
    rng = np.random.RandomState(41)
    feed = {
        "word": rng.randint(1, 80, (bs, seq)).astype("int64"),
        "seq_len": np.asarray([[seq], [seq - 4]], "int64"),
    }
    return ["word", "seq_len"], outs["logits"], feed


GOLDEN_MODELS["ssd"] = _ssd
GOLDEN_MODELS["switch_transformer"] = _switch_transformer

# models whose serving op set is beyond the C++ interpreter (dense
# detection ops / MoE dispatch): the golden pins the XLA engine only
# r5: empty — the SSD golden slice (pre-NMS head) ran in C++ all along,
# and the interpreter gained a moe_ffn kernel (Switch routing semantics
# mirrored loop-for-einsum); every committed golden now pins BOTH
# engines. Detection post-processing (multiclass_nms etc.) remains
# XLA-engine-only — no golden covers it, and the interpreter refuses
# those op types explicitly.
XLA_ONLY = set()
