"""Ragged paged-attention decode tests: kernel parity vs the composed
reference at ragged / non-page-multiple lengths, empty-slot safety,
O(page) pool writes, grid accounting proportional to RESIDENT pages,
and the paged SlotDecodeSession — staggered-admission greedy tokens
bit-identical to the dense slot decoder, page recycling across
release/readmit, pool-exhaustion admission control, seeded-sampler
replay determinism, and a zero-fresh-compile warm re-run of the
multi-token decode dispatch."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.core import exec_cache
from paddle_tpu.kernels import paged_attention as pa
from paddle_tpu.serving.generation import (
    NoFreePageError,
    NoFreeSlotError,
    Sampler,
    SlotDecodeSession,
)

VOCAB, SEQ, D = 24, 8, 32
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)


# -- kernel ------------------------------------------------------------------

def _pools(rng, S, H, dh, ps, npp, lengths):
    """Random pools + a ragged table: page 0 reserved (trash), each
    slot's tail aliased to its last valid page."""
    P = 1 + S * npp
    kp = rng.randn(P, H, ps, dh).astype("float32")
    vp = rng.randn(P, H, ps, dh).astype("float32")
    table = np.zeros((S, npp), np.int32)
    nxt = 1
    for s in range(S):
        n = pa.pages_for(lengths[s], ps)
        for p in range(n):
            table[s, p] = nxt
            nxt += 1
        for p in range(n, npp):
            table[s, p] = table[s, max(n - 1, 0)]
    return kp, vp, table


def test_kernel_parity_ragged_non_multiple_lengths():
    """interpret-mode Pallas kernel == composed reference at per-slot
    lengths that are ragged AND off the page grid (including a full
    slot and a single-token slot)."""
    import jax.numpy as jnp

    S, H, dh, ps, npp = 5, 2, 16, 4, 8
    lengths = np.array([7, 1, 32, 13, 30], np.int32)
    rng = np.random.RandomState(3)
    q = rng.randn(S, H, dh).astype("float32")
    kp, vp, table = _pools(rng, S, H, dh, ps, npp, lengths)
    ref = pa.paged_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(lengths))
    ker = pa.paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(lengths), force_pallas=True)
    np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_kernel_empty_slots_are_zero_not_nan():
    """A slot with NO resident tokens returns exactly 0 from both
    paths — softmax over an all-masked row is never NaN bait (the
    flash kernel's fully-masked-row contract extended to decode)."""
    import jax.numpy as jnp

    S, H, dh, ps, npp = 3, 2, 8, 4, 2
    lengths = np.array([0, 5, 0], np.int32)
    rng = np.random.RandomState(4)
    q = rng.randn(S, H, dh).astype("float32")
    kp, vp, table = _pools(rng, S, H, dh, ps, npp, lengths)
    for force in ("pallas", "reference"):
        out = np.asarray(pa.paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(lengths),
            force_pallas=force == "pallas",
            force_reference=force == "reference"))
        assert np.isfinite(out).all()
        assert np.abs(out[0]).max() == 0.0 and np.abs(out[2]).max() == 0.0
        assert np.abs(out[1]).max() > 0.0


def test_paged_kv_write_lands_in_page_and_trash_is_isolated():
    """The O(page) write puts each slot's row at
    (table[s, pos//ps], pos%ps) and leaves every other bit of the pool
    untouched; slots parked on the trash page can never corrupt a live
    slot's page."""
    import jax.numpy as jnp

    S, H, dh, ps, npp = 3, 2, 4, 4, 2
    lengths = np.array([6, 3, 0], np.int32)
    rng = np.random.RandomState(5)
    kp, vp, table = _pools(rng, S, H, dh, ps, npp, lengths)
    knew = rng.randn(S, H, dh).astype("float32")
    vnew = rng.randn(S, H, dh).astype("float32")
    # slots 0/1 write at their current length; slot 2 is unoccupied and
    # parked on the trash page (row 0)
    pos = np.array([5, 2, 0], np.int32)
    k2, v2 = pa.paged_kv_write(
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(knew),
        jnp.asarray(vnew), jnp.asarray(table), jnp.asarray(pos))
    k2, v2 = np.asarray(k2), np.asarray(v2)
    for s, p in ((0, 5), (1, 2)):
        page, off = table[s, p // ps], p % ps
        np.testing.assert_array_equal(k2[page, :, off, :], knew[s])
        np.testing.assert_array_equal(v2[page, :, off, :], vnew[s])
    # everything else bit-identical (trash page 0 excepted)
    mask = np.ones_like(kp, bool)
    mask[0] = False
    for s, p in ((0, 5), (1, 2)):
        mask[table[s, p // ps], :, p % ps, :] = False
    np.testing.assert_array_equal(k2[mask], kp[mask])
    np.testing.assert_array_equal(v2[mask], vp[mask])


def test_grid_accounting_scales_with_resident_pages():
    """The kernel's modeled HBM traffic follows pages actually
    RESIDENT, not S x max_length: half the resident tokens ~ half the
    KV bytes, and a low-occupancy pool moves a small fraction of the
    dense layout's traffic."""
    H, dh, ps, T = 2, 16, 4, 64
    lengths = [3, 17, 0, 0, 0, 0, 0, 0]
    acc = pa.grid_accounting(lengths, ps, H, dh, T)
    assert acc["valid_pages"] == pa.pages_for(3, ps) + pa.pages_for(17, ps)
    # raggedness: 6 pages of 128 page-slots -> far under the dense bytes
    assert acc["hbm_bytes"] < 0.1 * acc["dense_hbm_bytes"]
    # proportionality in the KV term: doubling resident pages doubles
    # the page traffic exactly
    acc2 = pa.grid_accounting([3, 17, 3, 17, 0, 0, 0, 0], ps, H, dh, T)
    page_bytes = acc["page_bytes"]
    assert (acc2["hbm_bytes"] - acc2["valid_pages"] * 2 * page_bytes
            == acc["hbm_bytes"] - acc["valid_pages"] * 2 * page_bytes)
    assert acc2["valid_pages"] == 2 * acc["valid_pages"]
    # dense bytes are occupancy-blind — identical for both loads
    assert acc2["dense_hbm_bytes"] == acc["dense_hbm_bytes"]


# -- session -----------------------------------------------------------------

@pytest.fixture(scope="module")
def trained(request):
    """One tiny trained transformer shared by every session test; the
    greedy oracle is the PR 8 dense slot decoder."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    startup.random_seed = 21
    from paddle_tpu.executor import global_scope
    from paddle_tpu.models import transformer

    # conftest swaps the global scope per test: capture THIS scope so
    # every test binds the same trained parameters through scope=...
    scope = global_scope()
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, max_length=SEQ,
            d_model=D, **CFG)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(22)
    for _ in range(30):
        src = rng.randint(3, VOCAB, (16, SEQ)).astype("int64")
        trg = np.full_like(src, 1)
        trg[:, 1:] = src[:, :-1]
        exe.run(main, feed={
            "src_word": src,
            "src_len": np.full((16, 1), SEQ, "int64"),
            "trg_word": trg,
            "trg_len": np.full((16, 1), SEQ, "int64"),
            "label": src,
        }, fetch_list=[loss])
    src = rng.randint(3, VOCAB, (5, SEQ)).astype("int64")
    src_len = np.asarray([[SEQ], [SEQ - 3], [SEQ - 1], [2], [SEQ]],
                         "int64")
    dense = SlotDecodeSession(exe, num_slots=3, max_length=SEQ,
                              d_model=D, scope=scope, **CFG)
    want = dense.generate(src, src_len)
    return {"exe": exe, "scope": scope, "src": src, "src_len": src_len,
            "want": want}


def _paged_session(trained, **kw):
    args = dict(num_slots=3, max_length=SEQ, d_model=D, paged=True,
                page_size=4, scope=trained["scope"])
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


def test_staggered_admissions_bit_identical_to_dense_decoder(trained):
    """The ORACLE: greedy tokens from the paged session under
    staggered mid-flight admissions are bit-identical to the PR 8
    dense slot decoder's."""
    sess = _paged_session(trained, steps=1)
    src, src_len, want = (trained["src"], trained["src_len"],
                          trained["want"])
    got = np.zeros_like(want)
    owner = {sess.admit(src[i], src_len[i]): i for i in range(3)}
    with pytest.raises(NoFreeSlotError):
        sess.admit(src[3], src_len[3])
    pending = [3, 4]
    steps = 0
    while owner or pending:
        while pending and sess.free_slots:
            i = pending.pop(0)
            owner[sess.admit(src[i], src_len[i])] = i
        for slot, tokens in sess.step().items():
            got[owner.pop(slot)] = tokens
        steps += 1
        assert steps < 100
    np.testing.assert_array_equal(got, want)
    assert sess.pages_in_use == 0  # everything recycled


def test_multi_token_dispatch_matches_and_reruns_warm(trained):
    """steps=K on-device scans produce the same tokens as per-token
    stepping, and a SECOND full batch through the warm session adds
    ZERO fresh compiles — the decode hot path is one cached multi-step
    executable plus the admit/table executables."""
    sess = _paged_session(trained, steps=4)
    got = sess.generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(got, trained["want"])
    before = exec_cache.stats()["fresh_compiles"]
    again = sess.generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(again, trained["want"])
    assert exec_cache.stats()["fresh_compiles"] == before, (
        "warm paged decode paid fresh compiles")


def test_pallas_kernel_in_session_matches_reference_impl(trained):
    """The whole session runs through the interpret-mode Pallas kernel
    (FLAGS_paged_attention=pallas) and produces the same greedy tokens
    as the composed-reference impl."""
    old = flags.get("paged_attention")
    flags.set_flag("paged_attention", "pallas")
    try:
        sess = _paged_session(trained, steps=2)
        got = sess.generate(trained["src"][:3], trained["src_len"][:3])
    finally:
        flags.set_flag("paged_attention", old)
    np.testing.assert_array_equal(got, trained["want"][:3])


def test_page_recycling_across_release_and_readmit(trained):
    """A pool sized for exactly the slot count keeps serving arbitrary
    request streams: completed sequences' pages are recycled into later
    admissions (B > slots > pages-at-once), and the free list returns
    to full when the pool drains."""
    sess = _paged_session(trained, steps=2,
                          num_pages=1 + 3 * pa.pages_for(SEQ, 4))
    total = sess.free_pages
    src = np.concatenate([trained["src"], trained["src"]], axis=0)
    src_len = np.concatenate([trained["src_len"], trained["src_len"]],
                             axis=0)
    want = np.concatenate([trained["want"], trained["want"]], axis=0)
    got = sess.generate(src, src_len)
    np.testing.assert_array_equal(got, want)
    assert sess.free_pages == total and sess.pages_in_use == 0


def test_pool_exhaustion_is_a_typed_admission_reject(trained):
    """An undersized pool rejects the admission whose WORST-CASE pages
    cannot be reserved (NoFreePageError), rolls the slot back, never
    wedges mid-flight (admitted sequences always provision), and the
    reservation is released on completion so a retry then succeeds."""
    # worst case is 2 pages per sequence; the pool holds exactly 2
    # allocatable — one sequence at a time, by reservation
    sess = _paged_session(trained, steps=1, num_pages=3)
    slot = sess.admit(trained["src"][0], trained["src_len"][0])
    free_before = sess.free_slots
    with pytest.raises(NoFreePageError):
        sess.admit(trained["src"][1], trained["src_len"][1])
    assert sess.free_slots == free_before  # rollback: slot not leaked
    out = {}
    while not out:
        out = sess.step()  # mid-flight provisioning must never raise
    np.testing.assert_array_equal(out[slot], trained["want"][0])
    assert sess.free_pages == 2  # pages recycled on completion
    # the reservation went with them: admission works again, and the
    # retried sequence decodes correctly through recycled pages
    slot2 = sess.admit(trained["src"][1], trained["src_len"][1])
    out = {}
    while not out:
        out = sess.step()
    np.testing.assert_array_equal(out[slot2], trained["want"][1])


def test_seeded_sampler_replay_is_bit_identical(trained):
    """Stochastic sampling (temperature / top-k) is deterministic
    under a fixed seed: a rebuilt session replays the exact token
    matrix, dispatch granularity notwithstanding (PRNG keys are
    (seed, slot, position), never the dispatch key)."""
    mk = lambda steps, strategy: _paged_session(
        trained, steps=steps,
        sampler=Sampler(strategy=strategy, temperature=0.8, top_k=3,
                        seed=11))
    a = mk(1, "top_k").generate(trained["src"], trained["src_len"])
    b = mk(4, "top_k").generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(a, b)
    c = mk(4, "temperature").generate(trained["src"], trained["src_len"])
    d = mk(2, "temperature").generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(c, d)
    # sampling actually happened (greedy and sampled streams differ)
    assert not np.array_equal(a, trained["want"]) or \
        not np.array_equal(c, trained["want"])
    # bos leads and every row terminates in the eos pad
    assert (a[:, 0] == 1).all()


def test_dense_fallback_fetches_token_ids_not_logits(trained):
    """Satellite: even the dense (reference-layout) session's step
    fetch is the [S, 1] device-selected token ids — the [S, 1, V]
    logits never cross the host boundary."""
    sess = SlotDecodeSession(trained["exe"], num_slots=2,
                             max_length=SEQ, d_model=D,
                             scope=trained["scope"], **CFG)
    sess.admit(trained["src"][0], trained["src_len"][0])
    fetched = sess._run(sess._step_prog, {
        "cur_tok": np.full((2, 1), 2, "int64"),
        "pe_row": np.zeros((2, 1, D), "float32"),
        "gen_pos": np.zeros((2, 1), "int64"),
    }, [sess._fetch_name])[0]
    assert np.asarray(fetched).shape == (2, 1)  # ids, not [S, 1, VOCAB]
    assert np.issubdtype(np.asarray(fetched).dtype, np.integer)
