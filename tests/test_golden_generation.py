"""Golden regression for the GENERATION stack: greedy decode over
deterministic weights must keep producing the committed token ids.

The per-step numerics goldens (tests/test_golden_cpp.py) pin the logits;
this pins everything above them — build_inference pruning, the
fixed-shape re-decode loop, argmax/eos handling — i.e. the deploy path a
reference user of the generation mode depends on. KV-cached and beam
decoding already have exact-parity tests against this path
(tests/test_attention.py), so one committed pin transitively anchors
all three decoders.

Regenerate deliberately: python tests/test_golden_generation.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid  # noqa: E402

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden", "transformer_greedy.npz")


def _generate():
    from paddle_tpu import unique_name
    from paddle_tpu.models import transformer
    from paddle_tpu.testing import set_deterministic_params

    unique_name.switch()
    bs, seq, vocab = 2, 10, 50
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        _, feeds, outs = transformer.build(
            src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq,
            n_layer=1, n_head=2, d_model=32, d_inner=64, dropout=0.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    set_deterministic_params(main, fluid.global_scope())
    infer = transformer.build_inference(main, outs["logits"])
    rng = np.random.RandomState(42)
    src = rng.randint(3, vocab, (bs, seq)).astype("int64")
    src_len = np.asarray([[seq], [seq - 4]], "int64")
    # eos_id=0 (the pad id, which argmax over random-ish logits never
    # emits) so the decode runs the FULL length and the pin covers every
    # step of the loop rather than an instant all-eos stop
    tokens = transformer.greedy_generate(
        exe, infer, outs["logits"], src, src_len, max_length=seq,
        eos_id=0)
    return src, src_len, np.asarray(tokens)


def test_greedy_generation_matches_committed_golden():
    src, src_len, tokens = _generate()
    assert os.path.exists(GOLDEN), (
        "missing committed golden %s — run this file as a script and "
        "commit the output" % GOLDEN)
    golden = np.load(GOLDEN)
    np.testing.assert_array_equal(src, golden["src"])
    np.testing.assert_array_equal(src_len, golden["src_len"])
    np.testing.assert_array_equal(
        tokens, golden["tokens"],
        err_msg="greedy decode drifted from the committed token ids")


if __name__ == "__main__":
    with fluid.scope_guard(fluid.executor.Scope()):
        src, src_len, tokens = _generate()
    np.savez_compressed(GOLDEN, src=src, src_len=src_len, tokens=tokens)
    print("wrote", GOLDEN, "tokens:\n", tokens)
