"""PagePool / PrefixCache unit + property tests (no device, no jax):
the refcounted allocator is proved as a UNIT under seeded random
admit/fork/release/prefix-hit drive — page conservation
(free + unique allocated == P - 1) at every step, copy-on-write
exclusivity (no page referenced by two sequences that both wrote past
the fork point), and NoFreePageError rollback leaving every count
unchanged. The device-level twin (real programs, real tokens) lives in
tests/test_kv_reuse.py."""

import numpy as np
import pytest

from paddle_tpu.serving.kv_pool import (
    NoFreePageError,
    PagePool,
    PrefixCache,
)

PS = 4  # page size for the host model


def test_acquire_ref_deref_conservation():
    pool = PagePool(8)  # 7 allocatable
    a = pool.acquire()
    b = pool.acquire()
    assert a != b and pool.free_count == 5 and pool.allocated_count == 2
    pool.ref(a)
    assert pool.refcount(a) == 2 and pool.shared_count == 1
    assert pool.extra_refs == 1
    assert pool.free_count + pool.allocated_count == 7  # sharing is free
    assert pool.deref(a) == 1
    assert pool.refcount(a) == 1 and pool.shared_count == 0
    assert pool.deref(a) == 0 and pool.refcount(a) == 0
    pool.deref(b)
    assert pool.free_count == 7 and pool.allocated_count == 0


def test_misuse_is_loud():
    pool = PagePool(3)
    with pytest.raises(ValueError):
        pool.ref(1)  # not allocated
    p = pool.acquire()
    pool.deref(p)
    with pytest.raises(ValueError):
        pool.deref(p)  # double free
    with pytest.raises(ValueError):
        PagePool(1)  # no allocatable page beside trash
    pool.acquire()
    pool.acquire()
    with pytest.raises(NoFreePageError):
        pool.acquire()


def test_acquire_reclaim_hook_evicts_cache():
    pool = PagePool(3)
    cache = PrefixCache(pool, PS, max_pages=4)
    a = pool.acquire()
    cache.insert("fp", (1, 2, 3, 4), [a])
    pool.deref(a)  # only the cache holds it now
    b = pool.acquire(cache.reclaim)  # free page exists: no eviction
    assert len(cache) == 1
    c = pool.acquire(cache.reclaim)  # pressure: cache page evicted
    assert len(cache) == 0 and {b, c} == {a, 2} or {b, c} == {1, 2}
    assert pool.free_count == 0 and pool.allocated_count == 2


def test_prefix_cache_trie_and_chain_eviction():
    pool = PagePool(16)
    cache = PrefixCache(pool, PS, max_pages=8)
    toks = (1, 5, 6, 7, 8, 9, 10, 11)  # two full pages at PS=4
    p0, p1 = pool.acquire(), pool.acquire()
    cache.insert("fp", toks, [p0, p1])
    assert cache.lookup("fp", toks) == [p0, p1]
    # a shorter prefix reuses only the chain it covers
    assert cache.lookup("fp", toks[:6]) == [p0]
    # a diverging prefix shares the first page, not the second
    assert cache.lookup("fp", toks[:4] + (99, 99, 99, 99)) == [p0]
    # another SOURCE shares nothing (prefix K/V depends on cross attn)
    assert cache.lookup("fp2", toks) == []
    # evicting the shallow entry evicts the orphaned deeper chain too
    cache._evict_chain(("fp", toks[:4]))
    assert len(cache) == 0
    assert pool.refcount(p0) == 1 and pool.refcount(p1) == 1  # ours


class _HostModel(object):
    """Host-side mirror of SlotDecodeSession's allocator discipline:
    sequences admit (reserve worst case), fork (reference a parent's
    prefix pages), write (COW any shared page first), release (deref).
    Tracks which sequences WROTE each page past their fork point so
    the exclusivity law is checkable."""

    def __init__(self, pool, npp):
        self.pool = pool
        self.npp = npp
        self.seqs = {}  # sid -> {"pages": [...], "written": set(idx)}
        self.reserved = 0
        self.next = 0
        self.writers = {}  # page -> set(sid) that wrote while owning

    def admit(self, cached=()):
        if self.reserved + self.npp > self.pool.num_pages - 1:
            raise NoFreePageError("reservation")
        self.reserved += self.npp
        sid = self.next
        self.next += 1
        pages = []
        for pg in cached:
            self.pool.ref(pg)
            pages.append(pg)
        self.seqs[sid] = {"pages": pages, "written": set()}
        return sid

    def fork(self, parent, upto):
        if self.reserved + self.npp > self.pool.num_pages - 1:
            raise NoFreePageError("reservation")
        self.reserved += self.npp
        sid = self.next
        self.next += 1
        pages = []
        for pg in self.seqs[parent]["pages"][:upto]:
            self.pool.ref(pg)
            pages.append(pg)
        self.seqs[sid] = {"pages": pages, "written": set()}
        return sid

    def write(self, sid, idx):
        st = self.seqs[sid]
        while len(st["pages"]) <= idx:
            if len(st["pages"]) >= self.npp:
                return
            st["pages"].append(self.pool.acquire())
        pg = st["pages"][idx]
        if self.pool.refcount(pg) > 1:  # COW
            dst = self.pool.acquire()
            st["pages"][idx] = dst
            self.pool.deref(pg)
            pg = dst
        st["written"].add(pg)
        self.writers.setdefault(pg, set()).add(sid)

    def release(self, sid):
        st = self.seqs.pop(sid)
        for pg in st["pages"]:
            if self.pool.deref(pg) == 0:
                self.writers.pop(pg, None)
        self.reserved -= self.npp

    def check(self):
        pool = self.pool
        assert pool.free_count + pool.allocated_count == \
            pool.num_pages - 1, "page conservation broken"
        # refcount integrity: every reference is accounted for
        refs = {}
        for st in self.seqs.values():
            for pg in st["pages"]:
                refs[pg] = refs.get(pg, 0) + 1
        for pg, c in refs.items():
            assert pool.refcount(pg) >= c
        # COW exclusivity: a page was never written by two sequences
        # (each live writer owned it privately at write time)
        for pg, sids in self.writers.items():
            live = sids & set(self.seqs)
            assert len(sids) <= 1 or len(live) <= 1, \
                "page %d written by concurrent sequences %s" % (pg, sids)
        # stronger: a LIVE slot never holds a written page another live
        # slot also wrote
        for sid, st in self.seqs.items():
            for other, ot in self.seqs.items():
                if other <= sid:
                    continue
                both = st["written"] & ot["written"]
                assert not both, \
                    "pages %s written past the fork by %d AND %d" \
                    % (both, sid, other)


def test_insert_never_creates_unreachable_chain_entries():
    """A cache smaller than a prefix's full-page count must degrade to
    caching the SHALLOW part of the chain, never a deeper entry whose
    predecessor was evicted (lookup could never reach it, so its page
    reference would be pinned forever)."""
    pool = PagePool(16)
    cache = PrefixCache(pool, PS, max_pages=2)
    toks = tuple(range(1, 13))  # three full pages at PS=4
    pages = [pool.acquire() for _ in range(3)]
    cache.insert("fp", toks, pages)
    # every surviving entry's predecessor chain is intact...
    for fp, t in list(cache._entries):
        depth = len(t)
        while depth > PS:
            depth -= PS
            assert (fp, t[:depth]) in cache._entries, \
                "unreachable entry (%s, depth %d)" % (fp, len(t))
    # ...and whatever was kept is actually reachable through lookup
    assert cache.lookup("fp", toks) == [
        cache._entries[k] for k in sorted(cache._entries,
                                          key=lambda k: len(k[1]))]
    # reference accounting: only reachable entries hold refs
    held = set(cache._entries.values())
    for pg in pages:
        assert pool.refcount(pg) == (2 if pg in held else 1)


def test_property_random_admit_fork_release_prefix():
    """Seeded random drive: 400 ops over a small pool + cache — now
    with SNAPSHOT/RESTORE interleaved (op 5: the allocator + trie are
    serialized through the decode-snapshot dialect's state_dict/
    from_state and the drive continues on the restored objects) — the
    conservation/exclusivity/rollback laws hold after every op AND
    across every restore."""
    rng = np.random.RandomState(1234)
    pool = PagePool(12)  # 11 allocatable
    npp = 3
    cache = PrefixCache(pool, PS, max_pages=4)
    model = _HostModel(pool, npp)
    cached_keys = []  # (fp, tokens) inserted so far
    restores = 0
    for opno in range(400):
        op = rng.randint(6)
        live = sorted(model.seqs)
        try:
            if op == 0:  # admit, maybe through a prefix-cache hit
                pages = []
                if cached_keys and rng.rand() < 0.5:
                    fp, toks = cached_keys[rng.randint(len(cached_keys))]
                    pages = cache.lookup(fp, toks)
                model.admit(pages)
            elif op == 1 and live:  # fork a live sequence
                parent = live[rng.randint(len(live))]
                upto = rng.randint(npp + 1)
                model.fork(parent, upto)
            elif op == 2 and live:  # write (forces COW on shared)
                sid = live[rng.randint(len(live))]
                model.write(sid, rng.randint(npp))
            elif op == 3 and live:  # release
                model.release(live[rng.randint(len(live))])
            elif op == 4 and live:  # cache a full page of a live seq
                sid = live[rng.randint(len(live))]
                st = model.seqs[sid]
                if st["pages"]:
                    fp = "fp%d" % rng.randint(3)
                    toks = tuple(rng.randint(2, 20, PS))
                    cache.insert(fp, toks, st["pages"][:1])
                    cached_keys.append((fp, toks))
            elif op == 5:  # snapshot/restore mid-drive: the allocator
                # and trie round-trip through the decode-snapshot
                # dialect (pool state carries ALL refcounts, including
                # the trie's; from_state re-refs nothing) and the drive
                # continues on the restored objects
                pool = PagePool.from_state(pool.state_dict())
                cache = PrefixCache.from_state(pool, cache.state_dict())
                model.pool = pool
                restores += 1
        except NoFreePageError:
            # the reject IS the property: counts must be unchanged by a
            # failed admission (checked below like every other op)
            pass
        model.check()
    assert restores > 0, "the drive never exercised a restore"
    # drain: release everything, clear the cache -> full free list
    for sid in sorted(model.seqs):
        model.release(sid)
    cache.clear()
    assert pool.free_count == pool.num_pages - 1
    assert pool.allocated_count == 0 and pool.extra_refs == 0


def test_state_dict_round_trip_is_exact_and_json_safe():
    """The decode-snapshot dialect: pool + trie serialize to plain JSON
    and rebuild EXACTLY — free-list order (recycling determinism),
    refcounts, LRU sequence, hit counters. A torn state (conservation
    broken, trie pointing at an unallocated page) fails loud."""
    import json

    pool = PagePool(8)
    a, b, c = pool.acquire(), pool.acquire(), pool.acquire()
    pool.ref(a)
    pool.deref(b)  # free-list order now non-trivial: [7..4, b]
    cache = PrefixCache(pool, PS, max_pages=4)
    cache.insert("fp", (1, 2, 3, 4), [c])
    cache.lookup("fp", (1, 2, 3, 4))
    cache.lookup("fp", (9, 9, 9, 9))

    pstate = json.loads(json.dumps(pool.state_dict()))
    cstate = json.loads(json.dumps(cache.state_dict()))
    pool2 = PagePool.from_state(pstate)
    cache2 = PrefixCache.from_state(pool2, cstate)
    assert pool2.state_dict() == pool.state_dict()
    assert cache2.state_dict() == cache.state_dict()
    assert pool2._free == pool._free  # exact order, not just set
    assert cache2.hit_rate == cache.hit_rate  # counters survive
    assert cache2.lookup("fp", (1, 2, 3, 4)) == [c]

    broken = dict(pstate, free=pstate["free"] + [a])  # conservation
    with pytest.raises(ValueError):
        PagePool.from_state(broken)
    with pytest.raises(ValueError):  # trie points at a free page
        PrefixCache.from_state(PagePool(8), cstate)


def test_reservation_rollback_leaves_counts_unchanged():
    pool = PagePool(7)  # 6 allocatable, npp=3 -> two sequences max
    model = _HostModel(pool, 3)
    a = model.admit()
    model.write(a, 0)
    b = model.admit()
    free, alloc, reserved = (pool.free_count, pool.allocated_count,
                             model.reserved)
    with pytest.raises(NoFreePageError):
        model.admit()
    assert (pool.free_count, pool.allocated_count, model.reserved) == \
        (free, alloc, reserved)
    model.release(a)
    model.release(b)
    c = model.admit()  # and the pool serves again after release
    model.write(c, 2)
    model.check()
