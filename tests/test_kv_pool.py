"""PagePool / PrefixCache unit + property tests (no device, no jax):
the refcounted allocator is proved as a UNIT under seeded random
admit/fork/release/prefix-hit drive — page conservation
(free + unique allocated == P - 1) at every step, copy-on-write
exclusivity (no page referenced by two sequences that both wrote past
the fork point), and NoFreePageError rollback leaving every count
unchanged. The device-level twin (real programs, real tokens) lives in
tests/test_kv_reuse.py."""

import numpy as np
import pytest

from paddle_tpu.serving.kv_pool import (
    NoFreePageError,
    PagePool,
    PrefixCache,
)

PS = 4  # page size for the host model


def test_acquire_ref_deref_conservation():
    pool = PagePool(8)  # 7 allocatable
    a = pool.acquire()
    b = pool.acquire()
    assert a != b and pool.free_count == 5 and pool.allocated_count == 2
    pool.ref(a)
    assert pool.refcount(a) == 2 and pool.shared_count == 1
    assert pool.extra_refs == 1
    assert pool.free_count + pool.allocated_count == 7  # sharing is free
    assert pool.deref(a) == 1
    assert pool.refcount(a) == 1 and pool.shared_count == 0
    assert pool.deref(a) == 0 and pool.refcount(a) == 0
    pool.deref(b)
    assert pool.free_count == 7 and pool.allocated_count == 0


def test_misuse_is_loud():
    pool = PagePool(3)
    with pytest.raises(ValueError):
        pool.ref(1)  # not allocated
    p = pool.acquire()
    pool.deref(p)
    with pytest.raises(ValueError):
        pool.deref(p)  # double free
    with pytest.raises(ValueError):
        PagePool(1)  # no allocatable page beside trash
    pool.acquire()
    pool.acquire()
    with pytest.raises(NoFreePageError):
        pool.acquire()


def test_acquire_reclaim_hook_evicts_cache():
    pool = PagePool(3)
    cache = PrefixCache(pool, PS, max_pages=4)
    a = pool.acquire()
    cache.insert("fp", (1, 2, 3, 4), [a])
    pool.deref(a)  # only the cache holds it now
    b = pool.acquire(cache.reclaim)  # free page exists: no eviction
    assert len(cache) == 1
    c = pool.acquire(cache.reclaim)  # pressure: cache page evicted
    assert len(cache) == 0 and {b, c} == {a, 2} or {b, c} == {1, 2}
    assert pool.free_count == 0 and pool.allocated_count == 2


def test_prefix_cache_trie_and_chain_eviction():
    pool = PagePool(16)
    cache = PrefixCache(pool, PS, max_pages=8)
    toks = (1, 5, 6, 7, 8, 9, 10, 11)  # two full pages at PS=4
    p0, p1 = pool.acquire(), pool.acquire()
    cache.insert("fp", toks, [p0, p1])
    assert cache.lookup("fp", toks) == [p0, p1]
    # a shorter prefix reuses only the chain it covers
    assert cache.lookup("fp", toks[:6]) == [p0]
    # a diverging prefix shares the first page, not the second
    assert cache.lookup("fp", toks[:4] + (99, 99, 99, 99)) == [p0]
    # another SOURCE shares nothing (prefix K/V depends on cross attn)
    assert cache.lookup("fp2", toks) == []
    # evicting the shallow entry evicts the orphaned deeper chain too
    cache._evict_chain(("fp", toks[:4]))
    assert len(cache) == 0
    assert pool.refcount(p0) == 1 and pool.refcount(p1) == 1  # ours


class _HostModel(object):
    """Host-side mirror of SlotDecodeSession's allocator discipline:
    sequences admit (reserve worst case), fork (reference a parent's
    prefix pages), write (COW any shared page first), release (deref).
    Tracks which sequences WROTE each page past their fork point so
    the exclusivity law is checkable."""

    def __init__(self, pool, npp):
        self.pool = pool
        self.npp = npp
        self.seqs = {}  # sid -> {"pages": [...], "written": set(idx)}
        self.reserved = 0
        self.next = 0
        self.writers = {}  # page -> set(sid) that wrote while owning

    def admit(self, cached=()):
        if self.reserved + self.npp > self.pool.num_pages - 1:
            raise NoFreePageError("reservation")
        self.reserved += self.npp
        sid = self.next
        self.next += 1
        pages = []
        for pg in cached:
            self.pool.ref(pg)
            pages.append(pg)
        self.seqs[sid] = {"pages": pages, "written": set()}
        return sid

    def fork(self, parent, upto):
        if self.reserved + self.npp > self.pool.num_pages - 1:
            raise NoFreePageError("reservation")
        self.reserved += self.npp
        sid = self.next
        self.next += 1
        pages = []
        for pg in self.seqs[parent]["pages"][:upto]:
            self.pool.ref(pg)
            pages.append(pg)
        self.seqs[sid] = {"pages": pages, "written": set()}
        return sid

    def write(self, sid, idx):
        st = self.seqs[sid]
        while len(st["pages"]) <= idx:
            if len(st["pages"]) >= self.npp:
                return
            st["pages"].append(self.pool.acquire())
        pg = st["pages"][idx]
        if self.pool.refcount(pg) > 1:  # COW
            dst = self.pool.acquire()
            st["pages"][idx] = dst
            # the writer moved off pg: it no longer HOLDS the page, so
            # its write claim goes with it (the remaining holder may
            # legitimately become sole owner and write in place later)
            st["written"].discard(pg)
            w = self.writers.get(pg)
            if w is not None:
                w.discard(sid)
            self.pool.deref(pg)
            pg = dst
        st["written"].add(pg)
        self.writers.setdefault(pg, set()).add(sid)

    def release(self, sid):
        st = self.seqs.pop(sid)
        for pg in st["pages"]:
            if self.pool.deref(pg) == 0:
                self.writers.pop(pg, None)
        self.reserved -= self.npp

    def reorder(self, sids, perm):
        """Beam hypothesis reorder as REFCOUNT REBINDS (the PR 15
        zero-copy path): hypothesis ``i`` adopts ``sids[perm[i]]``'s
        page list by reference — ref every adopted page FIRST, then
        deref every pre-reorder list, so no page transits refcount 0
        mid-reorder. A pure permutation nets every count unchanged
        (zero pages move, zero free); duplicated parents leave pages
        shared until ``write`` COWs them; dropped hypotheses' private
        tails free. Adopters continue their PARENT's lineage, so the
        reordered sids' write ownership resets — future writes re-claim
        pages one COW at a time."""
        old = [list(self.seqs[s]["pages"]) for s in sids]
        for p in perm:
            for pg in old[p]:
                self.pool.ref(pg)
        for lst in old:
            for pg in lst:
                if self.pool.deref(pg) == 0:
                    self.writers.pop(pg, None)
        for i, s in enumerate(sids):
            self.seqs[s]["pages"] = list(old[perm[i]])
            for pg in self.seqs[s]["written"]:
                w = self.writers.get(pg)
                if w is not None:
                    w.discard(s)
            self.seqs[s]["written"] = set()

    def speculate(self, sid, span):
        """Open a speculative verify window (PR 16): the k + 1 node
        tree writes the sequence's CURRENT write page plus up to
        ``span - 1`` grown pages — every page in the window is COWed /
        acquired BEFORE the dispatch (shared pages are read-only), and
        the indices grown FOR the window are remembered so
        :meth:`resolve_speculation` can return rejected growth to the
        free list. Growth is append-by-append, so a mid-window
        ``NoFreePageError`` leaves a shorter (still accounted) window,
        never a torn one."""
        st = self.seqs[sid]
        spec = st.setdefault("spec", [])
        start = len(st["pages"])
        if start:  # the anchor's write page is part of the window
            self.write(sid, start - 1)
        for idx in range(start, min(start + span - 1, self.npp)):
            self.write(sid, idx)  # acquires the page, claims the write
            spec.append(idx)

    def resolve_speculation(self, sid, accepted):
        """Close the window: keep ``accepted`` of the grown pages as
        ordinary committed storage, return the REJECTED tail to the
        free list — rejected branches' rows die with their pages."""
        st = self.seqs[sid]
        grown = st.pop("spec", [])
        for _ in grown[accepted:]:
            pg = st["pages"].pop()
            st["written"].discard(pg)
            w = self.writers.get(pg)
            if w is not None:
                w.discard(sid)
            if self.pool.deref(pg) == 0:
                self.writers.pop(pg, None)

    def check(self):
        pool = self.pool
        assert pool.free_count + pool.allocated_count == \
            pool.num_pages - 1, "page conservation broken"
        # refcount integrity: every reference is accounted for
        refs = {}
        for st in self.seqs.values():
            for pg in st["pages"]:
                refs[pg] = refs.get(pg, 0) + 1
        for pg, c in refs.items():
            assert pool.refcount(pg) >= c
        # COW exclusivity: a page was never written by two sequences
        # (each live writer owned it privately at write time)
        for pg, sids in self.writers.items():
            live = sids & set(self.seqs)
            assert len(sids) <= 1 or len(live) <= 1, \
                "page %d written by concurrent sequences %s" % (pg, sids)
        # stronger: a LIVE slot never holds a written page another live
        # slot also wrote
        for sid, st in self.seqs.items():
            for other, ot in self.seqs.items():
                if other <= sid:
                    continue
                both = st["written"] & ot["written"]
                assert not both, \
                    "pages %s written past the fork by %d AND %d" \
                    % (both, sid, other)
        # speculative windows: the grown pages are the sequence's
        # contiguous TAIL and privately owned (the pre-dispatch COW
        # discipline — tree rows never land on a shared page)
        for sid, st in self.seqs.items():
            spec = st.get("spec") or []
            n = len(st["pages"])
            assert spec == list(range(n - len(spec), n)), \
                "speculation window of %d is not its page-list tail" \
                % sid
            for idx in spec:
                assert pool.refcount(st["pages"][idx]) == 1, \
                    "speculated page %d of %d is shared" \
                    % (st["pages"][idx], sid)


def test_insert_never_creates_unreachable_chain_entries():
    """A cache smaller than a prefix's full-page count must degrade to
    caching the SHALLOW part of the chain, never a deeper entry whose
    predecessor was evicted (lookup could never reach it, so its page
    reference would be pinned forever)."""
    pool = PagePool(16)
    cache = PrefixCache(pool, PS, max_pages=2)
    toks = tuple(range(1, 13))  # three full pages at PS=4
    pages = [pool.acquire() for _ in range(3)]
    cache.insert("fp", toks, pages)
    # every surviving entry's predecessor chain is intact...
    for fp, t in list(cache._entries):
        depth = len(t)
        while depth > PS:
            depth -= PS
            assert (fp, t[:depth]) in cache._entries, \
                "unreachable entry (%s, depth %d)" % (fp, len(t))
    # ...and whatever was kept is actually reachable through lookup
    assert cache.lookup("fp", toks) == [
        cache._entries[k] for k in sorted(cache._entries,
                                          key=lambda k: len(k[1]))]
    # reference accounting: only reachable entries hold refs
    held = set(cache._entries.values())
    for pg in pages:
        assert pool.refcount(pg) == (2 if pg in held else 1)


def test_property_random_admit_fork_release_prefix():
    """Seeded random drive: 600 ops over a small pool + cache — with
    SNAPSHOT/RESTORE interleaved (op 5: the allocator + trie are
    serialized through the decode-snapshot dialect's state_dict/
    from_state and the drive continues on the restored objects) and
    the PR 15 BEAM ops (op 6 fork-K: a lane of K hypotheses
    referencing one parent's pages; op 7 reorder-permutation: the
    zero-copy rebind, with duplicating/dropping perms; op 8
    drop-hypothesis: one lane member cancels) AND the PR 16
    SPECULATIVE ops (op 9 speculate: COW/grow a k + 1 page window
    before the verify dispatch; op 10 resolve: commit a random prefix
    of the window and return every rejected page to the free list) —
    the conservation/exclusivity/rollback laws hold after every op AND
    across every restore, rejected branches really do return to the
    free list, and no page is ever double-written past a fork."""
    rng = np.random.RandomState(1234)
    pool = PagePool(24)  # 23 allocatable
    npp = 3
    cache = PrefixCache(pool, PS, max_pages=4)
    model = _HostModel(pool, npp)
    cached_keys = []  # (fp, tokens) inserted so far
    lanes = []        # beam lanes: lists of sids reordered together
    restores = reorders = pure_perms = 0
    speculations = rejected_pages = 0
    for opno in range(600):
        # beam ops weighted up: a lane must exist before a reorder can
        # fire, and fork-K's K x npp reservation rejects often on a
        # small pool — the drive needs the extra attempts
        op = [0, 1, 2, 3, 4, 5, 6, 6, 7, 7, 7, 8,
              9, 9, 9, 10, 10, 10][rng.randint(18)]
        live = sorted(model.seqs)
        # a lane survives as its LIVE members (a released/cancelled
        # hypothesis leaves the lattice; the rest keep reordering)
        lanes = [[s for s in ln if s in model.seqs] for ln in lanes]
        lanes = [ln for ln in lanes if len(ln) > 1]
        # a slot with an OPEN speculation window is mid-dispatch: the
        # session never interleaves host writes/forks/reorders with an
        # in-flight verify (and beam never composes with speculation),
        # so those ops draw from the spec-free live set
        inflight = [s for s in live if model.seqs[s].get("spec")]
        specfree = [s for s in live if not model.seqs[s].get("spec")]
        idle = [s for s in specfree
                if not any(s in ln for ln in lanes)]
        try:
            if op == 0:  # admit, maybe through a prefix-cache hit
                pages = []
                if cached_keys and rng.rand() < 0.5:
                    fp, toks = cached_keys[rng.randint(len(cached_keys))]
                    pages = cache.lookup(fp, toks)
                model.admit(pages)
            elif op == 1 and specfree:  # fork a live sequence
                parent = specfree[rng.randint(len(specfree))]
                upto = rng.randint(npp + 1)
                model.fork(parent, upto)
            elif op == 2 and specfree:  # write (forces COW on shared)
                sid = specfree[rng.randint(len(specfree))]
                model.write(sid, rng.randint(npp))
            elif op == 3 and live:  # release (cancel, even mid-window)
                model.release(live[rng.randint(len(live))])
            elif op == 4 and specfree:  # cache a page of a live seq
                sid = specfree[rng.randint(len(specfree))]
                st = model.seqs[sid]
                if st["pages"]:
                    fp = "fp%d" % rng.randint(3)
                    toks = tuple(rng.randint(2, 20, PS))
                    cache.insert(fp, toks, st["pages"][:1])
                    cached_keys.append((fp, toks))
            elif op == 5:  # snapshot/restore mid-drive: the allocator
                # and trie round-trip through the decode-snapshot
                # dialect (pool state carries ALL refcounts, including
                # the trie's; from_state re-refs nothing) and the drive
                # continues on the restored objects
                pool = PagePool.from_state(pool.state_dict())
                cache = PrefixCache.from_state(pool, cache.state_dict())
                model.pool = pool
                restores += 1
            elif op == 6 and specfree:  # beam fork-K: K hypotheses off
                # one parent (each a reservation-checked fork
                # referencing the parent's whole list — the beam
                # admission shape)
                parent = specfree[rng.randint(len(specfree))]
                upto = len(model.seqs[parent]["pages"])
                K = 2 + rng.randint(2)
                lane = [parent]
                for _ in range(K - 1):
                    lane.append(model.fork(parent, upto))
                lanes.append(lane)
            elif op == 7 and lanes:  # beam reorder: rebind refcounts
                # along a random parent map (duplicates drop losers,
                # repeats share winners; sometimes a pure permutation)
                lane = lanes[rng.randint(len(lanes))]
                K = len(lane)
                if rng.rand() < 0.4:  # pure permutation: zero net moves
                    perm = list(rng.permutation(K))
                    free0, alloc0 = pool.free_count, pool.allocated_count
                    model.reorder(lane, perm)
                    # THE zero-copy law: a pure permutation allocates
                    # nothing, frees nothing, copies nothing
                    assert (pool.free_count, pool.allocated_count) == \
                        (free0, alloc0)
                    pure_perms += 1
                else:
                    perm = [rng.randint(K) for _ in range(K)]
                    model.reorder(lane, perm)
                reorders += 1
            elif op == 8 and lanes:  # drop-hypothesis (cancel path)
                lane = lanes[rng.randint(len(lanes))]
                model.release(lane[rng.randint(len(lane))])
            elif op == 9 and idle:  # speculate: open a verify window
                sid = idle[rng.randint(len(idle))]
                model.speculate(sid, 1 + rng.randint(3))
                speculations += 1
            elif op == 10 and inflight:  # accept/reject resolution
                sid = inflight[rng.randint(len(inflight))]
                grown = len(model.seqs[sid]["spec"])
                accepted = rng.randint(grown + 1)
                free0 = pool.free_count
                model.resolve_speculation(sid, accepted)
                # every rejected page is back on the free list NOW —
                # rejected branches never linger as allocated garbage
                assert pool.free_count == free0 + (grown - accepted)
                rejected_pages += grown - accepted
        except NoFreePageError:
            # the reject IS the property: counts must be unchanged by a
            # failed admission (checked below like every other op)
            pass
        model.check()
    assert restores > 0, "the drive never exercised a restore"
    assert reorders > 5 and pure_perms > 0, \
        "the drive never exercised beam reorders (%d/%d)" \
        % (reorders, pure_perms)
    assert speculations > 5 and rejected_pages > 0, (
        "the drive never exercised speculation (%d windows, %d pages "
        "rejected)" % (speculations, rejected_pages))
    # drain: release everything, clear the cache -> full free list
    for sid in sorted(model.seqs):
        model.release(sid)
    cache.clear()
    assert pool.free_count == pool.num_pages - 1
    assert pool.allocated_count == 0 and pool.extra_refs == 0


def test_state_dict_round_trip_is_exact_and_json_safe():
    """The decode-snapshot dialect: pool + trie serialize to plain JSON
    and rebuild EXACTLY — free-list order (recycling determinism),
    refcounts, LRU sequence, hit counters. A torn state (conservation
    broken, trie pointing at an unallocated page) fails loud."""
    import json

    pool = PagePool(8)
    a, b, c = pool.acquire(), pool.acquire(), pool.acquire()
    pool.ref(a)
    pool.deref(b)  # free-list order now non-trivial: [7..4, b]
    cache = PrefixCache(pool, PS, max_pages=4)
    cache.insert("fp", (1, 2, 3, 4), [c])
    cache.lookup("fp", (1, 2, 3, 4))
    cache.lookup("fp", (9, 9, 9, 9))

    pstate = json.loads(json.dumps(pool.state_dict()))
    cstate = json.loads(json.dumps(cache.state_dict()))
    pool2 = PagePool.from_state(pstate)
    cache2 = PrefixCache.from_state(pool2, cstate)
    assert pool2.state_dict() == pool.state_dict()
    assert cache2.state_dict() == cache.state_dict()
    assert pool2._free == pool._free  # exact order, not just set
    assert cache2.hit_rate == cache.hit_rate  # counters survive
    assert cache2.lookup("fp", (1, 2, 3, 4)) == [c]

    broken = dict(pstate, free=pstate["free"] + [a])  # conservation
    with pytest.raises(ValueError):
        PagePool.from_state(broken)
    with pytest.raises(ValueError):  # trie points at a free page
        PrefixCache.from_state(PagePool(8), cstate)


def test_reservation_rollback_leaves_counts_unchanged():
    pool = PagePool(7)  # 6 allocatable, npp=3 -> two sequences max
    model = _HostModel(pool, 3)
    a = model.admit()
    model.write(a, 0)
    b = model.admit()
    free, alloc, reserved = (pool.free_count, pool.allocated_count,
                             model.reserved)
    with pytest.raises(NoFreePageError):
        model.admit()
    assert (pool.free_count, pool.allocated_count, model.reserved) == \
        (free, alloc, reserved)
    model.release(a)
    model.release(b)
    c = model.admit()  # and the pool serves again after release
    model.write(c, 2)
    model.check()
