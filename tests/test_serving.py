"""Continuous-batching serving engine tests: bit-exact batched parity
across mixed bucket shapes, admission control + deadline paths,
multi-tenant clones sharing one executable under concurrent load, the
slot-paged generation session's staggered-admission parity, the
FetchHandle deadline primitive, and the L001 bucket-ladder helper."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.analysis.lint import suggest_buckets
from paddle_tpu.core import exec_cache
from paddle_tpu.executor import FetchHandle, FetchTimeoutError
from paddle_tpu.inference import NativeConfig, create_paddle_predictor
from paddle_tpu.serving import (
    BatchingServer,
    DeadlineExceededError,
    QueueFullError,
    ServerClosedError,
    ServingError,
    loadgen,
)


@pytest.fixture(scope="module")
def demo_predictor(tmp_path_factory):
    # module-scoped: one train+save serves every server test (servers
    # clone it; weights are never written after load)
    path = str(tmp_path_factory.mktemp("serving") / "model")
    loadgen.build_demo_model(path)
    return create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))


# -- bucketed batching: parity ----------------------------------------------

def test_batched_results_bit_identical_across_mixed_buckets(
        demo_predictor):
    """Requests of every odd batch size, submitted concurrently so they
    coalesce into padded bucket batches, come back BIT-identical to the
    per-request run: raw ``Predictor.run`` for on-rung sizes, and the
    same request alone through the pad-to-rung policy
    (``run_reference``) for the rest — coalescing is numerically
    invisible either way."""
    server = BatchingServer(demo_predictor, max_batch=8, workers=2,
                            batch_linger_s=0.01)
    try:
        requests = loadgen.demo_requests(24)
        futures = [server.submit(r) for r in requests]
        got = [f.result(timeout=30) for f in futures]
        rungs = set(server.stats()["batch_buckets"])
        for req, outs in zip(requests, got):
            want = server.run_reference(req)
            assert len(outs) == len(want)
            for g, w in zip(outs, want):
                np.testing.assert_array_equal(np.asarray(g),
                                              np.asarray(w))
            assert outs[0].shape[0] == req["x"].shape[0]  # pad sliced off
            if req["x"].shape[0] in rungs:
                # on-rung: ALSO bit-identical to the raw per-request run
                for g, w in zip(outs, demo_predictor.run(req)):
                    np.testing.assert_array_equal(np.asarray(g),
                                                  np.asarray(w))
    finally:
        server.close()


def test_mixed_sizes_resolve_to_ladder_and_stop_compiling(
        demo_predictor):
    """After warmup over the bucket ladder, a mixed-batch-size load adds
    ZERO fresh compiles — the L001 mitigation, measured at the
    exec-cache counters the CI smoke scrapes."""
    server = BatchingServer(demo_predictor, max_batch=8, workers=1)
    try:
        assert server.warmup() == [2, 4, 8]
        before = exec_cache.stats()["fresh_compiles"]
        wall, ok, errors = loadgen.replay(
            server, loadgen.demo_requests(32), concurrency=4)
        assert ok == 32 and not errors
        assert exec_cache.stats()["fresh_compiles"] == before, (
            "steady-state mixed load paid fresh compiles")
        st = server.stats()
        assert st["batches"] >= 1
        assert st["latency_ms"]["p99_ms"] is not None
    finally:
        server.close()


def test_clone_multitenant_share_one_executable_under_load(
        demo_predictor):
    """4 worker threads = 4 Predictor clones; the content-addressed
    registry means the whole fleet compiles each bucket shape once."""
    server = BatchingServer(demo_predictor, max_batch=8, workers=4,
                            batch_linger_s=0.001)
    try:
        server.warmup()
        before = exec_cache.stats()["fresh_compiles"]
        wall, ok, errors = loadgen.replay(
            server, loadgen.demo_requests(48), concurrency=8)
        assert ok == 48 and not errors
        assert exec_cache.stats()["fresh_compiles"] == before
    finally:
        server.close()


# -- admission control -------------------------------------------------------

def test_queue_full_rejects_with_typed_error(demo_predictor):
    # a long linger below max_batch rows keeps the dispatcher holding
    # the batch open, so the queue observably fills
    server = BatchingServer(demo_predictor, max_batch=8,
                            max_queue_depth=2, batch_linger_s=5.0)
    try:
        f1 = server.submit({"x": np.zeros((1, 12), "float32")})
        f2 = server.submit({"x": np.zeros((1, 12), "float32")})
        with pytest.raises(QueueFullError):
            server.submit({"x": np.zeros((1, 12), "float32")})
        server.close(drain=True)  # drain skips the linger
        assert len(f1.result(timeout=30)[0]) == 1
        assert f2.done()
    finally:
        server.close()


def test_deadline_lapses_in_queue(demo_predictor):
    """A deadlined request stuck BEHIND a slow batch (the single worker
    is busy) is expired from the queue, never dispatched."""

    class SlowRun(object):
        def __init__(self, real):
            self._real = real
            self.feed_names = real.feed_names
            self.feed_shapes = real.feed_shapes

        def clone(self):
            return self

        def run(self, inputs):
            return self._real.run(inputs)

        def run_async(self, inputs):
            time.sleep(0.3)  # the worker is wedged on this batch
            return self._real.run_async(inputs)

    server = BatchingServer(SlowRun(demo_predictor), max_batch=8,
                            batch_linger_s=0.0, workers=1)
    try:
        first = server.submit({"x": np.zeros((1, 12), "float32")})
        fut = server.submit({"x": np.zeros((1, 12), "float32")},
                            deadline_s=0.05)
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=30)
        assert len(first.result(timeout=30)[0]) == 1
    finally:
        server.close(drain=False)


def test_deadline_lapses_in_flight(demo_predictor):
    """A dispatched batch that outlives its latest deadline is rejected
    through FetchHandle.result(timeout=...) — the server stays live."""

    class SlowHandle(object):
        def __init__(self, inner):
            self._inner = inner

        def result(self, timeout=None):
            if timeout is not None:
                # device never 'ready' inside the deadline
                time.sleep(timeout)
                raise FetchTimeoutError(timeout, ["out"])
            return self._inner.result()

    class SlowPredictor(object):
        def __init__(self, real):
            self._real = real
            self.feed_names = real.feed_names
            self.feed_shapes = real.feed_shapes

        def clone(self):
            return self

        def run(self, inputs):
            return self._real.run(inputs)

        def run_async(self, inputs):
            return SlowHandle(self._real.run_async(inputs))

    server = BatchingServer(SlowPredictor(demo_predictor), max_batch=8,
                            batch_linger_s=0.2)
    try:
        # both requests coalesce into ONE batch (the linger holds it):
        # the deadlined one must be rejected, the patient one must NOT
        # be collateral damage — the reusable handle serves it late
        patient = np.ones((1, 12), "float32")
        fut_patient = server.submit({"x": patient})
        fut_deadline = server.submit({"x": np.zeros((2, 12), "float32")},
                                     deadline_s=0.05)
        with pytest.raises(DeadlineExceededError):
            fut_deadline.result(timeout=30)
        out = fut_patient.result(timeout=30)
        np.testing.assert_array_equal(
            out[0], server.run_reference({"x": patient})[0])
        # the server survived: a fresh request still serves
        out = server.run({"x": np.ones((1, 12), "float32")})
        assert out[0].shape == (1, 3)
    finally:
        server.close()


def test_submit_validation_and_close_semantics(demo_predictor):
    server = BatchingServer(demo_predictor, max_batch=4)
    with pytest.raises(ServingError):
        server.submit({"x": np.zeros((5, 12), "float32")})  # > max_batch
    with pytest.raises(ServingError):
        server.submit({"wrong": np.zeros((1, 12), "float32")})
    with pytest.raises(ServingError):
        server.submit({"x": np.zeros((1, 7), "float32")})  # bad dim
    # positional (list) form works
    out = server.run([np.zeros((2, 12), "float32")])
    assert out[0].shape == (2, 3)
    server.close()
    with pytest.raises(ServerClosedError):
        server.submit({"x": np.zeros((1, 12), "float32")})


def test_pad_buckets_group_dynamic_lengths():
    """pad_buckets pads non-batch DYNAMIC dims up their ladder BEFORE
    grouping, so two different user lengths share one bucket signature
    (one executable). Mechanical check through the admission path, over
    a stub predictor declaring a variable-length feed."""

    class StubPredictor(object):
        feed_names = ["x"]
        feed_shapes = {"x": (-1, -1)}  # L001's classic dynamic dim

        def clone(self):
            return self

    ladders = ((1,), (8, 16))  # dim 1 buckets at 8 then 16
    server = BatchingServer(StubPredictor(), max_batch=4,
                            pad_buckets={"x": ladders})
    try:
        a, _ = server._normalize({"x": np.ones((1, 5), "float32")})
        b, _ = server._normalize({"x": np.ones((1, 8), "float32")})
        a = server._pad_request(a)
        b = server._pad_request(b)
        assert a["x"].shape == b["x"].shape == (1, 8)
        assert a["x"][0, 5:].sum() == 0  # padded with pad_value
        with pytest.raises(ServingError):
            server._pad_request(
                {"x": np.ones((1, 17), "float32")})  # above ladder top
    finally:
        server.close()


def test_deadline_inside_linger_dispatches_early(demo_predictor):
    """A request whose deadline lands inside the linger window must be
    DISPATCHED at once, not held open until it can only be rejected."""
    server = BatchingServer(demo_predictor, max_batch=8,
                            batch_linger_s=2.0)
    try:
        out = server.submit({"x": np.zeros((1, 12), "float32")},
                            deadline_s=0.5).result(timeout=30)
        assert out[0].shape == (1, 3)  # served, not deadline-rejected
    finally:
        server.close()


def test_warmup_covers_every_pad_rung(demo_predictor):
    """warmup compiles each pad-ladder rung (cartesian with the batch
    ladder), so lower rungs aren't left cold."""

    class ShapeRecorder(object):
        feed_names = ["x"]
        feed_shapes = {"x": (-1, -1)}
        feed_dtypes = {"x": "float32"}

        def __init__(self):
            self.shapes = []

        def clone(self):
            return self

        def run(self, inputs):
            self.shapes.append(inputs["x"].shape)
            return [np.zeros((inputs["x"].shape[0], 2), "float32")]

    rec = ShapeRecorder()
    server = BatchingServer(rec, max_batch=4,
                            pad_buckets={"x": ((1,), (4, 8))})
    try:
        server.warmup()
        assert set(rec.shapes) == {
            (b, d) for b in (2, 4) for d in (4, 8)}
    finally:
        server.close()


def test_batch_reduced_fetch_is_a_typed_error(demo_predictor):
    """A fetch whose leading dim isn't the batch rows cannot be sliced
    per request — the server must say so, not return garbage."""

    class PooledPredictor(object):
        feed_names = ["x"]
        feed_shapes = {"x": (-1, 12)}

        def clone(self):
            return self

        def run(self, inputs):
            return [inputs["x"].sum(axis=0, keepdims=True)]  # [1, 12]

        def run_async(self, inputs):
            outs = self.run(inputs)

            class H(object):
                def result(self, timeout=None):
                    return outs

            return H()

    server = BatchingServer(PooledPredictor(), max_batch=4)
    try:
        with pytest.raises(ServingError, match="leading dim"):
            server.run({"x": np.ones((2, 12), "float32")})
        with pytest.raises(ServingError, match="leading dim"):
            server.run_reference({"x": np.ones((2, 12), "float32")})
    finally:
        server.close()


# -- FetchHandle deadline primitive -----------------------------------------

class _LazyArray(object):
    """Array-like whose readiness the test controls."""

    def __init__(self, value):
        self._value = value
        self.ready = False

    def is_ready(self):
        return self.ready

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype else arr


def test_fetch_handle_timeout_is_typed_and_reusable():
    arr = _LazyArray([1.0, 2.0])
    handle = FetchHandle([arr], ["out"])
    t0 = time.perf_counter()
    with pytest.raises(FetchTimeoutError) as exc:
        handle.result(timeout=0.05)
    assert time.perf_counter() - t0 < 5.0
    assert exc.value.fetch_names == ["out"]
    # nothing was consumed: once the device work lands, the SAME handle
    # still materializes
    arr.ready = True
    (out,) = handle.result(timeout=1.0)
    np.testing.assert_array_equal(out, [1.0, 2.0])
    (again,) = handle.result()  # memoized
    np.testing.assert_array_equal(again, out)


def test_fetch_handle_timeout_on_real_dispatch():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.reduce_sum(fluid.layers.scale(x, 2.0), dim=[1])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.arange(8, dtype="float32").reshape(2, 4)}
    handle = exe.run_async(main, feed=feed, fetch_list=[y])
    (got,) = handle.result(timeout=30.0)
    (want,) = exe.run(main, feed=feed, fetch_list=[y])
    np.testing.assert_array_equal(got, np.asarray(want))


# -- suggest_buckets (the L001 mitigation) ----------------------------------

def test_suggest_buckets_sizes():
    assert suggest_buckets([3, 5, 9, 17]) == (4, 8, 16, 32)
    assert suggest_buckets(range(1, 9)) == (1, 2, 4, 8)
    assert suggest_buckets([7]) == (8,)
    # thinning drops the SMALL rungs, keeps the top
    assert suggest_buckets([1, 300], max_buckets=3) == (128, 256, 512)


def test_suggest_buckets_shapes_and_dict():
    ladders = suggest_buckets([(4, 32), (2, 48), (8, 32)])
    assert ladders == ((2, 4, 8), (32, 64))
    by_feed = suggest_buckets({"src": [3, 70], "bs": [1, 4]})
    assert by_feed == {"src": (16, 32, 64, 128), "bs": (1, 2, 4)}
    with pytest.raises(ValueError):
        suggest_buckets([])
    with pytest.raises(ValueError):
        suggest_buckets([(1, 2), (1, 2, 3)])  # mixed ranks
    with pytest.raises(ValueError):
        suggest_buckets([0, 4])


def test_l001_hint_names_the_mitigation():
    from paddle_tpu.analysis.lint import lint

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("txt", shape=[-1, 16], dtype="float32")
        fluid.layers.reduce_sum(x)
    diags = [d for d in lint(prog) if d.rule == "L001"]
    assert diags and any("suggest_buckets" in (d.hint or "")
                         for d in diags)


# -- slot-paged generation ---------------------------------------------------

def _copy_task_batch(rng, bs, seq, vocab):
    src = rng.randint(3, vocab, (bs, seq)).astype("int64")
    trg = np.full_like(src, 1)
    trg[:, 1:] = src[:, :-1]
    return {"src_word": src, "src_len": np.full((bs, 1), seq, "int64"),
            "trg_word": trg, "trg_len": np.full((bs, 1), seq, "int64"),
            "label": src}


def test_slot_decoder_staggered_admissions_match_dedicated_decode():
    """Sequences admitted into the slot pool MID-FLIGHT (fewer slots
    than sequences, ragged source lengths) produce exactly the tokens
    the dedicated full-prefix greedy decoder produces — the continuous-
    batching decode is numerically invisible."""
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.generation import (
        NoFreeSlotError,
        SlotDecodeSession,
    )

    vocab, seq, D = 24, 8, 32
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab,
               max_length=seq, n_layer=1, n_head=2, d_model=D,
               d_inner=64)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    startup.random_seed = 21
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, **cfg)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    infer_prog = transformer.build_inference(main, extras["logits"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(22)
    for _ in range(50):
        exe.run(main, feed=_copy_task_batch(rng, 16, seq, vocab),
                fetch_list=[loss])

    src = rng.randint(3, vocab, (5, seq)).astype("int64")
    src_len = np.asarray([[seq], [seq - 3], [seq - 1], [2], [seq]],
                         "int64")
    want = transformer.greedy_generate(
        exe, infer_prog, extras["logits"].name, src, src_len, seq)

    sess = SlotDecodeSession(exe, num_slots=3, max_length=seq,
                             d_model=D, src_vocab_size=vocab,
                             trg_vocab_size=vocab, n_layer=1, n_head=2,
                             d_inner=64)
    # hand-staggered: fill the pool, step, admit into freed slots
    got = np.zeros_like(want)
    owner = {sess.admit(src[i], src_len[i]): i for i in range(3)}
    with pytest.raises(NoFreeSlotError):
        sess.admit(src[3], src_len[3])
    pending = [3, 4]
    steps = 0
    while owner or pending:
        while pending and sess.free_slots:
            i = pending.pop(0)
            owner[sess.admit(src[i], src_len[i])] = i
        for slot, tokens in sess.step().items():
            got[owner.pop(slot)] = tokens
        steps += 1
        assert steps < 100
    np.testing.assert_array_equal(got, want)

    # one executable for every step dispatch regardless of occupancy:
    # the step program's shapes never changed, so a second full batch
    # through sess.generate adds no fresh compiles
    before = exec_cache.stats()["fresh_compiles"]
    again = sess.generate(src, src_len)
    np.testing.assert_array_equal(again, want)
    assert exec_cache.stats()["fresh_compiles"] == before


def test_server_metrics_exported(demo_predictor):
    """The SLO series land in the process registry scrape."""
    from paddle_tpu.observability import REGISTRY

    server = BatchingServer(demo_predictor, max_batch=4)
    try:
        server.run({"x": np.zeros((3, 12), "float32")})
        with pytest.raises(DeadlineExceededError):
            # a zero deadline always lapses before delivery
            server.submit({"x": np.zeros((1, 12), "float32")},
                          deadline_s=0.0).result(timeout=30)
        text = REGISTRY.to_prometheus()
        assert 'paddle_tpu_serving_requests_total{outcome="ok"}' in text
        assert "paddle_tpu_serving_request_seconds_bucket" in text
        assert "paddle_tpu_serving_batch_occupancy_count" in text
        assert "paddle_tpu_serving_queue_depth" in text
        assert 'paddle_tpu_serving_requests_total{outcome="deadline"}' \
            in text
    finally:
        server.close()
