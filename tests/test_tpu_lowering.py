"""Cross-platform TPU export of every Pallas kernel — no chip required.

The round-3 hardware window lost its kernel verdicts to a Mosaic
block-shape error that only surfaced on the real TPU (the LSTM block spec
violated the (8, 128) trailing-dim tiling rule; fixed in a2f4042). That
class of bug is catchable WITHOUT hardware: ``jax.export`` with
``platforms=["tpu"]`` runs the full Pallas->Mosaic lowering, including
``_check_block_mappings``, on any host. Every Pallas kernel configuration
the framework ships is exported here so a tiling regression can never
again wait for a hardware window to be discovered.

Reference analogy: paddle/fluid/operators/math/jit_kernel_test.cc compiles
every JIT kernel variant in CI regardless of the deploy target.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import importlib

from paddle_tpu.kernels import gru_cell, lstm_cell

# paddle_tpu.kernels re-exports the flash_attention FUNCTION, which
# shadows the submodule for every import-statement form; importlib
# resolves the module itself
fa = importlib.import_module("paddle_tpu.kernels.flash_attention")


def _export_tpu(fn, *args):
    """Lower ``fn`` for the TPU platform (Mosaic lowering included)."""
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


# the kernel_bench sweep's smallest shape plus a non-multiple batch that
# exercises the pad-to-block path
_RNN_SHAPES = [(32, 128, 256), (5, 16, 256)]


@pytest.mark.parametrize("bs,seq,d", _RNN_SHAPES)
def test_lstm_lowers_for_tpu(bs, seq, d):
    xw = jnp.zeros((bs, seq, 4 * d), jnp.float32)
    w_h = jnp.zeros((d, 4 * d), jnp.float32)
    bias = jnp.zeros((4 * d,), jnp.float32)

    _export_tpu(
        lambda xw, w_h, bias: lstm_cell.fused_lstm(
            xw, w_h, bias, force_pallas=True),
        xw, w_h, bias)


def test_lstm_peephole_masked_lowers_for_tpu():
    bs, seq, d = 8, 16, 256
    xw = jnp.zeros((bs, seq, 4 * d), jnp.float32)
    w_h = jnp.zeros((d, 4 * d), jnp.float32)
    bias = jnp.zeros((4 * d,), jnp.float32)
    peep = tuple(jnp.zeros((d,), jnp.float32) for _ in range(3))
    mask = jnp.ones((bs, seq), jnp.float32)

    _export_tpu(
        lambda xw, w_h, bias: lstm_cell.fused_lstm(
            xw, w_h, bias, peephole=peep, mask=mask, force_pallas=True),
        xw, w_h, bias)


@pytest.mark.parametrize("bs,seq,d", _RNN_SHAPES)
def test_gru_lowers_for_tpu(bs, seq, d):
    xw = jnp.zeros((bs, seq, 3 * d), jnp.float32)
    w_gate = jnp.zeros((d, 2 * d), jnp.float32)
    w_cand = jnp.zeros((d, d), jnp.float32)
    bias = jnp.zeros((3 * d,), jnp.float32)

    _export_tpu(
        lambda xw, wg, wc, b: gru_cell.fused_gru(
            xw, wg, wc, b, force_pallas=True),
        xw, w_gate, w_cand, bias)


def _qkv(b, h, t, d, kv_heads=None):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, kv_heads or h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, kv_heads or h, t, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fwd_bwd_lowers_for_tpu(causal):
    q, k, v = _qkv(1, 2, 256, 64)

    def loss(q, k, v):
        return fa.flash_attention(
            q, k, v, causal=causal, force_pallas=True).sum()

    _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_flash_gqa_window_lowers_for_tpu():
    # grouped-query (2 query heads per kv head) + sliding window + key
    # mask: the full round-3 feature set through fwd AND the FA2 backward
    q, k, v = _qkv(1, 4, 256, 64, kv_heads=2)
    mask = jnp.ones((1, 256), bool)

    def loss(q, k, v):
        return fa.flash_attention(
            q, k, v, causal=True, mask=mask, kv_group=2, window=128,
            force_pallas=True).sum()

    _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_flash_uneven_tail_lowers_for_tpu():
    # T not a multiple of the default block: exercises the tail-tile path
    q, k, v = _qkv(1, 2, 192, 64)

    def loss(q, k, v):
        return fa.flash_attention(q, k, v, force_pallas=True).sum()

    _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_ring_flash_lowers_for_tpu():
    """Ring attention's shard_map + per-block Pallas engine lowers for
    the TPU platform on the 8-device mesh — guards the Mosaic x
    shard_map composition (sequence parallelism's hot path) without
    hardware."""
    import pytest

    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = build_mesh(num_devices=8, data=8)
    q = jnp.zeros((1, 2, 8 * 128, 64), jnp.float32)

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name="data",
                              causal=True, impl="flash").sum()

    _export_tpu(loss, q, q, q)
