"""Flash-attention kernel + attention layers + Transformer tests."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.kernels.flash_attention import (
    flash_attention,
    flash_attention_reference,
)


def _np_attention(q, k, v, causal=False, mask=None):
    d = q.shape[-1]
    s = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(d)
    if causal:
        t, ss = s.shape[-2:]
        m = np.tril(np.ones((t, ss), bool))
        s = np.where(m, s, -1e30)
    if mask is not None:
        s = np.where(mask, s, -1e30)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bhts,bhsd->bhtd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_matches_reference(causal):
    """Pallas kernel (interpret mode on CPU) vs numpy, non-multiple shapes."""
    import jax

    rng = np.random.RandomState(0)
    B, H, T, S, d = 2, 3, 18, 21, 8
    q = rng.randn(B, H, T, d).astype("float32")
    k = rng.randn(B, H, S, d).astype("float32")
    v = rng.randn(B, H, S, d).astype("float32")
    if causal:
        S = T
        k, v = k[:, :, :T], v[:, :, :T]
    out = flash_attention(
        jax.numpy.asarray(q), jax.numpy.asarray(k), jax.numpy.asarray(v),
        causal=causal, block_q=8, block_k=8, force_pallas=True,
    )
    expect = _np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-5, rtol=2e-5)


def test_flash_kernel_grad_matches_reference():
    import jax

    rng = np.random.RandomState(1)
    B, H, T, d = 1, 2, 16, 8
    q = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    k = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    v = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))

    def loss_pallas(q, k, v):
        return jax.numpy.sum(
            flash_attention(q, k, v, causal=True, block_q=8, block_k=8,
                            force_pallas=True) ** 2
        )

    def loss_ref(q, k, v):
        return jax.numpy.sum(
            flash_attention_reference(q, k, v, causal=True) ** 2
        )

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


@pytest.mark.parametrize("mask_rank", [2, 4], ids=["BS", "B11S"])
def test_flash_kernel_key_mask_matches_reference(mask_rank):
    """[B, S] key-validity masks run through the Pallas kernel (interpret
    mode on CPU): forward and grads must match the masked reference."""
    import jax

    rng = np.random.RandomState(7)
    B, H, T, S, d = 2, 2, 10, 13, 8
    q = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    k = jax.numpy.asarray(rng.randn(B, H, S, d).astype("float32"))
    v = jax.numpy.asarray(rng.randn(B, H, S, d).astype("float32"))
    lens = np.asarray([S, S - 5])
    kv_valid = (np.arange(S)[None, :] < lens[:, None])
    mask = jax.numpy.asarray(
        kv_valid if mask_rank == 2 else kv_valid[:, None, None, :])

    out = flash_attention(q, k, v, mask=mask, block_q=8, block_k=8,
                          force_pallas=True)
    expect = _np_attention(np.asarray(q), np.asarray(k), np.asarray(v),
                           mask=kv_valid[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-5,
                               rtol=2e-5)

    def loss_pallas(q_, k_, v_):
        return jax.numpy.sum(flash_attention(
            q_, k_, v_, mask=mask, block_q=8, block_k=8,
            force_pallas=True) ** 2)

    def loss_ref(q_, k_, v_):
        m4 = mask if mask_rank == 4 else mask[:, None, None, :]
        return jax.numpy.sum(flash_attention_reference(
            q_, k_, v_, mask=m4) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_sdpa_layer_with_mask():
    B, H, T, d = 2, 2, 6, 4
    rng = np.random.RandomState(2)
    q = rng.randn(B, H, T, d).astype("float32")
    k = rng.randn(B, H, T, d).astype("float32")
    v = rng.randn(B, H, T, d).astype("float32")
    lens = np.array([3, 6], "int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        qv = fluid.layers.data("q", shape=[H, T, d])
        kv = fluid.layers.data("k", shape=[H, T, d])
        vv = fluid.layers.data("v", shape=[H, T, d])
        ln = fluid.layers.data("len", shape=[1], dtype="int64")
        m = fluid.layers.sequence_mask(ln, maxlen=T, dtype="float32")
        out = fluid.layers.scaled_dot_product_attention(qv, kv, vv, mask=m)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ov, = exe.run(
        main,
        feed={"q": q, "k": k, "v": v, "len": lens.reshape(-1, 1)},
        fetch_list=[out],
    )
    key_mask = (np.arange(T)[None, :] < lens[:, None])[:, None, None, :]
    expect = _np_attention(q, k, v, mask=key_mask)
    np.testing.assert_allclose(np.asarray(ov), expect, atol=1e-5, rtol=1e-5)


def test_multi_head_attention_trains():
    B, T, D = 4, 8, 16
    rng = np.random.RandomState(3)
    x = rng.randn(B, T, D).astype("float32")
    y = rng.randn(B, T, D).astype("float32") * 0.1

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 4
    startup.random_seed = 4
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, D])
        tgt = fluid.layers.data("y", shape=[T, D])
        out = fluid.layers.multi_head_attention(
            inp, None, None, d_key=4, d_value=4, d_model=D, n_head=4
        )
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(out, tgt))
        )
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = [
        float(np.asarray(
            exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])[0]
        ).ravel()[0])
        for _ in range(30)
    ]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def _copy_task_batch(rng, bs, seq, vocab):
    """Target = source shifted; teacher-forced decoder input."""
    src = rng.randint(3, vocab, (bs, seq)).astype("int64")
    label = src.copy()
    trg_in = np.concatenate(
        [np.ones((bs, 1), "int64"), src[:, :-1]], axis=1
    )  # <bos>=1 then shifted
    lens = np.full((bs, 1), seq, "int64")
    return {
        "src_word": src,
        "src_len": lens,
        "trg_word": trg_in,
        "trg_len": lens,
        "label": label,
    }


def test_transformer_converges_on_copy_task():
    from paddle_tpu.models import transformer

    vocab, seq = 30, 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            src_vocab_size=vocab,
            trg_vocab_size=vocab,
            max_length=seq,
            n_layer=1,
            n_head=2,
            d_model=32,
            d_inner=64,
            dropout=0.0,
            label_smooth_eps=0.0,
        )
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    losses = []
    for step in range(180):
        lv, = exe.run(
            main, feed=_copy_task_batch(rng, 16, seq, vocab),
            fetch_list=[loss],
        )
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert np.isfinite(losses[-1])
    # chance level is ln(30) ~ 3.4; copy task must be far below it
    assert min(losses[-10:]) < 1.0, (losses[0], losses[-10:])


def test_sdpa_seq_parallel_axis_in_program():
    """In-program sequence parallelism: a Fluid program whose attention
    runs ring attention over the ParallelExecutor mesh axis must match
    the single-device run step for step (context parallelism from the
    front-end API, not just the JAX level)."""
    from paddle_tpu.parallel_executor import ParallelExecutor

    seq, d_model, n_head, nclass = 16, 16, 4, 4

    def build(seq_axis=None):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 21
        startup.random_seed = 21
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [seq, d_model])
            label = fluid.layers.data("label", [1], dtype="int64")
            qkv = fluid.layers.fc(x, 3 * d_model, num_flatten_dims=2,
                                  bias_attr=False)
            q, k, v = fluid.layers.split(qkv, 3, dim=-1)

            def heads(t):
                t = fluid.layers.reshape(
                    t, [-1, seq, n_head, d_model // n_head])
                return fluid.layers.transpose(t, [0, 2, 1, 3])

            ctx = fluid.layers.scaled_dot_product_attention(
                heads(q), heads(k), heads(v), causal=True,
                seq_parallel_axis=seq_axis)
            ctx = fluid.layers.reshape(
                fluid.layers.transpose(ctx, [0, 2, 1, 3]),
                [-1, seq, d_model])
            pooled = fluid.layers.reduce_mean(ctx, dim=1)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    fluid.layers.fc(pooled, nclass), label))
            fluid.optimizer.SGD(0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(5)
    xs = rng.randn(4, 8, seq, d_model).astype("float32")
    ys = rng.randint(0, nclass, (4, 8, 1)).astype("int64")

    main, startup, loss = build(seq_axis=None)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    single = []
    for i in range(4):
        (lv,) = exe.run(main, feed={"x": xs[i], "label": ys[i]},
                        fetch_list=[loss])
        single.append(float(np.asarray(lv).ravel()[0]))

    main, startup, loss = build(seq_axis="data")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                          use_tpu=False)
    par = []
    for i in range(4):
        (lv,) = pe.run(fetch_list=[loss],
                       feed={"x": xs[i], "label": ys[i]})
        par.append(float(np.asarray(lv).ravel()[0]))
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)


def test_sdpa_seq_parallel_axis_requires_mesh():
    """Without a ParallelExecutor mesh the attr fails with a clear error
    instead of silently running unsharded."""
    import pytest

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = fluid.layers.data("q", [2, 8, 4])
        out = fluid.layers.scaled_dot_product_attention(
            q, q, q, seq_parallel_axis="data")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(Exception, match="seq_parallel_axis"):
        exe.run(main,
                feed={"q": np.zeros((1, 2, 8, 4), "float32")},
                fetch_list=[out])


def test_flash_key_mask_reference_fallback_normalizes():
    """A [B, S] key mask on the reference fallback (CPU target, no
    force_pallas) must be expanded to [B, 1, 1, S], not broadcast raw."""
    import jax

    rng = np.random.RandomState(9)
    B, H, T, S, d = 3, 2, 5, 7, 4  # B != T: raw broadcast would raise
    q = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    k = jax.numpy.asarray(rng.randn(B, H, S, d).astype("float32"))
    v = jax.numpy.asarray(rng.randn(B, H, S, d).astype("float32"))
    kv_valid = (np.arange(S)[None, :] < np.asarray([S, 3, 5])[:, None])
    out = flash_attention(q, k, v, mask=jax.numpy.asarray(kv_valid))
    expect = _np_attention(np.asarray(q), np.asarray(k), np.asarray(v),
                           mask=kv_valid[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-5,
                               rtol=2e-5)


def test_flash_kernel_long_context_fwd_bwd():
    """Long-context smoke: seq 1024 at block 128 (8x8 tile grid) through
    the Pallas kernels in interpret mode, fwd + backward, causal. The
    O(block) memory contract means this differs from seq 128 only in
    grid steps; grads stay finite and match the reference on a sampled
    slice."""
    import jax

    rng = np.random.RandomState(13)
    B, H, T, d = 1, 1, 1024, 8
    q = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32") * 0.3)
    k = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32") * 0.3)
    v = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32") * 0.3)

    def loss(q_, k_, v_):
        return jax.numpy.sum(flash_attention(
            q_, k_, v_, causal=True, force_pallas=True) ** 2)

    out = flash_attention(q, k, v, causal=True, force_pallas=True)
    ref = flash_attention_reference(np.asarray(q), np.asarray(k),
                                    np.asarray(v), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gq, = jax.grad(loss, argnums=(0,))(q, k, v)
    assert np.isfinite(np.asarray(gq)).all()


@pytest.mark.parametrize("n_kv", [1, 2], ids=["mqa", "gqa"])
def test_multi_head_attention_gqa(n_kv):
    """Grouped-query attention: K/V projected to n_kv heads then
    repeated per query group — equals full MHA run with the repeated
    projection weights; the K/V projections shrink accordingly."""
    B, T, D, H, dh = 2, 6, 16, 4, 4
    rng = np.random.RandomState(8)
    x = rng.randn(B, T, D).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, D])
        out = fluid.layers.multi_head_attention(
            inp, None, None, d_key=dh, d_value=dh, d_model=D, n_head=H,
            n_kv_head=n_kv, name="gqa")
    kw = [p for p in main.global_block().all_parameters()
          if p.name.startswith("gqa_k")][0]
    assert list(kw.shape) == [D, dh * n_kv], kw.shape  # shrunk projection
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (got,) = exe.run(main, feed={"x": x}, fetch_list=[out])

    # numpy oracle: repeat the kv projections across each query group
    scope = fluid.global_scope()
    wq, wk, wv, wo = (np.asarray(scope.get_value("gqa_%s.w_0" % s))
                      for s in ("q", "k", "v", "o"))
    group = H // n_kv
    q = (x @ wq).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    k = (x @ wk).reshape(B, T, n_kv, dh).transpose(0, 2, 1, 3)
    v = (x @ wv).reshape(B, T, n_kv, dh).transpose(0, 2, 1, 3)
    k = np.repeat(k, group, axis=1)
    v = np.repeat(v, group, axis=1)
    out_np = _np_attention(q, k, v)
    merged = out_np.transpose(0, 2, 1, 3).reshape(B, T, H * dh)
    np.testing.assert_allclose(np.asarray(got), merged @ wo,
                               atol=2e-5, rtol=2e-5)


def test_transformer_generates_after_training():
    """Generation API: train the copy task, then greedy AND beam decode
    reproduce the source through the shared-parameter inference graph."""
    from paddle_tpu.models import transformer

    vocab, seq = 24, 8
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab,
               max_length=seq, n_layer=1, n_head=2, d_model=32,
               d_inner=64)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 6
    startup.random_seed = 6
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, **cfg)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    infer_prog = transformer.build_inference(main, extras["logits"])
    infer_logits = extras["logits"].name
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(7)
    for _ in range(140):
        batch = _copy_task_batch(rng, 16, seq, vocab)
        exe.run(main, feed=batch, fetch_list=[loss])

    src = rng.randint(3, vocab, (4, seq)).astype("int64")
    src[:, -1] = 2  # train saw no eos; pin the tail so lengths align
    src_len = np.full((4, 1), seq, "int64")
    greedy = transformer.greedy_generate(
        exe, infer_prog, infer_logits, src, src_len, seq)
    beam = transformer.beam_generate(
        exe, infer_prog, infer_logits, src, src_len, seq, beam_size=3)
    # copy task: output tokens shifted from <bos> should echo the source
    g_acc = float((greedy[:, 1:] == src[:, :-1]).mean())
    b_acc = float((beam[:, 1:] == src[:, :-1]).mean())
    assert g_acc > 0.9, g_acc
    assert b_acc >= g_acc - 0.05, (g_acc, b_acc)


def test_transformer_cached_decode_matches_full_rerun():
    """KV-cached incremental decoding (build_cached_decoder) produces
    the same sequences as the full-prefix greedy loop on a trained
    model — the caches and single-token step reproduce the full decoder
    exactly."""
    from paddle_tpu.models import transformer

    vocab, seq, D = 24, 8, 32
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab,
               max_length=seq, n_layer=2, n_head=2, d_model=D,
               d_inner=64)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    startup.random_seed = 9
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, **cfg)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    infer_prog = transformer.build_inference(main, extras["logits"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(10)
    for _ in range(60):
        exe.run(main, feed=_copy_task_batch(rng, 16, seq, vocab),
                fetch_list=[loss])

    prepare, step, step_logits = transformer.build_cached_decoder(
        batch_size=4, **cfg)
    src = rng.randint(3, vocab, (4, seq)).astype("int64")
    src_len = np.full((4, 1), seq, "int64")
    full = transformer.greedy_generate(
        exe, infer_prog, extras["logits"].name, src, src_len, seq)
    cached = transformer.cached_greedy_generate(
        exe, prepare, step, step_logits, src, src_len, seq, D)
    np.testing.assert_array_equal(cached, full)


def test_transformer_cached_beam_matches_full_beam():
    """Cached beam decode (per-parent cache reordering) matches the
    full-prefix beam_generate on a trained model."""
    from paddle_tpu.models import transformer

    vocab, seq, D, K = 24, 8, 32, 3
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab,
               max_length=seq, n_layer=2, n_head=2, d_model=D,
               d_inner=64)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 12
    startup.random_seed = 12
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, **cfg)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    infer_prog = transformer.build_inference(main, extras["logits"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(13)
    for _ in range(60):
        exe.run(main, feed=_copy_task_batch(rng, 16, seq, vocab),
                fetch_list=[loss])

    prepare, step, step_logits = transformer.build_cached_decoder(
        batch_size=4 * K, **cfg)
    reorder = transformer.build_cache_reorder(4 * K, seq, 2, 2, D)
    src = rng.randint(3, vocab, (4, seq)).astype("int64")
    # ragged source lengths: the prepared per-row src mask must survive
    # the K-fold beam batching
    src_len = np.asarray([[seq], [seq - 3], [seq - 1], [2]], "int64")
    full = transformer.beam_generate(
        exe, infer_prog, extras["logits"].name, src, src_len, seq,
        beam_size=K)
    cached = transformer.cached_beam_generate(
        exe, prepare, step, reorder, step_logits, src, src_len, seq, D,
        beam_size=K)
    np.testing.assert_array_equal(cached, full)


def test_transformer_generation_survives_save_load(tmp_path):
    """Deployment flow: save_inference_model on the pruned generation
    graph, reload into a FRESH scope/program, greedy decode matches the
    original session's output."""
    from paddle_tpu.models import transformer

    vocab, seq = 24, 8
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab,
               max_length=seq, n_layer=1, n_head=2, d_model=32,
               d_inner=64)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 15
    startup.random_seed = 15
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, **cfg)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    infer_prog = transformer.build_inference(main, extras["logits"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(16)
    for _ in range(80):
        exe.run(main, feed=_copy_task_batch(rng, 16, seq, vocab),
                fetch_list=[loss])
    src = rng.randint(3, vocab, (3, seq)).astype("int64")
    src_len = np.full((3, 1), seq, "int64")
    want = transformer.greedy_generate(
        exe, infer_prog, extras["logits"].name, src, src_len, seq)

    path = str(tmp_path / "nmt")
    fluid.io.save_inference_model(
        path, ["src_word", "src_len", "trg_word"],
        [infer_prog.global_block().var(extras["logits"].name)], exe,
        main_program=infer_prog)

    with fluid.scope_guard(fluid.executor.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        loaded, feed_names, fetch_vars = fluid.io.load_inference_model(
            path, exe2)
        got = transformer.greedy_generate(
            exe2, loaded, fetch_vars[0].name
            if hasattr(fetch_vars[0], "name") else fetch_vars[0],
            src, src_len, seq)
    np.testing.assert_array_equal(got, want)


def test_rotary_embedding_properties():
    """RoPE: norm-preserving rotation; attention scores depend only on
    RELATIVE position (shifting q and k positions together leaves
    q . k unchanged); a Position offset reproduces the shifted slice —
    the property KV-cached decoding relies on."""
    import jax

    rng = np.random.RandomState(20)
    B, H, T, d = 2, 2, 8, 8
    q = rng.randn(B, H, T, d).astype("float32")
    k = rng.randn(B, H, T, d).astype("float32")

    def run(qv, kv, pos=None):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            qd = fluid.layers.data("q", shape=[H, qv.shape[2], d])
            kd = fluid.layers.data("k", shape=[H, kv.shape[2], d])
            feed = {"q": qv, "k": kv}
            inputs = dict(q=qd, k=kd)
            if pos is not None:
                pd = fluid.layers.data("pos", shape=[1], dtype="int64",
                                       append_batch_size=False)
                inputs["position"] = pd
                feed["pos"] = np.asarray([pos], "int64")
            qo, ko = fluid.layers.rotary_position_embedding(**inputs)
        exe = fluid.Executor(fluid.CPUPlace())
        return [np.asarray(v) for v in
                exe.run(main, feed=feed, fetch_list=[qo, ko])]

    q_rot, k_rot = run(q, k)
    # rotation preserves norms
    np.testing.assert_allclose(
        np.linalg.norm(q_rot, axis=-1), np.linalg.norm(q, axis=-1),
        rtol=1e-5)
    # relative-position property: scores at (t, s) shift-invariant
    s0 = np.einsum("bhtd,bhsd->bhts", q_rot, k_rot)
    q_shift, k_shift = run(q, k, pos=5)
    s5 = np.einsum("bhtd,bhsd->bhts", q_shift, k_shift)
    np.testing.assert_allclose(s5, s0, atol=2e-4, rtol=2e-4)
    # position offset == the matching slice of a longer rotation
    q_long = np.concatenate([np.zeros_like(q[:, :, :3]), q], axis=2)
    ql_rot, _ = run(q_long, q_long)
    q_off, _ = run(q, k, pos=3)
    np.testing.assert_allclose(q_off, ql_rot[:, :, 3:], atol=2e-5,
                               rtol=2e-5)


def test_rope_attention_trains():
    """RoPE + fused attention + GQA compose in a training program."""
    B, T, D, H = 4, 8, 16, 4
    rng = np.random.RandomState(21)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 22
    startup.random_seed = 22
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [T, D])
        t = fluid.layers.data("t", [T, D])
        nx = fluid.layers.fc(x, D, num_flatten_dims=2, name="rp_in")
        qh = fluid.layers.transpose(
            fluid.layers.reshape(nx, shape=[0, 0, H, D // H]),
            perm=[0, 2, 1, 3])
        q, k = fluid.layers.rotary_position_embedding(qh, qh)
        att = fluid.layers.scaled_dot_product_attention(
            q, k, qh, causal=True)
        out = fluid.layers.reshape(
            fluid.layers.transpose(att, perm=[0, 2, 1, 3]),
            shape=[0, 0, D])
        y = fluid.layers.fc(out, D, num_flatten_dims=2, name="rp_out")
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(y, t)))
        fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = rng.randn(B, T, D).astype("float32")
    tv = np.roll(xv, 1, 1) * 0.3
    losses = [float(np.ravel(exe.run(
        main, feed={"x": xv, "t": tv}, fetch_list=[loss])[0])[0])
        for _ in range(40)]
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


def test_flash_kernel_gqa_matches_reference():
    """kv_group through the Pallas kernel (interpret mode): the index
    map serves each kv head to its query group without materializing
    repeated K/V; forward and grads match the repeat-based reference."""
    import jax

    rng = np.random.RandomState(23)
    B, H, Hkv, T, d = 2, 4, 2, 10, 8
    q = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    k = jax.numpy.asarray(rng.randn(B, Hkv, T, d).astype("float32"))
    v = jax.numpy.asarray(rng.randn(B, Hkv, T, d).astype("float32"))
    g = H // Hkv

    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8,
                          force_pallas=True, kv_group=g)
    expect = _np_attention(np.asarray(q),
                           np.repeat(np.asarray(k), g, 1),
                           np.repeat(np.asarray(v), g, 1), causal=True)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-5,
                               rtol=2e-5)

    def loss_pallas(q_, k_, v_):
        return jax.numpy.sum(flash_attention(
            q_, k_, v_, causal=True, block_q=8, block_k=8,
            force_pallas=True, kv_group=g) ** 2)

    def loss_ref(q_, k_, v_):
        return jax.numpy.sum(flash_attention_reference(
            jax.numpy.asarray(q_),
            jax.numpy.repeat(k_, g, axis=1),
            jax.numpy.repeat(v_, g, axis=1), causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "bidir"])
def test_flash_kernel_sliding_window(causal):
    """window=w restricts attention to the local band (tile-level
    pruning included: T=24 at block 8 skips out-of-band tiles); forward
    and grads match the band-masked reference."""
    import jax

    rng = np.random.RandomState(30)
    B, H, T, d, w = 1, 2, 24, 8, 6
    q = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    k = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    v = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))

    qi = np.arange(T)[:, None]
    ki = np.arange(T)[None, :]
    band = (qi - ki) < w
    if causal:
        band &= ki <= qi
    else:
        band &= (ki - qi) < w

    out = flash_attention(q, k, v, causal=causal, window=w, block_q=8,
                          block_k=8, force_pallas=True)
    expect = _np_attention(np.asarray(q), np.asarray(k), np.asarray(v),
                           mask=band[None, None])
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-5,
                               rtol=2e-5)

    def loss_pallas(q_, k_, v_):
        return jax.numpy.sum(flash_attention(
            q_, k_, v_, causal=causal, window=w, block_q=8, block_k=8,
            force_pallas=True) ** 2)

    def loss_ref(q_, k_, v_):
        return jax.numpy.sum(flash_attention_reference(
            q_, k_, v_, causal=causal,
            mask=jax.numpy.asarray(band[None, None])) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_flash_kernel_gqa_window_mask_compose():
    """kv_group + sliding window + key-validity mask simultaneously:
    the three kernel features compose; forward and grads match the
    equivalently-masked repeat-based reference."""
    import jax

    rng = np.random.RandomState(31)
    B, H, Hkv, T, d, w = 2, 4, 2, 16, 8, 5
    g = H // Hkv
    q = jax.numpy.asarray(rng.randn(B, H, T, d).astype("float32"))
    k = jax.numpy.asarray(rng.randn(B, Hkv, T, d).astype("float32"))
    v = jax.numpy.asarray(rng.randn(B, Hkv, T, d).astype("float32"))
    lens = np.asarray([T, T - 6])
    kv_valid = np.arange(T)[None, :] < lens[:, None]
    qi = np.arange(T)[:, None]
    ki = np.arange(T)[None, :]
    band = ((qi - ki) < w) & (ki <= qi)
    full_mask = kv_valid[:, None, None, :] & band[None, None]

    out = flash_attention(
        q, k, v, causal=True, window=w,
        mask=jax.numpy.asarray(kv_valid), kv_group=g,
        block_q=8, block_k=8, force_pallas=True)
    expect = _np_attention(np.asarray(q),
                           np.repeat(np.asarray(k), g, 1),
                           np.repeat(np.asarray(v), g, 1),
                           mask=full_mask)
    # rows whose entire window is masked return 0 from the kernel
    dead = ~(full_mask.any(-1))  # [B, 1, T]
    expect = np.where(dead[..., None], 0.0, expect)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-5,
                               rtol=2e-5)

    def loss_pallas(q_, k_, v_):
        return jax.numpy.sum(flash_attention(
            q_, k_, v_, causal=True, window=w,
            mask=jax.numpy.asarray(kv_valid), kv_group=g,
            block_q=8, block_k=8, force_pallas=True) ** 2)

    dead_j = jax.numpy.asarray(dead[..., None])

    def loss_ref(q_, k_, v_):
        o = flash_attention_reference(
            q_, jax.numpy.repeat(k_, g, 1), jax.numpy.repeat(v_, g, 1),
            mask=jax.numpy.asarray(full_mask))
        return jax.numpy.sum(jax.numpy.where(dead_j, 0.0, o) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
