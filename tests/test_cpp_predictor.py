"""C++ interpreter breadth + standalone demo predictor tests.

Covers VERDICT round-1 item 7: the native interpreter executes real models
(MNIST CNN with conv/pool/bias/softmax, a ResNet block with batch_norm and
a residual add) and a C++-only main (ptpu_demo_predictor, the
train/demo/demo_trainer.cc analog) runs a saved model end to end with no
Python in the process.
"""

import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native
from paddle_tpu.inference import NativeConfig, create_paddle_predictor

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="native toolchain unavailable: %s" % native.last_error(),
)


def _save_model(tmp_path, build_fn, feed_shapes, seed=0):
    rng = np.random.RandomState(seed)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feeds, fetch = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        name: rng.rand(*shape).astype("float32")
        for name, shape in feed_shapes.items()
    }
    # oracle must be the inference-mode program (is_test batch_norm uses
    # running stats), same as what save_inference_model serializes
    test_prog = main.clone(for_test=True)
    (want,) = exe.run(test_prog, feed=feed, fetch_list=[fetch])
    path = str(tmp_path / "model")
    fluid.io.save_inference_model(path, list(feed_shapes), [fetch], exe,
                                  main_program=main)
    return path, feed, np.asarray(want)


def _mnist_cnn():
    img = fluid.layers.data("x", [1, 28, 28])
    c1 = fluid.nets.simple_img_conv_pool(
        img, filter_size=5, num_filters=4, pool_size=2, pool_stride=2,
        act="relu")
    c2 = fluid.nets.simple_img_conv_pool(
        c1, filter_size=5, num_filters=8, pool_size=2, pool_stride=2,
        act="relu")
    out = fluid.layers.fc(c2, 10, act="softmax")
    return ["x"], out


def test_native_interp_runs_mnist_cnn(tmp_path):
    path, feed, want = _save_model(
        tmp_path, _mnist_cnn, {"x": (3, 1, 28, 28)})
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def _resnet_block():
    x = fluid.layers.data("x", [4, 8, 8])
    c1 = fluid.layers.conv2d(x, 4, 3, padding=1, bias_attr=False)
    b1 = fluid.layers.batch_norm(c1)
    r1 = fluid.layers.relu(b1)
    c2 = fluid.layers.conv2d(r1, 4, 3, padding=1, bias_attr=False)
    b2 = fluid.layers.batch_norm(c2)
    s = fluid.layers.elementwise_add(b2, x)
    r2 = fluid.layers.relu(s)
    pooled = fluid.layers.pool2d(r2, pool_type="avg", global_pooling=True)
    return ["x"], pooled


def test_native_interp_runs_resnet_block(tmp_path):
    # randomize BN stats so the is_test normalization path is exercised
    path, feed, want = _save_model(
        tmp_path, _resnet_block, {"x": (2, 4, 8, 8)}, seed=3)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def _demo_binary(name="ptpu_demo_predictor"):
    from tests.conftest import build_native_binary

    return build_native_binary(name)


def test_demo_predictor_binary_end_to_end(tmp_path):
    """The reference's demo_trainer.cc capability: C++ main loads the saved
    model + params and predicts — no Python interpreter in that process."""
    binary = _demo_binary()
    if binary is None:
        pytest.skip("cmake/ninja unavailable to build the demo binary")
    path, feed, want = _save_model(
        tmp_path, _mnist_cnn, {"x": (2, 1, 28, 28)}, seed=7)
    inp = str(tmp_path / "input.npy")
    outp = str(tmp_path / "output.npy")
    np.save(inp, feed["x"])
    res = subprocess.run([binary, path, inp, outp],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "ok params=" in res.stdout
    got = np.load(outp)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_demo_trainer_binary_trains(tmp_path):
    """The reference's train/demo/demo_trainer.cc capability: a C++ main
    runs the STARTUP program, then loops the full training IR (forward +
    synthesized grads + sgd) and the loss falls — no Python in that
    process."""
    from paddle_tpu.core.program_bin import serialize_program

    binary = _demo_binary("ptpu_demo_trainer")
    if binary is None:
        pytest.skip("cmake/ninja unavailable to build the demo binary")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=img, size=32, act="relu")
        logits = fluid.layers.fc(input=h, size=10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    (tmp_path / "main.ptpb").write_bytes(serialize_program(main))
    (tmp_path / "startup.ptpb").write_bytes(serialize_program(startup))
    res = subprocess.run(
        [binary, str(tmp_path), loss.name, "30", "32"],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr + res.stdout
    last_line = res.stdout.strip().splitlines()[-1]
    first, last = float(last_line.split()[1]), float(last_line.split()[3])
    assert last < 0.25 * first, res.stdout


def test_compiled_predictor_binary_matches_python(tmp_path):
    """The api_impl.cc:141 capability on the COMPILED path: a C++ serving
    main executes the whole-program XLA executable (via the embedded
    CPython binding) on a conv model, matching the Python executor."""
    binary = _demo_binary("ptpu_compiled_predictor")
    if binary is None:
        pytest.skip("embeddable Python or cmake/ninja unavailable")
    path, feed, want = _save_model(
        tmp_path, _mnist_cnn, {"x": (2, 1, 28, 28)}, seed=9)
    inp = str(tmp_path / "input.npy")
    outp = str(tmp_path / "output.npy")
    np.save(inp, feed["x"])
    import sysconfig

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [root, sysconfig.get_paths()["purelib"]]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    res = subprocess.run([binary, path, inp, outp],
                         capture_output=True, text=True, timeout=300,
                         env=env)
    assert res.returncode == 0, res.stderr + res.stdout
    assert "ok compiled" in res.stdout
    got = np.load(outp)
    # same engine, same executable: tight tolerance
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_demo_predictor_rejects_garbage(tmp_path):
    binary = _demo_binary()
    if binary is None:
        pytest.skip("cmake/ninja unavailable to build the demo binary")
    (tmp_path / "__model__").write_bytes(b"not a program")
    res = subprocess.run(
        [binary, str(tmp_path), "nope.npy", "out.npy"],
        capture_output=True, text=True, timeout=60)
    assert res.returncode != 0


def _lstm_classifier():
    """Sequence classifier: embedding -> fc(4H) -> dynamic_lstm ->
    max-pool over time -> softmax head (the stacked_lstm book family)."""
    words = fluid.layers.data("words", [12], dtype="int64")
    length = fluid.layers.data("length", [1], dtype="int64")
    emb = fluid.layers.embedding(words, size=[50, 16])
    proj = fluid.layers.fc(emb, size=4 * 16, num_flatten_dims=2)
    hidden, _cell = fluid.layers.dynamic_lstm(
        input=proj, size=4 * 16, length=length)
    pooled = fluid.layers.sequence_pool(hidden, "max", length=length)
    avg = fluid.layers.sequence_pool(hidden, "average", length=length)
    # two-input fc emits a real sum op (nn.py fc multi-input path), so
    # the interpreter's RunSum is exercised too
    out = fluid.layers.fc([pooled, avg], 4, act="softmax")
    return ["words", "length"], out


def test_native_interp_runs_lstm_classifier(tmp_path):
    """The C++ interpreter executes the sequence-model op family
    (lookup_table, dynamic_lstm, sequence_pool, sum) with integer feeds,
    matching the XLA path."""
    rng = np.random.RandomState(11)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feeds, fetch = _lstm_classifier()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "words": rng.randint(0, 50, (3, 12)).astype("int64"),
        "length": np.asarray([[12], [7], [1]], "int64"),
    }
    test_prog = main.clone(for_test=True)
    (want,) = exe.run(test_prog, feed=feed, fetch_list=[fetch])
    path = str(tmp_path / "model")
    fluid.io.save_inference_model(path, feeds, [fetch], exe,
                                  main_program=main)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4, atol=1e-5)


def test_native_interp_runs_transformer_encoder(tmp_path):
    """The C++ interpreter serves a transformer encoder block end to end
    (layer_norm, transpose, fused scaled_dot_product_attention with a
    key-validity mask, sequence_mask, reduce_mean), matching the XLA
    path — the attention-era analog of the CNN serving tests."""
    from paddle_tpu.models.transformer import encoder_layer

    rng = np.random.RandomState(17)
    T, D = 6, 16
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [T, D])
        ln = fluid.layers.data("len", [1], dtype="int64")
        m = fluid.layers.sequence_mask(ln, maxlen=T, dtype="float32")
        h = encoder_layer(x, m, 4, D, 32, 0.0, True, "enc0")
        h = fluid.layers.layer_norm(h, begin_norm_axis=2, name="enc_final")
        out = fluid.layers.reduce_mean(h, dim=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "x": rng.randn(3, T, D).astype("float32"),
        "len": np.asarray([[6], [4], [1]], "int64"),
    }
    test_prog = main.clone(for_test=True)
    (want,) = exe.run(test_prog, feed=feed, fetch_list=[out])
    path = str(tmp_path / "model")
    fluid.io.save_inference_model(path, ["x", "len"], [out], exe,
                                  main_program=main)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4, atol=1e-5)


def test_native_interp_runs_gqa_attention(tmp_path):
    """The C++ SDPA kernel maps query heads onto grouped K/V heads
    (kv_group attr) — multi-query attention serves from C++ matching
    the XLA path."""
    rng = np.random.RandomState(19)
    T, D, H = 5, 16, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [T, D])
        out = fluid.layers.multi_head_attention(
            x, None, None, d_key=D // H, d_value=D // H, d_model=D,
            n_head=H, n_kv_head=1, causal=True, name="cppgqa")
        out = fluid.layers.reduce_mean(out, dim=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": rng.randn(2, T, D).astype("float32")}
    (want,) = exe.run(main.clone(for_test=True), feed=feed,
                      fetch_list=[out])
    path = str(tmp_path / "model")
    fluid.io.save_inference_model(path, ["x"], [out], exe,
                                  main_program=main)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_native_interp_sliding_window_attention(tmp_path, causal):
    """The C++ SDPA honors the sliding-window attr with the kernel's
    band semantics (q - w < k <= q causal, |q - k| < w otherwise) —
    before the fix it silently computed FULL attention for windowed
    programs (ADVICE r3 medium)."""
    rng = np.random.RandomState(23)
    B, H, T, D = 2, 2, 7, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = fluid.layers.data("q", [H, T, D])
        k = fluid.layers.data("k", [H, T, D])
        v = fluid.layers.data("v", [H, T, D])
        out = fluid.layers.scaled_dot_product_attention(
            q, k, v, causal=causal, window=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {n: rng.randn(B, H, T, D).astype("float32")
            for n in ("q", "k", "v")}
    (want,) = exe.run(main.clone(for_test=True), feed=feed,
                      fetch_list=[out])
    path = str(tmp_path / "model")
    fluid.io.save_inference_model(path, ["q", "k", "v"], [out], exe,
                                  main_program=main)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4,
                               atol=1e-5)
    # the window must actually bite: full attention differs
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        q2 = fluid.layers.data("q", [H, T, D])
        k2 = fluid.layers.data("k", [H, T, D])
        v2 = fluid.layers.data("v", [H, T, D])
        full = fluid.layers.scaled_dot_product_attention(
            q2, k2, v2, causal=causal)
    fluid.Executor(fluid.CPUPlace()).run(startup2)
    (unwindowed,) = fluid.Executor(fluid.CPUPlace()).run(
        main2, feed=feed, fetch_list=[full])
    assert not np.allclose(np.asarray(want), np.asarray(unwindowed))


# ---- op-level C++ breadth (VERDICT r3 Next #4). Whole-model serving
# parity for the zoo (GoogLeNet, SE-ResNeXt, AlexNet, Transformer, MT,
# VGG, ResNet, MNIST, stacked LSTM) lives in tests/test_golden_cpp.py,
# which pins BOTH engines to committed golden outputs; the tests here
# cover op semantics the goldens don't isolate.


def _serve_parity(tmp_path, feeds, fetch, feed, main, exe, rtol=1e-4,
                  atol=1e-5):
    from paddle_tpu.io import prune_program

    # oracle = the same pruned serving slice the predictor will run (the
    # full program's loss/metric head reads labels we don't feed)
    pruned = prune_program(main.clone(for_test=True), feeds,
                           [fetch.name])
    (want,) = exe.run(pruned, feed=feed, fetch_list=[fetch])
    path = str(tmp_path / "model")
    fluid.io.save_inference_model(path, feeds, [fetch], exe,
                                  main_program=main)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=path, use_tpu=False))
    got = predictor.run_native_reference(feed)
    np.testing.assert_allclose(got, np.asarray(want), rtol=rtol, atol=atol)






def test_native_interp_runs_gru_classifier(tmp_path):
    """dynamic_gru (incl. is_reverse + Length masking) matches the XLA
    scan through the C++ recurrence."""
    rng = np.random.RandomState(7)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = fluid.layers.data("words", [12], dtype="int64")
        length = fluid.layers.data("length", [1], dtype="int64")
        emb = fluid.layers.embedding(words, size=[50, 48])
        fwd = fluid.layers.dynamic_gru(emb, size=16, length=length)
        bwd = fluid.layers.dynamic_gru(emb, size=16, length=length,
                                       is_reverse=True)
        cat = fluid.layers.concat([fwd, bwd], axis=-1)
        pooled = fluid.layers.sequence_pool(cat, "max", length=length)
        out = fluid.layers.fc(pooled, size=4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "words": rng.randint(0, 50, (3, 12)).astype("int64"),
        "length": np.asarray([[12], [7], [1]], "int64"),
    }
    _serve_parity(tmp_path, ["words", "length"], out, feed, main, exe)


def test_native_interp_split_deconv(tmp_path):
    """split + conv2d_transpose (strided, padded) match XLA from C++."""
    rng = np.random.RandomState(8)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8, 6, 6])
        lo, hi = fluid.layers.split(x, 2, dim=1)
        up = fluid.layers.conv2d_transpose(
            lo, num_filters=5, filter_size=3, stride=2, padding=1)
        up2 = fluid.layers.conv2d_transpose(
            hi, num_filters=5, filter_size=3, stride=2, padding=1)
        out = fluid.layers.reduce_mean(
            fluid.layers.elementwise_add(up, up2), dim=[2, 3])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": rng.randn(2, 8, 6, 6).astype("float32")}
    _serve_parity(tmp_path, ["x"], out, feed, main, exe)


def test_native_interp_metric_heads(tmp_path):
    """The UNPRUNED eval head (cross_entropy on probs, top_k, accuracy)
    runs in C++, so a saved eval program needs no Python either."""
    rng = np.random.RandomState(9)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [20])
        label = fluid.layers.data("label", [1], dtype="int64")
        probs = fluid.layers.fc(x, size=5, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=probs, label=label))
        acc = fluid.layers.accuracy(input=probs, label=label, k=2)
        out = fluid.layers.elementwise_add(
            loss, fluid.layers.reduce_sum(acc))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "x": rng.randn(6, 20).astype("float32"),
        "label": rng.randint(0, 5, (6, 1)).astype("int64"),
    }
    _serve_parity(tmp_path, ["x", "label"], out, feed, main, exe)



def test_demo_trainer_binary_trains_conv_book_model(tmp_path):
    """VERDICT r4 Next #4: the C++ trainer runs the MNIST CONV book
    model (reference test_recognize_digits.py conv variant) end to end
    — conv2d/pool2d forwards AND backwards, gaussian_random startup
    init, cross_entropy/softmax grads — loss falls, no Python in the
    training process."""
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.models import mnist

    binary = _demo_binary("ptpu_demo_trainer")
    if binary is None:
        pytest.skip("cmake/ninja unavailable to build the demo binary")
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, _feeds, _outs = mnist.build()
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    (tmp_path / "main.ptpb").write_bytes(serialize_program(main))
    (tmp_path / "startup.ptpb").write_bytes(serialize_program(startup))
    res = subprocess.run(
        [binary, str(tmp_path), loss.name, "25", "16", "conv"],
        capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr + res.stdout
    last_line = res.stdout.strip().splitlines()[-1]
    first, last = float(last_line.split()[1]), float(last_line.split()[3])
    assert last < 0.5 * first, res.stdout


def test_conv_train_step_parity_cpp_vs_xla(tmp_path):
    """Golden-pinned first step (VERDICT r4 Next #4): ONE training step
    of the conv book model on a fixed feed, run by both engines from
    identical deterministic parameters — loss and the updated conv
    filter must agree. This pins every kernel in the C++ conv training
    path (conv2d/pool2d fwd+bwd, softmax/xent grads, broadcast bias
    grad, sgd) against the XLA lowering numerics."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.models import mnist
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, _feeds, _outs = mnist.build()
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    rng = np.random.RandomState(77)
    feed = {
        "pixel": rng.rand(4, 1, 28, 28).astype("float32"),
        "label": rng.randint(0, 10, (4, 1)).astype("int64"),
    }
    # engine 1: XLA executor over deterministic params
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        conv_w_xla = np.asarray(scope.get_value("conv2d_0.w_0"))

    # engine 2: C++ interpreter on the same program bytes + params
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        conv_w_cpp = ns.get("conv2d_0.w_0")
    finally:
        lib.ptpu_program_destroy(prog)

    np.testing.assert_allclose(
        np.ravel(cpp_loss)[0], np.ravel(np.asarray(xla_loss))[0],
        rtol=1e-4, atol=1e-5,
        err_msg="first-step loss diverged between engines")
    np.testing.assert_allclose(
        conv_w_cpp, conv_w_xla, rtol=1e-3, atol=1e-5,
        err_msg="updated conv filter diverged between engines")


def test_pool_ceil_mode_train_step_parity_cpp_vs_xla(tmp_path):
    """ceil_mode pooling was a C++ refusal until r5; now both engines
    implement it, INCLUDING the backward (the fuzz covers the forward;
    this pins pool2d_grad's ceil geometry): one SGD step of a tiny
    conv+ceil-pool net, loss and updated filter must match."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[2, 7, 7], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        v = fluid.layers.conv2d(x, num_filters=3, filter_size=3,
                                padding=1, act="relu")
        v = fluid.layers.pool2d(v, pool_size=2, pool_stride=2,
                                pool_type="max", ceil_mode=True)
        v = fluid.layers.pool2d(v, pool_size=3, pool_stride=2,
                                pool_type="avg", ceil_mode=True,
                                pool_padding=1)
        logits = fluid.layers.fc(v, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    rng = np.random.RandomState(5)
    feed = {"x": rng.rand(3, 2, 7, 7).astype("float32"),
            "label": rng.randint(0, 4, (3, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        w_xla = np.asarray(scope.get_value("conv2d_0.w_0"))

    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        w_cpp = ns.get("conv2d_0.w_0")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(w_cpp, w_xla, rtol=1e-3, atol=1e-5)


def test_adam_tanh_sigmoid_train_step_parity_cpp_vs_xla(tmp_path):
    """r5: the C++ trainer gains adam/momentum optimizer kernels and
    tanh/sigmoid grads. One Adam step of a tanh+sigmoid MLP from
    identical params: loss, updated weight AND updated Adam moment must
    match the XLA executor (the beta-pow scale ops ride the existing
    scale kernel)."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 10, act="tanh")
        h = fluid.layers.fc(h, 8, act="sigmoid")
        logits = fluid.layers.fc(h, 4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

    rng = np.random.RandomState(21)
    feed = {"x": rng.randn(5, 6).astype("float32"),
            "label": rng.randint(0, 4, (5, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        w_xla = np.asarray(scope.get_value("fc_0.w_0"))
        m_name = [n for n in scope.local_var_names()
                  if n.startswith("fc_0.w_0_moment1")][0]
        m_xla = np.asarray(scope.get_value(m_name))

    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        w_cpp = ns.get("fc_0.w_0")
        m_cpp = ns.get(m_name)
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(w_cpp, w_xla, rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(m_cpp, m_xla, rtol=1e-3, atol=1e-5)


def test_elementwise_grads_train_step_parity_cpp_vs_xla(tmp_path):
    """r5: sub/mul/div backward in C++ (broadcast-reducing dY like the
    add grad). One SGD step of a net exercising all three with a
    broadcast scale parameter: loss + updated scale must match XLA."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3, 4], dtype="float32")
        t = fluid.layers.data(name="t", shape=[3, 4], dtype="float32")
        h = fluid.layers.fc(x, 4, num_flatten_dims=2, act="tanh",
                            name="ew_fc")
        scale = fluid.layers.create_parameter(
            [4], "float32", name="ew_scale",
            default_initializer=fluid.initializer.Constant(1.5))
        h = fluid.layers.elementwise_mul(h, scale, axis=2)
        h = fluid.layers.elementwise_div(
            h, fluid.layers.scale(t, scale=0.5, bias=2.0))
        d = fluid.layers.elementwise_sub(h, t)
        loss = fluid.layers.mean(fluid.layers.square(d))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    rng = np.random.RandomState(33)
    feed = {"x": rng.randn(2, 3, 4).astype("float32"),
            "t": rng.randn(2, 3, 4).astype("float32")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        s_xla = np.asarray(scope.get_value("ew_scale.w_0"))
        w_xla = np.asarray(scope.get_value("ew_fc.w_0"))

    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        s_cpp = ns.get("ew_scale.w_0")
        w_cpp = ns.get("ew_fc.w_0")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(s_cpp, s_xla, rtol=1e-3, atol=1e-5,
                               err_msg="broadcast dY reduction diverged")
    np.testing.assert_allclose(w_cpp, w_xla, rtol=1e-3, atol=1e-5)


def test_elementwise_grad_trailing_one_broadcast_parity(tmp_path):
    """Review-found geometry corner: y with a TRAILING 1 dim under the
    default axis (x [B,4,1]-style). The grad must resolve the axis from
    the untrimmed y rank exactly like the forward (shared
    ResolveBroadcast); the divergent trim-first version computed dY
    with the wrong index mapping."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 3], dtype="float32")
        t = fluid.layers.data(name="t", shape=[4, 3], dtype="float32")
        # rowscale [4, 1]: trailing-1 y, default axis -> aligns at dim 1
        rows = fluid.layers.create_parameter(
            [4, 1], "float32", name="rowscale",
            default_initializer=fluid.initializer.Constant(1.2))
        h = fluid.layers.elementwise_mul(x, rows)
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(h, t)))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(9)
    feed = {"x": rng.randn(2, 4, 3).astype("float32"),
            "t": rng.randn(2, 4, 3).astype("float32")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        r_xla = np.asarray(scope.get_value("rowscale.w_0"))
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        r_cpp = ns.get("rowscale.w_0")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r_cpp, r_xla, rtol=1e-3, atol=1e-5,
                               err_msg="trailing-1 broadcast dY diverged")


@pytest.mark.parametrize("peephole", [False, True])
@pytest.mark.parametrize("reverse", [False, True])
@pytest.mark.parametrize("with_len", [False, True])
def test_lstm_train_step_parity_cpp_vs_xla(tmp_path, peephole, reverse,
                                           with_len):
    """r5: BPTT for dynamic_lstm in C++ (adjoint of the forward
    recurrence, peepholes + reverse + padded-step pass-through). One
    SGD step from identical params: loss, updated recurrent weight AND
    updated bias (incl. peephole diagonals) must match the XLA
    executor's scan vjp."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    D, B, T = 3, 2, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[T, 4 * D],
                              dtype="float32")
        t = fluid.layers.data(name="t", shape=[D], dtype="float32")
        kwargs = {}
        if with_len:
            length = fluid.layers.data(name="len", shape=[1],
                                       dtype="int64")
            kwargs["length"] = length
        h, _c = fluid.layers.dynamic_lstm(
            x, size=4 * D, use_peepholes=peephole, is_reverse=reverse,
            **kwargs)
        pooled = fluid.layers.reduce_mean(h, dim=[1])
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pooled, t)))
        fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)

    rng = np.random.RandomState(4)
    feed = {"x": rng.randn(B, T, 4 * D).astype("float32") * 0.5,
            "t": rng.randn(B, D).astype("float32")}
    if with_len:
        feed["len"] = np.asarray([[T], [T - 2]], "int64")
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        w_xla = np.asarray(scope.get_value("lstm_0.w_0"))
        b_xla = np.asarray(scope.get_value("lstm_0.w_1"))

    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        w_cpp = ns.get("lstm_0.w_0")
        b_cpp = ns.get("lstm_0.w_1")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        w_cpp, w_xla, rtol=2e-3, atol=1e-5,
        err_msg="LSTM recurrent weight grad diverged")
    np.testing.assert_allclose(
        np.ravel(b_cpp), np.ravel(b_xla), rtol=2e-3, atol=1e-5,
        err_msg="LSTM bias (incl. peephole) grad diverged")


def test_stacked_lstm_book_model_train_step_parity_cpp_vs_xla(tmp_path):
    """Capstone for C++ training breadth (r5): ONE SGD step of the
    stacked-LSTM book model — embedding, two LSTM layers, MAX sequence
    pooling, softmax head — from identical deterministic params. Loss,
    the embedding table grad (lookup_table_grad scatter) and an LSTM
    weight must match the XLA executor."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.models import stacked_lstm
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, feeds, _outs = stacked_lstm.build(
            seq_len=6, dict_size=30, emb_dim=8, hid_dim=8,
            stacked_num=2, class_num=3)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(15)
    feed = {"words": rng.randint(0, 30, (3, 6)).astype("int64"),
            "length": np.asarray([[6], [4], [2]], "int64"),
            "label": rng.randint(0, 3, (3, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        emb_xla = np.asarray(scope.get_value("embedding_0.w_0"))
        w_xla = np.asarray(scope.get_value("lstm_0.w_0"))
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        emb_cpp = ns.get("embedding_0.w_0")
        w_cpp = ns.get("lstm_0.w_0")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(emb_cpp, emb_xla, rtol=2e-3, atol=1e-5,
                               err_msg="embedding grad diverged")
    np.testing.assert_allclose(w_cpp, w_xla, rtol=2e-3, atol=1e-5,
                               err_msg="stacked-LSTM weight diverged")


def test_demo_trainer_binary_trains_stacked_lstm(tmp_path):
    """The C++-only trainer binary now covers the SEQUENCE book-model
    family: the stacked-LSTM sentiment model (embedding + LSTMs + MAX
    pooling) trains loss-down on synthetic token-band classes with no
    Python in the process."""
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.models import stacked_lstm

    binary = _demo_binary("ptpu_demo_trainer")
    if binary is None:
        pytest.skip("cmake/ninja unavailable to build the demo binary")
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, _feeds, _outs = stacked_lstm.build(
            seq_len=16, dict_size=50, emb_dim=12, hid_dim=12,
            stacked_num=2, class_num=2)
        fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
    (tmp_path / "main.ptpb").write_bytes(serialize_program(main))
    (tmp_path / "startup.ptpb").write_bytes(serialize_program(startup))
    res = subprocess.run(
        [binary, str(tmp_path), loss.name, "30", "16", "seq"],
        capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr + res.stdout
    last_line = res.stdout.strip().splitlines()[-1]
    first, last = float(last_line.split()[1]), float(last_line.split()[3])
    assert last < 0.6 * first, res.stdout


def test_structural_grads_train_step_parity_cpp_vs_xla(tmp_path):
    """reshape/transpose grads in C++: one SGD step of a net that
    reshapes and transposes between fc layers matches XLA."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[2, 6], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 6, num_flatten_dims=2, act="tanh",
                            name="sg_fc1")
        h = fluid.layers.transpose(h, perm=[0, 2, 1])
        h = fluid.layers.reshape(h, shape=[-1, 12])
        logits = fluid.layers.fc(h, 3, name="sg_fc2")
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(8)
    feed = {"x": rng.randn(4, 2, 6).astype("float32"),
            "label": rng.randint(0, 3, (4, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        w_xla = np.asarray(scope.get_value("sg_fc1.w_0"))
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        w_cpp = ns.get("sg_fc1.w_0")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(w_cpp, w_xla, rtol=1e-3, atol=1e-5,
                               err_msg="grad through transpose/reshape "
                                       "diverged")


@pytest.mark.parametrize("reverse", [False, True])
@pytest.mark.parametrize("with_len", [False, True])
def test_gru_train_step_parity_cpp_vs_xla(tmp_path, reverse, with_len):
    """r5: BPTT for dynamic_gru in C++. One SGD step from identical
    params: loss, updated recurrent weight AND bias match the XLA
    scan vjp (reverse x length grid)."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    D, B, T = 3, 2, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[T, 3 * D],
                              dtype="float32")
        t = fluid.layers.data(name="t", shape=[D], dtype="float32")
        kwargs = {}
        if with_len:
            length = fluid.layers.data(name="len", shape=[1],
                                       dtype="int64")
            kwargs["length"] = length
        h = fluid.layers.dynamic_gru(x, size=D, is_reverse=reverse,
                                     **kwargs)
        pooled = fluid.layers.reduce_mean(h, dim=[1])
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pooled, t)))
        fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
    rng = np.random.RandomState(6)
    feed = {"x": rng.randn(B, T, 3 * D).astype("float32") * 0.5,
            "t": rng.randn(B, D).astype("float32")}
    if with_len:
        feed["len"] = np.asarray([[T], [T - 2]], "int64")
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        w_xla = np.asarray(scope.get_value("gru_0.w_0"))
        b_xla = np.asarray(scope.get_value("gru_0.w_1"))
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        w_cpp = ns.get("gru_0.w_0")
        b_cpp = ns.get("gru_0.w_1")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(w_cpp, w_xla, rtol=2e-3, atol=1e-5,
                               err_msg="GRU recurrent weight diverged")
    np.testing.assert_allclose(np.ravel(b_cpp), np.ravel(b_xla),
                               rtol=2e-3, atol=1e-5,
                               err_msg="GRU bias diverged")


@pytest.mark.parametrize("causal,window,kv_group",
                         [(True, 0, 1), (False, 0, 1), (True, 3, 1),
                          (True, 0, 2)])
def test_transformer_block_train_step_parity_cpp_vs_xla(
        tmp_path, causal, window, kv_group):
    """Transformer-block training in C++ (r5 capstone #2): one SGD step
    of a pre-norm attention block — fc projections, fused SDPA
    (causal/window/GQA grid), layer_norm, residual — matches the XLA
    executor on loss and the QKV projection weight."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    B, T, H, dh = 2, 4, 4, 4
    D = H * dh
    Hkv = H // kv_group
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[T, D], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")

        def heads(tv, nh):
            tv = fluid.layers.reshape(tv, [-1, T, nh, dh])
            return fluid.layers.transpose(tv, [0, 2, 1, 3])

        nx = fluid.layers.layer_norm(x, begin_norm_axis=2,
                                     name="blk_ln")
        q = heads(fluid.layers.fc(nx, D, num_flatten_dims=2,
                                  bias_attr=False, name="blk_q"), H)
        k = heads(fluid.layers.fc(nx, Hkv * dh, num_flatten_dims=2,
                                  bias_attr=False, name="blk_k"), Hkv)
        v = heads(fluid.layers.fc(nx, Hkv * dh, num_flatten_dims=2,
                                  bias_attr=False, name="blk_v"), Hkv)
        att = fluid.layers.scaled_dot_product_attention(
            q, k, v, causal=causal, window=window, kv_group=kv_group,
            impl="reference")
        att = fluid.layers.reshape(
            fluid.layers.transpose(att, [0, 2, 1, 3]), [-1, T, D])
        h = fluid.layers.elementwise_add(
            x, fluid.layers.fc(att, D, num_flatten_dims=2,
                               bias_attr=False, name="blk_o"))
        pooled = fluid.layers.reduce_mean(h, dim=[1])
        logits = fluid.layers.fc(pooled, 3, name="blk_head")
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(12)
    feed = {"x": rng.randn(B, T, D).astype("float32") * 0.5,
            "label": rng.randint(0, 3, (B, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        w_xla = np.asarray(scope.get_value("blk_q.w_0"))
        ln_xla = np.asarray(scope.get_value("blk_ln.w_0"))
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        w_cpp = ns.get("blk_q.w_0")
        ln_cpp = ns.get("blk_ln.w_0")
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(w_cpp, w_xla, rtol=2e-3, atol=1e-5,
                               err_msg="attention-path weight diverged")
    np.testing.assert_allclose(ln_cpp, ln_xla, rtol=2e-3, atol=1e-5,
                               err_msg="layer_norm scale grad diverged")


@pytest.mark.parametrize("with_len", [False, True])
def test_attention_lstm_train_step_parity_cpp_vs_xla(tmp_path, with_len):
    """Final sequence family (r5): the fused attention_lstm decoder
    trains in C++ — one SGD step through attention (stored-alpha
    softmax adjoint, tanh scores, state projection) and the LSTM cell,
    H0 grads included, matches the XLA executor on loss and every
    attention/cell parameter."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.layer_helper import LayerHelper
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    B, T, S, D, C, M = 2, 3, 4, 3, 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[T, M], dtype="float32")
        ev = fluid.layers.data(name="ev", shape=[S, C], dtype="float32")
        ep = fluid.layers.data(name="ep", shape=[S, D], dtype="float32")
        t = fluid.layers.data(name="t", shape=[D], dtype="float32")
        h0 = fluid.layers.reduce_mean(
            fluid.layers.fc(x, D, num_flatten_dims=2, name="al_h0"),
            dim=[1])
        wsa = fluid.layers.create_parameter([D, D], "float32",
                                            name="al_wsa")
        waa = fluid.layers.create_parameter([2 * D, 1], "float32",
                                            name="al_waa")
        cw = fluid.layers.create_parameter([D + C + M, 4 * D],
                                           "float32", name="al_cw")
        cb = fluid.layers.create_parameter([1, 4 * D], "float32",
                                           name="al_cb")
        helper = LayerHelper("al")
        hid = helper.create_variable_for_type_inference("float32")
        cell = helper.create_variable_for_type_inference("float32")
        aw = helper.create_variable_for_type_inference("float32")
        inputs = {"X": [x.name], "EncoderVec": [ev.name],
                  "EncoderProj": [ep.name], "H0": [h0.name],
                  "StateProjW": [wsa.name], "AttnW": [waa.name],
                  "CellW": [cw.name], "CellB": [cb.name]}
        feed = {}
        if with_len:
            el = fluid.layers.data(name="el", shape=[1], dtype="int64")
            inputs["EncoderLen"] = [el.name]
            feed["el"] = np.asarray([[S], [S - 2]], "int64")
        helper.append_op(type="attention_lstm", inputs=inputs,
                         outputs={"Hidden": [hid.name],
                                  "Cell": [cell.name],
                                  "AttentionWeight": [aw.name]})
        pooled = fluid.layers.reduce_mean(hid, dim=[1])
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pooled, t)))
        fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
    rng = np.random.RandomState(2)
    feed.update({
        "x": (rng.randn(B, T, M) * 0.4).astype("float32"),
        "ev": rng.randn(B, S, C).astype("float32"),
        "ep": (rng.randn(B, S, D) * 0.4).astype("float32"),
        "t": rng.randn(B, D).astype("float32"),
    })
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        want = {n: np.asarray(scope.get_value(n))
                for n in ("al_wsa.w_0", "al_waa.w_0", "al_cw.w_0",
                          "al_cb.w_0", "al_h0.w_0")}
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        got = {n: ns.get(n) for n in want}
    finally:
        lib.ptpu_program_destroy(prog)
    np.testing.assert_allclose(np.ravel(cpp_loss)[0],
                               np.ravel(np.asarray(xla_loss))[0],
                               rtol=1e-4, atol=1e-5)
    for n in sorted(want):
        np.testing.assert_allclose(
            got[n], want[n], rtol=2e-3, atol=1e-5,
            err_msg="attention_lstm param %s diverged" % n)


def test_machine_translation_full_train_step_parity_cpp_vs_xla(tmp_path):
    """THE sequence capstone (r5): one SGD step of the FULL machine-
    translation golden model — source/target embeddings, bi-directional
    LSTM encoder, fused attention-LSTM decoder, masked CE head — from
    identical deterministic params. Loss plus every updated parameter
    must match the XLA executor; this exercises concat/lookup/seq-pool/
    LSTM/attention-LSTM/elementwise/reduce/reshape grads in one
    program."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.models import machine_translation as mt
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    V, Ts, Tt = 40, 5, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = mt.build(src_vocab=V, tgt_vocab=V, src_seq_len=Ts,
                        tgt_seq_len=Tt, emb_dim=8, encoder_size=8,
                        decoder_size=8)[0]
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(44)
    B = 2
    feed = {
        "source_sequence": rng.randint(1, V, (B, Ts)).astype("int64"),
        "source_length": np.asarray([[Ts], [Ts - 2]], "int64"),
        "target_sequence": rng.randint(1, V, (B, Tt)).astype("int64"),
        "label": rng.randint(1, V, (B, Tt)).astype("int64"),
        "label_mask": np.ones((B, Tt), "float32"),
    }
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        # compare the UPDATED PARAMETERS (the pre-step persistables),
        # not every scope float — intermediates/grad slots the native
        # engine legitimately handles differently would false-alarm
        after = {n: np.asarray(scope.get_value(n))
                 for n in params
                 if scope.get_value(n) is not None}
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        np.testing.assert_allclose(
            np.ravel(cpp_loss)[0], np.ravel(np.asarray(xla_loss))[0],
            rtol=1e-4, atol=1e-5)
        changed = 0
        for name, want in sorted(after.items()):
            if want.dtype.kind != "f":
                continue
            got = ns.get(name)
            assert got is not None, "missing %r" % name
            np.testing.assert_allclose(
                got, want, rtol=3e-3, atol=1e-5,
                err_msg="MT param %s diverged" % name)
            if not np.array_equal(np.asarray(got), params[name]):
                changed += 1
        assert changed >= 10, (
            "only %d params changed — the step didn't train" % changed)
    finally:
        lib.ptpu_program_destroy(prog)


def test_batch_norm_train_step_parity_cpp_vs_xla(tmp_path):
    """r5: TRAINING-mode batch_norm in C++ (batch stats, running-stat
    momentum update, classic adjoint). One SGD step of a conv+BN+relu
    block: loss, conv filter, BN scale/bias AND the updated running
    mean/variance must match the XLA executor."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[2, 6, 6], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        v = fluid.layers.conv2d(x, 4, 3, padding=1, bias_attr=False)
        v = fluid.layers.batch_norm(v, act="relu")   # TRAIN mode
        logits = fluid.layers.fc(v, 3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(19)
    feed = {"x": rng.randn(3, 2, 6, 6).astype("float32"),
            "label": rng.randint(0, 3, (3, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        want = {n: np.asarray(scope.get_value(n))
                for n in params}
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        np.testing.assert_allclose(
            np.ravel(cpp_loss)[0], np.ravel(np.asarray(xla_loss))[0],
            rtol=1e-4, atol=1e-5)
        for name in sorted(want):
            if want[name].dtype.kind != "f":
                continue
            got = ns.get(name)
            assert got is not None, "missing %r" % name
            np.testing.assert_allclose(
                got, want[name], rtol=2e-3, atol=1e-5,
                err_msg="BN-block var %s diverged (incl. running "
                        "stats)" % name)
    finally:
        lib.ptpu_program_destroy(prog)


def test_resnet_cifar_train_step_parity_cpp_vs_xla(tmp_path):
    """With training-mode batch_norm, a REAL ResNet (resnet_cifar10
    depth-8: conv+BN residual blocks with projection shortcuts) trains
    one SGD step in C++ with loss and every parameter incl. BN running
    stats matching the XLA executor."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.models import resnet
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="pixel", shape=[3, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        pred = resnet.resnet_cifar10(img, class_dim=4, depth=8)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    rng = np.random.RandomState(23)
    feed = {"pixel": rng.rand(2, 3, 8, 8).astype("float32"),
            "label": rng.randint(0, 4, (2, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        want = {n: np.asarray(scope.get_value(n)) for n in params}
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        np.testing.assert_allclose(
            np.ravel(cpp_loss)[0], np.ravel(np.asarray(xla_loss))[0],
            rtol=1e-4, atol=1e-5)
        for name in sorted(want):
            if want[name].dtype.kind != "f":
                continue
            got = ns.get(name)
            assert got is not None, "missing %r" % name
            np.testing.assert_allclose(
                got, want[name], rtol=3e-3, atol=2e-5,
                err_msg="resnet var %s diverged" % name)
    finally:
        lib.ptpu_program_destroy(prog)


def test_alexnet_style_train_step_parity_cpp_vs_xla(tmp_path):
    """lrn_grad completes the classic-CNN family: one SGD step of an
    AlexNet-style conv+lrn+pool stack matches XLA on loss and every
    parameter (the cross-channel lrn adjoint exercised at n=5 and even
    n=4)."""
    from paddle_tpu import native
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    if not native.available():
        pytest.skip("native toolchain unavailable: %s"
                    % native.last_error())
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3, 8, 8], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        v = fluid.layers.conv2d(x, 6, 3, padding=1, act="relu")
        v = fluid.layers.lrn(v, n=5)
        v = fluid.layers.pool2d(v, pool_size=2, pool_stride=2,
                                pool_type="max")
        v = fluid.layers.conv2d(v, 8, 3, padding=1, act="relu")
        v = fluid.layers.lrn(v, n=4)   # even-n window corner
        logits = fluid.layers.fc(v, 4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    rng = np.random.RandomState(27)
    feed = {"x": rng.rand(2, 3, 8, 8).astype("float32"),
            "label": rng.randint(0, 4, (2, 1)).astype("int64")}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        want = {n: np.asarray(scope.get_value(n)) for n in params}
    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    assert prog, native.last_error()
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        assert rc == 0, native.last_error()
        cpp_loss = ns.get(loss.name)
        np.testing.assert_allclose(
            np.ravel(cpp_loss)[0], np.ravel(np.asarray(xla_loss))[0],
            rtol=1e-4, atol=1e-5)
        for name in sorted(want):
            if want[name].dtype.kind != "f":
                continue
            got = ns.get(name)
            assert got is not None, "missing %r" % name
            np.testing.assert_allclose(
                got, want[name], rtol=3e-3, atol=2e-5,
                err_msg="alexnet-style var %s diverged" % name)
    finally:
        lib.ptpu_program_destroy(prog)
