"""Golden API-surface test (paddle/fluid/API.spec +
tools/print_signatures.py parity): the committed API.spec must match the
live public signatures; regenerate deliberately with
`python tools/print_signatures.py --update` when the API changes."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import print_signatures  # noqa: E402


def test_api_spec_matches_committed_golden():
    live = list(print_signatures.iter_spec())
    with open(os.path.join(REPO, "API.spec")) as f:
        committed = [
            line for line in f.read().splitlines()
            # '#' lines annotate DELIBERATE absences vs the reference
            # surface (async-pserver methods etc.); they are docs, not
            # signatures
            if line.strip() and not line.lstrip().startswith("#")
        ]
    live_set, committed_set = set(live), set(committed)
    removed = committed_set - live_set
    added = live_set - committed_set
    msg = []
    if removed:
        msg.append("API signatures removed/changed:\n  " +
                   "\n  ".join(sorted(removed)[:20]))
    if added:
        msg.append("API signatures added (update API.spec):\n  " +
                   "\n  ".join(sorted(added)[:20]))
    assert not msg, (
        "\n".join(msg) +
        "\nIf intentional: python tools/print_signatures.py --update"
    )


def test_api_spec_covers_core_surface():
    with open(os.path.join(REPO, "API.spec")) as f:
        spec = f.read()
    for must in [
        "paddle_tpu.layers.nn.fc ",
        "paddle_tpu.layers.nn.conv2d ",
        "paddle_tpu.layers.detection.ssd_loss ",
        "paddle_tpu.optimizer.Adam CLASS",
        "paddle_tpu.io.save_inference_model ",
        "paddle_tpu.backward.append_backward ",
    ]:
        assert must in spec, "missing from API.spec: %r" % must
