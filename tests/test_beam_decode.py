"""Batched beam search over the paged slot pool (PR 15), pinned at the
BIT level:

* the in-graph ``slot_beam_search`` selection is bit-exact against the
  dense ``beam_step`` lattice replayed OFFLINE over the step's own
  fetched logits (same reshapes, same parent gather, same float32
  log-softmax);
* the zero-copy rebind reorder decodes bit-identical tokens AND scores
  to the ``FLAGS_beam_reorder=reference`` copy-reorder oracle at
  staggered admissions — while physically moving ZERO pages (the
  oracle moves O(resident) per reorder);
* COW pairs coalesce into ONE bucket-laddered dispatch per step window
  (the dispatch count is pinned — beam reorders multiply pairs, not
  dispatches);
* ``cancel`` of any hypothesis releases the WHOLE beam with the pool
  conserved (the PR 14 disconnect path);
* a mid-beam ``DecodeSnapshotManager`` snapshot restores scores,
  parent maps and hypothesis->slot bindings bit-exactly (geometry
  drift raises the typed ``SnapshotMismatchError``), and
  ``tools/ckpt_inspect.py --verify`` cross-checks the beam bindings
  against the refcounts (exit 2 on a tampered binding);
* warm beam churn adds 0 fresh compiles.
"""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags as _flags
from paddle_tpu.core import exec_cache
from paddle_tpu.executor import global_scope
from paddle_tpu.serving.generation import (
    NoFreeSlotError,
    Sampler,
    SlotDecodeSession,
)

VOCAB, SEQ, D, S, BW = 26, 12, 32, 8, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=2,
           n_head=2, d_inner=64)
# both reorder modes share ONE geometry (and one content-addressed
# program set); the copy oracle's transient full-list copies need the
# free-page headroom
PAGES = 1 + 2 * S * (SEQ // 4 + 1)


@pytest.fixture(scope="module")
def trained():
    """One tiny trained 2-layer transformer (per-layer pools, COW and
    reorder all exercised past layer 0)."""
    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 43
    startup.random_seed = 43
    scope = global_scope()
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, max_length=SEQ,
            d_model=D, **CFG)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(44)
    # a handful of steps is enough: the suite pins BIT-equalities
    # between decode modes, not model quality — it only needs
    # deterministic, non-degenerate logits (enough spread that beams
    # actually diverge and COW fires; asserted downstream)
    for _ in range(6):
        src = rng.randint(3, VOCAB, (8, SEQ)).astype("int64")
        trg = np.full_like(src, 1)
        trg[:, 1:] = src[:, :-1]
        exe.run(main, feed={
            "src_word": src,
            "src_len": np.full((8, 1), SEQ, "int64"),
            "trg_word": trg,
            "trg_len": np.full((8, 1), SEQ, "int64"),
            "label": src,
        }, fetch_list=[loss])
    src = rng.randint(3, VOCAB, (4, SEQ)).astype("int64")
    return {"exe": exe, "scope": scope, "src": src}


def _beam(trained, **kw):
    args = dict(num_slots=S, max_length=SEQ, d_model=D, paged=True,
                page_size=4, beam_width=BW, num_pages=PAGES,
                scope=trained["scope"].new_scope())
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


def _staggered(sess, src, keep_going=True):
    """Two beams admitted 3 dispatches apart, a third back-to-back —
    the reorder/COW/release paths at mixed lane ages."""
    a = sess.admit_beam(src[0], SEQ)
    ra = sess.register_beam_owner(a)
    for _ in range(3):
        sess.step()
    b = sess.admit_beam(src[1], SEQ - 2)
    rb = sess.register_beam_owner(b)
    while sess.active_beams:
        sess.step()
    out = [sess.take_beam_result(ra), sess.take_beam_result(rb)]
    if keep_going:
        out.append(dict(zip(("tokens", "scores"),
                            sess.generate_beam(src[2], SEQ))))
    return out


# ---------------------------------------------------------------------------
# the lattice itself: in-graph selection == dense beam_step offline
# ---------------------------------------------------------------------------

def test_in_graph_selection_matches_offline_dense_lattice(trained):
    """Per step, the fetched (token, parent, score) must be bit-equal
    to ``ops.beam_search_ops.beam_step`` run OFFLINE on the step's own
    fetched logits with the session's pre-step lattice state — the
    proof that the slot-pool beam is the dense lattice, reshaped."""
    import jax

    from paddle_tpu.ops.beam_search_ops import beam_step

    sess = _beam(trained)
    scope = sess._scope
    lane = sess.admit_beam(trained["src"][0], SEQ)
    slots = sess.beam_slots(lane)
    # ride the step dispatch with a logits fetch (the builder exports
    # the name for exactly this test)
    sess._extra_step_fetches = [sess._beam_fetches["logits"]]
    checked = 0
    for _ in range(SEQ):
        if lane not in sess._beam_live:
            break
        pre_tok = np.asarray(scope.get_value("pgd_tok")).reshape(-1)
        pre_done = np.asarray(scope.get_value("pgd_done")).reshape(-1)
        pre_score = np.asarray(
            scope.get_value("pgd_score")).reshape(-1)
        sess.step()
        logits = sess.last_extra_fetches[0][:, 0, :].astype(np.float32)
        # offline replay of the op's lattice, lane slice only (lanes
        # are independent rows of the [B, K, V] lattice)
        forced = np.where(pre_done > 0, sess._eos, pre_tok)
        logp = np.asarray(jax.nn.log_softmax(logits[slots], axis=-1))
        tok, sel, parent = beam_step(
            forced[slots].reshape(1, BW).astype(np.int32),
            pre_score[slots].reshape(1, BW).astype(np.float32),
            logp.reshape(1, BW, -1), sess._eos, is_accumulated=False)
        ev = sess.last_beam_events.get(lane)
        if ev is None:  # the finishing step: compare the final chunk
            fin = sess.last_finished_beams[lane]
            got = (fin["step_tokens"], fin["parents"],
                   fin["step_scores"])
        else:
            got = (ev["tokens"], ev["parents"], ev["scores"])
        np.testing.assert_array_equal(np.asarray(tok).reshape(-1),
                                      got[0])
        np.testing.assert_array_equal(np.asarray(parent).reshape(-1),
                                      got[1])
        np.testing.assert_array_equal(
            np.asarray(sel, np.float32).reshape(-1),
            np.asarray(got[2], np.float32))
        checked += 1
    assert checked >= 3, "lattice never compared across a real decode"


# ---------------------------------------------------------------------------
# the tentpole: zero-copy rebind == copy oracle, staggered
# ---------------------------------------------------------------------------

def test_rebind_matches_copy_oracle_and_moves_zero_pages(trained):
    src = trained["src"]
    swap = _beam(trained)
    got = _staggered(swap, src, keep_going=False)
    # THE zero-copy law: every reorder this decode performed was pure
    # table-row rebinds + refcount moves — no KV page was copied to
    # execute a permutation (COW write-page splits are counted apart)
    assert swap.beam_reorder_pages == 0
    assert swap.pool_conserved and swap.pages_in_use == 0

    _flags.set_flag("beam_reorder", "reference")
    try:
        copy_sess = _beam(trained)
        ref = _staggered(copy_sess, src, keep_going=False)
    finally:
        _flags.set_flag("beam_reorder", "rebind")
    assert copy_sess.beam_reorder_pages > 0, \
        "the copy oracle never copied a page"
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g["tokens"], r["tokens"])
        np.testing.assert_array_equal(g["scores"], r["scores"])
    assert copy_sess.pool_conserved and copy_sess.pages_in_use == 0


def test_warm_beam_rerun_adds_zero_fresh_compiles(trained):
    sess = _beam(trained)
    # warmup compiles the beam set (incl. generate_beam's path)
    first = _staggered(sess, trained["src"])
    before = exec_cache.stats()["fresh_compiles"]
    again = _staggered(sess, trained["src"])
    assert exec_cache.stats()["fresh_compiles"] == before, \
        "staggered beam churn recompiled at warm steady state"
    # and the re-run is deterministic (greedy lattice, same pages)
    for g, r in zip(again, first):
        np.testing.assert_array_equal(g["tokens"], r["tokens"])


def test_cow_dispatches_coalesce_per_step_window(trained):
    """The satellite pin: COW pairs multiply per beam step (duplicated
    parents x layers of pages), but dispatches must NOT — one
    bucket-laddered executable per step window."""
    sess = _beam(trained)
    sess.generate_beam(trained["src"][0], SEQ)
    assert sess.cow_pairs > sess.cow_dispatches, (
        "coalescing never happened: %d pairs took %d dispatches"
        % (sess.cow_pairs, sess.cow_dispatches))
    # at most ONE coalesced dispatch per step window (+1 for the
    # admission-time provisioning none of these shapes need)
    assert sess.cow_dispatches <= sess.steps_done, (
        "%d COW dispatches over %d step windows — the window split"
        % (sess.cow_dispatches, sess.steps_done))


def test_cancel_releases_whole_beam_and_conserves(trained):
    sess = _beam(trained)
    lane = sess.admit_beam(trained["src"][0], SEQ)
    for _ in range(3):
        sess.step()
    slots = sess.beam_slots(lane)
    assert sess.cancel(slots[2])  # ANY member tears the whole beam down
    assert not sess.active_beams and sess.free_beams == S // BW
    assert sess.free_slots == S and not sess.active_slots
    assert sess.pool_conserved and sess.pages_in_use == 0
    # the lane is immediately reusable, bit-identically
    t1, s1 = sess.generate_beam(trained["src"][0], SEQ)
    t2, s2 = sess.generate_beam(trained["src"][0], SEQ)
    np.testing.assert_array_equal(t1, t2)


def test_beam_admission_rejects_are_typed(trained):
    sess = _beam(trained)
    lanes = [sess.admit_beam(trained["src"][i % 4], SEQ)
             for i in range(S // BW)]
    with pytest.raises(NoFreeSlotError):
        sess.admit_beam(trained["src"][0], SEQ)
    for lane in lanes:
        sess.cancel(sess.beam_slots(lane)[0])
    # beam sessions are admit-or-reject: the solo backlog is refused
    with pytest.raises(ValueError):
        sess.enqueue(trained["src"][0], SEQ)
    with pytest.raises(ValueError):
        sess.admit_group(trained["src"][0], n=2)
    # and a beam session cannot be mis-built
    with pytest.raises(ValueError):
        _beam(trained, steps=2)
    with pytest.raises(ValueError):
        _beam(trained, beam_width=3)  # 8 % 3 != 0
    with pytest.raises(ValueError):
        _beam(trained, sampler=Sampler(strategy="temperature",
                                       temperature=0.8))


def test_beam_shared_prefix_pages_count_once(trained):
    """A beam over a forced prefix references the prefix pages from
    every hypothesis — physically stored ONCE (the LONG_CONTEXT row)."""
    sess = _beam(trained)
    pfx = [int(t) for t in trained["src"][0][:7]]
    lane = sess.admit_beam(trained["src"][0], SEQ, prefix_tokens=pfx)
    # 7 forced tokens + bos at page_size 4 => 1 FULL shared prefix page
    # (+ the partial tail each hypothesis may COW later); 4 hypotheses
    # referencing it physically allocate 1, not 4
    assert sess.shared_pages >= 1
    full_prefix_pages = (len(pfx) + 1 - 1) // 4  # positions 0..6
    assert sess.pages_in_use < BW * (full_prefix_pages + 1) + 2
    sess.cancel(sess.beam_slots(lane)[0])
    assert sess.pool_conserved


# ---------------------------------------------------------------------------
# snapshot + inspector coverage
# ---------------------------------------------------------------------------

def test_snapshot_restores_mid_beam_bit_exact(trained, tmp_path):
    from paddle_tpu.serving.snapshot import (
        DecodeSnapshotManager,
        SnapshotMismatchError,
    )

    src = trained["src"]
    oracle = _beam(trained)
    want = _staggered(oracle, src, keep_going=False)

    victim = _beam(trained)
    a = victim.admit_beam(src[0], SEQ)
    ra = victim.register_beam_owner(a)
    for _ in range(3):
        victim.step()
    b = victim.admit_beam(src[1], SEQ - 2)
    rb = victim.register_beam_owner(b)
    mgr = DecodeSnapshotManager(victim, str(tmp_path))
    mgr.save()
    mgr.close(save=False)

    restored = _beam(trained)
    mgr2 = DecodeSnapshotManager(restored, str(tmp_path))
    assert mgr2.restore() is not None
    while restored.active_beams:
        restored.step()
    got = [restored.take_beam_result(ra),
           restored.take_beam_result(rb)]
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g["tokens"], w["tokens"])
        np.testing.assert_array_equal(g["scores"], w["scores"])
    mgr2.close(save=False)

    # geometry drift (a different beam tiling between the snapshot and
    # the session) is the TYPED error — drift the recorded width so the
    # bw=4 session we already have plays the mismatched restorer
    step_dir = sorted(glob.glob(str(tmp_path / "checkpoint_*")))[-1]
    mpath = os.path.join(step_dir, "__manifest__.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["extra"]["decode_snapshot"]["config"]["beam_width"] = 2
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(SnapshotMismatchError):
        DecodeSnapshotManager(restored, str(tmp_path)).restore()


def test_ckpt_inspect_prints_and_verifies_beam_state(trained,
                                                     tmp_path):
    from paddle_tpu.serving.snapshot import DecodeSnapshotManager

    sess = _beam(trained)
    sess.admit_beam(trained["src"][0], SEQ)
    for _ in range(2):
        sess.step()
    mgr = DecodeSnapshotManager(sess, str(tmp_path))
    mgr.save()
    mgr.close(save=False)
    step_dir = sorted(glob.glob(str(tmp_path / "checkpoint_*")))[-1]
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "ckpt_inspect.py")

    r = subprocess.run([sys.executable, tool, step_dir, "--verify"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "beam: width=4" in r.stdout and "lane 0:" in r.stdout

    # tamper the beam binding (a lane claiming a non-live slot): the
    # refcount/binding cross-check must fail OFFLINE with exit 2
    mpath = os.path.join(step_dir, "__manifest__.json")
    with open(mpath) as f:
        manifest = json.load(f)
    ds = manifest["extra"]["decode_snapshot"]
    lane0 = sorted(ds["beam"]["lanes"])[0]
    ds["beam"]["lanes"][lane0]["slots"][-1] = S - 1  # a free slot
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    r = subprocess.run([sys.executable, tool, step_dir, "--verify"],
                       capture_output=True, text=True)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "beam" in r.stdout
