"""Knobs the XLA execution model makes meaningless must WARN, not pass
silently (docs/XLA_EXECUTION.md; the reference honors these knobs, so a
porting user needs to hear about the difference immediately)."""

import warnings

import pytest

import paddle_tpu as fluid


def _tiny_train_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_fuse_elewise_knob_fuses_not_warns():
    # the knob is honored now (core/passes.py fuse_elewise_add_act), so it
    # must rewrite the graph and NOT warn
    main, startup, loss = _tiny_train_program()
    bs = fluid.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                               build_strategy=bs, num_devices=1)


def test_gradient_scale_strategy_warns():
    main, startup, loss = _tiny_train_program()
    bs = fluid.BuildStrategy()
    bs.gradient_scale_strategy = fluid.BuildStrategy.GradientScaleStrategy.One
    with pytest.warns(UserWarning, match="gradient_scale_strategy"):
        fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                               build_strategy=bs, num_devices=1)


def test_exec_strategy_scheduler_knobs_warn():
    main, startup, loss = _tiny_train_program()
    es = fluid.ExecutionStrategy()
    es.num_threads = 8
    es.allow_op_delay = True
    with pytest.warns(UserWarning, match="num_threads"):
        fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                               exec_strategy=es, num_devices=1)


def test_default_strategies_do_not_warn():
    main, startup, loss = _tiny_train_program()
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                               num_devices=1)


def test_async_dist_transpile_warns():
    main, startup, loss = _tiny_train_program()
    t = fluid.DistributeTranspiler()
    with pytest.warns(UserWarning, match="SYNCHRONOUS"):
        t.transpile(trainer_id=0, program=main, startup_program=startup,
                    pservers="127.0.0.1:6174", trainers=1, sync_mode=False)
