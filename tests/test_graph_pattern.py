"""GraphPatternDetector + fusion passes (graph_pattern_detector.cc,
fc_fuse_pass.cc, fuse_elewise_add_act_pass.cc roles): structural matches,
graph rewrites, and numeric parity fused-vs-unfused."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.graph_pattern import (
    GraphPatternDetector,
    consumers,
    producer,
)
from paddle_tpu.core.passes import apply_pass


def _mlp_infer_program():
    """x -> fc(mul+add) -> relu -> fc(mul+add) chain, built from raw ops."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        out = fluid.layers.fc(input=h, size=4)
        sm = fluid.layers.softmax(out)
    return main, startup, sm


def test_detector_matches_mul_add_chain():
    main, _, _ = _mlp_infer_program()
    block = main.block(0)
    pat = GraphPatternDetector()
    pat.op("mul", "mul", inputs={"X": "x", "Y": "w"}, outputs={"Out": "mid"})
    pat.op("add", "elementwise_add",
           inputs={"X": "mid", "Y": "b"}, outputs={"Out": "out"})
    matches = pat.detect(block)
    assert len(matches) == 2  # two fc layers
    m = matches[0]
    assert m.op("mul").type == "mul"
    assert m.var("mid") in m.op("add").input("X")
    # matches are disjoint
    assert not set(matches[0].op_indices()) & set(matches[1].op_indices())


def test_detector_edge_constraint_rejects_disconnected():
    main, _, _ = _mlp_infer_program()
    block = main.block(0)
    pat = GraphPatternDetector()
    # softmax's input must equal the FIRST mul's output: no such chain
    pat.op("mul", "mul", outputs={"Out": "v"})
    pat.op("sm", "softmax", inputs={"X": "v"})
    assert pat.detect(block) == []


def test_producer_consumers_helpers():
    main, _, _ = _mlp_infer_program()
    block = main.block(0)
    mul_out = block.ops[0].output("Out")[0]
    i, op = producer(block, mul_out)
    assert op.type == "mul" and i == 0
    cons = consumers(block, mul_out)
    assert [c[1].type for c in cons] == ["elementwise_add"]


def _run(main, startup, fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=[fetch])[0]


def test_fc_fuse_pass_structure_and_numerics():
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(5, 8).astype("float32")}
    main, startup, sm = _mlp_infer_program()
    ref = _run(main, startup, sm, feed)

    apply_pass(main, "fc_fuse")
    types = [op.type for op in main.block(0).ops]
    assert types.count("fc") == 2
    assert "mul" not in types and "elementwise_add" not in types
    # first fc absorbed its relu
    fcs = [op for op in main.block(0).ops if op.type == "fc"]
    assert fcs[0].attrs["activation_type"] == "relu"
    assert fcs[1].attrs["activation_type"] == ""
    got = _run(main, startup, sm, feed)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_fc_fuse_skips_shared_intermediate():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=4)
        # fc's internal mul output feeds ONLY the add; but the fc OUTPUT
        # feeding two consumers must not block fusion of the chain itself
        a = fluid.layers.relu(h)
        b = fluid.layers.tanh(h)
        out = fluid.layers.elementwise_add(a, b)
    apply_pass(main, "fc_fuse")
    types = [op.type for op in main.block(0).ops]
    # plain fc fused; the trailing act was NOT absorbed (h has 2 readers)
    assert "fc" in types and "relu" in types and "tanh" in types


def _add_act_train_program():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        w = fluid.layers.create_parameter(shape=[6, 6], dtype="float32",
                                          name="w_aa")
        z = fluid.layers.relu(
            fluid.layers.elementwise_add(fluid.layers.matmul(x, w), x))
        pred = fluid.layers.fc(input=z, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _train_losses(main, startup, loss, steps=4):
    rng = np.random.RandomState(5)
    feeds = [
        {"x": rng.rand(4, 6).astype("float32"),
         "y": rng.rand(4, 1).astype("float32")}
        for _ in range(steps)
    ]
    exe = fluid.Executor(fluid.CPUPlace())
    out = []
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        for f in feeds:
            out.append(float(exe.run(main, feed=f,
                                     fetch_list=[loss])[0].ravel()[0]))
    return out


def test_fuse_elewise_add_act_training_parity():
    ref = _train_losses(*_add_act_train_program())

    main, startup, loss = _add_act_train_program()
    apply_pass(main, "fuse_elewise_add_act")
    types = [op.type for op in main.block(0).ops]
    assert "fused_elemwise_activation" in types
    assert "relu" not in types
    # the backward twin collapsed too (the fc layer's own bias add_grad,
    # which has no paired activation, legitimately remains)
    assert "fused_elemwise_activation_grad" in types
    assert "relu_grad" not in types
    assert types.count("elementwise_add_grad") == 1
    got = _train_losses(main, startup, loss)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_fuse_keeps_intermediate_consumers_working():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        s = fluid.layers.elementwise_add(x, x)
        r = fluid.layers.relu(s)
        # a second reader of the pre-activation sum
        out = fluid.layers.elementwise_add(r, s)
    apply_pass(main, "fuse_elewise_add_act")
    types = [op.type for op in main.block(0).ops]
    assert "fused_elemwise_activation" in types
    feed = {"x": np.array([[-1.0, 2.0, -3.0, 4.0]], dtype="float32")}
    got = _run(main, startup, out, feed)
    np.testing.assert_allclose(
        got, np.array([[-2.0, 8.0, -6.0, 16.0]], dtype="float32"))


def test_fused_grad_op_keeps_backward_role():
    from paddle_tpu.framework import OP_ROLE_ATTR_NAME

    main, startup, loss = _add_act_train_program()
    roles = {op.type: op.attrs.get(OP_ROLE_ATTR_NAME)
             for op in main.block(0).ops}
    apply_pass(main, "fuse_elewise_add_act")
    for op in main.block(0).ops:
        if op.type == "fused_elemwise_activation":
            assert op.attrs[OP_ROLE_ATTR_NAME] == roles["elementwise_add"]
        if op.type == "fused_elemwise_activation_grad":
            # role-keyed passes (pipeline cut, gradient merge) must still
            # see a Backward op
            assert op.attrs[OP_ROLE_ATTR_NAME] == roles["elementwise_add_grad"]


def test_fc_fuse_rejects_axis0_bias():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        w = fluid.layers.create_parameter(shape=[4, 4], dtype="float32",
                                          name="w_ax")
        b = fluid.layers.create_parameter(shape=[3], dtype="float32",
                                          name="b_ax")
        h = fluid.layers.mul(x, w)
        # axis=0 broadcasts the bias per ROW — not what fc computes
        out = fluid.layers.elementwise_add(h, b, axis=0)
    apply_pass(main, "fc_fuse")
    assert "fc" not in [op.type for op in main.block(0).ops]


def test_fuse_interleaved_matches_stay_correct():
    """Two add+act chains whose act order is INVERTED vs their add order:
    the second processed match's recorded indices go stale after the
    first rewrite — it must be retried on fresh indices, not rewritten
    with stale ones (which deleted the model output op)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        a = fluid.layers.elementwise_add(x, x)       # op 0
        b = fluid.layers.elementwise_add(a, x)       # op 1
        r2 = fluid.layers.relu(b)                    # op 2: act for add 1
        r1 = fluid.layers.tanh(a)                    # op 3: act for add 0
        out = fluid.layers.elementwise_add(r1, r2)   # op 4: model output
    apply_pass(main, "fuse_elewise_add_act")
    types = [op.type for op in main.block(0).ops]
    assert types.count("fused_elemwise_activation") == 2
    assert "relu" not in types and "tanh" not in types
    # the final combining add survives and still produces the output
    feed = {"x": np.array([[1.0, -2.0, 3.0, -4.0]], dtype="float32")}
    got = _run(main, startup, out, feed)
    xv = feed["x"]
    np.testing.assert_allclose(
        got, np.tanh(2 * xv) + np.maximum(3 * xv, 0.0), rtol=1e-6)


def _lstm_infer_program(rnn="lstm"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6, 8], dtype="float32")
        if rnn == "lstm":
            proj = fluid.layers.fc(input=x, size=4 * 12, num_flatten_dims=2)
            out, _ = fluid.layers.dynamic_lstm(input=proj, size=4 * 12)
        else:
            proj = fluid.layers.fc(input=x, size=3 * 12, num_flatten_dims=2)
            out = fluid.layers.dynamic_gru(input=proj, size=12)
        final = fluid.layers.reduce_mean(out)
    return main, startup, final


@pytest.mark.parametrize("rnn", ["lstm", "gru"])
def test_fc_rnn_fuse_structure_and_numerics(rnn):
    """fc_lstm_fuse_pass.cc / fc_gru_fuse_pass.cc role: the projection fc
    collapses into fusion_lstm / fusion_gru with identical numerics."""
    rng = np.random.RandomState(2)
    feed = {"x": rng.rand(3, 6, 8).astype("float32")}
    main, startup, final = _lstm_infer_program(rnn)
    ref = _run(main, startup, final, feed)

    apply_pass(main, "fc_%s_fuse" % rnn)
    types = [op.type for op in main.block(0).ops]
    assert "fusion_%s" % rnn in types
    assert "mul" not in types and "dynamic_%s" % rnn not in types
    got = _run(main, startup, final, feed)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_fc_rnn_fuse_keeps_late_h0_producer_upstream():
    """The fused op must land at the RECURRENCE's position: an initial
    state produced between the projection fc and the lstm would otherwise
    end up downstream of its consumer (reproduced pre-fix)."""
    rng = np.random.RandomState(4)
    feed = {"x": rng.rand(2, 5, 8).astype("float32")}
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[5, 8], dtype="float32")
        proj = fluid.layers.fc(input=x, size=4 * 6, num_flatten_dims=2)
        # h0/c0 created AFTER the projection, feeding the lstm
        h0 = fluid.layers.fill_constant([2, 6], "float32", 0.3)
        c0 = fluid.layers.fill_constant([2, 6], "float32", 0.1)
        out, _ = fluid.layers.dynamic_lstm(
            input=proj, size=4 * 6, h_0=h0, c_0=c0)
        final = fluid.layers.reduce_mean(out)
    ref = _run(main, startup, final, feed)
    apply_pass(main, "fc_lstm_fuse")
    types = [op.type for op in main.block(0).ops]
    assert "fusion_lstm" in types
    assert types.index("fill_constant") < types.index("fusion_lstm")
    got = _run(main, startup, final, feed)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_inference_strategy_orders_rnn_fuse_before_fc_fuse():
    """fc_fuse must not claim the projection chain before fc_lstm_fuse
    sees it (the reference analyzer's pass-order contract)."""
    from paddle_tpu.core.passes import PassManager

    main, startup, final = _lstm_infer_program("lstm")
    pm = PassManager(strategy="inference")
    fused = pm.apply(main, feed_names=["x"], fetch_names=[final.name])
    types = [op.type for op in fused.block(0).ops]
    assert "fusion_lstm" in types and "fc" not in types


def test_embedding_fc_lstm_fuse_chain():
    """lookup_table -> fc -> lstm collapses end to end: fc_lstm_fuse
    builds the fusion_lstm, embedding_fc_lstm_fuse absorbs the lookup
    (embedding_fc_lstm_fuse_pass.cc role); numerics identical."""
    rng = np.random.RandomState(6)
    feed = {"ids": rng.randint(0, 50, (2, 7)).astype("int64")}
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[7], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[50, 8])
        proj = fluid.layers.fc(input=emb, size=4 * 6, num_flatten_dims=2)
        out, _ = fluid.layers.dynamic_lstm(input=proj, size=4 * 6)
        final = fluid.layers.reduce_mean(out)
    ref = _run(main, startup, final, feed)
    apply_pass(main, "fc_lstm_fuse")
    apply_pass(main, "embedding_fc_lstm_fuse")
    types = [op.type for op in main.block(0).ops]
    assert "fused_embedding_fc_lstm" in types
    assert "lookup_table" not in types and "fusion_lstm" not in types
    got = _run(main, startup, final, feed)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_seqconv_eltadd_relu_fuse():
    rng = np.random.RandomState(8)
    feed = {"x": rng.rand(2, 9, 4).astype("float32")}
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 23
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[9, 4], dtype="float32")
        conv = fluid.layers.sequence_conv(
            x, num_filters=6, filter_size=3, act=None)
        out = fluid.layers.relu(conv)
        final = fluid.layers.reduce_mean(out)
    ref = _run(main, startup, final, feed)
    apply_pass(main, "seqconv_eltadd_relu_fuse")
    types = [op.type for op in main.block(0).ops]
    assert "fusion_seqconv_eltadd_relu" in types
    assert "sequence_conv" not in types and "relu" not in types
    got = _run(main, startup, final, feed)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_build_strategy_knob_applies_fusion():
    main, startup, loss = _add_act_train_program()
    bs = fluid.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        pe = fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                                    build_strategy=bs, num_devices=2)
        rng = np.random.RandomState(5)
        feed = {"x": rng.rand(4, 6).astype("float32"),
                "y": rng.rand(4, 1).astype("float32")}
        lv = pe.run(feed=feed, fetch_list=[loss.name])[0]
    assert np.isfinite(np.asarray(lv)).all()
    assert any(op.type == "fused_elemwise_activation"
               for op in main.block(0).ops)


def test_fusion_parity_on_8_device_mesh():
    """The fused program must train to the same losses as the unfused one
    under GSPMD data parallelism (fusion x mesh composition)."""
    def run(fuse):
        main, startup, loss = _add_act_train_program()
        bs = fluid.BuildStrategy()
        bs.fuse_elewise_add_act_ops = fuse
        exe = fluid.Executor(fluid.CPUPlace())
        out = []
        with fluid.scope_guard(fluid.executor.Scope()):
            exe.run(startup)
            pe = fluid.ParallelExecutor(loss_name=loss.name,
                                        main_program=main,
                                        build_strategy=bs, num_devices=8)
            rng = np.random.RandomState(5)
            for _ in range(4):
                feed = {"x": rng.rand(8, 6).astype("float32"),
                        "y": rng.rand(8, 1).astype("float32")}
                lv = pe.run(feed=feed, fetch_list=[loss.name])[0]
                out.append(float(np.ravel(np.asarray(lv))[0]))
        return out

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_fusion_seqexpand_concat_fc_op():
    """fusion_seqexpand_concat_fc: sequence + broadcast vectors + one fc,
    oracle = the unfused expand/concat/fc composition."""
    rng = np.random.RandomState(15)
    B, T, M0, M1, D = 2, 5, 3, 4, 6
    xv = rng.rand(B, T, M0).astype("float32")
    vv = rng.rand(B, M1).astype("float32")
    wv = rng.rand(M0 + M1, D).astype("float32")
    bv = rng.rand(D).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[T, M0], dtype="float32")
        v = fluid.layers.data(name="v", shape=[M1], dtype="float32")
        w = fluid.layers.data(name="w", shape=[M0 + M1, D],
                              dtype="float32", append_batch_size=False)
        b = fluid.layers.data(name="b", shape=[D], dtype="float32",
                              append_batch_size=False)
        block = main.current_block()
        out = block.create_var(name="fx_out", dtype="float32", shape=None)
        fco = block.create_var(name="fx_fco", dtype="float32", shape=None)
        block.append_op(
            "fusion_seqexpand_concat_fc",
            inputs={"X": [x.name, v.name], "FCWeight": [w.name],
                    "FCBias": [b.name]},
            outputs={"Out": [out.name], "FCOut": [fco.name]},
            attrs={"fc_activation": "relu"})
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, = exe.run(main, feed={"x": xv, "v": vv, "w": wv, "b": bv},
                   fetch_list=[out])
    cat = np.concatenate(
        [xv, np.broadcast_to(vv[:, None, :], (B, T, M1))], axis=-1)
    want = np.maximum(cat @ wv + bv, 0.0)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)
