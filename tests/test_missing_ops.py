"""pool3d / precision_recall / InferenceTranspiler tests.

Reference: tests/unittests/test_pool3d_op.py, test_precision_recall_op.py,
tests/test_inference_transpiler (inference_transpiler.py fuse_batch_norm).
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(build_fn, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    outs = exe.run(main, feed=feed, fetch_list=list(fetches))
    return [np.asarray(o) for o in outs]


def _np_pool3d(x, k, s, p, ptype, exclusive):
    n, c, d, h, w = x.shape
    od = (d + 2 * p[0] - k[0]) // s[0] + 1
    oh = (h + 2 * p[1] - k[1]) // s[1] + 1
    ow = (w + 2 * p[2] - k[2]) // s[2] + 1
    out = np.zeros((n, c, od, oh, ow), x.dtype)
    xp = np.pad(x, [(0, 0), (0, 0)] + [(pp, pp) for pp in p],
                constant_values=-np.inf if ptype == "max" else 0.0)
    for i in range(od):
        for j in range(oh):
            for l in range(ow):
                patch = xp[:, :, i * s[0]:i * s[0] + k[0],
                           j * s[1]:j * s[1] + k[1],
                           l * s[2]:l * s[2] + k[2]]
                if ptype == "max":
                    out[:, :, i, j, l] = patch.max(axis=(2, 3, 4))
                else:
                    total = patch.sum(axis=(2, 3, 4))
                    if exclusive:
                        ones = np.pad(np.ones_like(x),
                                      [(0, 0), (0, 0)] + [(pp, pp) for pp in p])
                        cnt = ones[:, :, i * s[0]:i * s[0] + k[0],
                                   j * s[1]:j * s[1] + k[1],
                                   l * s[2]:l * s[2] + k[2]].sum(axis=(2, 3, 4))
                    else:
                        cnt = np.prod(k)
                    out[:, :, i, j, l] = total / cnt
    return out


@pytest.mark.parametrize("ptype", ["max", "avg"])
def test_pool3d_matches_numpy(ptype):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 6, 6, 6).astype("float32")

    def build():
        xv = fluid.layers.data("x", [3, 6, 6, 6])
        out = fluid.layers.pool3d(xv, pool_size=2, pool_type=ptype,
                                  pool_stride=2, pool_padding=1)
        return (out,)

    (out,) = _run(build, {"x": x})
    exp = _np_pool3d(x, [2] * 3, [2] * 3, [1] * 3, ptype, True)
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-5)


def test_pool3d_global_and_grad():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 4, 4, 4).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [2, 4, 4, 4], stop_gradient=False)
        out = fluid.layers.pool3d(xv, pool_type="avg", global_pooling=True)
        loss = fluid.layers.mean(out)
        grads = fluid.backward.calc_gradient(loss, [xv])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    o, g = exe.run(main, feed={"x": x}, fetch_list=[out, grads[0]])
    np.testing.assert_allclose(
        np.asarray(o)[:, :, 0, 0, 0], x.mean(axis=(2, 3, 4)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.full_like(x, 1.0 / (2 * 64)),
                               rtol=1e-5)


def test_precision_recall_matches_numpy():
    rng = np.random.RandomState(3)
    n, c = 32, 4
    probs = rng.rand(n, c).astype("float32")
    probs /= probs.sum(1, keepdims=True)
    labels = rng.randint(0, c, (n, 1)).astype("int32")

    def build():
        pv = fluid.layers.data("p", [c])
        lv = fluid.layers.data("l", [1], dtype="int32")
        batch_m, accum_m, states = fluid.layers.precision_recall(pv, lv, c)
        return batch_m, accum_m, states

    batch_m, accum_m, states = _run(build, {"p": probs, "l": labels})

    pred = probs.argmax(1)
    gold = labels.reshape(-1)
    tp = np.zeros(c)
    fp = np.zeros(c)
    fn = np.zeros(c)
    tn = np.zeros(c)
    for p_i, g_i in zip(pred, gold):
        if p_i == g_i:
            tp[p_i] += 1
            tn += 1
            tn[p_i] -= 1
        else:
            fp[p_i] += 1
            fn[g_i] += 1
            tn += 1
            tn[p_i] -= 1
            tn[g_i] -= 1
    np.testing.assert_allclose(states, np.stack([tp, fp, tn, fn], 1), atol=1e-5)
    # empty classes score 1.0 (precision_recall_op.h CalcPrecision/CalcRecall)
    prec = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1e-10), 1.0)
    rec = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1e-10), 1.0)
    macro_p, macro_r = prec.mean(), rec.mean()
    micro_p = tp.sum() / (tp.sum() + fp.sum())
    micro_r = tp.sum() / (tp.sum() + fn.sum())
    np.testing.assert_allclose(batch_m[0], macro_p, rtol=1e-5)
    np.testing.assert_allclose(batch_m[1], macro_r, rtol=1e-5)
    np.testing.assert_allclose(batch_m[3], micro_p, rtol=1e-5)
    np.testing.assert_allclose(batch_m[4], micro_r, rtol=1e-5)
    # single batch: accumulated == batch
    np.testing.assert_allclose(accum_m, batch_m, rtol=1e-5)


def test_precision_recall_accumulates_across_batches():
    rng = np.random.RandomState(5)
    c = 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pv = fluid.layers.data("p", [c])
        lv = fluid.layers.data("l", [1], dtype="int32")
        batch_m, accum_m, states = fluid.layers.precision_recall(pv, lv, c)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    totals = []
    for _ in range(3):
        probs = rng.rand(16, c).astype("float32")
        labels = rng.randint(0, c, (16, 1)).astype("int32")
        _, _, st = exe.run(main, feed={"p": probs, "l": labels},
                           fetch_list=[batch_m, accum_m, states])
        totals.append(np.asarray(st))
    # TP+FP+TN+FN per class = accumulated sample count
    assert totals[-1].sum() == pytest.approx(3 * 16 * c)
    assert (totals[1].sum(1) >= totals[0].sum(1)).all()


def test_inference_transpiler_folds_batch_norm():
    rng = np.random.RandomState(7)
    x = rng.randn(2, 3, 8, 8).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [3, 8, 8])
        c = fluid.layers.conv2d(xv, 4, 3, padding=1, bias_attr=True)
        bn = fluid.layers.batch_norm(c)
        out = fluid.layers.relu(bn)
    test_prog = main.clone(for_test=True)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # make BN stats non-trivial so folding is actually exercised
    scope = fluid.global_scope()
    scope.set_value("batch_norm_0.w_0",
                    rng.rand(4).astype("float32") + 0.5)  # scale
    scope.set_value("batch_norm_0.b_0", rng.randn(4).astype("float32"))
    for name in scope.local_var_names():
        if "mean" in name:
            scope.set_value(name, rng.randn(4).astype("float32") * 0.1)
        if "variance" in name:
            scope.set_value(name, rng.rand(4).astype("float32") + 0.5)

    (before,) = exe.run(test_prog, feed={"x": x}, fetch_list=[out])

    t = fluid.transpiler.InferenceTranspiler()
    t.transpile(test_prog, scope)
    bn_ops = [op for op in test_prog.global_block().ops
              if op.type == "batch_norm"]
    assert not bn_ops, "batch_norm op should be folded away"

    (after,) = exe.run(test_prog, feed={"x": x}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=1e-4, atol=1e-4)


def test_inference_transpiler_without_conv_bias():
    rng = np.random.RandomState(9)
    x = rng.randn(2, 3, 6, 6).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [3, 6, 6])
        c = fluid.layers.conv2d(xv, 2, 3, bias_attr=False)
        bn = fluid.layers.batch_norm(c)
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    scope.set_value("batch_norm_1.b_0", rng.randn(2).astype("float32"))
    (before,) = exe.run(test_prog, feed={"x": x}, fetch_list=[bn])
    fluid.transpiler.InferenceTranspiler().transpile(test_prog, scope)
    assert not any(op.type == "batch_norm"
                   for op in test_prog.global_block().ops)
    (after,) = exe.run(test_prog, feed={"x": x}, fetch_list=[bn])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=1e-4, atol=1e-4)


def test_precision_recall_empty_class_scores_one():
    # class 2 never appears: contributes P=R=1.0 to the macro averages
    probs = np.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1]], "float32")
    labels = np.array([[0], [1]], "int32")

    def build():
        pv = fluid.layers.data("p", [3])
        lv = fluid.layers.data("l", [1], dtype="int32")
        batch_m, _, _ = fluid.layers.precision_recall(pv, lv, 3)
        return (batch_m,)

    (m,) = _run(build, {"p": probs, "l": labels})
    np.testing.assert_allclose(m[0], 1.0, rtol=1e-6)  # macro-P
    np.testing.assert_allclose(m[1], 1.0, rtol=1e-6)  # macro-R


def test_inference_transpiler_skips_residual_add():
    """conv -> elementwise_add(conv, skip) -> batch_norm must NOT be folded
    as if the skip activation were a bias parameter."""
    rng = np.random.RandomState(11)
    x = rng.randn(2, 2, 6, 6).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [2, 6, 6])
        c = fluid.layers.conv2d(xv, 2, 3, padding=1, bias_attr=False)
        res = fluid.layers.elementwise_add(c, xv)  # residual, not bias
        bn = fluid.layers.batch_norm(res)
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (before,) = exe.run(test_prog, feed={"x": x}, fetch_list=[bn])
    fluid.transpiler.InferenceTranspiler().transpile(test_prog,
                                                     fluid.global_scope())
    (after,) = exe.run(test_prog, feed={"x": x}, fetch_list=[bn])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=1e-4, atol=1e-4)


def test_pad_constant_like_and_errors():
    x = np.zeros((1, 4, 6), "float32")
    y = np.arange(6, dtype="float32").reshape(1, 2, 3)

    def build():
        xv = fluid.layers.data("x", [4, 6])
        yv = fluid.layers.data("y", [2, 3])
        return (fluid.layers.pad_constant_like(xv, yv, 9.0),)

    (out,) = _run(build, {"x": x, "y": y})
    assert out.shape == (1, 4, 6)
    np.testing.assert_array_equal(out[0, :2, :3], y[0])
    assert (out[0, 2:, :] == 9.0).all() and (out[0, :, 3:] == 9.0).all()

    # grad flows through Y only (X is shape-only)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [4, 6])
        yv = fluid.layers.data("y", [2, 3], stop_gradient=False)
        p = fluid.layers.pad_constant_like(xv, yv)
        loss = fluid.layers.mean(p)
        grads = fluid.backward.calc_gradient(loss, [yv])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (g,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[grads[0]])
    np.testing.assert_allclose(np.asarray(g), np.full_like(y, 1.0 / 24),
                               rtol=1e-6)


def test_sequence_reshape_rechunks_and_validates():
    x = np.arange(24, dtype="float32").reshape(1, 4, 6)

    def build():
        xv = fluid.layers.data("x", [4, 6])
        return (fluid.layers.sequence_reshape(xv, 3),)

    (out,) = _run(build, {"x": x})
    assert out.shape == (1, 8, 3)
    np.testing.assert_array_equal(out.reshape(1, 24), x.reshape(1, 24))

    def build_bad():
        xv = fluid.layers.data("x", [4, 6])
        return (fluid.layers.sequence_reshape(xv, 7),)

    with pytest.raises(Exception, match="sequence_reshape"):
        _run(build_bad, {"x": x})


def test_flatten2_unsqueeze2_xshape_variants():
    """The *2 op variants carry an XShape intermediate (reference op pair
    design); Out matches the base ops."""
    import numpy as np
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3, 4], dtype="float32")
        block = main.current_block()
        f_out = block.create_var(name="f2_out", dtype="float32", shape=None)
        f_xs = block.create_var(name="f2_xs", dtype="float32", shape=None)
        block.append_op("flatten2", inputs={"X": [x.name]},
                        outputs={"Out": [f_out.name], "XShape": [f_xs.name]},
                        attrs={"axis": 1})
        u_out = block.create_var(name="u2_out", dtype="float32", shape=None)
        u_xs = block.create_var(name="u2_xs", dtype="float32", shape=None)
        block.append_op("unsqueeze2", inputs={"X": [x.name]},
                        outputs={"Out": [u_out.name], "XShape": [u_xs.name]},
                        attrs={"axes": [1]})
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.arange(24, dtype="float32").reshape(2, 3, 4)
    fo, uo = exe.run(main, feed={"x": xv}, fetch_list=[f_out, u_out])
    np.testing.assert_allclose(fo, xv.reshape(2, 12))
    np.testing.assert_allclose(uo, xv[:, None])


def test_depthwise_conv2d_transpose():
    """groups == channels transpose conv == per-channel transpose convs."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as fluid

    rng = np.random.RandomState(3)
    xv = rng.rand(2, 3, 5, 5).astype("float32")
    wv = rng.rand(3, 1, 3, 3).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3, 5, 5], dtype="float32")
        w = fluid.layers.data(name="w", shape=[1, 3, 3], dtype="float32")
        block = main.current_block()
        out = block.create_var(name="dct_out", dtype="float32", shape=None)
        block.append_op(
            "depthwise_conv2d_transpose",
            inputs={"Input": [x.name], "Filter": [w.name]},
            outputs={"Output": [out.name]},
            attrs={"strides": [2, 2], "paddings": [1, 1]})
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, = exe.run(main, feed={"x": xv, "w": wv}, fetch_list=[out])
    # oracle: per-channel conv2d_transpose stacked
    chans = []
    for c in range(3):
        main2, startup2 = fluid.Program(), fluid.Program()
        with fluid.program_guard(main2, startup2):
            xc = fluid.layers.data(name="xc", shape=[1, 5, 5],
                                   dtype="float32")
            wc = fluid.layers.data(name="wc", shape=[1, 3, 3],
                                   dtype="float32")
            b2 = main2.current_block()
            oc = b2.create_var(name="oc", dtype="float32", shape=None)
            b2.append_op(
                "conv2d_transpose",
                inputs={"Input": [xc.name], "Filter": [wc.name]},
                outputs={"Output": [oc.name]},
                attrs={"strides": [2, 2], "paddings": [1, 1]})
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup2)
        got_c, = exe2.run(main2, feed={"xc": xv[:, c:c + 1],
                                       "wc": wv[c:c + 1]},
                          fetch_list=[oc])
        chans.append(np.asarray(got_c))
    want = np.concatenate(chans, axis=1)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)
