"""DistributeTranspiler plan/structure tests + sharded checkpoint tests
(reference: tests/unittests/test_dist_transpiler.py asserts the rewritten
program structure; test_dist_base.py asserts dist-vs-local loss parity —
here the GSPMD path IS the local program, so parity is structural +
pserver-program numerical equivalence)."""

import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
    slice_variable,
)


class _FakeVar(object):
    def __init__(self, name, shape):
        self.name = name
        self.shape = shape


def test_slice_variable_blocks():
    # 10x1024 = 10240 elements over 3 servers, min block 8192:
    # max_pserver_count = floor(10240/8192) = 1 -> single block.
    blocks = slice_variable([_FakeVar("w", (10, 1024))], 3, 8192)
    assert len(blocks) == 1 and blocks[0].size == 10240

    # 100x1024 over 3 servers -> 3 row-aligned blocks covering everything.
    blocks = slice_variable([_FakeVar("w", (100, 1024))], 3, 8192)
    assert len(blocks) == 3
    assert all(b.size % 1024 == 0 for b in blocks[:-1])  # row alignment
    assert sum(b.size for b in blocks) == 100 * 1024
    offs = [b.offset for b in blocks]
    assert offs == sorted(offs) and offs[0] == 0


def _build_train_program(seed=9, lr=0.1):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=128, act="relu")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y)
        )
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, loss


def test_transpile_places_all_params():
    main, startup, _ = _build_train_program()
    t = DistributeTranspiler()
    t.transpile(
        trainer_id=0, program=main, startup_program=startup,
        pservers="127.0.0.1:6174,127.0.0.1:6175", trainers=2,
    )
    placed = {b.varname for eps in t.param_block_map.values() for b in eps}
    all_params = {
        p.name for p in main.global_block().all_parameters()
    }
    assert placed == all_params
    # Both endpoints own something (round-robin over 4 params).
    assert len(t.param_block_map) == 2
    assert t.get_trainer_program() is main


def test_pserver_program_structure_and_numerics():
    """The pserver program holds exactly the optimize ops of its owned
    params, and running it on a grad reproduces the SGD update."""
    main, startup, _ = _build_train_program()
    t = DistributeTranspiler()
    t.transpile(
        trainer_id=0, program=main, startup_program=startup,
        pservers="ep0,ep1", trainers=1,
    )
    ep = "ep0"
    owned = {b.varname for b in t.param_block_map[ep]}
    pprog = t.get_pserver_program(ep)
    pstartup = t.get_startup_program(ep, pprog)
    opt_ops = [op for op in pprog.global_block().ops]
    assert opt_ops, "pserver program has no ops"
    from paddle_tpu.framework import OP_ROLE_VAR_ATTR_NAME

    for op in opt_ops:
        rv = op.attrs.get(OP_ROLE_VAR_ATTR_NAME)
        if rv:
            assert rv[0] in owned

    # Numerics: run the pserver program on a synthetic grad.
    param = sorted(owned)[0]
    grad_name = t.param_grad_map[param]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(pstartup)
        before = np.array(scope.get_value(param))
        g = np.random.RandomState(0).randn(*before.shape).astype("float32")
        sgd_ops = [
            op for op in pprog.global_block().ops
            if op.attrs.get(OP_ROLE_VAR_ATTR_NAME)
            and op.attrs[OP_ROLE_VAR_ATTR_NAME][0] == param
        ]
        single = fluid.Program()
        sblock = single.global_block()
        for name in {param, grad_name, "learning_rate_0"}:
            v = pprog.global_block()._find_var_recursive(name)
            if v is not None:
                sblock.create_var(name=v.name, shape=v.shape, dtype=v.dtype,
                                  type=v.type, persistable=v.persistable)
        for op in sgd_ops:
            sblock.append_op(
                type=op.type,
                inputs={k: list(v) for k, v in op.inputs.items()},
                outputs={k: list(v) for k, v in op.outputs.items()},
                attrs=dict(op.attrs),
            )
        exe.run(single, feed={grad_name: g}, fetch_list=[])
        after = np.array(scope.get_value(param))
    np.testing.assert_allclose(after, before - 0.1 * g, rtol=1e-5,
                               atol=1e-6)


def test_transpiled_trainer_converges_on_mesh():
    """The trainer program under the transpiler's sharding policy (GSPMD
    'reduce' = the pserver-sharded capability) trains to parity with the
    single-device run."""
    import jax

    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    main, startup, loss = _build_train_program(seed=13)
    t = DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="ep0,ep1", trainers=2)

    rng = np.random.RandomState(1)
    w_true = rng.randn(64, 1).astype("float32")

    def batch(bs=32):
        xb = rng.randn(bs, 64).astype("float32")
        return xb, (xb @ w_true).astype("float32")

    data = [batch() for _ in range(12)]

    # Single-device baseline.
    exe = fluid.Executor(fluid.CPUPlace())
    s1 = Scope()
    with fluid.scope_guard(s1):
        exe.run(startup)
        for xb, yb in data:
            (l1,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
    base_loss = float(np.asarray(l1).ravel()[0])

    # Mesh run with the transpiler's policy.
    s2 = Scope()
    with fluid.scope_guard(s2):
        exe.run(startup)
        bs_strategy = BuildStrategy()
        bs_strategy.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
        pe = ParallelExecutor(
            loss_name=loss.name, main_program=main,
            build_strategy=bs_strategy, use_tpu=False,
            num_devices=len(jax.devices()),
        )
        for xb, yb in data:
            (l2,) = pe.run(fetch_list=[loss], feed={"x": xb, "y": yb})
    mesh_loss = float(np.asarray(l2).ravel()[0])
    np.testing.assert_allclose(mesh_loss, base_loss, rtol=2e-3, atol=2e-4)


def test_sharded_checkpoint_roundtrip(tmp_path):
    """Train on the 8-device mesh with ZeRO-style sharded state, write a
    sharded checkpoint (per-shard files), resume in a fresh scope, and
    match the uninterrupted run step for step."""
    import jax

    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    ckpt = str(tmp_path / "ckpts")
    rng = np.random.RandomState(2)
    w_true = rng.randn(64, 1).astype("float32")
    data = []
    for _ in range(8):
        xb = rng.randn(32, 64).astype("float32")
        data.append((xb, (xb @ w_true).astype("float32")))

    def make_pe(scope_holder):
        # Identical var names across the three program builds (A, B, C) so
        # the checkpoint round-trips by name.
        with fluid.unique_name.guard():
            main, startup, loss = _build_train_program(seed=21, lr=0.01)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        bs_strategy = BuildStrategy()
        bs_strategy.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
        pe = ParallelExecutor(
            loss_name=loss.name, main_program=main,
            build_strategy=bs_strategy, use_tpu=False,
            num_devices=len(jax.devices()),
        )
        return main, loss, pe, exe

    # Uninterrupted 8 steps.
    sA = Scope()
    with fluid.scope_guard(sA):
        mainA, lossA, peA, exeA = make_pe(sA)
        lossesA = []
        for xb, yb in data:
            (lv,) = peA.run(fetch_list=[lossA], feed={"x": xb, "y": yb})
            lossesA.append(float(np.asarray(lv).ravel()[0]))

    # 4 steps, checkpoint, fresh scope, load, 4 more steps.
    sB = Scope()
    with fluid.scope_guard(sB):
        mainB, lossB, peB, exeB = make_pe(sB)
        for xb, yb in data[:4]:
            peB.run(fetch_list=[lossB], feed={"x": xb, "y": yb})
        step_dir = fluid.io.save_checkpoint(
            exeB, ckpt, main_program=mainB, serial=4
        )
        # Sharded state must actually be sharded on disk.
        shard_files = [f for f in os.listdir(step_dir) if ".shard" in f]
        assert shard_files, os.listdir(step_dir)

    sC = Scope()
    with fluid.scope_guard(sC):
        mainC, lossC, peC, exeC = make_pe(sC)
        serial = fluid.io.load_checkpoint(exeC, ckpt, main_program=mainC)
        assert serial == 4
        lossesC = []
        for xb, yb in data[4:]:
            (lv,) = peC.run(fetch_list=[lossC], feed={"x": xb, "y": yb})
            lossesC.append(float(np.asarray(lv).ravel()[0]))
    np.testing.assert_allclose(lossesC, lossesA[4:], rtol=1e-4, atol=1e-6)


def test_selected_rows_sparse_update():
    """SelectedRows interchange type: merge-add semantics + sparse SGD row
    update (selected_rows.h / selected_rows_functor capability)."""
    from paddle_tpu import SelectedRows

    sr = SelectedRows(
        rows=[2, 0, 2], value=np.array([[1., 1.], [2., 2.], [3., 3.]]),
        height=4,
    )
    dense = sr.to_dense()
    np.testing.assert_allclose(dense[2], [4.0, 4.0])  # duplicates summed
    np.testing.assert_allclose(dense[0], [2.0, 2.0])
    assert dense.shape == (4, 2) and (dense[1] == 0).all()

    merged = sr.merge_rows()
    assert list(merged.rows) == [0, 2]

    table = np.ones((4, 2), np.float32)
    sr.apply_sgd(table, lr=0.5)
    np.testing.assert_allclose(table[2], 1.0 - 0.5 * 4.0)
    np.testing.assert_allclose(table[1], 1.0)

    picked = SelectedRows.from_dense_rows(np.arange(8).reshape(4, 2), [3, 1])
    np.testing.assert_array_equal(picked.value, [[6, 7], [2, 3]])
