"""Program-level pipeline parallelism (parallel/program_pipeline.py).

The contract: ParallelExecutor(pipeline_stages=S) trains an ORDINARY
Program (heterogeneous per-stage params, optimizer.minimize) over the
mesh's pipe axis with loss parity against the plain single-device
Executor — the transparent multi-device story of the reference's
multi_devices_graph_pass.cc, extended to the pipeline dimension.
Runs on the 8-device virtual CPU mesh (conftest.py).
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _deep_mlp(depth=8, width=32, seed=11):
    from paddle_tpu import unique_name

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[width], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = x
        for _ in range(depth):
            h = fluid.layers.fc(input=h, size=width, act="tanh")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9).minimize(loss)
    return main, startup, loss


def _block_stack(n_blocks=4, width=32, seed=13):
    """Encoder-style residual blocks (fc + residual + layer_norm):
    heterogeneous params, single-var block boundaries."""
    from paddle_tpu import unique_name

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[width], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=width, act=None)
        for _ in range(n_blocks):
            inner = fluid.layers.fc(input=h, size=width * 2, act="relu")
            proj = fluid.layers.fc(input=inner, size=width, act=None)
            h = fluid.layers.layer_norm(h + proj)
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    return main, startup, loss


def _data(bs=32, width=32, seed=2):
    rng = np.random.RandomState(seed)
    xs = rng.randn(bs, width).astype("float32")
    w = rng.randn(width, 1).astype("float32")
    ys = (np.tanh(xs) @ w).astype("float32")
    return xs, ys


def _train(build, runner, steps=12):
    """runner(main, startup, loss) -> callable(feed) -> loss value."""
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, loss = build()
        step = runner(main, startup, loss)
        xs, ys = _data()
        return [float(step({"x": xs, "y": ys})) for _ in range(steps)]


def _single_device(main, startup, loss):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return lambda feed: exe.run(main, feed=feed, fetch_list=[loss])[0][0]


def _pipelined(stages, micro, num_devices=None):
    def runner(main, startup, loss):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main,
            pipeline_stages=stages, pipeline_microbatches=micro,
            num_devices=num_devices)
        return lambda feed: pe.run([loss], feed=feed)[0][0]

    return runner


@pytest.mark.parametrize("stages,micro,ndev,rtol", [
    (4, 4, 4, 5e-4),   # pure pipeline
    # dp reduces the batch mean in a different order; float drift
    # compounds along the trajectory, hence the looser bound
    (4, 4, 8, 5e-3),   # pipeline x data parallel (data axis = 2)
    (8, 4, 8, 5e-4),   # one stage per device
], ids=["pipe4", "pipe4xdp2", "pipe8"])
def test_mlp_loss_parity(stages, micro, ndev, rtol):
    base = _train(_deep_mlp, _single_device)
    piped = _train(_deep_mlp, _pipelined(stages, micro, ndev))
    np.testing.assert_allclose(piped, base, rtol=rtol, atol=1e-5)


def test_block_stack_adam_parity():
    """Heterogeneous stages (first/last differ from the middle) + Adam
    (packed moments + shared beta-pow scalars)."""
    base = _train(_block_stack, _single_device)
    piped = _train(_block_stack, _pipelined(4, 4, 8))
    np.testing.assert_allclose(piped, base, rtol=1e-3, atol=1e-5)


def _transformer_encoder(n_blocks=4, d_model=32, n_head=4, seq=16,
                         vocab=128, seed=31):
    """Real attention stack: embedding -> N x (self-attention + FFN with
    residuals/layer_norm) -> pooled classifier. Block boundaries are
    single [B, seq, d_model] vars, so the cutter can pipeline it."""
    from paddle_tpu import unique_name

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        tok = fluid.layers.data(name="x", shape=[seq], dtype="int64")
        label = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.embedding(input=tok, size=[vocab, d_model])
        for _ in range(n_blocks):
            qkv = fluid.layers.fc(h, size=3 * d_model, num_flatten_dims=2,
                                  bias_attr=False)
            q, k, v = fluid.layers.split(qkv, num_or_sections=3, dim=-1)

            def heads(t):
                t = fluid.layers.reshape(
                    t, [-1, seq, n_head, d_model // n_head])
                return fluid.layers.transpose(t, [0, 2, 1, 3])

            ctx = fluid.layers.scaled_dot_product_attention(
                heads(q), heads(k), heads(v))
            ctx = fluid.layers.transpose(ctx, [0, 2, 1, 3])
            ctx = fluid.layers.reshape(ctx, [-1, seq, d_model])
            att = fluid.layers.fc(ctx, size=d_model, num_flatten_dims=2)
            h = fluid.layers.layer_norm(h + att)
            ffn = fluid.layers.fc(h, size=2 * d_model, num_flatten_dims=2,
                                  act="relu")
            ffn = fluid.layers.fc(ffn, size=d_model, num_flatten_dims=2)
            h = fluid.layers.layer_norm(h + ffn)
        pooled = fluid.layers.reduce_mean(h, dim=1)
        logits = fluid.layers.fc(pooled, size=8)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


def test_transformer_encoder_pipeline_parity():
    """VERDICT r2 item 4's done-criterion on the Transformer side: an
    attention Program trained over the pipe axis (x dp), loss parity
    against the single-device Executor."""
    rng = np.random.RandomState(5)
    tok = rng.randint(0, 128, (16, 16)).astype("int64")
    lab = rng.randint(0, 8, (16, 1)).astype("int64")

    def train(runner):
        with fluid.scope_guard(fluid.executor.Scope()):
            main, startup, loss = _transformer_encoder()
            step = runner(main, startup, loss)
            return [float(step({"x": tok, "y": lab})) for _ in range(6)]

    base = train(_single_device)
    piped = train(_pipelined(4, 4, 8))
    np.testing.assert_allclose(piped, base, rtol=2e-3, atol=1e-5)


def test_params_sync_back_to_scope():
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, loss = _deep_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.global_scope()
        before = {
            p.name: np.asarray(scope.find_var(p.name).value).copy()
            for p in main.global_block().all_parameters()}
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main,
            pipeline_stages=4, pipeline_microbatches=4, num_devices=4)
        xs, ys = _data()
        for _ in range(3):
            pe.run([loss], feed={"x": xs, "y": ys})
        pe.pipeline_sync_scope()
        moved = 0
        for name, old in before.items():
            new = np.asarray(scope.find_var(name).value)
            assert new.shape == old.shape
            if not np.array_equal(new, old):
                moved += 1
        assert moved == len(before), (
            "only %d/%d params updated in scope" % (moved, len(before)))


def test_rejects_non_loss_fetch_and_bad_batch():
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, loss = _deep_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main,
            pipeline_stages=4, pipeline_microbatches=4, num_devices=4)
        xs, ys = _data()
        with pytest.raises(ValueError, match="fetch only the loss"):
            pe.run(["fc_0.w_0"], feed={"x": xs, "y": ys})
        with pytest.raises(ValueError, match="divide"):
            pe.run([loss], feed={"x": xs[:30], "y": ys[:30]})


def test_feed_shape_change_keeps_training_state():
    """A new batch size must rebuild the executable, NOT restart training
    from the startup weights."""
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, loss = _deep_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main,
            pipeline_stages=4, pipeline_microbatches=4, num_devices=4)
        xs, ys = _data(bs=32)
        first = float(pe.run([loss], feed={"x": xs, "y": ys})[0][0])
        for _ in range(10):
            lv = float(pe.run([loss], feed={"x": xs, "y": ys})[0][0])
        assert lv < first
        # half-size batch: new shapes, same (carried-over) weights
        lv_small = float(
            pe.run([loss], feed={"x": xs[:16], "y": ys[:16]})[0][0])
        assert lv_small < 0.9 * first, (
            "feed-shape change restarted training: %.4f vs first %.4f"
            % (lv_small, first))


def test_list_feed_and_device_array_fetch():
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, loss = _deep_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main,
            pipeline_stages=4, pipeline_microbatches=4, num_devices=4)
        xs, ys = _data(bs=32)
        # fluid-style per-device list feed: concatenated along batch
        out = pe.run([loss], feed=[
            {"x": xs[:16], "y": ys[:16]}, {"x": xs[16:], "y": ys[16:]}])
        assert np.isfinite(float(out[0][0]))
        out = pe.run([loss], feed={"x": xs, "y": ys}, return_numpy=False)
        import jax

        assert isinstance(out[0], jax.Array), type(out[0])


def test_rejects_undivisible_stages():
    main, startup, loss = _deep_mlp()
    with pytest.raises(ValueError, match="divide the device count"):
        fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main,
            pipeline_stages=3, pipeline_microbatches=4, num_devices=8)
