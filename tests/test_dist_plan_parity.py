"""DistributeTranspiler plan <-> GSPMD execution parity (VERDICT r3
Next #8): the slice_variable planning surface and the ShardingPolicy the
plan EXECUTES as must correspond — same row-extents on the params GSPMD
dim-0-shards, and a visible fallback note wherever the two legitimately
diverge — so the planning surface cannot silently drift from what runs.

Reference: python/paddle/fluid/transpiler/distribute_transpiler.py:81
(slice_variable feeds the pserver placement that listen_and_serv then
executes); here the executed form is the "reduce" (ZeRO-ish) dim-0
sharding over the mesh's data axis (parallel/mesh.py).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import build_mesh
from paddle_tpu.transpiler.distribute_transpiler import (
    DistributeTranspiler,
    slice_variable,
)

N_SHARD = 4  # pserver count == mesh data-axis size


class _Var(object):
    def __init__(self, name, shape):
        self.name = name
        self.shape = shape


def _policy_for(shapes):
    """A transpiled program whose params have ``shapes``, and the
    ShardingPolicy its plan executes as on a data=N_SHARD mesh."""
    import jax

    if len(jax.devices()) < N_SHARD:
        pytest.skip("needs %d virtual devices" % N_SHARD)
    mesh = build_mesh(num_devices=N_SHARD, data=N_SHARD)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [int(shapes["w"][0])])
        w = fluid.layers.create_parameter(shapes["w"], "float32", name="w")
        y = fluid.layers.mul(x, w)
        if "v" in shapes:
            v = fluid.layers.create_parameter(
                shapes["v"], "float32", name="v")
            y = fluid.layers.elementwise_add(
                y, fluid.layers.reduce_sum(v))
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    t = DistributeTranspiler()
    t.transpile(
        trainer_id=0, program=main,
        pservers=",".join("127.0.0.1:%d" % (7164 + i)
                          for i in range(N_SHARD)),
        trainers=1)
    # create_parameter suffixes names ("w" -> "w.w_0"): resolve the
    # real param names the transpiler planned for
    names = {base: next(p for p in t.param_grad_map
                        if p.startswith(base + "."))
             for base in shapes}
    policy = t.build_sharding_policy(
        mesh, state_shapes={names[b]: tuple(shapes[b]) for b in shapes})
    return t, policy, mesh, names


def test_plan_blocks_match_gspmd_shards():
    """A large divisible param: the plan's per-pserver row blocks equal
    the rows of the REAL GSPMD shards placed on each device."""
    import jax

    shapes = {"w": (128, 512)}  # 65536 elems: 4 blocks of 32 rows
    t, policy, mesh, names = _policy_for(shapes)

    blocks = [b for b in t.param_blocks if b.varname == names["w"]]
    assert len(blocks) == N_SHARD
    dim1 = shapes["w"][1]
    plan_rows = [b.size // dim1 for b in blocks]
    assert all(b.size % dim1 == 0 for b in blocks), "row alignment"

    sharding = policy.state_sharding(names["w"])
    assert "data" in str(sharding.spec)
    arr = jax.device_put(
        np.zeros(shapes["w"], np.float32), sharding)
    shard_rows = []
    seen_devices = set()
    for shard in sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0):
        shard_rows.append(shard.data.shape[0])
        assert shard.data.shape[1] == dim1  # dim-0 sharding only
        seen_devices.add(shard.device)
    # the executed placement: one shard per mesh device, and the plan's
    # row split IS the shard row split
    assert len(seen_devices) == N_SHARD
    assert sorted(plan_rows) == sorted(shard_rows), (
        "slice_variable planned %s rows/pserver but GSPMD executes %s "
        "rows/device" % (plan_rows, shard_rows))


def test_small_param_whole_in_both():
    """A tiny param stays whole in the plan (min_block_size) AND
    replicated in execution (numel threshold): the two surfaces agree."""
    shapes = {"w": (16, 16), "v": (10,)}  # both under the thresholds
    t, policy, _, names = _policy_for(shapes)
    for base in ("w", "v"):
        blocks = [b for b in t.param_blocks if b.varname == names[base]]
        assert len(blocks) == 1, base
        assert str(policy.state_sharding(names[base]).spec) == str(
            policy.replicated().spec), base


def test_divergence_is_flagged_not_silent():
    """A big param whose dim0 the mesh cannot divide: the plan still
    slices it (byte-balanced pserver placement) but execution replicates —
    that divergence MUST surface in plan() as a fallback note, the
    observability contract that keeps the two surfaces honest."""
    shapes = {"w": (66, 512)}  # 33792 elems, 66 % 4 != 0
    t, policy, _, names = _policy_for(shapes)
    blocks = [b for b in t.param_blocks if b.varname == names["w"]]
    assert len(blocks) > 1  # the plan slices by bytes
    sharding = policy.state_sharding(names["w"])
    assert str(sharding.spec) == str(policy.replicated().spec)
    plan = policy.plan()
    assert plan[names["w"]][1] == "fallback", (
        "plan/execution divergence for 'w' must be tagged: %r" % (plan,))


def test_derived_plan_matches_handwritten_tp_layout():
    """The sharding transpiler must rediscover the Megatron layout the
    hand-written ``dist_trainer_tp.py`` overrides (TP_OVERRIDES) encode:
    for every weight the hand layout model-shards on dim D, the derived
    plan shards dim D over the ``tp`` axis — so retiring tp_layout loses
    nothing. min_shard_numel=1: this compares STRUCTURE at the driver's
    tiny d_model, not the size heuristic."""
    import __graft_entry__
    from paddle_tpu.analysis.shard_check import spec_axes
    from paddle_tpu.parallel.sharding import derive_sharding

    main, _startup, _loss = __graft_entry__.build_tp_block_program()
    plan = derive_sharding(
        main, {"data": 2, "fsdp": 2, "tp": 2},
        feed_shapes={"x": (16, 8, 16), "label": (16, 1)},
        min_shard_numel=1)
    for name, hand_spec in __graft_entry__.TP_OVERRIDES.items():
        derived = plan.specs[name]
        for dim, hand_entry in enumerate(hand_spec):
            if hand_entry == "model":
                entry = derived[dim] if dim < len(derived) else None
                axes = spec_axes((entry,))
                assert "tp" in axes, (
                    "hand layout model-shards %s dim %d but the derived "
                    "plan gives %s" % (name, dim, derived))


def test_derived_plan_fsdp_rows_match_gspmd_shards():
    """The derived fsdp sharding EXECUTES as the rows it plans: the
    per-device shard rows of a P('fsdp', ...) param equal dim0 / fsdp
    (the slice_variable-rows == GSPMD-shards contract, restated for the
    planning mesh)."""
    import jax

    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.sharding import (
        DerivedShardingPolicy, derive_sharding)

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = build_mesh(num_devices=8, data=2, fsdp=4, tp=1)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [128])
        w = fluid.layers.create_parameter([128, 512], "float32", name="w")
        y = fluid.layers.mul(x, w)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, mesh, feed_shapes={"x": (16, 128)})
    wname = next(n for n in plan.param_specs() if n.startswith("w."))
    assert "fsdp" in plan.specs[wname][0]
    policy = DerivedShardingPolicy(mesh, plan)
    arr = jax.device_put(np.zeros((128, 512), np.float32),
                         policy.state_sharding(wname))
    rows = sorted({s.data.shape[0] for s in arr.addressable_shards})
    assert rows == [128 // 4], rows
    assert plan.shard_factor(wname) == 4


def test_slice_variable_rows_equal_shard_rows_across_sizes():
    """Property over a size sweep: whenever the policy dim-0-shards, the
    plan's blocks (at the policy's own thresholds) carry exactly the
    shard row counts."""
    from paddle_tpu.parallel.mesh import ShardingPolicy

    import jax

    if len(jax.devices()) < N_SHARD:
        pytest.skip("needs %d virtual devices" % N_SHARD)
    mesh = build_mesh(num_devices=N_SHARD, data=N_SHARD)
    for rows, cols in [(8, 256), (64, 128), (256, 64), (4096, 8)]:
        shape = (rows, cols)
        policy = ShardingPolicy(mesh, strategy="reduce",
                                state_shapes={"p": shape})
        sharding = policy.state_sharding("p")
        if "data" not in str(sharding.spec):
            continue  # replicated: nothing to correspond
        blocks = slice_variable(
            [_Var("p", shape)], N_SHARD,
            min_block_size=rows * cols // N_SHARD)
        assert len(blocks) == N_SHARD, shape
        arr = jax.device_put(np.zeros(shape, np.float32), sharding)
        shard_rows = sorted(s.data.shape[0]
                            for s in arr.addressable_shards)
        plan_rows = sorted(b.size // cols for b in blocks)
        assert plan_rows == shard_rows, shape
