"""Serving resilience (PR 13): preemption-safe decode snapshots,
graceful degradation, chaos-hardened serving dispatch.

* a mid-flight ``SlotDecodeSession`` (live fork groups, shared prefix
  pages, a pending request backlog) snapshots atomically and restores
  into a FRESH session whose remaining tokens are BIT-identical to the
  uninterrupted run's — the (seed, slot, position) PRNG contract;
* corrupt snapshots quarantine and fall back; geometry drift raises a
  typed ``SnapshotMismatchError`` (operator error, not corruption);
* ``tools/ckpt_inspect.py`` prints the decode dialect and ``--verify``
  re-checks page conservation + refcount accounting offline (exit 2);
* the healthy -> brownout -> shed machine sheds load with typed
  retriable ``DegradedError``\\ s (retry-after hints) in BOTH the
  batching server (queue depth) and the decode session (page/slot
  occupancy: brownout evicts the prefix cache and refuses forks, shed
  refuses admissions while in-flight work drains) — and recovers;
* a chaos fault at ``serve.admit`` rolls the whole group back and,
  under classified retry, re-admits bit-identically; a fault at
  ``snapshot.write`` fails the save without touching the session;
* a Pallas ``paged_attention`` failure trips the once-per-process
  reference fallback (counter + flag) instead of killing the request;
* SIGTERM mid-decode finishes the in-flight dispatch, banks a final
  snapshot and dies BY the signal (subprocess leg).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.executor import global_scope
from paddle_tpu.observability import REGISTRY
from paddle_tpu.resilience import chaos
from paddle_tpu.serving.degradation import (
    BROWNOUT,
    HEALTHY,
    SHED,
    DegradedError,
    HealthMonitor,
)
from paddle_tpu.serving.generation import Sampler, SlotDecodeSession
from paddle_tpu.serving.snapshot import (
    DecodeSnapshotManager,
    SnapshotMismatchError,
)

VOCAB, SEQ, D, S = 24, 8, 32, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=2,
           n_head=2, d_inner=64)


@pytest.fixture(scope="module")
def trained(request):
    """One tiny trained 2-layer transformer (2 layers so cross/self
    pools past layer 0 are in every snapshot) shared by the module."""
    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 41
    startup.random_seed = 41
    scope = global_scope()
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(7)
    src = rng.randint(3, VOCAB, (8, SEQ)).astype("int64")
    src_len = np.asarray([SEQ, 3, SEQ - 1, 5, SEQ, 4, SEQ - 2, SEQ],
                         "int64")
    return {"exe": exe, "scope": scope, "src": src, "src_len": src_len}


def _paged(trained, **kw):
    # every session gets its OWN child of the trained scope: params
    # resolve through the parent chain, pgd_* state shadows per child,
    # so two live sessions (oracle / victim / restored) never collide
    args = dict(num_slots=S, max_length=SEQ, d_model=D, paged=True,
                page_size=4, steps=2, num_groups=2,
                prefix_cache_pages=8,
                sampler=Sampler(strategy="top_k", top_k=4,
                                temperature=0.9, seed=11),
                scope=trained["scope"].new_scope())
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


@pytest.fixture(autouse=True)
def _clean_chaos_and_flags():
    yield
    chaos.disable()
    flags.set_flag("dispatch_retries", 0)


# -- snapshot / restore ------------------------------------------------------

def test_snapshot_restore_is_bit_exact_mid_flight(trained, tmp_path):
    src, src_len = trained["src"], trained["src_len"]
    pfx = [int(x) for x in src[0][:5]]

    def drive(sess):
        """Deterministic load: fork group with prefix, then a backlog
        pumped through the 4-slot pool."""
        gslots = sess.admit_group(src[0], n=2, src_len=int(src_len[0]),
                                  prefix_tokens=pfx)
        rids = [sess.enqueue(src[i], int(src_len[i]))
                for i in range(1, 6)]
        return gslots, rids

    # oracle: the uninterrupted run
    oracle = _paged(trained)
    og, orids = drive(oracle)
    odone = {}
    for _ in range(40):
        odone.update(oracle.pump())
        if len(odone) >= len(orids):
            break

    # victim: same drive, snapshot after 2 pump rounds (live slots,
    # shared pages, prefix cache and backlog all nonempty)
    victim = _paged(trained)
    vg, vrids = drive(victim)
    vdone = {}
    for _ in range(2):
        vdone.update(victim.pump())
    assert victim._live and victim._pending, "snapshot point too late"
    assert victim.shared_pages > 0 or victim.cached_pages > 0
    mgr = DecodeSnapshotManager(victim, str(tmp_path / "snap"))
    mgr.save()
    mgr.close(save=False)

    # restored: a FRESH session + restore, then the same continuation
    restored = _paged(trained)
    mgr2 = DecodeSnapshotManager(restored, str(tmp_path / "snap"))
    manifest = mgr2.restore()
    assert manifest is not None
    assert restored.steps_done == victim.steps_done
    assert restored.pending_requests == victim.pending_requests
    assert restored._pool.state_dict() == victim._pool.state_dict()

    rdone = dict(vdone)
    vdone2 = dict(vdone)
    for _ in range(40):
        vdone2.update(victim.pump())
        rdone.update(restored.pump())
        if len(rdone) >= len(vrids):
            break
    # every request's tokens: victim continuation == restored
    # continuation == oracle (same seeds, same slots, same positions)
    for rid in vrids:
        np.testing.assert_array_equal(rdone[rid], vdone2[rid])
    for o_rid, rid in zip(orids, vrids):
        np.testing.assert_array_equal(odone[o_rid], rdone[rid])
    mgr2.close(save=False)


def test_snapshot_quarantines_corruption_and_falls_back(trained,
                                                        tmp_path):
    sess = _paged(trained)
    sess.admit(trained["src"][0], int(trained["src_len"][0]))
    snap = str(tmp_path / "snap")
    mgr = DecodeSnapshotManager(sess, snap)
    mgr.save(serial=1)
    sess.step()
    mgr.save(serial=2)
    # flip one byte of a var file in the NEWEST serial
    newest = os.path.join(snap, "checkpoint_2")
    victim_file = os.path.join(newest, "pgd_pos.npy")
    blob = bytearray(open(victim_file, "rb").read())
    blob[-1] ^= 0xFF
    open(victim_file, "wb").write(bytes(blob))

    fresh = _paged(trained)
    mgr2 = DecodeSnapshotManager(fresh, snap)
    manifest = mgr2.restore()
    assert manifest is not None and int(manifest["serial"]) == 1
    assert not os.path.exists(newest), "corrupt serial not quarantined"
    assert any(".corrupt-" in d for d in os.listdir(snap))
    mgr.close(save=False)
    mgr2.close(save=False)


def test_snapshot_geometry_mismatch_is_typed_not_quarantined(
        trained, tmp_path):
    sess = _paged(trained)
    sess.admit(trained["src"][0], int(trained["src_len"][0]))
    snap = str(tmp_path / "snap")
    DecodeSnapshotManager(sess, snap).save()
    other = _paged(trained,
                   num_groups=3)  # different geometry
    with pytest.raises(SnapshotMismatchError):
        DecodeSnapshotManager(other, snap).restore()
    # the serial is still there — operator error, not corruption
    assert os.path.isdir(os.path.join(snap, "checkpoint_0"))


def test_dense_session_is_refused_with_guidance(trained):
    dense = SlotDecodeSession(trained["exe"], num_slots=S,
                              max_length=SEQ, d_model=D,
                              scope=global_scope().new_scope(), **CFG)
    with pytest.raises(ValueError, match="paged"):
        DecodeSnapshotManager(dense, "/tmp/unused")


def test_ckpt_inspect_knows_the_decode_dialect(trained, tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "tools"))
    try:
        import ckpt_inspect
    finally:
        sys.path.pop(0)
    sess = _paged(trained)
    sess.admit_group(trained["src"][0], n=2,
                     src_len=int(trained["src_len"][0]),
                     prefix_tokens=[int(x) for x in trained["src"][0][:5]])
    snap = str(tmp_path / "snap")
    DecodeSnapshotManager(sess, snap).save(serial=7)
    step_dir = os.path.join(snap, "checkpoint_7")
    assert ckpt_inspect.main([step_dir, "--verify"]) == 0

    # break refcount conservation INSIDE the dialect block (digests
    # cover var files, not the manifest) — --verify must exit 2
    mpath = os.path.join(step_dir, "__manifest__.json")
    manifest = json.load(open(mpath))
    ds = manifest["extra"]["decode_snapshot"]
    page = next(iter(ds["pool"]["ref"]))
    ds["pool"]["ref"][page] = int(ds["pool"]["ref"][page]) + 1
    json.dump(manifest, open(mpath, "w"))
    assert ckpt_inspect.main([step_dir, "--verify"]) == 2
    assert ckpt_inspect.main([step_dir]) == 0  # print-only still reads


@pytest.mark.parametrize("drafter", ["ngram", "model"])
def test_snapshot_restores_mid_speculation_bit_exact(trained, tmp_path,
                                                     drafter):
    """A snapshot taken BETWEEN speculative dispatches restores into a
    fresh session that finishes every request bit-identically to the
    uninterrupted victim: counters, drafter state and (for the model
    drafter) the draft K/V pool rows all travel in the dialect."""
    src, src_len = trained["src"], trained["src_len"]

    def spec_sess():
        return _paged(trained, steps=1,
                      speculative={"k": 2, "drafter": drafter})

    victim = spec_sess()
    vrids = [victim.enqueue(src[i], int(src_len[i])) for i in range(5)]
    vdone = {}
    for _ in range(2):
        vdone.update(victim.pump())
    assert victim._live and victim.spec_dispatches > 0, \
        "snapshot point is not mid-speculation"
    snap = str(tmp_path / "snap")
    mgr = DecodeSnapshotManager(victim, snap)
    mgr.save()
    mgr.close(save=False)

    restored = spec_sess()
    mgr2 = DecodeSnapshotManager(restored, snap)
    assert mgr2.restore() is not None
    assert restored.spec_proposed == victim.spec_proposed
    assert restored.spec_accepted == victim.spec_accepted
    assert restored.spec_dispatches == victim.spec_dispatches
    assert (restored._spec_drafter.state_dict()
            == victim._spec_drafter.state_dict())

    if drafter == "model":
        # the draft params must travel: victim and restored drafters
        # are independently RANDOMLY initialised, and a weight delta
        # shifts acceptance TIMING — which slot a backlog request
        # lands in after restore — which keys the sampler stream.
        # Without the snapshot carrying them this test only fails
        # when the two random inits happen to disagree early enough.
        vp = victim._spec_drafter.param_arrays()
        rp = restored._spec_drafter.param_arrays()
        assert sorted(vp) == sorted(rp) and vp
        for n in vp:
            np.testing.assert_array_equal(rp[n], vp[n], err_msg=n)

    rdone, vdone2 = dict(vdone), dict(vdone)
    for _ in range(40):
        vdone2.update(victim.pump())
        rdone.update(restored.pump())
        if len(rdone) >= len(vrids) and len(vdone2) >= len(vrids):
            break
    for rid in vrids:
        np.testing.assert_array_equal(rdone[rid], vdone2[rid])
    mgr2.close(save=False)

    # speculative config is part of the snapshot contract: a session
    # without the drafter cannot re-own the watermark/draft rows
    plain = _paged(trained, steps=1)
    with pytest.raises(SnapshotMismatchError):
        DecodeSnapshotManager(plain, snap).restore()


def test_ckpt_inspect_crosschecks_speculative_bindings(trained,
                                                       tmp_path,
                                                       capsys):
    """``--verify`` on a speculative snapshot cross-checks tree-page
    bindings: a page laundered out of a slot's list (ref moved to the
    free list so conservation and refcount accounting both still
    balance) is exactly the tamper only the resident-coverage check
    catches — exit 2."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "tools"))
    try:
        import ckpt_inspect
    finally:
        sys.path.pop(0)
    sess = _paged(trained, steps=1,
                  speculative={"k": 2, "drafter": "ngram"})
    sess.admit(trained["src"][0], SEQ)
    while sess._live and all(
            int(st["pos"]) < 5 for st in sess._live.values()):
        sess.step()
    assert sess._live, "request finished before spanning two pages"
    snap = str(tmp_path / "snap")
    DecodeSnapshotManager(sess, snap).save(serial=3)
    step_dir = os.path.join(snap, "checkpoint_3")
    assert ckpt_inspect.main([step_dir, "--verify"]) == 0
    out = capsys.readouterr().out
    assert "speculative: k=2 drafter=ngram" in out

    mpath = os.path.join(step_dir, "__manifest__.json")
    manifest = json.load(open(mpath))
    ds = manifest["extra"]["decode_snapshot"]
    slot = next(iter(ds["slot_pages"]))
    page = int(ds["slot_pages"][slot].pop())
    del ds["pool"]["ref"][str(page)]
    ds["pool"]["free"].append(page)
    ds["live_pages"] = [p for p in ds["live_pages"] if int(p) != page]
    json.dump(manifest, open(mpath, "w"))
    assert ckpt_inspect.main([step_dir, "--verify"]) == 2
    out = capsys.readouterr().out
    assert "speculative slot" in out


# -- degradation -------------------------------------------------------------

def test_health_monitor_hysteresis_and_metrics():
    mon = HealthMonitor("unit", brownout_at=0.5, shed_at=0.9,
                        recover_at=0.3)
    assert mon.observe(0.2) == HEALTHY
    assert mon.observe(0.6) == BROWNOUT
    assert mon.observe(0.4) == BROWNOUT  # hysteresis band: hold
    assert mon.observe(0.95) == SHED
    assert mon.observe(0.6) == SHED      # brownout band can't relax shed
    assert mon.observe(0.1) == BROWNOUT  # one level per crossing
    assert mon.observe(0.1) == HEALTHY
    assert mon.transitions == 4
    err = mon.reject("unit test")
    assert isinstance(err, DegradedError)
    assert err.retry_after_s > 0
    from paddle_tpu.resilience.retry import is_transient

    assert is_transient(err), "DegradedError must classify retriable"
    text = REGISTRY.to_prometheus()
    assert 'paddle_tpu_serving_health{component="unit"} 0' in text
    assert "paddle_tpu_serving_health_transitions_total" in text


def test_decode_brownout_evicts_cache_refuses_forks_then_recovers(
        trained):
    src, src_len = trained["src"], trained["src_len"]
    sess = _paged(trained, num_groups=S,
                  degradation=dict(brownout_at=0.5, shed_at=0.95,
                                   recover_at=0.3))
    pfx = [int(x) for x in src[0][:5]]
    # populate the prefix cache while healthy
    sess.admit(src[0], int(src_len[0]), prefix_tokens=pfx)
    assert sess.cached_pages > 0 and sess.health == HEALTHY
    # second admission crosses 0.5 occupancy at the NEXT gate check
    sess.admit(src[1], int(src_len[1]))
    sess.admit(src[2], int(src_len[2]))
    assert sess.health == BROWNOUT
    # brownout evicted the prefix cache on transition...
    assert sess.cached_pages == 0
    # ...and refuses forks (n=1 only) with a typed retriable error
    with pytest.raises(DegradedError) as exc_info:
        sess.admit_group(src[3], n=2, src_len=int(src_len[3]))
    assert exc_info.value.state == BROWNOUT
    assert exc_info.value.retry_after_s > 0
    sess.admit(src[3], int(src_len[3]))  # solo admission still served
    # full pool: shed refuses EVERYTHING while in-flight work drains
    with pytest.raises(DegradedError) as exc_info:
        sess.admit(src[4], int(src_len[4]))
    assert exc_info.value.state == SHED
    for _ in range(30):  # drain: each step observes the falling load
        if not sess._live:
            break
        sess.step()
    # recovery relaxes ONE level per observation below recover_at, so
    # a couple more public ops land it: the admission gate observes
    # (shed -> brownout at worst, then the solo admit serves), and the
    # drain steps observe again (-> healthy)
    sess.admit(src[4], int(src_len[4]))
    for _ in range(30):
        if not sess._live:
            break
        sess.step()
    assert sess.health == HEALTHY


def test_generate_survives_degradation_by_deferring(trained):
    """pump() treats a DegradedError like a pool reject: defer to the
    queue front and drain — generate() completes every request."""
    src, src_len = trained["src"], trained["src_len"]
    sess = _paged(trained,
                  degradation=dict(brownout_at=0.5, shed_at=0.75,
                                   recover_at=0.5))
    clean = _paged(trained)
    got = sess.generate(src, src_len)
    want = clean.generate(src, src_len)
    # degradation defers ADMISSION ORDER only; tokens are a per-slot
    # function of (seed, slot, position), and requests are admitted in
    # row order either way, so the outputs still match wherever the
    # slot assignment sequence matches. At minimum: every row decoded
    # to a complete, bos-led stream and nothing wedged.
    assert got.shape == want.shape
    assert (got[:, 0] == 1).all()
    assert sess.free_slots == S and sess.pages_in_use == sess.cached_pages


def test_server_shed_types_rejects_and_recovers(trained, tmp_path):
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import loadgen
    from paddle_tpu.serving.server import BatchingServer

    model_dir = str(tmp_path / "demo")
    loadgen.build_demo_model(model_dir, train_steps=5)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))
    server = BatchingServer(
        predictor, max_batch=8, workers=1, max_queue_depth=8,
        batch_linger_s=0.05,
        degradation=dict(brownout_at=0.5, shed_at=0.75, recover_at=0.25,
                         retry_after_s=0.1))
    reqs = loadgen.demo_requests(16)
    futures, rejects = [], []
    with server:
        for req in reqs:
            try:
                futures.append(server.submit(req))
            except DegradedError as exc:
                assert exc.state == SHED
                assert exc.retry_after_s == 0.1
                rejects.append(exc)
        assert rejects, "the flood never tripped shed"
        # nothing wedges: every admitted future completes
        for fut in futures:
            fut.result(timeout=30.0)
        # drained: the monitor recovered (observe runs at dispatch)
        for req in reqs:  # resubmit the rejected volume — serving again
            server.run(req)
        stats = server.stats()
    assert stats["health"] == HEALTHY
    assert stats["degraded"] == len(rejects)
    text = REGISTRY.to_prometheus()
    assert 'paddle_tpu_serving_health{component="server"} 0' in text


# -- chaos + retry on serving paths ------------------------------------------

def test_admit_chaos_fault_rolls_back_and_retries_bit_exact(trained):
    src, src_len = trained["src"], trained["src_len"]
    clean = _paged(trained)
    want = clean.generate_best_of(src[0], 2, src_len=int(src_len[0]),
                                  prefix_tokens=[int(x)
                                                 for x in src[0][:5]])
    before = REGISTRY.counter(
        "paddle_tpu_retries_total",
        "transient-failure retries by origin",
        ["origin"]).value(origin="serve.admit")
    chaos.configure("seed=3;io@site=serve.admit,n=1")
    flags.set_flag("dispatch_retries", 2)
    sess = _paged(trained)
    got = sess.generate_best_of(src[0], 2, src_len=int(src_len[0]),
                                prefix_tokens=[int(x)
                                               for x in src[0][:5]])
    assert chaos.fires("serve.admit") == 1, "the fault never fired"
    np.testing.assert_array_equal(got, want)
    after = REGISTRY.counter(
        "paddle_tpu_retries_total",
        "transient-failure retries by origin",
        ["origin"]).value(origin="serve.admit")
    assert after == before + 1
    # rollback left the books clean for the retry: nothing leaked
    assert sess._leaked_pages == 0


def test_admit_chaos_fault_without_retries_is_clean_rollback(trained):
    src, src_len = trained["src"], trained["src_len"]
    sess = _paged(trained)
    free_pages = sess.free_pages
    chaos.configure("io@site=serve.admit,n=1")
    with pytest.raises(IOError):
        sess.admit_group(src[0], n=2, src_len=int(src_len[0]))
    chaos.disable()
    assert sess.free_slots == S and sess.free_groups == 2
    assert sess.free_pages == free_pages and sess._reserved_pages == 0
    slots = sess.admit_group(src[0], n=2, src_len=int(src_len[0]))
    assert slots == [0, 1], "rollback changed the slot pop order"


def test_snapshot_write_chaos_fails_save_not_session(trained, tmp_path):
    sess = _paged(trained)
    sess.admit(trained["src"][0], int(trained["src_len"][0]))
    mgr = DecodeSnapshotManager(sess, str(tmp_path / "snap"))
    chaos.configure("io@site=snapshot.write,n=1")
    with pytest.raises(IOError):
        mgr.save(serial=1)
    chaos.disable()
    assert mgr.latest_serial() is None  # nothing half-written visible
    sess.step()  # the session was never touched: still serving
    mgr.save(serial=2)
    assert mgr.latest_serial() == 2
    mgr.close(save=False)


def test_pool_acquire_is_a_chaos_site():
    from paddle_tpu.serving.kv_pool import PagePool

    pool = PagePool(4)
    chaos.configure("io@site=pool.acquire,n=1")
    with pytest.raises(IOError):
        pool.acquire()
    chaos.disable()
    assert pool.free_count == 3  # the faulted acquire allocated nothing
    assert pool.acquire() in (1, 2, 3)


# -- kernel degradation ------------------------------------------------------

def test_paged_attention_falls_back_once_per_process(monkeypatch):
    from paddle_tpu.kernels import paged_attention as pa

    rng = np.random.RandomState(5)
    q = rng.randn(2, 2, 8).astype("float32")
    kp = rng.randn(3, 2, 4, 8).astype("float32")
    vp = rng.randn(3, 2, 4, 8).astype("float32")
    table = np.asarray([[1, 1], [2, 2]], "int32")
    lengths = np.asarray([3, 4], "int32")
    want = np.asarray(pa.paged_attention_reference(
        q, kp, vp, table, lengths))

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("pallas toolchain exploded")

    pa.reset_kernel_fallback()
    monkeypatch.setattr(pa, "_paged_pallas", boom)
    try:
        got = np.asarray(pa.paged_attention(
            q, kp, vp, table, lengths, force_pallas=True))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        assert pa.kernel_fallback_tripped()
        # second call: the tripped flag routes straight to reference —
        # the broken kernel is attempted ONCE per process
        np.asarray(pa.paged_attention(q, kp, vp, table, lengths,
                                      force_pallas=True))
        assert calls["n"] == 1
        count = REGISTRY.counter(
            "paddle_tpu_kernel_fallbacks_total",
            "Pallas kernels abandoned for their reference path this "
            "process (once per kernel)",
            labels=("kernel",)).value(kernel="paged_attention")
        assert count >= 1
    finally:
        pa.reset_kernel_fallback()


# -- watchdog over serving dispatch ------------------------------------------

def test_server_dispatch_arms_watchdog(trained, tmp_path, monkeypatch):
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import loadgen
    from paddle_tpu.serving import server as server_mod

    model_dir = str(tmp_path / "demo")
    loadgen.build_demo_model(model_dir, train_steps=5)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))
    events = []

    class SpyWatchdog(object):
        ENABLED = True

        @staticmethod
        def arm(tag="work", scale=1):
            events.append(("arm", tag))
            return 99

        @staticmethod
        def disarm(token):
            events.append(("disarm", token))

    monkeypatch.setattr(server_mod, "_watchdog", SpyWatchdog)
    with server_mod.BatchingServer(predictor, max_batch=2,
                                   workers=1) as server:
        server.run(loadgen.demo_requests(1)[0])
    assert ("arm", "serve.dispatch") in events
    assert ("disarm", 99) in events
    assert (len([e for e in events if e[0] == "arm"])
            == len([e for e in events if e[0] == "disarm"]))


# -- SIGTERM mid-decode (subprocess) -----------------------------------------

_CHILD = r"""
import os, sys, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import paddle_tpu as fluid
from paddle_tpu.models import transformer
from paddle_tpu.serving.generation import Sampler, SlotDecodeSession
from paddle_tpu.serving.snapshot import DecodeSnapshotManager

snap_dir = sys.argv[1]
VOCAB, SEQ, D, S = 24, 8, 32, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)
main, startup = fluid.Program(), fluid.Program()
main.random_seed = 41; startup.random_seed = 41
with fluid.program_guard(main, startup):
    transformer.build(dropout=0.0, label_smooth_eps=0.0,
                      max_length=SEQ, d_model=D, **CFG)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(startup)
sess = SlotDecodeSession(exe, num_slots=S, max_length=SEQ, d_model=D,
                         paged=True, page_size=4, steps=2,
                         sampler=Sampler(seed=3), **CFG)
mgr = DecodeSnapshotManager(sess, snap_dir,
                            install_signal_handlers=True)
rng = np.random.RandomState(7)
src = rng.randint(3, VOCAB, (64, SEQ)).astype("int64")
for i in range(64):
    sess.enqueue(src[i])
print("READY", flush=True)
while sess._pending or sess._live:
    sess.pump()
    time.sleep(0.01)
print("DRAINED", flush=True)  # only reached if SIGTERM never lands
"""


@pytest.mark.slow
def test_sigterm_banks_final_snapshot_and_dies_by_signal(tmp_path):
    snap_dir = str(tmp_path / "snap")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FLAGS_chaos_spec", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, snap_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
        cwd=os.path.join(os.path.dirname(__file__), os.pardir))
    try:
        line = proc.stdout.readline().strip()
        assert line == "READY", (line, proc.stderr.read())
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        proc.kill()
    # died BY the signal (handler chain re-delivered it), after the
    # in-flight dispatch finished and a final sync snapshot landed
    assert proc.returncode == -signal.SIGTERM, (proc.returncode, err)
    assert "DRAINED" not in out
    from paddle_tpu.resilience.checkpoint import (
        complete_serials,
        read_manifest,
    )

    serials = complete_serials(snap_dir)
    assert serials, "no final snapshot banked on SIGTERM"
    manifest = read_manifest(
        os.path.join(snap_dir, "checkpoint_%d" % serials[-1]))
    meta = manifest["extra"]["decode_snapshot"]
    assert meta["live"] or meta["pending"], \
        "snapshot carries no in-flight state"
