"""Beam search ops + machine-translation model tests.

Mirrors the reference's test_beam_search_op.py / test_beam_search_decode_op
semantics checks and the book test_machine_translation.py convergence +
generation pattern (SURVEY.md §4), on the dense static-shape contract.
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _numpy_beam_step(pre_ids, pre_scores, logp, end_id):
    """Straightforward per-batch priority-queue reference."""
    B, K = pre_ids.shape
    V = logp.shape[2]
    sel_ids = np.zeros((B, K), np.int64)
    sel_scores = np.zeros((B, K), np.float32)
    parents = np.zeros((B, K), np.int64)
    for b in range(B):
        cands = []  # (score, parent, token)
        for k in range(K):
            if pre_ids[b, k] == end_id:
                cands.append((pre_scores[b, k], k, end_id))
            else:
                for v in range(V):
                    cands.append((pre_scores[b, k] + logp[b, k, v], k, v))
        cands.sort(key=lambda t: -t[0])
        for k, (s, p, v) in enumerate(cands[:K]):
            sel_scores[b, k] = s
            parents[b, k] = p
            sel_ids[b, k] = v
    return sel_ids, sel_scores, parents


def test_beam_search_op_matches_numpy():
    rng = np.random.RandomState(0)
    B, K, V, end_id = 3, 4, 11, 0
    pre_ids = rng.randint(0, V, (B, K)).astype(np.int64)
    pre_ids[0, 1] = end_id  # one finished beam
    pre_scores = rng.randn(B, K).astype(np.float32)
    logp = np.log(
        rng.dirichlet(np.ones(V), size=(B, K)).astype(np.float32)
    )

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pi = fluid.layers.data(name="pi", shape=[K], dtype="int64")
        ps = fluid.layers.data(name="ps", shape=[K], dtype="float32")
        sc = fluid.layers.data(name="sc", shape=[K, V], dtype="float32")
        ids, scores, parent = fluid.layers.beam_search(
            pi, ps, sc, beam_size=K, end_id=end_id, is_accumulated=False
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got_ids, got_scores, got_parent = exe.run(
        main,
        feed={"pi": pre_ids, "ps": pre_scores, "sc": np.exp(logp)},
        fetch_list=[ids, scores, parent],
    )
    want_ids, want_scores, want_parents = _numpy_beam_step(
        pre_ids, pre_scores, logp, end_id
    )
    np.testing.assert_allclose(
        np.asarray(got_scores), want_scores, rtol=2e-5, atol=2e-5
    )
    np.testing.assert_array_equal(np.asarray(got_ids), want_ids)
    np.testing.assert_array_equal(
        np.asarray(got_parent).astype(np.int64), want_parents
    )


def test_beam_search_op_accumulated_scores():
    """is_accumulated=True: scores already contain pre_scores; ranking must
    not add them again."""
    rng = np.random.RandomState(1)
    B, K, V, end_id = 2, 3, 7, 0
    pre_ids = rng.randint(1, V, (B, K)).astype(np.int64)
    pre_scores = rng.randn(B, K).astype(np.float32)
    logp = np.log(rng.dirichlet(np.ones(V), size=(B, K)).astype(np.float32))
    accumulated = pre_scores[:, :, None] + logp

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pi = fluid.layers.data(name="pi", shape=[K], dtype="int64")
        ps = fluid.layers.data(name="ps", shape=[K], dtype="float32")
        sc = fluid.layers.data(name="sc", shape=[K, V], dtype="float32")
        ids, scores, parent = fluid.layers.beam_search(
            pi, ps, sc, beam_size=K, end_id=end_id, is_accumulated=True
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got_ids, got_scores, got_parent = exe.run(
        main,
        feed={"pi": pre_ids, "ps": pre_scores, "sc": accumulated},
        fetch_list=[ids, scores, parent],
    )
    want_ids, want_scores, want_parents = _numpy_beam_step(
        pre_ids, pre_scores, logp, end_id
    )
    np.testing.assert_allclose(
        np.asarray(got_scores), want_scores, rtol=2e-5, atol=2e-5
    )
    np.testing.assert_array_equal(np.asarray(got_ids), want_ids)


def test_beam_search_decode_backtrack():
    # T=3, B=1, K=2 hand-built lattice.
    #  t0: beams pick tokens [5, 6] (parents [0, 1])
    #  t1: beam0 <- parent 1 token 7; beam1 <- parent 0 token 8
    #  t2: beam0 <- parent 0 token 9; beam1 <- parent 0 token 3
    ids = np.array(
        [[[5, 6]], [[7, 8]], [[9, 3]]], np.int64
    )
    parents = np.array(
        [[[0, 1]], [[1, 0]], [[0, 0]]], np.int64
    )
    step_scores = np.array(
        [[[0.5, 0.6]], [[0.7, 0.8]], [[0.9, 0.3]]], np.float32
    )
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        iv = fluid.layers.data(name="ids", shape=[1, 2], dtype="int64",
                               append_batch_size=False)
        pv = fluid.layers.data(name="par", shape=[1, 2], dtype="int64",
                               append_batch_size=False)
        sv = fluid.layers.data(name="sc", shape=[1, 2], dtype="float32",
                               append_batch_size=False)
        # feed carries [T, B, K] directly
        sent, sent_scores = fluid.layers.beam_search_decode(
            iv, pv, scores=sv, beam_size=2
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, got_scores = exe.run(
        main, feed={"ids": ids, "par": parents, "sc": step_scores},
        fetch_list=[sent, sent_scores],
    )
    got = np.asarray(got)
    got_scores = np.asarray(got_scores)
    # beam0 final: t2 token 9 <- t1 beam0 (token 7, parent beam1 at t0=6)
    np.testing.assert_array_equal(got[0, 0], [6, 7, 9])
    # beam1 final: t2 token 3 <- same prefix
    np.testing.assert_array_equal(got[0, 1], [6, 7, 3])
    # Per-token scores ride the same lattice.
    np.testing.assert_allclose(got_scores[0, 0], [0.6, 0.7, 0.9], rtol=1e-6)
    np.testing.assert_allclose(got_scores[0, 1], [0.6, 0.7, 0.3], rtol=1e-6)


def _copy_task_batch(rng, batch, seq, vocab, start_id, end_id):
    """Target = source (copy task). Tokens in [3, vocab)."""
    lens = rng.randint(2, seq - 1, (batch,))
    src = np.zeros((batch, seq), np.int64)
    tgt_in = np.zeros((batch, seq), np.int64)
    label = np.full((batch, seq), end_id, np.int64)
    mask = np.zeros((batch, seq), np.float32)
    for i, ln in enumerate(lens):
        toks = rng.randint(3, vocab, (ln,))
        src[i, :ln] = toks
        tgt_in[i, 0] = start_id
        tgt_in[i, 1:ln + 1] = toks[: seq - 1]
        label[i, :ln] = toks
        label[i, ln] = end_id
        mask[i, :ln + 1] = 1.0
    return {
        "source_sequence": src,
        "source_length": lens.reshape(-1, 1).astype(np.int64),
        "target_sequence": tgt_in,
        "label": label,
        "label_mask": mask,
    }


@pytest.fixture(scope="module")
def trained_mt():
    from paddle_tpu.models import machine_translation as mt

    vocab, seq = 24, 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        loss, feeds, _ = mt.build(
            src_vocab=vocab, tgt_vocab=vocab, src_seq_len=seq,
            tgt_seq_len=seq, emb_dim=32, encoder_size=32, decoder_size=32,
        )
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    # Dedicated scope: the autouse _fresh_programs fixture resets the global
    # scope per test, and this module fixture outlives several tests.
    from paddle_tpu.core.scope import Scope

    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        rng = np.random.RandomState(7)
        losses = []
        for step in range(180):
            feed = _copy_task_batch(rng, 16, seq, vocab, 1, 2)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    return {
        "losses": losses, "vocab": vocab, "seq": seq, "exe": exe,
        "scope": scope,
    }


def test_machine_translation_converges(trained_mt):
    losses = trained_mt["losses"]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_machine_translation_beam_generation(trained_mt):
    from paddle_tpu.models import machine_translation as mt

    vocab, seq = trained_mt["vocab"], trained_mt["seq"]
    exe = trained_mt["exe"]
    gen_prog, gen_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(gen_prog, gen_startup):
        ids, scores, feeds = mt.build_generator(
            src_vocab=vocab, tgt_vocab=vocab, src_seq_len=seq,
            emb_dim=32, encoder_size=32, decoder_size=32,
            beam_size=3, max_len=seq, start_id=1, end_id=2,
        )
    rng = np.random.RandomState(11)
    batch = _copy_task_batch(rng, 4, seq, vocab, 1, 2)
    with fluid.scope_guard(trained_mt["scope"]):
        got_ids, got_scores = exe.run(
            gen_prog,
            feed={
                "source_sequence": batch["source_sequence"],
                "source_length": batch["source_length"],
            },
            fetch_list=[ids, scores],
        )
    got_ids = np.asarray(got_ids)
    got_scores = np.asarray(got_scores)
    assert got_ids.shape == (4, 3, seq)
    assert got_scores.shape == (4, 3)
    assert (got_ids >= 0).all() and (got_ids < vocab).all()
    # Beams are returned best-first: scores non-increasing along beam axis.
    assert (np.diff(got_scores, axis=1) <= 1e-5).all()
    # The trained copy-task model should reproduce at least the first source
    # token in its best beam for most rows.
    first_match = (
        got_ids[:, 0, 0] == batch["source_sequence"][:, 0]
    ).mean()
    assert first_match >= 0.5, (got_ids[:, 0], batch["source_sequence"])
