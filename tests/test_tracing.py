"""Request-scoped tracing (observability/tracing.py + the serving
hooks):

* trace lifecycle: root span covers the handling window, leaked spans
  force-close (flagged), derived stats (TTFT, phase split, inter-token
  distribution, span coverage) come out of the span timeline;
* histogram exemplars land in the narrowest bucket, ride the JSON
  snapshot, and resolve against the completed-trace ring;
* Perfetto export is structurally valid Chrome trace JSON;
* CONTINUITY across preemption: a session snapshotted mid-flight
  restores with its ``rid -> trace_id`` bindings intact, re-banks its
  backlogged streams under the ORIGINAL ids (session-origin
  continuation records), and ``take_result`` still names the trace at
  claim time — the frontend's post-restore claim path;
* cancel / drop paths close every span: the ring sweep finds no open
  or force-closed spans and the in-flight table drains to empty;
* blackbox snapshots list in-flight trace ids.

Tracing must also be FREE when off — that half (byte-identical wire
streams, zero fresh compiles, no minted context) is proved over real
sockets by tools/trace_smoke.py (CI ``trace`` stage).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import global_scope
from paddle_tpu.observability import blackbox, tracing
from paddle_tpu.observability.metrics_registry import (
    DECODE_BUCKETS,
    MetricsRegistry,
)
from paddle_tpu.serving.generation import Sampler, SlotDecodeSession
from paddle_tpu.serving.snapshot import DecodeSnapshotManager

VOCAB, SEQ, D, S = 24, 8, 32, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=2,
           n_head=2, d_inner=64)


@pytest.fixture(scope="module")
def trained():
    """One tiny transformer shared by the module (the serving
    resilience suite's pattern)."""
    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 41
    startup.random_seed = 41
    scope = global_scope()
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(7)
    src = rng.randint(3, VOCAB, (8, SEQ)).astype("int64")
    src_len = np.asarray([SEQ, 3, SEQ - 1, 5, SEQ, 4, SEQ - 2, SEQ],
                         "int64")
    return {"exe": exe, "scope": scope, "src": src, "src_len": src_len}


def _paged(trained, **kw):
    args = dict(num_slots=S, max_length=SEQ, d_model=D, paged=True,
                page_size=4, steps=2, num_groups=2,
                prefix_cache_pages=8,
                sampler=Sampler(strategy="top_k", top_k=4,
                                temperature=0.9, seed=11),
                scope=trained["scope"].new_scope())
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


@pytest.fixture(autouse=True)
def _tracing_reset():
    tracing.reset()
    tracing.enable(True)
    yield
    tracing.enable(False)
    tracing.reset()


def _sweep_ring(recs):
    """The span-closure sweep: every span in every completed record is
    closed, and none was force-closed at finish (a force-close means a
    code path finished the trace with a span still open)."""
    for rec in recs:
        for sp in rec["spans"]:
            assert sp["t1"] is not None, (
                "open span %r in completed trace %s"
                % (sp["name"], rec["trace_id"]))
            assert not sp["meta"].get("force_closed"), (
                "force-closed span %r leaked to finish in trace %s "
                "(outcome=%s)" % (sp["name"], rec["trace_id"],
                                  rec["outcome"]))


# -- unit: lifecycle, stats, ring, exemplars, perfetto -----------------------

def test_trace_lifecycle_and_derived_stats():
    tr = tracing.start(endpoint="generate", t_client_send=None)
    assert tr.id in tracing.inflight_ids()
    tr.span("queue", tr.t0, tr.t0 + 0.001)
    sp = tr.begin("prefill", prefix_hit_pages=2)
    tr.end(sp)
    for _ in range(3):
        d = tr.begin("decode.step", tokens=2, cow_copies=1,
                     speculative=True)
        tr.end(d)
        tr.bump("tokens", 2)
        tr.bump("tokens_from_spec", 1)
        tr.bump("cow_copies", 1)
    tr.mark("first_token")
    tr.mark("first_token")  # idempotent: first occurrence wins
    rec = tracing.finish(tr, outcome="ok")
    assert tr.id not in tracing.inflight_ids()
    st = rec["stats"]
    assert st["tokens"] == 6 and st["tokens_from_spec"] == 3
    assert st["spec_fraction"] == 0.5 and st["cow_copies"] == 3
    assert st["queue_s"] == pytest.approx(0.001, abs=5e-4)
    assert st["ttft_s"] is not None and st["wall_s"] > 0
    # the root "request" span spans the whole window -> full coverage
    assert st["span_coverage"] == 1.0
    assert tracing.get(tr.id) is rec and rec["outcome"] == "ok"
    _sweep_ring([rec])


def test_finish_force_closes_leaked_spans_and_flags_them():
    tr = tracing.start(endpoint="generate")
    tr.begin("decode.step")  # never ended
    rec = tracing.finish(tr, outcome="error")
    leaked = [sp for sp in rec["spans"]
              if sp["meta"].get("force_closed")]
    assert len(leaked) == 1 and leaked[0]["name"] == "decode.step"
    # the root span closes at finish by design, never flagged
    assert not any(sp["meta"].get("force_closed")
                   for sp in rec["spans"] if sp["name"] == "request")


def test_mint_ids_unique_and_ring_is_bounded():
    ids = {tracing.mint_id() for _ in range(64)}
    assert len(ids) == 64 and all(len(i) == 16 for i in ids)
    for _ in range(tracing.RING + 5):
        tracing.finish(tracing.start(endpoint="generate"))
    assert len(tracing.completed()) == tracing.RING


def test_histogram_exemplar_lands_in_narrowest_bucket():
    reg = MetricsRegistry()
    h = reg.histogram("t_seconds", "t", buckets=DECODE_BUCKETS)
    h.observe(0.0008, exemplar="aaaa")   # -> the 0.001 bucket (idx 3)
    h.observe(0.0009, exemplar="bbbb")   # same bucket: last writer wins
    h.observe(99.0, exemplar="cccc")     # -> +Inf overflow bucket
    ex = h.exemplars()
    assert ex[3]["id"] == "bbbb" and ex[3]["value"] == 0.0009
    assert ex[len(DECODE_BUCKETS)]["id"] == "cccc"
    snap = h.snapshot()
    assert snap["exemplars"][3]["id"] == "bbbb"
    # an untraced observation never allocates exemplar state
    h2 = reg.histogram("p_seconds", "p", buckets=DECODE_BUCKETS)
    h2.observe(0.001)
    assert h2.exemplars() == {} and "exemplars" not in h2.snapshot()


def test_exemplar_resolves_against_completed_ring():
    tr = tracing.start(endpoint="generate")
    rec = tracing.finish(tr)
    assert tracing.get(tr.id) is rec
    assert tracing.get("0000000000000000") is None


def test_perfetto_events_are_valid_chrome_trace():
    tr = tracing.start(endpoint="generate")
    sp = tr.begin("decode.step", tokens=2)
    tr.end(sp)
    rec = tracing.finish(tr)
    events = tracing.perfetto_events(rec, row=3, pid=9)
    kinds = {e["ph"] for e in events}
    assert kinds == {"M", "X"}
    slices = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in slices} == {"request", "decode.step"}
    for e in slices:
        assert e["pid"] == 9 and e["tid"] == 3
        assert e["dur"] >= 0 and e["ts"] > 0
        assert e["args"]["trace_id"] == rec["trace_id"]


def test_blackbox_snapshot_lists_inflight_traces():
    tr = tracing.start(endpoint="generate")
    entries = blackbox.snapshot(reason="test")["inflight_traces"]
    mine = [e for e in entries if e["trace_id"] == tr.id]
    assert mine and mine[0]["endpoint"] == "generate"
    assert mine[0]["spans_open"] == 1  # the root span
    tracing.finish(tr)
    assert not [e for e in
                blackbox.snapshot(reason="test")["inflight_traces"]
                if e["trace_id"] == tr.id]


# -- session integration: continuity, cancel, page accounting ----------------

def test_traced_backlog_rides_snapshot_under_original_ids(trained,
                                                          tmp_path):
    """THE continuity property: a session snapshotted with traced
    requests mid-flight restores with the rid -> trace-id bindings
    intact, re-banks the backlog under the ORIGINAL ids, and
    take_result still names each trace at claim time."""
    src, src_len = trained["src"], trained["src_len"]
    victim = _paged(trained)
    tids = {}
    for i in range(1, 6):
        tid = tracing.mint_id()
        rid = victim.enqueue(src[i], int(src_len[i]), trace_id=tid)
        tids[rid] = tid
    for _ in range(2):
        victim.pump()
    assert victim._pending, "snapshot point too late to carry backlog"
    assert victim._trace_ids, "bindings already retired"
    mgr = DecodeSnapshotManager(victim, str(tmp_path / "snap"))
    mgr.save()
    mgr.close(save=False)

    # simulate the process boundary: the restored twin has no in-flight
    # traces — continuation must START session-origin traces from the
    # restored bindings, not find frontend ones
    tracing.reset()
    restored = _paged(trained)
    mgr2 = DecodeSnapshotManager(restored, str(tmp_path / "snap"))
    assert mgr2.restore() is not None
    # the bindings survived the dialect round trip verbatim
    assert restored._trace_ids == {
        rid: tid for rid, tid in tids.items()
        if rid in victim._trace_ids}
    for _ in range(40):
        restored.pump()
        if not restored.pending_requests and not restored.active_slots:
            break
    banked = {rec["trace_id"]: rec for rec in tracing.completed()}
    for rid in list(tids):
        tokens = restored.take_result(rid)
        if tokens is None:
            continue  # claimed by the pre-snapshot victim pumps
        tid = tids[rid]
        rec = banked.get(tid)
        assert rec is not None, (
            "restored request %d re-banked under a NEW id, not its "
            "original trace %s" % (rid, tid))
        assert rec["origin"] == "session" and rec["outcome"] == "banked"
        assert any(sp["name"] == "decode.step" for sp in rec["spans"])
    # claims retired every binding
    assert not restored._trace_ids
    assert not tracing.inflight_ids()
    _sweep_ring(tracing.completed())
    mgr2.close(save=False)


def test_cancel_and_drop_close_every_span(trained):
    """Cancel (live slot) and drop (queued request) both finish their
    traces with no open spans — swept across the whole ring — and the
    in-flight table drains to empty."""
    src, src_len = trained["src"], trained["src_len"]
    sess = _paged(trained)
    rids = {}
    for i in range(6):
        tid = tracing.mint_id()
        rid = sess.enqueue(src[i % len(src)], int(src_len[i]),
                           trace_id=tid)
        rids[rid] = tid
    admitted = sess.admit_pending()
    assert admitted and sess._slot_traces
    sess.step()  # one dispatch so cancelled traces carry decode spans
    for slot in list(admitted):
        sess.cancel(slot)
    for rid in list(sess._trace_ids):
        sess.drop_pending(rid)
    assert not sess._slot_traces and not sess._trace_ids
    assert not tracing.inflight_ids(), (
        "cancel/drop leaked open traces: %r" % tracing.inflight_ids())
    recs = tracing.completed()
    # queued-never-admitted requests have no trace OBJECT yet (the
    # session only continues traces at admission) — dropping them just
    # retires the binding; admitted ones must finish as cancelled
    assert {r["outcome"] for r in recs} <= {"cancelled", "banked"}
    assert any(r["outcome"] == "cancelled" for r in recs)
    _sweep_ring(recs)
    assert sess.pool_conserved


def test_traced_decode_accumulates_pages_and_tokens(trained):
    """A traced request driven to completion accumulates tokens and
    integrates page-seconds; its session-origin record derives a full
    stats block."""
    src, src_len = trained["src"], trained["src_len"]
    sess = _paged(trained)
    tid = tracing.mint_id()
    rid = sess.enqueue(src[0], int(src_len[0]), trace_id=tid)
    for _ in range(40):
        sess.pump()
        if sess.take_result(rid) is not None:
            break
    rec = tracing.get(tid)
    assert rec is not None and rec["outcome"] == "banked"
    st = rec["stats"]
    assert st["tokens"] > 0
    assert st["page_seconds"] > 0
    assert st["queue_s"] >= 0 and st["prefill_s"] > 0
    assert st["decode_s"] > 0
    names = {sp["name"] for sp in rec["spans"]}
    assert {"request", "queue", "prefill", "decode.step"} <= names
    _sweep_ring([rec])


def test_tracing_off_session_allocates_nothing(trained):
    """With tracing off, the session's per-request maps stay empty —
    the zero-allocation half of the overhead contract at the session
    layer (the wire half is tools/trace_smoke.py's control leg)."""
    tracing.enable(False)
    src, src_len = trained["src"], trained["src_len"]
    sess = _paged(trained)
    rid = sess.enqueue(src[0], int(src_len[0]))
    for _ in range(40):
        sess.pump()
        if sess.take_result(rid) is not None:
            break
    assert sess._trace_ids == {} and sess._slot_traces == {}
    assert sess._trace_cow == {}
    assert tracing.completed() == [] and not tracing.inflight_ids()
