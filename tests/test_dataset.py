"""Dataset package tests (python/paddle/dataset parity).

Runs with PADDLE_TPU_DATASET=synthetic so no network is touched: each
module must serve deterministic, well-formed, learnable samples. The
recognize-digits book test then trains on the mnist reader exactly as the
reference's test_recognize_digits does on real MNIST — when a cached real
download exists the same test consumes it transparently (common.py contract).
Reference: python/paddle/dataset/tests/*, book/test_recognize_digits.py.
"""

import itertools
import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_DATASET", "synthetic")

import paddle_tpu as fluid
import paddle_tpu.dataset as ds
from paddle_tpu.dataset import common


def _take(reader, n):
    return list(itertools.islice(reader(), n))


def test_mnist_shapes_and_determinism():
    a = _take(ds.mnist.train(), 32)
    b = _take(ds.mnist.train(), 32)
    assert len(a) == 32
    for (img, lbl), (img2, lbl2) in zip(a, b):
        assert img.shape == (784,) and img.dtype == np.float32
        assert -1.0 <= img.min() and img.max() <= 1.0
        assert 0 <= lbl <= 9
        np.testing.assert_array_equal(img, img2)
        assert lbl == lbl2
    test_set = _take(ds.mnist.test(), 16)
    assert len(test_set) == 16


def test_cifar_readers():
    for reader, classes in [(ds.cifar.train10(), 10), (ds.cifar.test10(), 10),
                            (ds.cifar.train100(), 100)]:
        img, lbl = _take(reader, 2)[0]
        assert img.shape == (3072,) and img.dtype == np.float32
        assert 0 <= lbl < classes


def test_uci_housing_feature_scaling():
    rows = _take(ds.uci_housing.train(), 64)
    x = np.stack([r[0] for r in rows])
    y = np.stack([r[1] for r in rows])
    assert x.shape == (64, 13) and y.shape == (64, 1)
    assert np.isfinite(x).all() and np.isfinite(y).all()


def test_imdb_word_dict_and_readers():
    wd = ds.imdb.word_dict()
    assert len(wd) > 50
    sample = _take(ds.imdb.train(wd), 4)
    for words, label in sample:
        assert len(words) > 0 and all(isinstance(w, int) for w in words)
        assert label in (0, 1)


def test_imikolov_ngrams():
    wd = ds.imikolov.build_dict(min_word_freq=1)
    n = 5
    grams = _take(ds.imikolov.train(wd, n), 8)
    assert all(len(g) == n for g in grams)
    vocab = len(wd)
    assert all(0 <= w < vocab for g in grams for w in g)


def test_movielens_schema():
    rows = _take(ds.movielens.train(), 8)
    assert len(rows) == 8
    assert ds.movielens.max_user_id() > 0
    # each row: user features..., movie features..., rating (last)
    for row in rows:
        assert np.isfinite(float(np.asarray(row[-1]).reshape(-1)[0]))


def test_conll05_srl_samples():
    rows = _take(ds.conll05.test(), 4)
    word_dict, verb_dict, label_dict = ds.conll05.get_dict()
    assert len(word_dict) > 0 and len(label_dict) > 0
    for row in rows:
        # (words, ctx_n2..ctx_p2, verb, mark, labels) per the reference layout
        assert len(row) >= 3


def test_image_datasets():
    img, lbl = _take(ds.flowers.train(), 1)[0]
    assert img.ndim == 1 and img.size % 3 == 0
    img2, seg = _take(ds.voc2012.train(), 1)[0]
    assert img2.ndim >= 1


def test_sentiment_reader():
    wd = ds.sentiment.get_word_dict()
    rows = _take(ds.sentiment.train(), 4)
    for words, label in rows:
        assert label in (0, 1) and len(words) > 0


@pytest.mark.parametrize("mod,args", [
    ("wmt14", (30,)),
    ("wmt16", (30, 30)),
])
def test_wmt_translation_pairs(mod, args):
    reader = getattr(ds, mod).train(*args)
    rows = _take(reader, 4)
    for row in rows:
        src, trg = row[0], row[1]
        assert len(src) > 0 and len(trg) > 0
        assert all(0 <= w < args[0] for w in src)


def test_common_download_uses_cache(tmp_path):
    # a file:// URL exercises download+md5 without network
    src = tmp_path / "blob.bin"
    src.write_bytes(b"hello paddle_tpu")
    md5 = common.md5file(str(src))
    old_home, common.DATA_HOME = common.DATA_HOME, str(tmp_path / "cache")
    old_mode = os.environ.get("PADDLE_TPU_DATASET")
    os.environ["PADDLE_TPU_DATASET"] = "auto"
    try:
        p1 = common.download("file://" + str(src), "t", md5)
        assert os.path.exists(p1)
        os.remove(src)  # cached copy must now satisfy the second call
        p2 = common.download("file://" + str(src), "t", md5)
        assert p1 == p2
        with pytest.raises(IOError):
            common.download("file://" + str(tmp_path / "missing"), "t")
    finally:
        common.DATA_HOME = old_home
        os.environ["PADDLE_TPU_DATASET"] = old_mode or "synthetic"


def test_recognize_digits_trains_on_mnist_reader():
    """Book test: MLP on the mnist dataset reader to an accuracy threshold
    (reference book/test_recognize_digits.py; real data when cached,
    synthetic-template fallback offline — either stream is learnable)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [784], stop_gradient=False)
        label = fluid.layers.data("label", [1], dtype="int64")
        h = fluid.layers.fc(img, 64, act="relu")
        logits = fluid.layers.fc(h, 10, act=None)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        fluid.optimizer.Adam(1e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    reader = ds.mnist.train()
    batch = []
    accs = []
    for epoch in range(3):
        for sample in itertools.islice(reader(), 512):
            batch.append(sample)
            if len(batch) == 64:
                imgs = np.stack([s[0] for s in batch]).astype("float32")
                lbls = np.array([[s[1]] for s in batch], "int64")
                _, a = exe.run(main, feed={"img": imgs, "label": lbls},
                               fetch_list=[loss, acc])
                accs.append(float(np.asarray(a).reshape(-1)[0]))
                batch = []
    assert np.mean(accs[-4:]) > 0.8, "final train acc %s" % accs[-4:]
