"""Resilience tests: atomic/digest-verified checkpoints, crash/resume
bit-equality (in-process and via SIGKILLed subprocesses), retry
classification, chaos determinism, master-restart client survival.

The headline contracts (ISSUE 5 acceptance):
* a TrainSession child SIGKILLed mid-step resumes from the newest
  COMPLETE serial and reproduces the uninterrupted run's loss trajectory
  bit-exactly;
* a child killed mid-checkpoint-write leaves only a temp dir, which the
  restart ignores;
* a corrupted latest checkpoint is quarantined (kept for autopsy, out of
  the serial namespace) and the previous complete serial loads instead.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.resilience import chaos, retry
from paddle_tpu.resilience.checkpoint import (
    CheckpointManager, complete_serials, read_manifest,
    verify_checkpoint_dir)
from paddle_tpu.resilience.session import TrainSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shared tiny model
# ---------------------------------------------------------------------------

def _build_model(seed=17, dropout=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], stop_gradient=False)
        y = fluid.layers.data("y", [1])
        h = fluid.layers.fc(x, 8, act="relu")
        if dropout:
            h = fluid.layers.dropout(h, 0.3)
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    main.random_seed = seed
    return main, startup, loss


def _feed_for(step):
    r = np.random.RandomState(1000 + step)
    return {"x": r.rand(8, 4).astype("float32"),
            "y": r.rand(8, 1).astype("float32")}


def _session(exe, ckpt_dir, main, **kw):
    kw.setdefault("install_signal_handlers", False)
    kw.setdefault("emergency_on_hang", False)
    return TrainSession(exe, str(ckpt_dir), main_program=main, **kw)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

def test_manager_save_restore_roundtrip(tmp_path):
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed_for(0), fetch_list=[loss])
    mgr = CheckpointManager(str(tmp_path), executor=exe, main_program=main)
    mgr.save(step=1)
    w_before = np.asarray(fluid.global_scope().get_value(
        main.global_block().all_parameters()[0].name))
    # clobber, then restore
    fluid.global_scope().set_value(
        main.global_block().all_parameters()[0].name,
        np.zeros_like(w_before))
    manifest = mgr.restore()
    assert manifest["step"] == 1 and manifest["serial"] == 1
    w_after = np.asarray(fluid.global_scope().get_value(
        main.global_block().all_parameters()[0].name))
    np.testing.assert_array_equal(w_before, w_after)
    # manifest carries digests + rng for every var file
    m = read_manifest(str(tmp_path / "checkpoint_1"))
    assert m["rng"]["run_counter"] == exe._run_counter
    assert all(v["sha256"] for v in m["vars"].values())
    assert verify_checkpoint_dir(str(tmp_path / "checkpoint_1")) == []


def test_manager_async_save_and_retention(tmp_path):
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path), executor=exe,
                            main_program=main, max_to_keep=2)
    for step in range(1, 6):
        exe.run(main, feed=_feed_for(step), fetch_list=[loss])
        mgr.save_async(step)
    mgr.wait()
    assert mgr.last_error is None
    assert complete_serials(str(tmp_path)) == [4, 5]


def test_restore_skips_and_quarantines_corrupt_latest(tmp_path):
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path), executor=exe, main_program=main)
    mgr.save(step=1)
    exe.run(main, feed=_feed_for(1), fetch_list=[loss])
    mgr.save(step=2)
    # corrupt the newest serial: flip bytes in one var file
    d2 = tmp_path / "checkpoint_2"
    victim = next(f for f in os.listdir(d2) if f.endswith(".npy"))
    with open(d2 / victim, "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\xff\xff\xff\xff")
    manifest = mgr.restore()
    assert manifest["serial"] == 1  # fell back to previous complete
    assert 2 not in complete_serials(str(tmp_path))
    corrupt = [d for d in os.listdir(tmp_path) if ".corrupt-" in d]
    assert corrupt, "corrupt serial must be quarantined, not deleted"


def test_restore_ignores_partial_tmp_dir(tmp_path):
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path), executor=exe, main_program=main)
    mgr.save(step=3)
    # a writer killed mid-save leaves var files but no manifest, under
    # a temp name — restore must not even consider it
    fake = tmp_path / "checkpoint_9.tmp-12345"
    fake.mkdir()
    np.save(fake / "garbage.npy", np.zeros(3))
    manifest = mgr.restore()
    assert manifest["serial"] == 3
    assert complete_serials(str(tmp_path)) == [3]


def test_restore_skips_v1_marker_manifests(tmp_path):
    """A dir written by io.save_checkpoint (v1 manifest, no digests/vars)
    is complete but not the manager's dialect: restore must fall back to
    a manager serial instead of 'loading' zero vars and claiming ok."""
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path), executor=exe, main_program=main)
    mgr.save(step=2)
    fluid.io.save_checkpoint(exe, str(tmp_path), main_program=main,
                             serial=9)  # v1 dialect, newest serial
    manifest = mgr.restore()
    assert manifest["serial"] == 2  # v1 dir skipped, NOT quarantined
    assert os.path.isdir(tmp_path / "checkpoint_9")
    assert not [d for d in os.listdir(tmp_path) if ".corrupt-" in d]


def test_restore_empty_dir_returns_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "nope"))
    assert mgr.restore() is None
    assert mgr.latest_serial() is None


def test_checkpoint_failure_counted(tmp_path):
    from paddle_tpu.observability.metrics_registry import REGISTRY

    ctr = REGISTRY.counter("paddle_tpu_checkpoint_failures_total",
                           labels=["stage"])
    before = ctr.value(stage="save")
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path), executor=exe, main_program=main)
    chaos.configure("io@site=ckpt.write,p=1,n=1")
    try:
        with pytest.raises(IOError):
            mgr.save(step=1)
    finally:
        chaos.disable()
    assert ctr.value(stage="save") == before + 1
    assert complete_serials(str(tmp_path)) == []  # tmp dir cleaned up


# ---------------------------------------------------------------------------
# io.save_checkpoint atomicity (satellite)
# ---------------------------------------------------------------------------

def test_io_save_checkpoint_atomic_and_partial_skipped(tmp_path):
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ckpt = tmp_path / "ckpt"
    step_dir = fluid.io.save_checkpoint(exe, str(ckpt), main_program=main,
                                        serial=1)
    assert os.path.exists(os.path.join(step_dir, "__manifest__.json"))
    # a torn write: dir exists, manifest (and sharding marker) missing
    partial = ckpt / "checkpoint_7"
    partial.mkdir()
    np.save(partial / "w.npy", np.zeros(2))
    # and a stale temp dir from a killed writer
    (ckpt / "checkpoint_8.tmp-999").mkdir()
    assert fluid.io._checkpoint_serials(str(ckpt)) == [1]
    serial = fluid.io.load_checkpoint(exe, str(ckpt), main_program=main)
    assert serial == 1  # NOT 7: the partial dir is never "latest"


def test_io_load_checkpoint_reads_manager_dirs(tmp_path):
    """One on-disk dialect: io.load_checkpoint loads what the v2 manager
    wrote (plain npy layout + manifest)."""
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path), executor=exe, main_program=main)
    mgr.save(step=4)
    pname = main.global_block().all_parameters()[0].name
    w = np.asarray(fluid.global_scope().get_value(pname))
    fluid.global_scope().set_value(pname, np.zeros_like(w))
    assert fluid.io.load_checkpoint(exe, str(tmp_path),
                                    main_program=main) == 4
    np.testing.assert_array_equal(
        w, np.asarray(fluid.global_scope().get_value(pname)))


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

def test_retry_classification_table():
    assert retry.is_transient(IOError("disk glitch"))
    assert retry.is_transient(ConnectionError("reset"))
    assert retry.is_transient(EOFError())
    assert retry.is_transient(retry.TransientError("wrapped"))
    assert retry.is_transient(chaos.ChaosIOError("injected"))
    assert retry.is_transient(RuntimeError("UNAVAILABLE: backend"))
    assert not retry.is_transient(ValueError("bad shape"))
    assert not retry.is_transient(KeyError("var"))
    # deterministic OS failures: retrying replays them verbatim
    assert not retry.is_transient(FileNotFoundError("gone"))
    assert not retry.is_transient(PermissionError("denied"))
    assert not retry.is_transient(IsADirectoryError("dir"))
    assert not retry.is_transient(RuntimeError("NaN/Inf detected in x"))
    assert not retry.is_transient(RuntimeError("some other failure"))
    from paddle_tpu.analysis import ProgramVerifyError

    assert not retry.is_transient(ProgramVerifyError([]))


def test_retry_succeeds_after_transient_and_counts():
    from paddle_tpu.observability.metrics_registry import REGISTRY

    ctr = REGISTRY.counter("paddle_tpu_retries_total",
                           labels=["origin"])
    before = ctr.value(origin="test.flaky")
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient %d" % len(calls))
        return "ok"

    flags.set_flag("retry_backoff_s", 0.0)
    try:
        assert retry.call(flaky, origin="test.flaky", retries=5) == "ok"
    finally:
        flags.set_flag("retry_backoff_s", 0.05)
    assert len(calls) == 3
    assert ctr.value(origin="test.flaky") == before + 2


def test_retry_never_retries_user_errors():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("user bug")

    with pytest.raises(ValueError):
        retry.call(broken, origin="test.user", retries=5)
    assert len(calls) == 1


def test_retry_disabled_by_default_flag():
    calls = []

    def flaky():
        calls.append(1)
        raise IOError("transient")

    # FLAGS_dispatch_retries defaults to 0: straight through, no retry
    with pytest.raises(IOError):
        retry.call(flaky, origin="test.off")
    assert len(calls) == 1


def test_executor_dispatch_retries_injected_fault():
    from paddle_tpu.observability.metrics_registry import REGISTRY

    ctr = REGISTRY.counter("paddle_tpu_retries_total",
                           labels=["origin"])
    before = ctr.value(origin="Executor.dispatch")
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    flags.set_flag("dispatch_retries", 3)
    flags.set_flag("retry_backoff_s", 0.0)
    chaos.configure("compile@site=exec.dispatch,n=2")
    try:
        out = exe.run(main, feed=_feed_for(0), fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()
        fired = chaos.fires("exec.dispatch")
    finally:
        chaos.disable()
        flags.set_flag("dispatch_retries", 0)
        flags.set_flag("retry_backoff_s", 0.05)
    assert ctr.value(origin="Executor.dispatch") == before + 2
    assert fired == 2


def test_executor_fresh_compile_retries_injected_fault():
    main, startup, loss = _build_model(seed=23)
    exe = fluid.Executor(fluid.CPUPlace())
    flags.set_flag("dispatch_retries", 2)
    flags.set_flag("retry_backoff_s", 0.0)
    chaos.configure("compile@n=1")  # home site: exec.compile
    try:
        exe.run(startup)
        # use_program_cache=False forces a re-trace even when an earlier
        # test already published this structure to the shared registry —
        # the injected fault must hit a real fresh-compile path
        out = exe.run(main, feed=_feed_for(0), fetch_list=[loss],
                      use_program_cache=False)
        assert np.isfinite(np.asarray(out[0])).all()
        fired = chaos.fires("exec.compile")
    finally:
        chaos.disable()
        flags.set_flag("dispatch_retries", 0)
        flags.set_flag("retry_backoff_s", 0.05)
    assert fired == 1


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------

def test_chaos_spec_parse_and_defaults():
    cl = chaos.configure(
        "seed=9;kill@step=12;io@site=exec.dispatch,p=0.25,n=3;"
        "slow@site=master.call,secs=0.01")
    assert [c["kind"] for c in cl] == ["kill", "io", "slow"]
    assert cl[0]["site"] == "session.step" and cl[0]["n"] == 1
    assert cl[1]["p"] == 0.25 and cl[1]["n"] == 3
    assert cl[2]["secs"] == 0.01
    chaos.disable()
    assert not chaos.ENABLED


def test_chaos_bad_spec_rejected():
    with pytest.raises(ValueError):
        chaos.configure("explode@p=1")
    with pytest.raises(ValueError):
        chaos.configure("io@p=1")  # io has no default site
    chaos.disable()


def test_chaos_seeded_draws_are_deterministic():
    def fire_pattern():
        chaos.configure("seed=3;io@site=t.x,p=0.5,n=100")
        hits = []
        for i in range(20):
            try:
                chaos.fault("t.x")
                hits.append(0)
            except chaos.ChaosIOError:
                hits.append(1)
        chaos.disable()
        return hits

    a, b = fire_pattern(), fire_pattern()
    assert a == b and 0 < sum(a) < 20


def test_chaos_step_clause_fires_exactly_once():
    chaos.configure("kill@step=5,site=t.step")  # site override: no SIGKILL
    # kill clauses raise nothing at non-matching steps
    for step in (0, 1, 4, 6):
        chaos.fault("t.step", step=step)
    assert chaos.fires() == 0
    chaos.disable()


def test_chaos_counts_in_metrics():
    from paddle_tpu.observability.metrics_registry import REGISTRY

    ctr = REGISTRY.counter("paddle_tpu_chaos_faults_total",
                           labels=["site", "kind"])
    before = ctr.value(site="t.m", kind="io")
    chaos.configure("io@site=t.m,p=1,n=2")
    for _ in range(2):
        with pytest.raises(chaos.ChaosIOError):
            chaos.fault("t.m")
    chaos.fault("t.m")  # budget exhausted: no fire
    chaos.disable()
    assert ctr.value(site="t.m", kind="io") == before + 2


# ---------------------------------------------------------------------------
# TrainSession (in-process)
# ---------------------------------------------------------------------------

def test_session_periodic_checkpoint_and_resume(tmp_path):
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    sess = _session(exe, tmp_path, main, interval_steps=2)
    for i in range(5):
        sess.run(feed=_feed_for(i), fetch_list=[loss])
    sess.close()  # final sync save at step 5
    assert 5 in complete_serials(str(tmp_path))

    # a "restarted process": fresh executor + scope, same program build
    from paddle_tpu.core.scope import Scope
    import paddle_tpu.executor as executor_mod

    executor_mod._global_scope = Scope()
    executor_mod._scope_stack = [executor_mod._global_scope]
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup)
    sess2 = _session(exe2, tmp_path, main)
    assert sess2.step == 5 and sess2.resumed_serial == 5
    sess2.close(save=False)


def test_session_resume_is_bit_identical_with_dropout(tmp_path):
    """The loss-trajectory contract, in-process: save at step 5, restart
    into a fresh scope/executor, steps 5..9 match the uninterrupted
    run's bit for bit — including dropout masks (RNG stream restored)."""
    from paddle_tpu.core.scope import Scope
    import paddle_tpu.executor as executor_mod

    def fresh_world():
        from paddle_tpu import framework, unique_name

        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        unique_name.switch({})
        executor_mod._global_scope = Scope()
        executor_mod._scope_stack = [executor_mod._global_scope]
        np.random.seed(42)

    def run_steps(sess, loss, start, n):
        return [float(np.asarray(
            sess.run(feed=_feed_for(start + i), fetch_list=[loss])[0]
        ).reshape(-1)[0]) for i in range(n)]

    fresh_world()
    main, startup, loss = _build_model(dropout=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    sA = _session(exe, tmp_path / "none", main)
    uninterrupted = run_steps(sA, loss, 0, 10)
    sA.close(save=False)

    fresh_world()
    main, startup, loss = _build_model(dropout=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    sB = _session(exe, tmp_path / "ck", main)
    resumed = run_steps(sB, loss, 0, 5)
    sB.close()  # checkpoint at step 5; "process dies" here

    fresh_world()
    main, startup, loss = _build_model(dropout=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    sB2 = _session(exe, tmp_path / "ck", main)
    assert sB2.step == 5
    resumed += run_steps(sB2, loss, 5, 5)
    sB2.close(save=False)

    assert resumed == uninterrupted  # bit-exact, not allclose


@pytest.mark.slow
def test_session_sigterm_checkpoints_then_dies_by_signal(tmp_path):
    """Subprocess: SIGTERM mid-training → the in-flight step finishes, a
    final checkpoint lands, and the process dies BY the signal (what a
    preemption supervisor keys on)."""
    child = _spawn_child(tmp_path, mode="sigterm", steps=50)
    assert child.returncode == -signal.SIGTERM, child.returncode
    serials = complete_serials(str(tmp_path / "ckpt"))
    assert serials, "SIGTERM must leave a final checkpoint"
    m = read_manifest(
        str(tmp_path / "ckpt" / ("checkpoint_%d" % serials[-1])))
    assert m["step"] >= 1


# ---------------------------------------------------------------------------
# subprocess crash/resume legs
# ---------------------------------------------------------------------------

_CHILD = os.path.join(REPO, "tools", "chaos_smoke.py")


def _spawn_child(tmp_path, mode, steps, chaos_spec="", extra_env=None,
                 timeout=120):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu", FLAGS_chaos_spec=chaos_spec)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, _CHILD, "child", "--mode", mode,
         "--ckpt-dir", str(tmp_path / "ckpt"), "--steps", str(steps),
         "--out", str(tmp_path / ("out_%s.json" % mode))],
        env=env, timeout=timeout)


def _child_losses(tmp_path, mode):
    with open(tmp_path / ("out_%s.json" % mode)) as f:
        return json.load(f)


@pytest.mark.slow
def test_sigkill_resume_bit_identical_subprocess(tmp_path):
    """THE acceptance test: child killed by SIGKILL at a seeded step
    (no cleanup possible), restarted child resumes from the newest
    complete serial, and the combined trajectory equals an
    uninterrupted run at the same total step count, bit for bit."""
    # uninterrupted reference
    ref = _spawn_child(tmp_path, mode="ref", steps=12)
    assert ref.returncode == 0, ref.returncode
    reference = _child_losses(tmp_path, "ref")
    assert len(reference["losses"]) == 12

    kill_dir = tmp_path / "k"
    kill_dir.mkdir()
    victim = _spawn_child(kill_dir, mode="train", steps=12,
                          chaos_spec="kill@step=7")
    assert victim.returncode == -signal.SIGKILL, victim.returncode
    survivor = _spawn_child(kill_dir, mode="train", steps=12)
    assert survivor.returncode == 0, survivor.returncode
    out = _child_losses(kill_dir, "train")
    assert out["resumed_step"] > 0, "child must resume, not restart at 0"
    assert out["losses"] == reference["losses"][out["resumed_step"]:]
    assert out["final_loss"] == reference["final_loss"]


@pytest.mark.slow
def test_sigkill_mid_checkpoint_write_leaves_only_tmp(tmp_path):
    """Kill the background writer mid-checkpoint: the next restart must
    see only complete serials (the torn write is a temp dir)."""
    victim = _spawn_child(
        tmp_path, mode="train", steps=12,
        chaos_spec="kill@site=ckpt.write,n=1")
    assert victim.returncode == -signal.SIGKILL, victim.returncode
    ckpt = tmp_path / "ckpt"
    leftovers = sorted(os.listdir(ckpt)) if ckpt.exists() else []
    assert any(".tmp-" in d for d in leftovers), leftovers
    # none of the complete serials is the torn one; a restart resumes
    survivor = _spawn_child(tmp_path, mode="train", steps=12)
    assert survivor.returncode == 0, survivor.returncode
    out = _child_losses(tmp_path, "train")
    assert np.isfinite(out["final_loss"])


# ---------------------------------------------------------------------------
# master-restart client survival (satellite)
# ---------------------------------------------------------------------------

def test_master_client_survives_master_restart(tmp_path):
    from paddle_tpu.distributed import MasterClient, MasterService

    snap = str(tmp_path / "master.json")
    s = MasterService(timeout_s=5.0, snapshot_path=snap)
    s.set_dataset(["a", "b", "c", "d"])
    host, port = s.serve()
    c = MasterClient((host, port))
    t = c.get_task()
    assert t is not None
    c.task_finished(t.task_id)
    # master dies and comes back on the SAME port with its snapshot
    s.close()
    s2 = MasterService(timeout_s=5.0, snapshot_path=snap)
    s2.serve(host=host, port=port)
    # the client's socket is dead; _call must reconnect-and-retry once
    # instead of surfacing a raw socket error to the training loop
    t2 = c.get_task()
    assert t2 is not None
    assert c.task_finished(t2.task_id)
    c.close()
    s2.close()


def test_ckpt_inspect_cli(tmp_path):
    """The operator CLI: exit 0 + digest report on a good checkpoint,
    exit 2 after a byte flip (the restore-gate contract)."""
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path), executor=exe, main_program=main)
    mgr.save(step=2)
    cli = os.path.join(REPO, "tools", "ckpt_inspect.py")
    proc = subprocess.run(
        [sys.executable, cli, str(tmp_path), "--verify"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all digests match" in proc.stdout
    d = tmp_path / "checkpoint_2"
    victim = next(f for f in os.listdir(d) if f.endswith(".npy"))
    with open(d / victim, "r+b") as f:
        f.seek(-2, os.SEEK_END)
        f.write(b"\x00\x00")
    proc = subprocess.run(
        [sys.executable, cli, str(d), "--verify"],
        capture_output=True, text=True)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "digest mismatch" in proc.stdout


def test_watchdog_on_hang_registry():
    from paddle_tpu.observability import watchdog

    seen = []
    cb = watchdog.register_on_hang(seen.append)
    try:
        with watchdog._lock:
            assert seen.append in watchdog._on_hang_extra
    finally:
        watchdog.unregister_on_hang(cb)
    with watchdog._lock:
        assert seen.append not in watchdog._on_hang_extra
