"""Nested-LoD (lod_level=2) behaviors pinned per docs/LOD_DESIGN.md.

Reference: paddle/fluid/framework/lod_tensor_test.cc and
tests/unittests/test_lod_tensor.py — here restricted to the host-boundary
contract the TPU design keeps (offsets never reach the device).
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.lod import LoDTensor, create_lod_tensor


def test_level2_lod_roundtrip_and_validity():
    # 2 "documents": first has 2 sentences (lens 2, 3), second has 1 (len 1)
    words = np.arange(6 * 4, dtype=np.float32).reshape(6, 4)
    t = create_lod_tensor(words, [[2, 1], [2, 3, 1]])
    assert t.lod() == [[0, 2, 3], [0, 2, 5, 6]]
    assert t.has_valid_recursive_sequence_lengths()
    assert t.recursive_sequence_lengths() == [[2, 1], [2, 3, 1]]

    # innermost-level densification: 3 sentences padded to len 3
    padded, lengths = t.to_padded(pad_value=0.0)
    assert padded.shape == (3, 3, 4)
    np.testing.assert_array_equal(lengths, [2, 3, 1])
    np.testing.assert_array_equal(padded[0, :2], words[0:2])
    np.testing.assert_array_equal(padded[1], words[2:5])
    np.testing.assert_array_equal(padded[0, 2], np.zeros(4))

    # round trip back to ragged
    back = LoDTensor.from_padded(padded, lengths)
    np.testing.assert_array_equal(back.numpy(), words)


def test_invalid_nested_lod_detected():
    words = np.zeros((6, 2), np.float32)
    bad = LoDTensor(words, [[0, 2, 3], [0, 2, 5]])  # inner doesn't cover 6
    assert not bad.has_valid_recursive_sequence_lengths()
    bad2 = LoDTensor(words, [[1, 2, 3], [0, 2, 5, 6]])  # level not 0-based
    assert not bad2.has_valid_recursive_sequence_lengths()


def test_sequence_ops_consume_innermost_level_of_nested_lod():
    """A level-2 batch flows through sequence_pool by densifying the inner
    level; the outer level groups results on the host (design note
    'lod_level>2 graph ops')."""
    words = np.arange(6 * 4, dtype=np.float32).reshape(6, 4)
    t = create_lod_tensor(words, [[2, 1], [2, 3, 1]])
    padded, lengths = t.to_padded()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [3, 4])
        lv = fluid.layers.data("len", [], dtype="int32")
        pooled = fluid.layers.sequence_pool(xv, "sum", length=lv)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (out,) = exe.run(main, feed={"x": padded, "len": lengths},
                     fetch_list=[pooled])
    out = np.asarray(out)
    # sentence sums honoring true lengths, not padding
    np.testing.assert_allclose(out[0], words[0:2].sum(0), rtol=1e-6)
    np.testing.assert_allclose(out[1], words[2:5].sum(0), rtol=1e-6)
    np.testing.assert_allclose(out[2], words[5:6].sum(0), rtol=1e-6)
    # outer level reduces host-side: document means over sentence vectors
    doc_split = np.split(out, np.cumsum([2, 1])[:-1])
    assert len(doc_split) == 2 and doc_split[0].shape == (2, 4)
