"""Elastic training tests: lease timeout requeue, failure discard, worker
kill mid-epoch, master snapshot recovery, training-through-failure.

Reference: go/master/service_internal_test.go + the fault-tolerance design
(go/master/service.go:368,411,455; snapshot :207, recover :166).
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.distributed import MasterClient, MasterService, task_reader


def _service(**kw):
    kw.setdefault("timeout_s", 0.5)
    kw.setdefault("failure_max", 3)
    return MasterService(**kw)


def test_partition_and_basic_flow():
    s = _service(chunks_per_task=2)
    s.set_dataset(["c%d" % i for i in range(5)])
    assert s.status()["todo"] == 3  # ceil(5/2)
    t1, err = s.get_task(0)
    assert err is None and t1.chunks == ["c0", "c1"]
    assert s.task_finished(t1.task_id)
    assert s.status()["done"] == 1
    # finishing an unleased task is rejected
    assert not s.task_finished(99)
    s.close()


def test_lease_timeout_requeues_task():
    s = _service(timeout_s=0.3)
    s.set_dataset(["a", "b"])
    t1, _ = s.get_task(0)
    # worker "dies": no finish report; lease must expire and requeue
    deadline = time.time() + 5
    while s.status()["todo"] < 1 and time.time() < deadline:
        time.sleep(0.05)
    st = s.status()
    assert st["todo"] >= 1, st
    t2, _ = s.get_task(0)
    # the re-dispatched lease carries a bumped epoch, so a stale failure
    # report from the dead worker is ignored
    if t2.task_id == t1.task_id:
        assert t2.epoch > t1.epoch
        assert not s.task_failed(t1.task_id, epoch=t1.epoch)
    s.close()


def test_failure_max_discards_task():
    s = _service(failure_max=2)
    s.set_dataset(["poison", "good"])
    seen_poison = 0
    done = 0
    for _ in range(10):
        t, err = s.get_task(0)
        if t is None:
            break
        if "poison" in t.chunks:
            seen_poison += 1
            s.task_failed(t.task_id, t.epoch)
        else:
            s.task_finished(t.task_id)
            done += 1
    assert seen_poison == 2  # dispatched twice, then discarded
    assert s.status()["failed"] == 0 or s.status()["cur_pass"] >= 1
    s.close()


def test_pass_rollover_and_client_sync():
    s = _service()
    s.set_dataset(["a", "b"])
    addr = s.serve()
    c = MasterClient(addr)
    for _ in range(2):
        t = c.get_task()
        assert t is not None
        c.task_finished(t.task_id)
    # pass 0 drained -> master rolled to pass 1; client syncs forward
    assert s.status()["cur_pass"] == 1
    t = c.get_task()
    assert t is not None and c.pass_id == 1
    c.close()
    s.close()


def test_worker_killed_mid_epoch_completes_and_resumes(tmp_path):
    """The headline elastic contract: one worker dies holding a lease,
    the surviving worker still drains the pass; a restarted master
    resumes from its snapshot with no lost tasks."""
    snap = str(tmp_path / "master.json")
    s = _service(timeout_s=0.4, snapshot_path=snap)
    chunks = ["chunk%d" % i for i in range(6)]
    s.set_dataset(chunks)
    addr = s.serve()

    processed = []
    lock = threading.Lock()

    def worker(kill_after):
        c = MasterClient(addr)
        n = 0
        deadline = time.time() + 15
        while time.time() < deadline:
            t = c.get_task()
            if t is None:
                # pass drained or tasks still leased by the dead worker:
                # wait for the lease timeout to requeue them
                st = c.status()
                if st and st["cur_pass"] >= 1:
                    break
                time.sleep(0.1)
                continue
            if kill_after is not None and n >= kill_after:
                # simulate a crash while holding the lease: no report
                c.close()
                return
            with lock:
                processed.extend(t.chunks)
            c.task_finished(t.task_id)
            n += 1
        c.close()

    w1 = threading.Thread(target=worker, args=(1,))  # dies on 2nd task
    w2 = threading.Thread(target=worker, args=(None,))
    w1.start()
    w2.start()
    w1.join(10)
    w2.join(20)
    assert not w2.is_alive()
    # every chunk processed at least once despite the crashed worker
    assert set(chunks) <= set(processed)
    assert s.status()["cur_pass"] >= 1

    # master "crashes"; a new instance recovers the snapshot
    s.close()
    s2 = MasterService(timeout_s=0.4, snapshot_path=snap)
    st = s2.status()
    assert st["cur_pass"] >= 1
    assert st["todo"] + st["pending"] + st["done"] == 6
    t, err = s2.get_task(st["cur_pass"])
    assert t is not None and err is None
    s2.close()


def test_task_reader_trains_through_worker_failure(tmp_path):
    """End to end: a model trains off task_reader while one reader thread
    fails mid-pass; loss stays finite and all chunks contribute."""
    rng = np.random.RandomState(0)
    # each chunk is a (slope-ish) linear-regression shard
    data = {
        "c%d" % i: (rng.rand(8, 4).astype("float32"),)
        for i in range(4)
    }
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], "float32")

    s = _service(timeout_s=0.4)
    s.set_dataset(sorted(data))
    addr = s.serve()

    def load_chunk(chunk):
        (x,) = data[chunk]
        y = x @ w_true
        for i in range(x.shape[0]):
            yield x[i], y[i]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [4], stop_gradient=False)
        yv = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(xv, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, yv))
        fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    # a "bad" client leases one task and vanishes
    bad = MasterClient(addr)
    bad.get_task()
    bad.close()

    c = MasterClient(addr)
    reader = task_reader(c, load_chunk, poll_s=0.05, max_polls=100)
    losses = []
    # one reader() iteration == one pass; epochs loop over it
    for epoch in range(4):
        batch_x, batch_y = [], []
        for x, y in reader():
            batch_x.append(x)
            batch_y.append(y)
            if len(batch_x) == 8:
                (lv,) = exe.run(
                    main,
                    feed={"x": np.stack(batch_x), "y": np.stack(batch_y)},
                    fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
                batch_x, batch_y = [], []
    assert len(losses) >= 6
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
    c.close()
    s.close()
