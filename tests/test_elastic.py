"""Elastic training tests: lease timeout requeue, failure discard, worker
kill mid-epoch, master snapshot recovery, training-through-failure — plus
the PR 9 fleet runtime: FleetCoordinator membership/generations/eviction,
ElasticTrainSession reshapes with bit-identical trajectories, chaos sites
fleet.heartbeat/fleet.register, and the master snapshot-race hardening.

Reference: go/master/service_internal_test.go + the fault-tolerance design
(go/master/service.go:368,411,455; snapshot :207, recover :166).
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.distributed import MasterClient, MasterService, task_reader
from paddle_tpu.elastic.coordinator import (
    FleetClient,
    FleetCoordinator,
    FleetEvictedError,
)


def _service(**kw):
    kw.setdefault("timeout_s", 0.5)
    kw.setdefault("failure_max", 3)
    return MasterService(**kw)


def test_partition_and_basic_flow():
    s = _service(chunks_per_task=2)
    s.set_dataset(["c%d" % i for i in range(5)])
    assert s.status()["todo"] == 3  # ceil(5/2)
    t1, err = s.get_task(0)
    assert err is None and t1.chunks == ["c0", "c1"]
    assert s.task_finished(t1.task_id)
    assert s.status()["done"] == 1
    # finishing an unleased task is rejected
    assert not s.task_finished(99)
    s.close()


def test_lease_timeout_requeues_task():
    s = _service(timeout_s=0.3)
    s.set_dataset(["a", "b"])
    t1, _ = s.get_task(0)
    # worker "dies": no finish report; lease must expire and requeue
    deadline = time.time() + 5
    while s.status()["todo"] < 1 and time.time() < deadline:
        time.sleep(0.05)
    st = s.status()
    assert st["todo"] >= 1, st
    t2, _ = s.get_task(0)
    # the re-dispatched lease carries a bumped epoch, so a stale failure
    # report from the dead worker is ignored
    if t2.task_id == t1.task_id:
        assert t2.epoch > t1.epoch
        assert not s.task_failed(t1.task_id, epoch=t1.epoch)
    s.close()


def test_failure_max_discards_task():
    s = _service(failure_max=2)
    s.set_dataset(["poison", "good"])
    seen_poison = 0
    done = 0
    for _ in range(10):
        t, err = s.get_task(0)
        if t is None:
            break
        if "poison" in t.chunks:
            seen_poison += 1
            s.task_failed(t.task_id, t.epoch)
        else:
            s.task_finished(t.task_id)
            done += 1
    assert seen_poison == 2  # dispatched twice, then discarded
    assert s.status()["failed"] == 0 or s.status()["cur_pass"] >= 1
    s.close()


def test_pass_rollover_and_client_sync():
    s = _service()
    s.set_dataset(["a", "b"])
    addr = s.serve()
    c = MasterClient(addr)
    for _ in range(2):
        t = c.get_task()
        assert t is not None
        c.task_finished(t.task_id)
    # pass 0 drained -> master rolled to pass 1; client syncs forward
    assert s.status()["cur_pass"] == 1
    t = c.get_task()
    assert t is not None and c.pass_id == 1
    c.close()
    s.close()


def test_worker_killed_mid_epoch_completes_and_resumes(tmp_path):
    """The headline elastic contract: one worker dies holding a lease,
    the surviving worker still drains the pass; a restarted master
    resumes from its snapshot with no lost tasks."""
    snap = str(tmp_path / "master.json")
    s = _service(timeout_s=0.4, snapshot_path=snap)
    chunks = ["chunk%d" % i for i in range(6)]
    s.set_dataset(chunks)
    addr = s.serve()

    processed = []
    lock = threading.Lock()

    def worker(kill_after):
        c = MasterClient(addr)
        n = 0
        deadline = time.time() + 15
        while time.time() < deadline:
            t = c.get_task()
            if t is None:
                # pass drained or tasks still leased by the dead worker:
                # wait for the lease timeout to requeue them
                st = c.status()
                if st and st["cur_pass"] >= 1:
                    break
                time.sleep(0.1)
                continue
            if kill_after is not None and n >= kill_after:
                # simulate a crash while holding the lease: no report
                c.close()
                return
            with lock:
                processed.extend(t.chunks)
            c.task_finished(t.task_id)
            n += 1
        c.close()

    w1 = threading.Thread(target=worker, args=(1,))  # dies on 2nd task
    w2 = threading.Thread(target=worker, args=(None,))
    w1.start()
    w2.start()
    w1.join(10)
    w2.join(20)
    assert not w2.is_alive()
    # every chunk processed at least once despite the crashed worker
    assert set(chunks) <= set(processed)
    assert s.status()["cur_pass"] >= 1

    # master "crashes"; a new instance recovers the snapshot
    s.close()
    s2 = MasterService(timeout_s=0.4, snapshot_path=snap)
    st = s2.status()
    assert st["cur_pass"] >= 1
    assert st["todo"] + st["pending"] + st["done"] == 6
    t, err = s2.get_task(st["cur_pass"])
    assert t is not None and err is None
    s2.close()


def test_task_reader_trains_through_worker_failure(tmp_path):
    """End to end: a model trains off task_reader while one reader thread
    fails mid-pass; loss stays finite and all chunks contribute."""
    rng = np.random.RandomState(0)
    # each chunk is a (slope-ish) linear-regression shard
    data = {
        "c%d" % i: (rng.rand(8, 4).astype("float32"),)
        for i in range(4)
    }
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], "float32")

    s = _service(timeout_s=0.4)
    s.set_dataset(sorted(data))
    addr = s.serve()

    def load_chunk(chunk):
        (x,) = data[chunk]
        y = x @ w_true
        for i in range(x.shape[0]):
            yield x[i], y[i]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [4], stop_gradient=False)
        yv = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(xv, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, yv))
        fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    # a "bad" client leases one task and vanishes
    bad = MasterClient(addr)
    bad.get_task()
    bad.close()

    c = MasterClient(addr)
    reader = task_reader(c, load_chunk, poll_s=0.05, max_polls=100)
    losses = []
    # one reader() iteration == one pass; epochs loop over it
    for epoch in range(4):
        batch_x, batch_y = [], []
        for x, y in reader():
            batch_x.append(x)
            batch_y.append(y)
            if len(batch_x) == 8:
                (lv,) = exe.run(
                    main,
                    feed={"x": np.stack(batch_x), "y": np.stack(batch_y)},
                    fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
                batch_x, batch_y = [], []
    assert len(losses) >= 6
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
    c.close()
    s.close()


# ---------------------------------------------------------------------------
# fleet coordinator: membership, generations, eviction, recovery
# ---------------------------------------------------------------------------


def _coordinator(**kw):
    kw.setdefault("lease_s", 0.6)
    return FleetCoordinator(**kw)


def test_register_assigns_dense_ranks_and_bumps_generation():
    c = _coordinator()
    try:
        a = c.register("a")
        b = c.register("b")
        assert (a["rank"], a["generation"], a["world"]) == (0, 1, 1)
        assert (b["rank"], b["generation"], b["world"]) == (1, 2, 2)
        # heartbeat reflects the CURRENT membership, not the join-time one
        view = c.heartbeat("a", step=5)
        assert view["rank"] == 0 and view["world"] == 2
        assert view["generation"] == 2
        assert c.status()["members"]["a"]["step"] == 5
    finally:
        c.close()


def test_eviction_compacts_ranks_and_moves_chief():
    c = _coordinator(lease_s=0.4)
    try:
        c.register("chief")
        c.register("second")
        c.register("third")
        gen = c.status()["generation"]
        # the chief stops heartbeating; others stay alive
        deadline = time.time() + 5
        while "chief" in c.status()["members"] and time.time() < deadline:
            c.heartbeat("second")
            c.heartbeat("third")
            time.sleep(0.05)
        st = c.status()
        assert "chief" not in st["members"], st
        # survivors keep their relative order; the OLDEST survivor is the
        # new chief (rank 0)
        assert st["members"]["second"]["rank"] == 0
        assert st["members"]["third"]["rank"] == 1
        assert st["generation"] > gen
        # the dead worker's heartbeat gets the typed eviction signal
        assert c.heartbeat("chief") is None
    finally:
        c.close()


def test_batched_eviction_is_one_generation_bump():
    c = _coordinator(lease_s=0.3)
    try:
        c.register("keep")
        c.register("die1")
        c.register("die2")
        gen = c.status()["generation"]
        deadline = time.time() + 5
        while c.status()["world"] > 1 and time.time() < deadline:
            c.heartbeat("keep")
            time.sleep(0.05)
        st = c.status()
        assert st["world"] == 1
        # two workers died in one sweep: survivors see ONE reshape
        assert st["generation"] == gen + 1, st
    finally:
        c.close()


def test_reshard_serial_registry_and_history_bound():
    c = _coordinator(max_reshard_history=3)
    try:
        c.register("a")
        for g in range(1, 6):
            c.report_reshard(g, 100 + g)
        view = c.heartbeat("a")
        assert view["reshard"] == {3: 103, 4: 104, 5: 105}
    finally:
        c.close()


def test_eviction_watcher_survives_fleet_emptying():
    """The eviction watcher exits with the last member but releases its
    slot atomically with that decision — members admitted afterwards
    must still be evicted (a dying thread must never be trusted to keep
    sweeping)."""
    c = _coordinator(lease_s=0.3)
    try:
        c.register("a")
        deadline = time.time() + 5
        while c.status()["world"] and time.time() < deadline:
            time.sleep(0.05)
        assert c.status()["world"] == 0
        c.register("b")  # fleet was empty: a fresh watcher must spawn
        deadline = time.time() + 5
        while c.status()["world"] and time.time() < deadline:
            time.sleep(0.05)
        assert c.status()["world"] == 0, (
            "member admitted after the fleet emptied was never evicted")
    finally:
        c.close()


def test_corrupt_snapshot_is_quarantined_not_silently_eaten(tmp_path):
    """An existing-but-unreadable snapshot must not make recovery look
    like a clean cold start: the file is quarantined for autopsy and the
    reset is logged."""
    import logging

    snap = tmp_path / "fleet.json"
    snap.write_text("{definitely not json")
    with _caplog_at_warning() as records:
        c = _coordinator(snapshot_path=str(snap))
        c.close()
    assert not snap.exists()
    assert any(".corrupt-" in d.name for d in tmp_path.iterdir())
    assert any("unreadable" in r.getMessage() for r in records)


class _caplog_at_warning(object):
    """Tiny handler context: collect WARNING+ records from the
    paddle_tpu.distributed logger without pytest's caplog (which the
    surrounding threaded tests can race)."""

    def __enter__(self):
        import logging

        self.records = []
        self.handler = logging.Handler()
        self.handler.emit = self.records.append
        self.logger = logging.getLogger("paddle_tpu.distributed")
        self.logger.addHandler(self.handler)
        return self.records

    def __exit__(self, *exc):
        self.logger.removeHandler(self.handler)
        return False


def test_coordinator_snapshot_recovery_preserves_membership(tmp_path):
    snap = str(tmp_path / "fleet.json")
    c = _coordinator(snapshot_path=snap)
    c.register("a")
    c.register("b")
    c.report_reshard(2, 17)
    gen = c.status()["generation"]
    c.close()

    c2 = _coordinator(snapshot_path=snap)
    try:
        st = c2.status()
        # same generation (no spurious reshape for survivors), same ranks,
        # reshard map intact; recovered members run on fresh leases
        assert st["generation"] == gen
        assert st["members"]["a"]["rank"] == 0
        assert st["members"]["b"]["rank"] == 1
        assert st["reshard"] == {2: 17}
        view = c2.heartbeat("a")
        assert view["rank"] == 0 and view["world"] == 2
        # a NEW registration continues the generation sequence
        v = c2.register("c")
        assert v["generation"] == gen + 1 and v["rank"] == 2
    finally:
        c2.close()


def test_fleet_client_over_tcp_and_eviction_error():
    c = _coordinator(lease_s=0.5)
    addr = c.serve()
    cl = FleetClient(addr)
    try:
        view = cl.register("w")
        assert view["worker_id"] == "w" and view["rank"] == 0
        cl.report_reshard(view["generation"], 9)
        hb = cl.heartbeat("w", step=2)
        assert hb["reshard"] == {view["generation"]: 9}  # int keys back
        with pytest.raises(FleetEvictedError):
            cl.heartbeat("ghost")
    finally:
        cl.close()
        c.close()


def test_client_minted_ids_make_register_retry_safe():
    """FleetClient mints the worker identity, so a register retried
    across a coordinator restart replaces the committed member instead
    of minting a ghost that inflates the world (and could squat on the
    chief rank)."""
    c = _coordinator()
    addr = c.serve()
    cl = FleetClient(addr)
    try:
        view = cl.register()
        wid = view["worker_id"]
        assert wid.startswith("w-") and len(wid) > 6
        # the retry scenario: the same identity registers again — one
        # member, not two
        view2 = cl.register(wid)
        assert view2["world"] == 1 and view2["rank"] == 0
        assert view2["generation"] > view["generation"]
    finally:
        cl.close()
        c.close()


def test_failed_session_construction_leaves_no_zombie_member(tmp_path):
    """A constructor that cannot finish (fleet never ready) must
    deregister and stop heartbeating — not leave a lease-renewing ghost
    inflating the fleet forever."""
    from paddle_tpu.elastic.worker import ElasticTrainSession

    c = _coordinator(min_workers=2)
    addr = c.serve()
    try:
        with pytest.raises(TimeoutError):
            ElasticTrainSession(
                addr, str(tmp_path / "ckpt"),
                lambda world, rank: (_ for _ in ()).throw(
                    AssertionError("build_fn must not run")),
                heartbeat_interval_s=0.1, ready_timeout_s=0.5)
        deadline = time.time() + 5
        while c.status()["world"] and time.time() < deadline:
            time.sleep(0.05)
        assert c.status()["world"] == 0, (
            "the failed worker is still a member: %s" % c.status())
    finally:
        c.close()


def test_fleet_client_status_maps_reshard_keys_to_ints():
    c = _coordinator()
    addr = c.serve()
    cl = FleetClient(addr)
    try:
        cl.register("w")
        cl.report_reshard(1, 5)
        st = cl.status()
        assert st["reshard"] == {1: 5}  # ints over TCP, like every view
    finally:
        cl.close()
        c.close()


def test_pinned_serial_survives_retention(tmp_path):
    """A published barrier serial is pinned on the manager: periodic
    saves must never prune it while a slow joiner may still be
    restoring it."""
    import numpy as np

    from paddle_tpu.elastic.reshard import ShardedCheckpointManager

    m = ShardedCheckpointManager(str(tmp_path / "ck"), max_to_keep=1)
    m.pinned_serials.add(0)
    for s in range(4):
        m.write_state({"w": np.full((2, 2), s, "float32")}, step=s,
                      serial=s)
    left = sorted(d for d in (tmp_path / "ck").iterdir()
                  if d.name.startswith("checkpoint_"))
    names = [d.name for d in left]
    assert "checkpoint_0" in names, names   # pinned: kept beyond the cap
    assert "checkpoint_3" in names, names   # newest always kept


@pytest.mark.slow
def test_failed_reshape_deregisters_instead_of_wedging(tmp_path):
    """A build_fn that dies during a reshape must not leave a lease-
    renewing zombie: the worker deregisters (the fleet reshapes around
    it) and the error surfaces to the caller."""
    from paddle_tpu.elastic.worker import ElasticTrainSession

    co = FleetCoordinator(lease_s=1.0, min_workers=1)
    addr = co.serve()
    dummy = FleetClient(addr)
    try:
        with fluid.scope_guard(fluid.Scope()):
            build_fn, holder = _elastic_model()
            calls = []

            def flaky_build(world, rank):
                calls.append(world)
                if len(calls) > 1:
                    raise RuntimeError("rebuild exploded")
                return build_fn(world, rank)

            sess = ElasticTrainSession(
                addr, str(tmp_path / "ckpt"), flaky_build,
                worker_id="w0", heartbeat_interval_s=0.1)
            sess.run(feed=_elastic_feed(0), fetch_list=[holder["loss"]])
            dummy.register("joiner")  # forces a reshape -> flaky rebuild
            deadline = time.time() + 5
            while ((sess._hb.latest or {}).get("world") != 2
                   and time.time() < deadline):
                dummy.heartbeat("joiner")
                time.sleep(0.05)
            with pytest.raises(RuntimeError, match="rebuild exploded"):
                sess.run(feed=_elastic_feed(1),
                         fetch_list=[holder["loss"]])
            # the failed worker LEFT: only the joiner remains, no zombie
            deadline = time.time() + 5
            while ("w0" in co.status()["members"]
                   and time.time() < deadline):
                time.sleep(0.05)
            assert "w0" not in co.status()["members"]
            with pytest.raises(RuntimeError, match="closed"):
                sess.run(feed=_elastic_feed(2),
                         fetch_list=[holder["loss"]])
    finally:
        dummy.close()
        co.close()


def test_fleet_metrics_exported():
    from paddle_tpu.observability.metrics_registry import REGISTRY

    c = _coordinator(lease_s=0.3)
    try:
        c.register("a")
        c.register("b")
        scrape = REGISTRY.to_prometheus()
        assert "paddle_tpu_fleet_size 2" in scrape
        deadline = time.time() + 5
        while c.status()["world"] > 1 and time.time() < deadline:
            c.heartbeat("a")
            time.sleep(0.05)
        scrape = REGISTRY.to_prometheus()
        assert "paddle_tpu_fleet_size 1" in scrape
        gen = c.status()["generation"]
        assert ("paddle_tpu_fleet_generation %d" % gen) in scrape
        evs = [line for line in scrape.splitlines()
               if line.startswith("paddle_tpu_fleet_evictions_total")]
        assert evs and float(evs[0].rsplit(None, 1)[-1]) >= 1
    finally:
        c.close()


def test_chaos_sites_fleet_heartbeat_and_register():
    """Satellite: churn is injectable with the seeded FLAGS_chaos_spec
    grammar at fleet.register / fleet.heartbeat; the client's
    reconnect-retry-once absorbs a single injected fault."""
    from paddle_tpu.resilience import chaos

    c = _coordinator()
    addr = c.serve()
    cl = FleetClient(addr)
    try:
        chaos.configure("seed=3;io@site=fleet.register,n=1;"
                        "io@site=fleet.heartbeat,n=1")
        view = cl.register("w")  # survives the injected register fault
        assert view["rank"] == 0
        assert chaos.fires("fleet.register") == 1
        hb = cl.heartbeat("w")  # survives the injected heartbeat fault
        assert hb["world"] == 1
        assert chaos.fires("fleet.heartbeat") == 1
    finally:
        chaos.disable()
        cl.close()
        c.close()


# ---------------------------------------------------------------------------
# ElasticTrainSession: reshapes with a bit-identical trajectory
# ---------------------------------------------------------------------------


def _elastic_model():
    """Deterministic 2-layer MLP + dropout (RNG-dependent on purpose),
    built ONCE and reused across executor rebuilds — rebuilding the
    program would advance the unique-name counters and break restore
    name matching (the documented build_fn contract)."""
    holder = {}

    def build_fn(world_size, rank):
        if "main" not in holder:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", [4], stop_gradient=False)
                y = fluid.layers.data("y", [1])
                h = fluid.layers.fc(x, 8, act="relu")
                h = fluid.layers.dropout(h, 0.3)
                pred = fluid.layers.fc(h, 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(0.05).minimize(loss)
            main.random_seed = 17
            startup.random_seed = 17
            holder.update(main=main, startup=startup, loss=loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(holder["startup"])
        return exe, holder["main"]

    return build_fn, holder


def _elastic_feed(step):
    r = np.random.RandomState(1000 + step)
    return {"x": r.rand(8, 4).astype("float32"),
            "y": r.rand(8, 1).astype("float32")}


def _run_elastic(tmp_path, churn, steps=12):
    from paddle_tpu.elastic.worker import ElasticTrainSession

    co = FleetCoordinator(lease_s=1.0, min_workers=1)
    addr = co.serve()
    dummy = FleetClient(addr)
    losses, gens = [], []
    try:
        with fluid.scope_guard(fluid.Scope()):
            build_fn, holder = _elastic_model()
            sess = ElasticTrainSession(
                addr, str(tmp_path / "ckpt"), build_fn,
                worker_id="real", heartbeat_interval_s=0.1)
            joined = stopped = False
            while sess.step < steps:
                out = sess.run(feed=_elastic_feed(sess.step),
                               fetch_list=[holder["loss"]])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
                gens.append(sess.generation)
                if not churn:
                    continue
                if sess.step == 4 and not joined:
                    # a second member joins: world 1 -> 2 at the barrier
                    dummy.register("joiner")
                    joined = True
                    deadline = time.time() + 5
                    while ((sess._hb.latest or {}).get("world") != 2
                           and time.time() < deadline):
                        dummy.heartbeat("joiner")
                        time.sleep(0.05)
                elif joined and not stopped and sess.step < 8:
                    dummy.heartbeat("joiner")
                elif sess.step == 8 and not stopped:
                    # the joiner goes silent: eviction, world 2 -> 1
                    stopped = True
                    deadline = time.time() + 6
                    while ((sess._hb.latest or {}).get("world") != 1
                           and time.time() < deadline):
                        time.sleep(0.05)
            reshapes = list(sess.reshapes)
            sess.close()
    finally:
        dummy.close()
        co.close()
    return losses, reshapes, gens


@pytest.mark.slow
def test_elastic_session_reshapes_with_bit_identical_trajectory(tmp_path):
    """The tentpole contract, in-process: a fleet that reshapes
    1 -> 2 -> 1 mid-run (join at the step barrier, eviction by lease
    timeout) produces EXACTLY the losses of an undisturbed run — the
    reshard-restore re-seats state, RNG stream and step counter."""
    ref, ref_reshapes, _ = _run_elastic(tmp_path / "ref", churn=False)
    # the undisturbed run still pays exactly one build (cold start)
    assert len(ref_reshapes) == 1
    churned, reshapes, gens = _run_elastic(tmp_path / "churn", churn=True)
    assert churned == ref, (
        "trajectory diverged across reshapes:\nref: %s\nchurn: %s"
        % (ref, churned))
    # cold start + join reshape + eviction reshape
    assert len(reshapes) == 3, reshapes
    assert [r["world"] for r in reshapes] == [1, 2, 1]
    assert gens[-1] > gens[0]
    # every reshape restored the serial the chief banked at its barrier
    for r in reshapes[1:]:
        assert r["serial"] == r["step"]


@pytest.mark.slow
def test_elastic_session_rejoins_after_eviction(tmp_path):
    """A worker whose lease lapses (e.g. a long stall) is evicted; its
    next step barrier re-registers it as a NEW member at the next
    generation and training continues from the published serial."""
    from paddle_tpu.elastic.worker import ElasticTrainSession

    co = FleetCoordinator(lease_s=0.4, min_workers=1)
    addr = co.serve()
    try:
        with fluid.scope_guard(fluid.Scope()):
            build_fn, holder = _elastic_model()
            sess = ElasticTrainSession(
                addr, str(tmp_path / "ckpt"), build_fn,
                worker_id="w0", heartbeat_interval_s=0.1)
            for _ in range(3):
                sess.run(feed=_elastic_feed(sess.step),
                         fetch_list=[holder["loss"]])
            gen_before = sess.generation
            # wedge the heartbeats past the lease: eviction
            sess._hb.evicted = True  # simulate the latched typed signal
            deadline = time.time() + 5
            while "w0" in co.status()["members"] and time.time() < deadline:
                time.sleep(0.05)
            assert "w0" not in co.status()["members"]
            sess.run(feed=_elastic_feed(sess.step),
                     fetch_list=[holder["loss"]])
            assert sess.worker_id != "w0"  # rejoined as a new member
            assert sess.generation > gen_before
            assert co.status()["world"] == 1
            sess.close()
    finally:
        co.close()


# ---------------------------------------------------------------------------
# master.py hardening (satellite): snapshot writes off the service lock
# ---------------------------------------------------------------------------


def test_stale_snapshot_write_loses_to_newer_commit(tmp_path):
    """The seq-ordered commit, white box: a writer that grabbed an older
    capture and stalled must NOT clobber a newer snapshot that committed
    while it slept — its tmp file is discarded instead."""
    import json

    from paddle_tpu.distributed.master import ThrottledSnapshot

    path = str(tmp_path / "s.json")
    snap = ThrottledSnapshot(path, interval_s=0.0)
    snap.capture({"state": "old", "todo": ["leased-task"]})
    # thread A's flush grabs the pending capture... then stalls
    with snap._mu:
        stalled, snap._pending = snap._pending, None
    assert stalled[0] == 1
    # meanwhile the service mutates and a newer flush commits (close())
    snap.capture({"state": "final", "todo": []})
    snap.flush()
    with open(path) as f:
        assert json.load(f)["state"] == "final"
    # thread A wakes up and finishes its flush with the STALE capture
    with snap._mu:
        snap._pending = stalled
    snap.flush()
    with open(path) as f:
        assert json.load(f)["state"] == "final", (
            "stale seq-1 write clobbered the final snapshot")
    # and it cleaned up after losing: no orphaned tmp files
    assert [d for d in tmp_path.iterdir() if ".tmp-" in d.name] == []


class _SlowSnapshotService(MasterService):
    """Test shim: makes the FIRST snapshot disk write block until
    released, from the flush (off-lock) path."""

    def __init__(self, *a, **kw):
        super(_SlowSnapshotService, self).__init__(*a, **kw)
        self.release = threading.Event()
        self.first_write_started = threading.Event()
        self._slowed = [False]
        snap = self._snap
        orig_flush = snap.flush
        mu = threading.Lock()

        def slow_flush():
            with mu:
                first, self._slowed[0] = not self._slowed[0], True
            if first:
                self.first_write_started.set()
                self.release.wait(10)
            orig_flush()

        snap.flush = slow_flush


def test_rpcs_do_not_block_behind_snapshot_write(tmp_path):
    """Hardening (a): a slow snapshot write must not hold the service
    lock — a concurrent get_task completes while the write is stuck."""
    s = _SlowSnapshotService(
        timeout_s=5.0, snapshot_path=str(tmp_path / "m.json"),
        snapshot_interval_s=0.0)
    try:
        stuck = threading.Thread(
            target=s.set_dataset, args=(["a", "b", "c"],), daemon=True)
        stuck.start()
        assert s.first_write_started.wait(5)
        # the writer is wedged INSIDE its flush; the lease path must not
        # queue behind it
        t0 = time.time()
        task, err = s.get_task(0)
        elapsed = time.time() - t0
        assert task is not None and err is None
        assert elapsed < 1.0, (
            "get_task blocked %.1fs behind a snapshot write" % elapsed)
    finally:
        s.release.set()
        s.close()


def test_close_snapshot_never_resurrects_finished_task(tmp_path):
    """Hardening (b): a stale in-flight snapshot write losing the race
    to close()'s final capture must NOT win the disk — recovery must see
    the finish, not re-dispatch the task as todo."""
    snap = str(tmp_path / "m.json")
    s = _SlowSnapshotService(timeout_s=5.0, snapshot_path=snap,
                             snapshot_interval_s=0.0)
    # set_dataset's flush wedges on another thread holding the OLD state
    # (todo=[t0]); meanwhile the task is leased AND finished, then the
    # service closes — its final capture must be the one that lands even
    # though the stale writer finishes afterwards
    stuck = threading.Thread(
        target=s.set_dataset, args=(["only"],), daemon=True)
    stuck.start()
    assert s.first_write_started.wait(5)
    task, err = s.get_task(0)
    assert err is None
    assert s.task_finished(task.task_id)
    closer = threading.Thread(target=s.close, daemon=True)
    closer.start()
    time.sleep(0.2)          # close() reaches its (ordered) final flush
    s.release.set()          # NOW the stale writer finishes... and loses
    stuck.join(5)
    closer.join(5)
    assert not stuck.is_alive() and not closer.is_alive()

    s2 = MasterService(snapshot_path=snap)
    try:
        st = s2.status()
        # the finish rolled the pass (single task): recovery must show
        # the rolled state, not the stale pre-lease todo of pass 0
        assert st["cur_pass"] == 1, (
            "stale snapshot won the disk; recovered state: %s" % st)
    finally:
        s2.close()
