"""Native runtime (C++ libptpu_core) + PTPB program IR tests.

Covers: recordio round-trip + corruption detection through ctypes, the
C++ blocking queue under Python producer/consumer threads, NativeScope
host-tensor store, and — the lockstep guarantee — Python-serialized
programs parsing and re-serializing BYTE-IDENTICALLY in C++, then
deserializing back to an equivalent Python Program that still executes.
"""

import os
import subprocess
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native
from paddle_tpu.core.program_bin import (
    deserialize_program,
    serialize_program,
)

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="native toolchain unavailable: %s" % native.last_error(),
)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "data.recordio")
    records = [b"alpha", b"", b"x" * 70000, np.arange(100).tobytes()]
    with native.RecordIOWriter(path) as w:
        for r in records:
            w.write(r)
    with native.RecordIOReader(path) as r:
        got = list(r)
    assert got == records

    # Flip a payload byte -> IOError on that record.
    blob = bytearray(open(path, "rb").read())
    blob[4 + 8 + 4 + 1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with native.RecordIOReader(path) as r:
        with pytest.raises(IOError):
            next(r)


def test_native_queue_producer_consumer():
    q = native.NativeBlockingQueue(capacity=4)
    n_items = 200

    def producer():
        for i in range(n_items):
            q.push(b"item-%04d" % i)
        q.close()

    got = []
    t = threading.Thread(target=producer)
    t.start()
    while True:
        item = q.pop()
        if item is None:
            break
        got.append(item)
    t.join()
    assert len(got) == n_items
    assert got[0] == b"item-0000" and got[-1] == b"item-0199"
    assert q.is_closed()

    q.reopen()
    q.push(b"epoch2")
    assert q.pop(timeout_ms=1000) == b"epoch2"
    with pytest.raises(TimeoutError):
        q.pop(timeout_ms=50)


def test_native_scope():
    scope = native.NativeScope()
    w = np.arange(12, dtype="float32").reshape(3, 4)
    scope.set("w", w)
    scope.set("step", np.asarray([7], "int64"))
    child = scope.new_child()
    np.testing.assert_array_equal(child.get("w"), w)  # parent walk
    child.set("w", np.zeros((2,), "float32"))  # shadowing
    assert child.get("w").shape == (2,)
    assert scope.get("w").shape == (3, 4)
    assert scope.get("absent") is None
    assert set(scope.var_names()) == {"w", "step"}
    assert len(scope) == 2
    assert scope.erase("step")
    assert len(scope) == 1


def _build_sample_program():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y)
        )
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_ptpb_python_cpp_lockstep():
    """C++ parse + re-serialize must reproduce the Python bytes exactly."""
    main, _, _ = _build_sample_program()
    blob = serialize_program(main)
    nblocks, ops, reserialized = native.parse_program_bytes(blob)
    assert nblocks == len(main.blocks)
    assert ops[0] == len(main.global_block().ops)
    assert reserialized == blob


def test_ptpb_roundtrip_executes(tmp_path):
    """serialize -> C++ -> deserialize: the program still runs and matches
    the original's losses step for step."""
    main, startup, loss = _build_sample_program()
    blob = serialize_program(main)
    _, _, blob2 = native.parse_program_bytes(blob)
    restored = deserialize_program(blob2)

    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype("float32")
    y = rng.randn(16, 1).astype("float32")

    from paddle_tpu.core.scope import Scope

    results = []
    for prog in (main, restored):
        # Fresh Executor per run: the PRNG key folds in a per-executor run
        # counter, so determinism holds for identical run sequences.
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(Scope()):
            exe.run(startup)
            vals = []
            for _ in range(3):
                (lv,) = exe.run(prog, feed={"x": x, "y": y},
                                fetch_list=[loss.name])
                vals.append(float(np.asarray(lv).ravel()[0]))
            results.append(vals)
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)


def test_cpp_unit_suite_with_program_file(tmp_path):
    """Run the assert-based C++ suite end to end, feeding it a real
    Python-written PTPB file for its round-trip section."""
    main, _, _ = _build_sample_program()
    prog_path = str(tmp_path / "prog.ptpb")
    open(prog_path, "wb").write(serialize_program(main))
    test_bin = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native", "build", "ptpu_native_test",
    )
    assert os.path.exists(test_bin), "build the native tests first"
    out = subprocess.run(
        [test_bin, prog_path], capture_output=True, text=True, timeout=120
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ALL NATIVE TESTS PASSED" in out.stdout
    assert "program roundtrip ok" in out.stdout


def test_save_inference_model_uses_ptpb(tmp_path):
    """save_inference_model emits the language-neutral PTPB format (C++
    predictor loadable), not a Python pickle."""
    main, startup, loss = _build_sample_program()
    exe = fluid.Executor(fluid.CPUPlace())
    from paddle_tpu.core.scope import Scope

    with fluid.scope_guard(Scope()):
        exe.run(startup)
        block = main.global_block()
        pred = block.var("fc_1.tmp_1") if "fc_1.tmp_1" in block.vars else None
        target = pred if pred is not None else loss
        path = str(tmp_path / "model")
        fluid.io.save_inference_model(path, ["x", "y"], [target], exe,
                                      main_program=main)
        blob = open(os.path.join(path, "__model__"), "rb").read()
        assert blob[:4] == b"PTPB"
        prog, feeds, fetches = fluid.io.load_inference_model(path, exe)
        assert feeds == ["x", "y"] or set(feeds) <= {"x", "y"}
        assert fetches[0] is not None


def test_ptpb_lockstep_covers_fused_ops():
    """Programs rewritten by the fusion passes (fused ops with list/None
    attrs) still round-trip byte-exactly through the C++ PTPB parser."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        z = fluid.layers.relu(fluid.layers.elementwise_add(h, h))
        proj = fluid.layers.fc(input=fluid.layers.unsqueeze(z, axes=[1]),
                               size=4 * 6, num_flatten_dims=2)
        out, _ = fluid.layers.dynamic_lstm(input=proj, size=4 * 6)
    apply_pass(main, "fc_lstm_fuse")
    apply_pass(main, "fuse_elewise_add_act")
    apply_pass(main, "fc_fuse")
    types = [op.type for op in main.global_block().ops]
    assert "fusion_lstm" in types and "fused_elemwise_activation" in types
    blob = serialize_program(main)
    nblocks, ops, reserialized = native.parse_program_bytes(blob)
    assert reserialized == blob
    back = deserialize_program(blob)
    assert [op.type for op in back.global_block().ops] == types
