"""RNN + control-flow tests.

Reference parity: tests/unittests/test_lstm_op.py, test_gru_op.py,
test_recurrent_op.py, test_while_op.py, test_dynrnn_* — adapted to the
dense-padded sequence regime.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import backward


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_dynamic_lstm_matches_numpy():
    B, T, D = 3, 5, 4
    np.random.seed(0)
    x = np.random.randn(B, T, 4 * D).astype("float32") * 0.1

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, 4 * D])
        h, c = fluid.layers.dynamic_lstm(
            input=inp, size=4 * D, use_peepholes=False,
            param_attr=fluid.ParamAttr(
                name="lstm_w",
                initializer=fluid.initializer.ConstantInitializer(0.05),
            ),
            bias_attr=fluid.ParamAttr(
                name="lstm_b",
                initializer=fluid.initializer.ConstantInitializer(0.1),
            ),
        )
    hv, cv = _run(main, startup, {"x": x}, [h, c])

    # numpy reference
    w = np.full((D, 4 * D), 0.05, "float32")
    b = np.full((4 * D,), 0.1, "float32")
    hp = np.zeros((B, D), "float32")
    cp = np.zeros((B, D), "float32")
    for t in range(T):
        g = x[:, t] + hp @ w + b
        i = _sigmoid(g[:, :D])
        f = _sigmoid(g[:, D:2 * D])
        cand = np.tanh(g[:, 2 * D:3 * D])
        o = _sigmoid(g[:, 3 * D:])
        cp = f * cp + i * cand
        hp = o * np.tanh(cp)
    np.testing.assert_allclose(np.asarray(hv)[:, -1], hp, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cv)[:, -1], cp, atol=1e-5)


def test_dynamic_lstm_length_mask():
    """Hidden state freezes past each sequence's end."""
    B, T, D = 2, 6, 3
    np.random.seed(1)
    x = np.random.randn(B, T, 4 * D).astype("float32")
    lens = np.array([3, 6], "int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, 4 * D])
        ln = fluid.layers.data("len", shape=[1], dtype="int64")
        h, _ = fluid.layers.dynamic_lstm(input=inp, size=4 * D, length=ln)
    hv, = _run(main, startup, {"x": x, "len": lens}, [h])
    hv = np.asarray(hv)
    # steps >= len keep the value from step len-1
    np.testing.assert_allclose(hv[0, 3], hv[0, 2], atol=1e-6)
    np.testing.assert_allclose(hv[0, 5], hv[0, 2], atol=1e-6)
    assert not np.allclose(hv[1, 5], hv[1, 2])


def test_dynamic_gru_runs_and_trains():
    B, T, D = 4, 7, 8
    np.random.seed(2)
    x = np.random.randn(B, T, 3 * D).astype("float32") * 0.1
    y = np.random.randn(B, D).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, 3 * D])
        label = fluid.layers.data("y", shape=[D])
        proj = fluid.layers.fc(input=inp, size=3 * D, num_flatten_dims=2)
        hidden = fluid.layers.dynamic_gru(input=proj, size=D)
        last = fluid.layers.sequence_last_step(hidden)
        out = fluid.layers.fc(input=last, size=D)
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(out, label))
        )
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = [
        float(np.asarray(exe.run(main, feed={"x": x, "y": y},
                                 fetch_list=[loss])[0]).ravel()[0])
        for _ in range(40)
    ]
    assert losses[-1] < losses[0] * 0.7, losses


def test_static_rnn_matches_manual_loop():
    B, T, D = 2, 4, 3
    np.random.seed(3)
    x = np.random.randn(B, T, D).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, D])
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(inp)
            h_prev = rnn.memory(shape=[-1, D], batch_ref=inp, init_value=0.0)
            h = fluid.layers.elementwise_add(
                fluid.layers.tanh(x_t), h_prev
            )
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
    ov, = _run(main, startup, {"x": x}, [out])
    ov = np.asarray(ov)

    hp = np.zeros((B, D), "float32")
    expect = []
    for t in range(T):
        hp = np.tanh(x[:, t]) + hp
        expect.append(hp)
    np.testing.assert_allclose(ov, np.stack(expect, 1), atol=1e-5)


def test_static_rnn_with_fc_trains():
    """StaticRNN with a parameterized step (fc) — grads flow through scan."""
    B, T, D, H = 4, 5, 6, 8
    np.random.seed(4)
    x = np.random.randn(B, T, D).astype("float32")
    y = np.random.randn(B, H).astype("float32") * 0.3

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, D])
        label = fluid.layers.data("y", shape=[H])
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(inp)
            h_prev = rnn.memory(shape=[-1, H], batch_ref=inp)
            h = fluid.layers.fc(input=[x_t, h_prev], size=H, act="tanh")
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
        last = fluid.layers.sequence_last_step(out)
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(last, label))
        )
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = [
        float(np.asarray(exe.run(main, feed={"x": x, "y": y},
                                 fetch_list=[loss])[0]).ravel()[0])
        for _ in range(25)
    ]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_while_loop_accumulates():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "int64", 0)
        limit = fluid.layers.fill_constant([1], "int64", 10)
        acc = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            acc2 = fluid.layers.elementwise_add(
                acc, fluid.layers.cast(i, "float32")
            )
            fluid.layers.assign(acc2, acc)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
    av, = _run(main, startup, {}, [acc])
    assert float(np.asarray(av).ravel()[0]) == sum(range(10))


def test_cond_branches():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        p = fluid.layers.data("p", shape=[1], dtype="bool")

        def true_fn():
            return fluid.layers.scale(x, scale=2.0)

        def false_fn():
            return fluid.layers.scale(x, scale=-1.0)

        out = fluid.layers.cond(p, true_fn, false_fn)
    xv = np.random.randn(2, 4).astype("float32")
    ov_t, = _run(main, startup,
                 {"x": xv, "p": np.array([True])}, [out])
    np.testing.assert_allclose(np.asarray(ov_t), xv * 2.0, atol=1e-6)
    exe = fluid.Executor(fluid.CPUPlace())
    ov_f, = exe.run(main, feed={"x": xv, "p": np.array([False])},
                    fetch_list=[out])
    np.testing.assert_allclose(np.asarray(ov_f), -xv, atol=1e-6)


def test_cond_passthrough_branch():
    """A branch may return a parent var untouched (identity branch)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        p = fluid.layers.data("p", shape=[1], dtype="bool")
        out = fluid.layers.cond(
            p, lambda: fluid.layers.scale(x, scale=3.0), lambda: x
        )
    xv = np.random.randn(2, 4).astype("float32")
    ov, = _run(main, startup, {"x": xv, "p": np.array([False])}, [out])
    np.testing.assert_allclose(np.asarray(ov), xv, atol=1e-6)


def test_static_rnn_user_error_not_masked():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[4, 3])
        rnn = fluid.layers.StaticRNN()
        with pytest.raises(RuntimeError, match="user error"):
            with rnn.step():
                rnn.step_input(inp)
                raise RuntimeError("user error")


def test_ifelse_elementwise_merge():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        c = fluid.layers.data("c", shape=[1], dtype="bool")
        ie = fluid.layers.IfElse(c)
        with ie.true_block():
            ie.output(fluid.layers.scale(x, scale=10.0))
        with ie.false_block():
            ie.output(fluid.layers.scale(x, scale=0.0))
        out, = ie()
    xv = np.ones((4, 3), "float32")
    cv = np.array([[True], [False], [True], [False]])
    ov, = _run(main, startup, {"x": xv, "c": cv}, [out])
    ov = np.asarray(ov)
    np.testing.assert_allclose(ov[0], 10 * np.ones(3), atol=1e-6)
    np.testing.assert_allclose(ov[1], np.zeros(3), atol=1e-6)


def test_while_with_seeded_tensor_array():
    """Decode-loop pattern: array seeded before the loop, grown inside."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        i = fluid.layers.fill_constant([1], "int64", 1)
        limit = fluid.layers.fill_constant([1], "int64", 5)
        i0 = fluid.layers.fill_constant([1], "int64", 0)
        arr = fluid.layers.array_write(x, i0, capacity=8)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            prev = fluid.layers.array_read(
                arr, fluid.layers.elementwise_sub(
                    i, fluid.layers.fill_constant([1], "int64", 1))
            )
            fluid.layers.array_write(
                fluid.layers.scale(prev, scale=2.0), i, array=arr
            )
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        last = fluid.layers.array_read(
            arr, fluid.layers.fill_constant([1], "int64", 4)
        )
    xv = np.ones((2, 3), "float32")
    lv, = _run(main, startup, {"x": xv}, [last])
    np.testing.assert_allclose(np.asarray(lv), 16.0 * xv, atol=1e-5)


def test_while_unseeded_carry_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "int64", 0)
        limit = fluid.layers.fill_constant([1], "int64", 3)
        arr = fluid.layers.create_array("float32")
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with pytest.raises(ValueError, match="no value before the loop"):
            with w.block():
                fluid.layers.array_write(
                    fluid.layers.cast(i, "float32"), i, array=arr
                )
                fluid.layers.increment(i, value=1, in_place=True)
                fluid.layers.less_than(i, limit, cond=cond)


def test_static_rnn_output_feeds_fc():
    """Shape inference flows through the recurrent mega-op (rnn -> fc)."""
    B, T, D = 2, 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[T, D])
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(inp)
            h_prev = rnn.memory(shape=[-1, D], batch_ref=inp)
            h = fluid.layers.elementwise_add(x_t, h_prev)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
        assert out.shape is not None and out.shape[-1] == D, out.shape
        last = fluid.layers.sequence_last_step(out)
        logits = fluid.layers.fc(input=last, size=5)
    x = np.random.randn(B, T, D).astype("float32")
    lv, = _run(main, startup, {"x": x}, [logits])
    assert np.asarray(lv).shape == (B, 5)


def test_tensor_array_write_read():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        i0 = fluid.layers.fill_constant([1], "int64", 0)
        i1 = fluid.layers.fill_constant([1], "int64", 1)
        arr = fluid.layers.array_write(x, i0, capacity=4)
        fluid.layers.array_write(
            fluid.layers.scale(x, scale=2.0), i1, array=arr
        )
        r = fluid.layers.array_read(arr, i1)
        n = fluid.layers.array_length(arr)
    xv = np.random.randn(2, 3).astype("float32")
    rv, nv = _run(main, startup, {"x": xv}, [r, n])
    np.testing.assert_allclose(np.asarray(rv), 2 * xv, atol=1e-6)
    assert int(np.asarray(nv).ravel()[0]) == 2
