"""Failure-forensics coverage: black-box dumps on induced failures, the
hang watchdog, NaN provenance blaming the exact op, and the per-device
multichip metric surface on the 8-device virtual CPU mesh.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.observability import (
    blackbox,
    explain,
    nan_provenance,
    telemetry,
    watchdog,
)
from paddle_tpu.observability.metrics_registry import REGISTRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _quiet_forensics():
    """Forensics subsystems off and empty around every test; the shared
    executable registry is purged so dispatch/compile events are scoped
    to the test."""
    import paddle_tpu.executor as executor_mod

    executor_mod._shared_executables.clear()
    telemetry.enable(False)
    telemetry.reset(flops=True)
    explain.reset()
    blackbox.disable()
    blackbox.reset()
    watchdog.stop()
    yield
    watchdog.stop()
    blackbox.disable()
    blackbox.reset()
    telemetry.enable(False)
    telemetry.reset(flops=True)
    explain.reset()


def _nan_program():
    """x -> scale -> log -> mean; feeding a zero makes op 1 (log) emit
    -inf while its inputs are finite."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        h = fluid.layers.scale(x, scale=2.0)
        y = fluid.layers.log(h)
        out = fluid.layers.mean(y)
    return main, startup, out


def _mlp_program(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32])
        label = fluid.layers.data("label", [1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


# -- black box ---------------------------------------------------------------

def test_blackbox_dump_on_induced_executor_exception(tmp_path):
    box = str(tmp_path / "box.json")
    blackbox.enable(box, handlers=False)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(RuntimeError):
        exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                fetch_list=["never_produced"])
    snap = json.load(open(box))
    assert snap["reason"] == "unhandled_exception:Executor.run"
    kinds = [e["kind"] for e in snap["events"]]
    # the ring ends with the failing step: its dispatch, then the error
    assert kinds[-1] == "exception"
    assert "dispatch" in kinds
    last = snap["events"][-1]
    assert last["origin"] == "Executor.run"
    assert "never_produced" in last["exc_message"]
    disp = [e for e in snap["events"] if e["kind"] == "dispatch"][-1]
    assert disp["fetch_names"] == ["never_produced"]
    assert any(n == "x" for n, _s, _d in disp["feed_specs"])
    # a dump is a full incident report: flag snapshot + explainer tail
    assert snap["flags"]["check_nan_inf"] is False
    assert isinstance(snap["recompiles"], list)


def test_blackbox_dump_once_per_exception_across_layers(tmp_path):
    """Predictor wrapping Executor records two origins but writes ONE
    dump for one exception object."""
    box = str(tmp_path / "box.json")
    blackbox.enable(box, handlers=False)
    err = ValueError("boom")
    blackbox.record_exception("Executor.run", err)
    first = os.path.getmtime(box)
    time.sleep(0.02)
    blackbox.record_exception("Predictor.run", err)
    assert os.path.getmtime(box) == first  # no second write
    origins = [e.get("origin") for e in blackbox.events()
               if e["kind"] == "exception"]
    assert origins == ["Executor.run", "Predictor.run"]


def test_blackbox_disabled_records_nothing(tmp_path):
    assert not blackbox.ENABLED
    exe = fluid.Executor(fluid.CPUPlace())
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        out = fluid.layers.mean(fluid.layers.scale(x, scale=1.0))
    exe.run(startup)
    exe.run(main, feed={"x": np.ones((1, 4), "float32")},
            fetch_list=[out])
    assert blackbox.events() == []
    assert blackbox.dump() is None  # no path configured


def test_subprocess_killed_by_signal_leaves_readable_box(tmp_path):
    """The acceptance path: a SIGTERM'd process dies BY the signal and
    still leaves a dump whose events end at the failing point."""
    box = str(tmp_path / "sig.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", FLAGS_blackbox_path=box)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "forensics_smoke.py"),
         "child-signal", box],
        env=env, capture_output=True, timeout=180)
    assert proc.returncode == -signal.SIGTERM, proc.stderr.decode()[-500:]
    snap = json.load(open(box))
    assert snap["reason"] == "fatal_signal:SIGTERM"
    kinds = [e["kind"] for e in snap["events"]]
    assert kinds[-1] == "fatal_signal" and "dispatch" in kinds
    assert snap["thread_stacks"]


# -- watchdog ----------------------------------------------------------------

def test_watchdog_fires_on_stalled_fetch(tmp_path):
    box = str(tmp_path / "hang.json")
    blackbox.enable(box, handlers=False)
    fired = []
    before = REGISTRY.counter("paddle_tpu_watchdog_fires_total").value()
    watchdog.start(timeout=0.2, on_hang=fired.append, abort=False)
    token = watchdog.arm("FetchHandle.result")  # the artificial stall
    deadline = time.time() + 5.0
    while not fired and time.time() < deadline:
        time.sleep(0.05)
    watchdog.disarm(token)
    assert len(fired) == 1
    report = fired[0]
    assert report["stalled"][0]["tag"] == "FetchHandle.result"
    assert report["timeout_s"] == pytest.approx(0.2)
    assert report["dump_path"] == box
    snap = json.load(open(box))
    assert snap["reason"] == "watchdog_hang"
    assert snap["thread_stacks"]  # every live thread, formatted
    assert snap["watchdog"]["stalled"][0]["tag"] == "FetchHandle.result"
    c = REGISTRY.counter("paddle_tpu_watchdog_fires_total")
    assert c.value() == before + 1
    assert watchdog.last_hang()["stalled"] == report["stalled"]


def test_watchdog_idle_gap_does_not_instafire():
    """An idle process (nothing armed) accrues no hang debt: work armed
    after a gap longer than the timeout starts a fresh clock."""
    fired = []
    watchdog.start(timeout=0.2, on_hang=fired.append, abort=False)
    time.sleep(0.45)  # idle > timeout
    token = watchdog.arm("late-work")
    time.sleep(0.1)   # younger than the timeout
    assert fired == []
    watchdog.disarm(token)


def test_watchdog_wedged_token_not_masked_by_other_threads():
    """Per-token aging: one wedged fetch fires (once) even while other
    work keeps arming/disarming, and progress() on the wedged token
    re-arms its episode."""
    fired = []
    watchdog.start(timeout=0.25, on_hang=fired.append, abort=False)
    wedged = watchdog.arm("wedged-fetch")
    deadline = time.time() + 4.0
    while not fired and time.time() < deadline:
        t = watchdog.arm("healthy")
        time.sleep(0.05)
        watchdog.disarm(t)
    assert len(fired) == 1
    assert fired[0]["stalled"][0]["tag"] == "wedged-fetch"
    time.sleep(0.4)
    assert len(fired) == 1  # once per stall episode
    watchdog.progress(wedged)  # it moved: a new stall is a new episode
    deadline = time.time() + 4.0
    while len(fired) < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert len(fired) == 2
    watchdog.disarm(wedged)


def test_watchdog_suspend_covers_slow_compiles():
    """watchdog.suspend() (wrapped around executable resolution in
    core/lowering.py) masks slow-but-alive host work, and the armed
    clocks restart on exit."""
    fired = []
    watchdog.start(timeout=0.2, on_hang=fired.append, abort=False)
    token = watchdog.arm("Executor.run")
    with watchdog.suspend():
        time.sleep(0.5)  # "compiling": longer than the timeout
    time.sleep(0.1)      # clock restarted on exit, still young
    assert fired == []
    watchdog.disarm(token)


def test_watchdog_quiet_while_progress_flows():
    fired = []
    watchdog.start(timeout=0.2, on_hang=fired.append, abort=False)
    token = watchdog.arm("Executor.run")
    for _ in range(5):
        time.sleep(0.08)
        watchdog.progress()  # advancing work must never trip it
    watchdog.disarm(token)
    time.sleep(0.3)  # disarmed + idle: nothing armed, nothing fires
    assert fired == []


def test_watchdog_auto_timeout_follows_p95():
    telemetry.enable(True)
    for _ in range(20):
        telemetry.record_step("single", 2.0)  # p95 = 2s
    watchdog.start(abort=False)  # no explicit timeout, flag is 0
    try:
        assert watchdog.effective_timeout() == pytest.approx(
            max(2.0 * watchdog._AUTO_MULT, watchdog._AUTO_MIN))
    finally:
        watchdog.stop()
    telemetry.reset()
    # no telemetry window -> the fixed default
    assert watchdog.effective_timeout() == watchdog._AUTO_DEFAULT


def test_executor_run_arms_and_disarms_watchdog():
    """Executor.run wears the blackbox.guard shell: every run arms the
    watchdog with its origin and disarms on completion."""
    events = []
    real_arm, real_disarm = watchdog.arm, watchdog.disarm

    def arm(tag, scale=1):
        events.append(("arm", tag))
        return real_arm(tag, scale=scale)

    def disarm(tok):
        events.append(("disarm", tok))
        return real_disarm(tok)

    watchdog.start(timeout=60.0, abort=False)
    try:
        watchdog.arm, watchdog.disarm = arm, disarm
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [4])
            out = fluid.layers.mean(fluid.layers.scale(x, scale=1.0))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((1, 4), "float32")},
                fetch_list=[out])
    finally:
        watchdog.arm, watchdog.disarm = real_arm, real_disarm
        watchdog.stop()
    arms = [e for e in events if e[0] == "arm"]
    disarms = [e for e in events if e[0] == "disarm"]
    assert len(arms) >= 2 and len(arms) == len(disarms)
    assert all(tag == "Executor.run" for _, tag in arms)


# -- NaN provenance ----------------------------------------------------------

def test_nan_provenance_blames_exact_op(tmp_path):
    box = str(tmp_path / "nan.json")
    blackbox.enable(box, handlers=False)
    main, startup, out = _nan_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    flags.set_flag("check_nan_inf", True)
    try:
        with pytest.raises(RuntimeError, match="NaN/Inf") as ei:
            exe.run(main,
                    feed={"x": np.array([[1.0, 2.0, 0.0, 3.0]],
                                        "float32")},
                    fetch_list=[out])
    finally:
        flags.set_flag("check_nan_inf", False)
    assert isinstance(ei.value, nan_provenance.NonFiniteError)
    d = ei.value.diagnostic
    assert d.rule == "N001" and d.severity == "error"
    assert d.op_type == "log" and d.op_idx == 1 and d.block_idx == 0
    assert d.var_names == ("log_0.tmp_0",)
    assert "clip" in d.hint
    # the finding is in the black box for post-mortem tooling
    snap = json.load(open(box))
    assert snap["nan_diagnostic"]["op_type"] == "log"


def test_nan_provenance_async_result_path():
    main, startup, out = _nan_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    flags.set_flag("check_nan_inf", True)
    try:
        handle = exe.run_async(
            main, feed={"x": np.array([[0.5, 0.0, 1.0, 2.0]], "float32")},
            fetch_list=[out])
        with pytest.raises(nan_provenance.NonFiniteError) as ei:
            handle.result()
    finally:
        flags.set_flag("check_nan_inf", False)
    assert ei.value.diagnostic.op_type == "log"


def test_nan_provenance_blames_poisoned_feed():
    main, startup, out = _nan_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    flags.set_flag("check_nan_inf", True)
    try:
        with pytest.raises(RuntimeError, match="NaN/Inf") as ei:
            exe.run(main,
                    feed={"x": np.array([[1.0, np.nan, 1.0, 1.0]],
                                        "float32")},
                    fetch_list=[out])
    finally:
        flags.set_flag("check_nan_inf", False)
    d = getattr(ei.value, "diagnostic", None)
    assert d is not None and d.op_idx is None  # var-level: upstream
    assert "x" in d.var_names
    assert "upstream" in d.hint


def test_nan_provenance_off_keeps_plain_error():
    main, startup, out = _nan_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    flags.set_flag("check_nan_inf", True)
    flags.set_flag("nan_provenance", False)
    try:
        with pytest.raises(RuntimeError, match="NaN/Inf") as ei:
            exe.run(main,
                    feed={"x": np.array([[1.0, 0.0, 1.0, 1.0]],
                                        "float32")},
                    fetch_list=[out])
    finally:
        flags.set_flag("check_nan_inf", False)
        flags.set_flag("nan_provenance", True)
    assert not isinstance(ei.value, nan_provenance.NonFiniteError)


def test_blame_step_clean_program_returns_none():
    main, startup, out = _nan_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    key = jax.random.PRNGKey(0)
    diag = nan_provenance.blame_step(
        main, {}, {"x": np.ones((1, 4), "float32")}, key)
    assert diag is None


# -- per-device multichip observability --------------------------------------

def test_per_device_metrics_one_label_per_device():
    from paddle_tpu.parallel_executor import ParallelExecutor

    main, startup, loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    telemetry.reset()
    pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                          use_tpu=False)
    n_dev = pe.device_count
    assert n_dev == 8
    rng = np.random.RandomState(0)
    for _ in range(2):
        pe.run(fetch_list=[loss],
               feed={"x": rng.randn(32, 32).astype("float32"),
                     "label": rng.randint(0, 4, (32, 1)).astype("int64")})
    labels = {"cpu:%d" % i for i in range(n_dev)}
    step_g = REGISTRY.gauge("paddle_tpu_device_step_seconds",
                            labels=("device",))
    assert {dict(k)["device"] for k in step_g._series()} == labels
    xfer = REGISTRY.counter("paddle_tpu_device_transfer_bytes_total",
                            labels=("device",))
    series = {dict(k)["device"]: v for k, v in xfer._series().items()}
    assert set(series) == labels
    # x sharded over data axis: 32x32 f32 / 8 = 512B; label 32x1 i64 / 8
    # = 32B; two steps
    assert all(v == 2 * (512 + 32) for v in series.values())
    assert REGISTRY.gauge("paddle_tpu_device_step_imbalance").value() >= 1.0
    rec = telemetry.step_records()[-1]
    assert set(rec["device_times"]) == labels
    # the Prometheus scrape carries the labeled series
    text = REGISTRY.to_prometheus()
    assert 'paddle_tpu_device_step_seconds{device="cpu:7"}' in text
    assert REGISTRY.gauge("paddle_tpu_mesh_devices").value() == n_dev


def test_device_memory_sums_across_devices(monkeypatch):
    class _Dev(object):
        def __init__(self, i, b):
            self.platform, self.id, self._b = "tpu", i, b

        def memory_stats(self):
            return {"bytes_in_use": self._b}

    monkeypatch.setattr(jax, "local_devices",
                        lambda: [_Dev(0, 100), _Dev(1, 250)])
    assert telemetry.device_memory_bytes() == 350  # sum, not device 0
    assert telemetry.device_memory_bytes(per_device=True) == {
        "tpu:0": 100, "tpu:1": 250}
    # the aggregate gauge keeps its pre-existing name; per-device series
    # land on the labeled twin
    telemetry.record_step("single", 0.01)
    assert REGISTRY.gauge(
        "paddle_tpu_device_bytes_in_use").value() == 350
    per = REGISTRY.gauge("paddle_tpu_device_bytes_in_use_per_device",
                         labels=("device",))
    assert per.value(device="tpu:1") == 250


def test_pipeline_occupancy_gauge():
    occ = telemetry.record_pipeline_occupancy(4, 8)
    assert occ == pytest.approx(8.0 / 11.0)
    g = REGISTRY.gauge("paddle_tpu_pipeline_stage_occupancy",
                       labels=("stage",))
    assert {dict(k)["stage"] for k in g._series()} >= {"0", "1", "2", "3"}
    assert g.value(stage="3") == pytest.approx(8.0 / 11.0)


# -- tool CLIs (jax-free: fast subprocesses) ---------------------------------

def test_blackbox_dump_cli_friendly_on_missing_file(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "blackbox_dump.py"),
         str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "FLAGS_blackbox_path" in proc.stdout
    assert "Traceback" not in proc.stderr


def test_blackbox_dump_cli_exit_codes(tmp_path):
    clean = str(tmp_path / "clean.json")
    blackbox.enable(clean, handlers=False)
    blackbox.record_dispatch("Executor.run", fetch_names=["loss"])
    blackbox.dump(reason="on_demand")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "blackbox_dump.py"),
         clean], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    from paddle_tpu.analysis.diagnostics import Diagnostic

    blackbox.record_nan_diagnostic(Diagnostic(
        "N001", "non-finite-output", "error", "op 'log' went non-finite",
        block_idx=0, op_idx=3, op_type="log", var_names=("y",),
        hint="clip it"))
    blackbox.dump(reason="nan_diagnostic")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "blackbox_dump.py"),
         clean], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 3
    assert "N001" in proc.stdout and "clip it" in proc.stdout


def test_step_breakdown_friendly_on_missing_jsonl(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "step_breakdown.py"),
         "--from-jsonl", str(tmp_path / "none.steps.jsonl")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0
    out = proc.stdout + proc.stderr
    assert "FLAGS_telemetry" in out and "Traceback" not in out


def test_step_breakdown_per_device_view(tmp_path):
    path = str(tmp_path / "steps.jsonl")
    with open(path, "w") as f:
        for wall, dt in ((0.010, {"cpu:0": 0.009, "cpu:1": 0.013}),
                         (0.012, {"cpu:0": 0.010, "cpu:1": 0.014})):
            f.write(json.dumps({
                "ts": 1.0, "executor": "parallel", "wall_s": wall,
                "steps": 1, "step_s": wall, "feed_bytes": 64,
                "fetch_bytes": 4, "device_times": dt}) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "step_breakdown.py"),
         "--from-jsonl", path, "--per-device"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    per_dev = next(l for l in lines if "per_device" in l)
    assert per_dev["most_frequent_straggler"] == "cpu:1"
    assert per_dev["per_device"]["cpu:1"]["steps"] == 2
