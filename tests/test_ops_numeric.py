"""Per-op output + numeric-gradient checks through the OpTest harness
(reference: tests/unittests/test_*_op.py, ~300 files — coverage of the
kernel families used by the benchmark models)."""

import numpy as np
import pytest

from op_test import OpTest


def _softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


class TestMulOp(OpTest):
    op_type = "mul"

    def setup(self):
        x = np.random.rand(4, 5).astype("float32")
        y = np.random.rand(5, 3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}


class TestMatmulTransposed(OpTest):
    op_type = "matmul"

    def setup(self):
        x = np.random.rand(2, 5, 4).astype("float32")
        y = np.random.rand(2, 5, 3).astype("float32")
        self.attrs = {"transpose_X": True}
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.einsum("bkm,bkn->bmn", x, y)}


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype("float32")
        y = np.random.rand(3).astype("float32")
        self.attrs = {"axis": 1}
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}


class TestSoftmaxOp(OpTest):
    op_type = "softmax"

    def setup(self):
        x = np.random.rand(6, 10).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": _softmax(x)}


class TestSoftmaxWithXentOp(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup(self):
        logits = np.random.rand(5, 7).astype("float32") * 4
        label = np.random.randint(0, 7, (5, 1)).astype("int64")
        sm = _softmax(logits)
        loss = -np.log(sm[np.arange(5), label.ravel()]).reshape(5, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": sm, "Loss": loss}


class TestReduceMean(OpTest):
    op_type = "reduce_mean"

    def setup(self):
        x = np.random.rand(3, 4, 5).astype("float32")
        self.attrs = {"dim": [1], "keep_dim": False}
        self.inputs = {"X": x}
        self.outputs = {"Out": x.mean(1)}


class TestConv2dOp(OpTest):
    op_type = "conv2d"

    def setup(self):
        x = np.random.rand(2, 3, 8, 8).astype("float32")
        w = np.random.rand(4, 3, 3, 3).astype("float32")
        self.attrs = {"strides": [2, 2], "paddings": [1, 1]}
        self.inputs = {"Input": x, "Filter": w}
        import jax

        out = jax.lax.conv_general_dilated(
            x, w, (2, 2), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        self.outputs = {"Output": np.asarray(out)}


class TestPool2dAvg(OpTest):
    op_type = "pool2d"

    def setup(self):
        x = np.random.rand(2, 3, 6, 6).astype("float32")
        self.attrs = {
            "pooling_type": "avg",
            "ksize": [2, 2],
            "strides": [2, 2],
            "paddings": [0, 0],
        }
        self.inputs = {"X": x}
        out = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
        self.outputs = {"Out": out}


class TestBatchNormTrain(OpTest):
    op_type = "batch_norm"

    def setup(self):
        np.random.seed(3)
        x = np.random.rand(4, 3, 5, 5).astype("float32")
        scale = np.random.rand(3).astype("float32") + 0.5
        bias = np.random.rand(3).astype("float32")
        mean = np.zeros(3, "float32")
        var = np.ones(3, "float32")
        eps = 1e-5
        mu = x.mean(axis=(0, 2, 3))
        sig2 = x.var(axis=(0, 2, 3))
        y = (x - mu.reshape(1, 3, 1, 1)) / np.sqrt(
            sig2.reshape(1, 3, 1, 1) + eps
        ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
        self.inputs = {
            "X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var,
        }
        self.attrs = {"epsilon": eps, "momentum": 0.9}
        self.outputs = {
            "Y": y,
            "MeanOut": 0.9 * mean + 0.1 * mu,
            "VarianceOut": 0.9 * var + 0.1 * sig2,
        }

    def check_output(self, **kw):
        super(TestBatchNormTrain, self).check_output(atol=1e-4, **kw)


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def setup(self):
        x = np.random.rand(4, 6).astype("float32")
        scale = np.random.rand(6).astype("float32") + 0.5
        bias = np.random.rand(6).astype("float32")
        mu = x.mean(1, keepdims=True)
        sig = x.var(1, keepdims=True)
        y = (x - mu) / np.sqrt(sig + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": 1e-5, "begin_norm_axis": 1}
        self.outputs = {"Y": y, "Mean": mu.ravel(), "Variance": sig.ravel()}


class TestSumOp(OpTest):
    op_type = "sum"

    def setup(self):
        a = np.random.rand(3, 4).astype("float32")
        b = np.random.rand(3, 4).astype("float32")
        c = np.random.rand(3, 4).astype("float32")
        self.inputs = {"X": [("x0", a), ("x1", b), ("x2", c)]}
        self.outputs = {"Out": a + b + c}


class TestConcatOp(OpTest):
    op_type = "concat"

    def setup(self):
        a = np.random.rand(2, 3).astype("float32")
        b = np.random.rand(2, 5).astype("float32")
        self.attrs = {"axis": 1}
        self.inputs = {"X": [("ca", a), ("cb", b)]}
        self.outputs = {"Out": np.concatenate([a, b], 1)}


class TestLookupTable(OpTest):
    op_type = "lookup_table"

    def setup(self):
        w = np.random.rand(10, 4).astype("float32")
        ids = np.array([[1], [3], [5]], "int64")
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids.ravel()]}


class TestCosSim(OpTest):
    op_type = "cos_sim"

    def setup(self):
        x = np.random.rand(4, 3, 5).astype("float32") + 0.1
        y = np.random.rand(4, 3, 5).astype("float32") + 0.1
        xf = x.reshape(4, -1)
        yf = y.reshape(4, -1)
        xn = np.linalg.norm(xf, axis=1, keepdims=True)
        yn = np.linalg.norm(yf, axis=1, keepdims=True)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {
            "Out": (xf * yf).sum(1, keepdims=True) / (xn * yn),
            "XNorm": xn,
            "YNorm": yn,
        }


ALL_TESTS = [
    TestMulOp,
    TestMatmulTransposed,
    TestElementwiseAddBroadcast,
    TestSoftmaxOp,
    TestSoftmaxWithXentOp,
    TestReduceMean,
    TestConv2dOp,
    TestPool2dAvg,
    TestBatchNormTrain,
    TestLayerNorm,
    TestSumOp,
    TestConcatOp,
    TestLookupTable,
    TestCosSim,
]

GRAD_SPECS = {
    TestMulOp: (["X", "Y"], "Out"),
    TestMatmulTransposed: (["X", "Y"], "Out"),
    TestElementwiseAddBroadcast: (["X", "Y"], "Out"),
    TestSoftmaxOp: (["X"], "Out"),
    TestSoftmaxWithXentOp: (["Logits"], "Loss"),
    TestReduceMean: (["X"], "Out"),
    TestConv2dOp: (["Input", "Filter"], "Output"),
    TestPool2dAvg: (["X"], "Out"),
    TestBatchNormTrain: (["X", "Scale", "Bias"], "Y"),
    TestLayerNorm: (["X", "Scale", "Bias"], "Y"),
    TestSumOp: (["x0", "x1"], "Out"),
    TestConcatOp: (["ca", "cb"], "Out"),
    TestLookupTable: (["W"], "Out"),
    TestCosSim: (["X", "Y"], "Out"),
}


@pytest.mark.parametrize("cls", ALL_TESTS, ids=lambda c: c.__name__)
def test_output(cls):
    t = cls()
    no_check = ()
    if cls is TestBatchNormTrain:
        no_check = ("SavedMean", "SavedVariance")
    t.check_output(no_check_set=no_check)


@pytest.mark.parametrize(
    "cls", list(GRAD_SPECS), ids=lambda c: c.__name__ + "_grad"
)
def test_grad(cls):
    t = cls()
    inputs_to_check, out = GRAD_SPECS[cls]
    err, delta = 5e-3, 5e-3
    if cls in (TestBatchNormTrain, TestConv2dOp):
        # fp32 forward noise / (2*delta) dominates: widen delta + tolerance
        # (reference BN op tests run at comparable tolerances on fp32).
        err, delta = 5e-2, 2e-2
    t.check_grad(inputs_to_check, out, max_relative_error=err, delta=delta)
