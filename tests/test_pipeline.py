"""Pipeline-parallel (GPipe schedule) tests on the 8-device virtual CPU
mesh: forward/gradient parity vs the sequential network, and a training
loop whose pipelined losses track the non-pipelined run step for step.

Capability reference: the reference framework predates pipeline
parallelism (docs/DISTRIBUTED_DESIGN.md); design per
paddle_tpu/parallel/pipeline.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.parallel.pipeline import gpipe, stack_stage_params


def _stage(p, a):
    return jnp.tanh(a @ p["w"] + p["b"])


def _make(S, D, seed=0, scale=0.3):
    rng = np.random.RandomState(seed)
    stages = [
        {"w": jnp.asarray(rng.randn(D, D).astype("float32") * scale),
         "b": jnp.asarray(rng.randn(D).astype("float32") * 0.1)}
        for _ in range(S)
    ]
    return stages, stack_stage_params(stages)


def _sequential(stages, x):
    a = x
    for p in stages:
        a = _stage(p, a)
    return a


@pytest.mark.parametrize("S,M", [(4, 6), (8, 8), (2, 1)])
def test_gpipe_forward_matches_sequential(S, M):
    D, B = 8, 3
    stages, params = _make(S, D)
    x = jnp.asarray(np.random.RandomState(1).randn(M, B, D).astype("float32"))
    mesh = Mesh(np.asarray(jax.devices()[:S]), ("pipe",))
    out = gpipe(_stage, params, x, mesh)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stages, x)), atol=1e-5)


def test_gpipe_gradients_match_sequential():
    S, M, B, D = 4, 5, 2, 8
    stages, params = _make(S, D, seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(M, B, D).astype("float32"))
    mesh = Mesh(np.asarray(jax.devices()[:S]), ("pipe",))
    tgt = jnp.asarray(np.random.RandomState(4).randn(M, B, D).astype("float32"))

    def loss_pipe(params):
        return jnp.mean((gpipe(_stage, params, x, mesh) - tgt) ** 2)

    def loss_seq(stages):
        return jnp.mean((_sequential(stages, x) - tgt) ** 2)

    gp = jax.grad(loss_pipe)(params)
    gs = jax.grad(loss_seq)(stages)
    for i in range(S):
        np.testing.assert_allclose(
            np.asarray(gp["w"][i]), np.asarray(gs[i]["w"]), atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(gp["b"][i]), np.asarray(gs[i]["b"]), atol=1e-4)
    # grads also flow to the input
    gx = jax.grad(lambda x: jnp.sum(gpipe(_stage, params, x, mesh)))(x)
    assert np.isfinite(np.asarray(gx)).all()


def test_gpipe_training_tracks_sequential():
    """SGD on the pipelined loss must reproduce the sequential trajectory
    (the schedule is a layout, not a math change)."""
    S, M, B, D = 4, 4, 4, 6
    stages, params = _make(S, D, seed=5)
    mesh = Mesh(np.asarray(jax.devices()[:S]), ("pipe",))
    rng = np.random.RandomState(6)
    w_true = rng.randn(D, D).astype("float32") * 0.2

    def batch():
        x = rng.randn(M, B, D).astype("float32")
        y = np.tanh(x @ w_true)
        return jnp.asarray(x), jnp.asarray(y)

    def loss_pipe(params, x, y):
        return jnp.mean((gpipe(_stage, params, x, mesh) - y) ** 2)

    def loss_seq(stages, x, y):
        return jnp.mean((_sequential(stages, x) - y) ** 2)

    @jax.jit
    def step_pipe(params, x, y):
        l, g = jax.value_and_grad(loss_pipe)(params, x, y)
        return l, jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, params, g)

    @jax.jit
    def step_seq(stages, x, y):
        l, g = jax.value_and_grad(loss_seq)(stages, x, y)
        return l, jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, stages, g)

    lp_hist, ls_hist = [], []
    for _ in range(10):
        x, y = batch()
        lp, params = step_pipe(params, x, y)
        ls, stages = step_seq(stages, x, y)
        lp_hist.append(float(lp))
        ls_hist.append(float(ls))
    np.testing.assert_allclose(lp_hist, ls_hist, rtol=1e-4, atol=1e-5)
    assert lp_hist[-1] < lp_hist[0] * 0.7  # actually learning


def test_gpipe_rejects_wrong_stage_count():
    _, params = _make(4, 4)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("pipe",))
    x = jnp.zeros((2, 2, 4))
    with pytest.raises(ValueError):
        gpipe(_stage, params, x, mesh)


def test_gpipe_bubble_safe_for_nonfinite_at_zero_stages():
    """Stages that are non-finite at zero activations (log) must produce
    finite outputs AND gradients: bubbles are skipped via lax.cond."""
    S, M, B, D = 2, 3, 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:S]), ("pipe",))
    rng = np.random.RandomState(7)
    params = stack_stage_params([
        {"w": jnp.asarray(np.abs(rng.randn(D, D)).astype("float32") + 0.5)}
        for _ in range(S)
    ])
    x = jnp.asarray(np.abs(rng.randn(M, B, D)).astype("float32") + 1.0)

    def log_stage(p, a):
        return jnp.log(a @ p["w"] + 1.0)  # -inf at a == 0... if it ran

    def loss(params):
        return jnp.sum(gpipe(log_stage, params, x, mesh))

    val, grads = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(val))
    assert np.isfinite(np.asarray(grads["w"])).all()


def test_gpipe_scalar_leaf_rejected_with_clear_error():
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("pipe",))
    params = {"t": jnp.float32(1.0)}
    with pytest.raises(ValueError, match="leading stage dim"):
        gpipe(lambda p, a: a, params, jnp.zeros((2, 2, 4)), mesh)


def test_gpipe_composes_with_data_parallel():
    """pp x dp on a 2-D mesh: 4 stages x 2-way batch sharding; the batch
    stays sharded through the pipeline (no silent all-gather) and output
    matches the sequential net."""
    devs = np.asarray(jax.devices()).reshape(4, 2)
    mesh2 = Mesh(devs, ("pipe", "data"))
    S, M, B, D = 4, 4, 8, 8
    stages, params = _make(S, D, seed=9)
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = jax.device_put(params, NamedSharding(mesh2, P("pipe")))
    x = jnp.asarray(np.random.RandomState(10).randn(M, B, D)
                    .astype("float32"))
    x = jax.device_put(x, NamedSharding(mesh2, P(None, "data")))
    out = gpipe(_stage, params, x, mesh2, batch_axis="data")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stages, x)), atol=1e-5)
    assert "data" in tuple(out.sharding.spec), out.sharding
    # gradients under the composed sharding match the sequential net
    # (the data-axis psum in the transpose must happen)
    g = jax.jit(jax.grad(
        lambda p: jnp.sum(
            gpipe(_stage, p, x, mesh2, batch_axis="data") ** 2)))(params)
    gs = jax.grad(
        lambda st: jnp.sum(_sequential(st, x) ** 2))(stages)
    for i in range(S):
        np.testing.assert_allclose(
            np.asarray(g["w"][i]), np.asarray(gs[i]["w"]), atol=1e-4)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="batch_axis"):
        gpipe(_stage, params, x, mesh2, batch_axis="pipe")
    with _pytest.raises(ValueError, match="batch_axis"):
        gpipe(_stage, params, x, mesh2, batch_axis=0)
