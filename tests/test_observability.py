"""Flight-recorder coverage: metrics registry under threads, Prometheus
text golden, step-percentile math, the recompile explainer's
one-event-per-fresh-compile contract, and the unified chrome trace.
"""

import json
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, profiler, unique_name
from paddle_tpu.observability import explain, telemetry
from paddle_tpu.observability.metrics_registry import (
    REGISTRY,
    MetricsRegistry,
)


@pytest.fixture(autouse=True)
def _quiet_observability():
    """Telemetry off unless a test enables it; explainer memory scoped to
    the test so nearest-entry diffs see only this test's compiles. The
    process-global executable registry is purged so a structurally
    identical program from an earlier test can't serve this test's run
    (explainer events only fire on real trace misses)."""
    import paddle_tpu.executor as executor_mod

    executor_mod._shared_executables.clear()
    telemetry.enable(False)
    telemetry.reset(flops=True)
    explain.reset()
    yield
    telemetry.enable(False)
    telemetry.reset(flops=True)
    explain.reset()


def _build_mlp(width=8):
    unique_name.switch({})
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6])
        hid = fluid.layers.fc(x, size=width, act="relu")
        loss = fluid.layers.mean(fluid.layers.fc(hid, size=2))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _feed(bs=3):
    return {"x": np.arange(bs * 6, dtype="float32").reshape(bs, 6) / 10.0}


# -- registry ----------------------------------------------------------------

def test_registry_counter_exact_under_threads():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "x", labels=("worker",))
    h = reg.histogram("t_lat", "x", buckets=(0.5, 1.5))
    n_threads, n_iter = 8, 500

    def work(i):
        for _ in range(n_iter):
            c.inc(worker="w%d" % (i % 2))
            h.observe(1.0)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = c.value(worker="w0") + c.value(worker="w1")
    assert total == n_threads * n_iter
    snap = h.snapshot()
    assert snap["count"] == n_threads * n_iter
    assert snap["sum"] == pytest.approx(n_threads * n_iter * 1.0)
    # every 1.0 observation lands in the le=1.5 bucket, none in le=0.5
    assert snap["buckets"] == [0, n_threads * n_iter]


def test_registry_rejects_conflicting_reregistration():
    reg = MetricsRegistry()
    reg.counter("a_total", "x", labels=("k",))
    assert reg.counter("a_total", "x", labels=("k",)) is not None  # idempotent
    with pytest.raises(ValueError):
        reg.gauge("a_total", "x")
    with pytest.raises(ValueError):
        reg.counter("a_total", "x", labels=("other",))
    with pytest.raises(ValueError):
        reg.counter("b_total").inc(-1)


def test_prometheus_text_golden():
    reg = MetricsRegistry()
    c = reg.counter("steps_total", "steps run", labels=("executor",))
    g = reg.gauge("mem_bytes", "bytes in use")
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    c.inc(3, executor="single")
    c.inc(2, executor="async")
    g.set(1024)
    h.observe(0.0625)
    h.observe(0.5)
    h.observe(5.0)
    golden = "\n".join([
        "# HELP steps_total steps run",
        "# TYPE steps_total counter",
        'steps_total{executor="async"} 2',
        'steps_total{executor="single"} 3',
        "# HELP mem_bytes bytes in use",
        "# TYPE mem_bytes gauge",
        "mem_bytes 1024",
        "# HELP lat_seconds latency",
        "# TYPE lat_seconds histogram",
        'lat_seconds_bucket{le="0.1"} 1',
        'lat_seconds_bucket{le="1.0"} 2',
        'lat_seconds_bucket{le="+Inf"} 3',
        "lat_seconds_sum 5.5625",
        "lat_seconds_count 3",
        "",
    ])
    assert reg.to_prometheus() == golden


def test_registry_jsonl_snapshot_round_trip(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c_total").inc(7)
    path = str(tmp_path / "snap.jsonl")
    reg.write_jsonl(path)
    reg.counter("c_total").inc(1)
    reg.write_jsonl(path)
    with open(path) as f:
        snaps = [json.loads(line) for line in f]
    assert len(snaps) == 2
    assert snaps[0]["metrics"]["c_total"]["series"][0]["value"] == 7
    assert snaps[1]["metrics"]["c_total"]["series"][0]["value"] == 8


def test_global_registry_carries_exec_cache_collector():
    text = REGISTRY.to_prometheus()
    assert "paddle_tpu_fresh_compiles_total" in text
    assert "# TYPE paddle_tpu_exec_cache_hits_total counter" in text


# -- step telemetry ----------------------------------------------------------

def test_step_percentile_math():
    telemetry.enable(True)
    for ms in range(1, 101):  # 1..100 ms
        telemetry.record_step("single", ms / 1000.0)
    st = telemetry.step_stats()
    assert st["count"] == 100
    assert st["p50_ms"] == pytest.approx(50.0)
    assert st["p95_ms"] == pytest.approx(95.0)
    assert st["p99_ms"] == pytest.approx(99.0)
    assert st["mean_ms"] == pytest.approx(50.5)
    assert st["total_s"] == pytest.approx(5.05)


def test_step_stats_mfu_weights_by_fingerprint():
    telemetry.enable(True)
    telemetry.register_flops("fpA", 2e9)
    telemetry.record_step("single", 0.1, fingerprint="fpA")
    telemetry.record_step("single", 0.1, fingerprint="unknown")
    st = telemetry.step_stats(peak=100e9)
    # only the known-fingerprint record enters the MFU accounting
    assert st["flops_per_sec"] == pytest.approx(2e10)
    assert st["mfu"] == pytest.approx(0.2)
    assert st["count"] == 2


def test_async_dispatch_excluded_from_percentiles_and_mfu():
    """run_async records host dispatch latency (microseconds) — letting
    it into the MFU denominator would report MFU >> 1."""
    telemetry.enable(True)
    telemetry.register_flops("fp", 1e9)
    telemetry.record_step("single", 1.0, fingerprint="fp")
    telemetry.record_step("async", 1e-6, fingerprint="fp",
                          dispatch_only=True)
    st = telemetry.step_stats(peak=10e9)
    assert st["count"] == 2                      # both count as steps
    assert st["p50_ms"] == pytest.approx(1000.0)  # dispatch excluded
    assert st["mfu"] == pytest.approx(0.1)        # 1e9/1.0/10e9, not 1e6x
    recs = telemetry.step_records()
    assert [r["dispatch_only"] for r in recs] == [False, True]


def test_telemetry_reset_keeps_flop_table():
    """Phase-scoped reset() (tools/step_breakdown.py) must not lose the
    per-fingerprint FLOPs: executables register them only once."""
    telemetry.enable(True)
    telemetry.register_flops("fp", 1e9)
    telemetry.record_step("single", 1.0, fingerprint="fp")
    telemetry.reset()
    telemetry.record_step("single", 1.0, fingerprint="fp")
    assert telemetry.step_stats(peak=1e9)["mfu"] == pytest.approx(1.0)
    telemetry.reset(flops=True)
    telemetry.record_step("single", 1.0, fingerprint="fp")
    assert telemetry.step_stats(peak=1e9)["mfu"] is None


def test_registry_reset_keeps_module_handles_alive():
    reg = MetricsRegistry()
    c = reg.counter("h_total", "x")
    c.inc(5)
    reg.reset()
    assert c.value() == 0
    c.inc(2)  # the pre-reset handle still feeds the scrape
    assert "h_total 2" in reg.to_prometheus()


def test_multi_step_record_divides_per_step():
    telemetry.enable(True)
    telemetry.record_step("multi_step", 1.0, steps=10)
    st = telemetry.step_stats()
    assert st["count"] == 10
    assert st["p50_ms"] == pytest.approx(100.0)


def test_step_timer_and_callbacks():
    telemetry.enable(True)
    seen = []
    telemetry.add_step_callback(seen.append)
    try:
        with telemetry.StepTimer("trainer", feed_bytes=64):
            pass
    finally:
        telemetry.remove_step_callback(seen.append)
    assert len(seen) == 1
    assert seen[0]["executor"] == "trainer"
    assert seen[0]["feed_bytes"] == 64
    assert telemetry.step_stats()["count"] == 1


def test_executor_records_steps_and_bytes():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    telemetry.reset()
    for _ in range(4):
        exe.run(main, feed=_feed(), fetch_list=[loss])
    recs = telemetry.step_records()
    assert len(recs) == 4
    assert all(r["executor"] == "single" for r in recs)
    assert all(r["feed_bytes"] == 18 * 4 for r in recs)  # 3x6 f32
    assert all(r["fetch_bytes"] == 4 for r in recs)      # scalar f32 loss
    assert all(r["wall_s"] > 0 and r["h2d_seconds"] >= 0 for r in recs)
    st = profiler.step_stats(peak=1e12)  # the profiler-surface alias
    assert st["count"] == 4 and st["p50_ms"] is not None
    assert st["mfu"] is not None and st["mfu"] > 0


def test_flops_keyed_per_executable_not_per_program():
    """Two feed shapes of one program compile to two executables with
    different FLOP counts; a program-level key would let the second
    overwrite the first and mis-price every step."""
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    telemetry.reset(flops=True)
    exe.run(main, feed=_feed(bs=3), fetch_list=[loss])
    exe.run(main, feed=_feed(bs=6), fetch_list=[loss])
    recs = telemetry.step_records()
    fps = [r["fingerprint"] for r in recs]
    assert fps[0] != fps[1]
    from paddle_tpu.observability.telemetry import _flops

    assert fps[0] in _flops and fps[1] in _flops
    # both estimates survive side by side, and the bigger batch does
    # more work (not exactly 2x: the optimizer update is batch-free)
    assert _flops[fps[1]] > _flops[fps[0]]


def test_fetch_handle_records_materialize_histogram():
    from paddle_tpu.observability.telemetry import _fetch_materialize

    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    before = _fetch_materialize.snapshot()["count"]
    handle = exe.run_async(main, feed=_feed(), fetch_list=[loss])
    handle.result()
    handle.result()  # memoized: no second observation
    assert _fetch_materialize.snapshot()["count"] == before + 1
    # telemetry off -> hot path untouched, nothing recorded
    telemetry.enable(False)
    h2 = exe.run_async(main, feed=_feed(), fetch_list=[loss])
    assert h2._t_dispatch is None and h2._track is None
    h2.result()
    assert _fetch_materialize.snapshot()["count"] == before + 1


# -- recompile explainer -----------------------------------------------------

def test_explainer_fires_once_per_fresh_compile_and_stays_quiet_warm():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[loss])
    n = len(explain.events())
    assert n >= 1  # startup + train compiles, one event each
    for _ in range(3):  # warm reruns: zero new events
        exe.run(main, feed=_feed(), fetch_list=[loss])
    assert len(explain.events()) == n


def test_explainer_names_feed_spec_change():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(bs=3), fetch_list=[loss])
    n = len(explain.events())
    exe.run(main, feed=_feed(bs=5), fetch_list=[loss])  # induced shape change
    events = explain.events()
    assert len(events) == n + 1
    ev = events[-1]
    assert ev["changed"] == ["feed_specs"]
    assert "(3, 6)" in ev["detail"]["feed_specs"]
    assert "(5, 6)" in ev["detail"]["feed_specs"]


def test_explainer_names_flag_change():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[loss])
    n = len(explain.events())
    flags.set_flag("remat_gradients", True)
    try:
        exe.run(main, feed=_feed(), fetch_list=[loss])
    finally:
        flags.set_flag("remat_gradients", False)
    events = explain.events()
    assert len(events) == n + 1
    assert events[-1]["changed"] == ["flags"]
    assert "remat_gradients" in events[-1]["detail"]["flags"]


def test_explainer_counts_in_registry():
    from paddle_tpu.observability.explain import _recompiles

    before = _recompiles.value(changed="feed_specs")
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed=_feed(bs=2), fetch_list=[loss])
    exe.run(main, feed=_feed(bs=7), fetch_list=[loss])
    assert _recompiles.value(changed="feed_specs") == before + 1


# -- unified chrome trace ----------------------------------------------------

def test_chrome_trace_merges_threads_compiles_and_async(tmp_path):
    main, startup, loss = _build_mlp(width=16)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    trace_path = str(tmp_path / "trace.json")
    with profiler.profiler(profile_path=trace_path):
        with profiler.RecordEvent("main_work"):
            exe.run(main, feed=_feed(), fetch_list=[loss])

        def side():
            with profiler.RecordEvent("side_work"):
                pass

        t = threading.Thread(target=side)
        t.start()
        t.join()
        handle = exe.run_async(main, feed=_feed(), fetch_list=[loss])
        handle.result()
    with open(trace_path) as f:
        trace = json.load(f)  # round-trips through json.load
    events = trace["traceEvents"]
    host = [e for e in events if e.get("cat") == "host"]
    names = {e["name"] for e in host}
    assert {"main_work", "side_work"} <= names
    # thread-correct: the two RecordEvents ran on different threads
    tid_of = {e["name"]: e["tid"] for e in host}
    assert tid_of["main_work"] != tid_of["side_work"]
    # every span carries a unique id
    span_ids = [e["args"]["span_id"] for e in events if e["ph"] == "X"]
    assert len(span_ids) == len(set(span_ids))
    # compile spans from the exec-cache monitoring taps are in-stream
    assert any(e.get("cat") == "compile" for e in events)
    # async-fetch lifetime: nestable begin/instant/end sharing one id
    fetch = [e for e in events if e.get("cat") == "async_fetch"]
    phases = sorted(e["ph"] for e in fetch)
    assert phases == ["b", "e", "n"]
    assert len({e["id"] for e in fetch}) == 1
    # thread metadata rows name every referenced tid
    meta_tids = {e["tid"] for e in events if e["ph"] == "M"}
    assert {e["tid"] for e in host} <= meta_tids


def test_stop_profiler_quiet_by_default(capsys):
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with profiler.profiler(profile_path="/dev/null"):
        with profiler.RecordEvent("quiet_step"):
            exe.run(main, feed=_feed(), fetch_list=[loss])
    assert capsys.readouterr().out == ""
    with profiler.profiler(profile_path="/dev/null", print_report=True):
        with profiler.RecordEvent("loud_step"):
            exe.run(main, feed=_feed(), fetch_list=[loss])
    out = capsys.readouterr().out
    assert "Profiling Report" in out and "loud_step" in out


def test_profiler_event_appends_race_free():
    """Concurrent RecordEvents from many threads must all land (the old
    plain-list append dropped events under the GIL's mercy and exported
    every span as tid=0)."""
    profiler.start_profiler()
    n_threads, n_events = 8, 200
    barrier = threading.Barrier(n_threads)

    def work():
        barrier.wait()  # all threads alive at once -> distinct idents
        for i in range(n_events):
            with profiler.RecordEvent("race"):
                pass

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with profiler._lock:
        count = sum(1 for e in profiler._state["events"]
                    if e["name"] == "race")
        tids = {e["tid"] for e in profiler._state["events"]
                if e["name"] == "race"}
    profiler.stop_profiler(profile_path="/dev/null")
    assert count == n_threads * n_events
    assert len(tids) == n_threads


# -- flush / files -----------------------------------------------------------

def test_flush_writes_prometheus_and_steps_jsonl(tmp_path):
    telemetry.enable(True)
    telemetry.record_step("single", 0.01)
    path = str(tmp_path / "metrics.prom")
    assert telemetry.flush(path) == path
    with open(path) as f:
        text = f.read()
    assert "paddle_tpu_steps_total" in text
    with open(path + ".steps.jsonl") as f:
        recs = [json.loads(line) for line in f]
    assert recs and recs[-1]["executor"] == "single"
