"""Host-plane concurrency analysis (PR 18).

Each C rule gets a minimal bad module that must trigger it (asserting
the rule id) and a clean twin that must not; the suppression grammar is
exercised both ways (reasoned marker silences, bare marker is its own
C000 finding and silences nothing); the runtime lock witness is proved
on an ABBA order cycle, a hold spanning a (fake and real) device
dispatch, and the zero-overhead-off contract; and the timed-acquire
C003 fixes in blackbox/watchdog/memory get degrade regression tests.
The whole-tree lint run at the bottom is the same gate CI's conclint
stage applies.
"""

import os
import threading
import time

import pytest

from paddle_tpu.analysis import concurrency
from paddle_tpu.analysis.concurrency import lint_source
from paddle_tpu.observability import lock_witness as lw

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(diags):
    return sorted({d.rule for d in diags})


def _lint(src):
    return lint_source(src, filename="m.py", module="m")


# ---------------------------------------------------------------------------
# lint rules: one bad module + one clean twin each
# ---------------------------------------------------------------------------


class TestLintRules(object):
    def test_c001_lock_order_cycle(self):
        ds = _lint("""
import threading
_a = threading.Lock()
_b = threading.Lock()
def f():
    with _a:
        with _b:
            pass
def g():
    with _b:
        with _a:
            pass
""")
        assert _rules(ds) == ["C001"]
        assert "m._a" in ds[0].message and "m._b" in ds[0].message

    def test_c001_consistent_order_clean(self):
        assert _lint("""
import threading
_a = threading.Lock()
_b = threading.Lock()
def f():
    with _a:
        with _b:
            pass
def g():
    with _a:
        with _b:
            pass
""") == []

    def test_c002_blocking_call_under_lock(self):
        ds = _lint("""
import threading
_lock = threading.Lock()
def send(sock, data):
    with _lock:
        sock.sendall(data)
""")
        assert _rules(ds) == ["C002"]
        assert "sendall" in ds[0].message

    def test_c002_fetch_result_under_lock(self):
        ds = _lint("""
import threading
_lock = threading.Lock()
def wait(handle):
    with _lock:
        return handle.result()
""")
        assert _rules(ds) == ["C002"]

    def test_c002_send_outside_lock_clean(self):
        assert _lint("""
import threading
_lock = threading.Lock()
def send(sock, data):
    with _lock:
        n = len(data)
    sock.sendall(data)
""") == []

    def test_c003_untimed_acquire_reachable_from_handler(self):
        ds = _lint("""
import signal
import threading
_lock = threading.Lock()
def _flush():
    with _lock:
        pass
def _handler(signum, frame):
    _flush()
signal.signal(signal.SIGTERM, _handler)
""")
        assert _rules(ds) == ["C003"]
        # the finding names the reach chain, not just the site
        assert "_handler -> _flush" in ds[0].message

    def test_c003_timed_acquire_clean(self):
        assert _lint("""
import signal
import threading
_lock = threading.Lock()
def _flush():
    if not _lock.acquire(timeout=1.0):
        return
    try:
        pass
    finally:
        _lock.release()
def _handler(signum, frame):
    _flush()
signal.signal(signal.SIGTERM, _handler)
""") == []

    def test_c004_unnamed_thread(self):
        ds = _lint("""
import threading
def start(fn):
    t = threading.Thread(target=fn)
    t.start()
""")
        assert _rules(ds) == ["C004"]

    def test_c004_named_thread_clean(self):
        assert _lint("""
import threading
def start(fn):
    t = threading.Thread(target=fn, name="paddle-tpu-worker")
    t.start()
""") == []

    def test_c005_unguarded_global_write_from_thread_target(self):
        ds = _lint("""
import threading
_state = {}
def _worker():
    _state["k"] = 1
def start():
    threading.Thread(target=_worker, name="w").start()
""")
        assert _rules(ds) == ["C005"]
        assert ds[0].severity == "warning"

    def test_c005_guarded_write_clean(self):
        assert _lint("""
import threading
_state = {}
_lock = threading.Lock()
def _worker():
    with _lock:
        _state["k"] = 1
def start():
    threading.Thread(target=_worker, name="w").start()
""") == []

    def test_c006_wait_without_predicate_loop(self):
        ds = _lint("""
import threading
_cond = threading.Condition()
def take():
    with _cond:
        if True:
            _cond.wait()
""")
        assert _rules(ds) == ["C006"]

    def test_c006_wait_in_while_clean(self):
        assert _lint("""
import threading
_cond = threading.Condition()
def take(ready):
    with _cond:
        while not ready():
            _cond.wait()
""") == []

    def test_witness_factories_are_lock_ctors(self):
        # locks built through the lock_witness factories participate in
        # the same analysis as plain threading ctors
        ds = _lint("""
from paddle_tpu.observability import lock_witness
_a = lock_witness.make_lock("m.a")
_b = lock_witness.make_lock("m.b")
def f():
    with _a:
        with _b:
            pass
def g():
    with _b:
        with _a:
            pass
""")
        assert _rules(ds) == ["C001"]


# ---------------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------------


class TestSuppressions(object):
    _BAD = """
import threading
_lock = threading.Lock()
def send(sock, data):
    with _lock:
        %s
        sock.sendall(data)
"""

    def test_reasoned_marker_silences(self):
        src = self._BAD % (
            "# conclint: C002 reason=handshake frame, sub-ms by contract")
        assert _lint(src) == []

    def test_bare_marker_is_c000_and_silences_nothing(self):
        ds = _lint(self._BAD % "# conclint: C002")
        assert _rules(ds) == ["C000", "C002"]

    def test_marker_for_other_rule_does_not_silence(self):
        ds = _lint(self._BAD % "# conclint: C004 reason=wrong rule")
        assert "C002" in _rules(ds)

    def test_global_suppress_list(self):
        src = self._BAD % "pass"
        assert _rules(_lint(src)) == ["C002"]
        assert lint_source(src, filename="m.py", module="m",
                           suppress=("C002",)) == []


# ---------------------------------------------------------------------------
# runtime lock witness
# ---------------------------------------------------------------------------


@pytest.fixture
def witness():
    lw.enable()
    lw.reset()
    try:
        yield lw
    finally:
        lw.disable()
        lw.reset()


class TestLockWitness(object):
    def test_zero_overhead_when_off(self):
        assert not lw.ENABLED  # the suite runs with the flag unset
        assert type(lw.make_lock("t.off")) is type(threading.Lock())
        assert type(lw.make_rlock("t.off")) is type(threading.RLock())
        assert isinstance(lw.make_condition("t.off"), threading.Condition)
        # off-path construction registers nothing
        assert "t.off" not in lw.registered_locks()

    def test_abba_cycle_detected_without_deadlock(self, witness):
        a = lw.make_lock("t.a")
        b = lw.make_lock("t.b")
        with a:
            with b:
                pass
        with b:
            with a:  # opposite order: closes the cycle in the graph
                pass
        rep = lw.report()
        assert not rep["degraded"]
        assert rep["edges"]["t.a -> t.b"] == 1
        assert rep["edges"]["t.b -> t.a"] == 1
        assert len(rep["cycles"]) == 1
        assert set(rep["cycles"][0]["cycle"]) == {"t.a", "t.b"}

    def test_consistent_order_no_cycle(self, witness):
        a = lw.make_lock("t.a")
        b = lw.make_lock("t.b")
        for _ in range(3):
            with a:
                with b:
                    pass
        rep = lw.report()
        assert rep["edges"] == {"t.a -> t.b": 3}
        assert rep["cycles"] == []

    def test_rlock_reacquire_records_no_edge(self, witness):
        r = lw.make_rlock("t.r")
        with r:
            with r:  # reentrant: depth bump, not a t.r -> t.r edge
                pass
        assert lw.report()["edges"] == {}

    def test_long_hold_across_dispatch(self, witness):
        g = lw.make_lock("t.guard")
        with g:
            lw.note_dispatch()
        rep = lw.report()
        assert len(rep["long_holds"]) == 1
        assert rep["long_holds"][0]["locks"] == ["t.guard"]

    def test_allow_dispatch_exempt(self, witness):
        g = lw.make_lock("t.serial", allow_dispatch=True)
        with g:
            lw.note_dispatch()
        assert lw.report()["long_holds"] == []

    def test_no_hold_no_long_hold(self, witness):
        lw.note_dispatch()
        assert lw.report()["long_holds"] == []

    def test_held_by_thread_annotation(self, witness):
        g = lw.make_lock("t.held")
        ident = threading.get_ident()
        with g:
            assert lw.held_by_thread().get(ident) == ["t.held"]
        assert ident not in lw.held_by_thread()

    def test_condition_interop(self, witness):
        cond = lw.make_condition("t.cond")
        box = []

        def consumer():
            with cond:
                while not box:
                    cond.wait(timeout=5.0)

        t = threading.Thread(target=consumer, name="t-cond-consumer")
        t.start()
        time.sleep(0.05)
        with cond:
            box.append(1)
            cond.notify()
        t.join(timeout=5.0)
        assert not t.is_alive()

    def test_cross_thread_orders_merge(self, witness):
        # the graph merges per-thread orders: thread 1 takes a->b,
        # thread 2 takes b->a; neither deadlocks (they never overlap)
        # but the union is the latent ABBA
        a = lw.make_lock("t.x")
        b = lw.make_lock("t.y")

        def one_order(first, second):
            with first:
                with second:
                    time.sleep(0.01)

        t1 = threading.Thread(target=one_order, args=(a, b), name="t-ab")
        t1.start()
        t1.join()
        t2 = threading.Thread(target=one_order, args=(b, a), name="t-ba")
        t2.start()
        t2.join()
        assert len(lw.report()["cycles"]) == 1

    def test_executor_dispatch_is_witnessed(self, witness):
        # executor._dispatch calls note_dispatch(): holding a witnessed
        # lock across a real run must surface as a long hold
        import numpy as np

        import paddle_tpu as fluid

        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [4])
            y = fluid.layers.fc(input=x, size=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        g = lw.make_lock("t.across_dispatch")
        with g:
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y])
        holds = lw.report()["long_holds"]
        assert any("t.across_dispatch" in h["locks"] for h in holds)


# ---------------------------------------------------------------------------
# C003 fix regressions: handler-reachable paths degrade, never block
# ---------------------------------------------------------------------------


class TestTimedAcquireDegrade(object):
    def test_blackbox_record_drops_instead_of_blocking(self):
        from paddle_tpu.observability import blackbox

        assert blackbox._lock.acquire(timeout=1.0)
        try:
            t0 = time.monotonic()
            blackbox.record("conclint_test_event")  # must time out + drop
            elapsed = time.monotonic() - t0
        finally:
            blackbox._lock.release()
        assert elapsed < 5.0
        assert all(e["kind"] != "conclint_test_event"
                   for e in blackbox.events())

    def test_watchdog_unregister_degrades(self):
        from paddle_tpu.observability import watchdog

        assert watchdog._lock.acquire(timeout=1.0)
        try:
            t0 = time.monotonic()
            watchdog.unregister_on_hang(lambda: None)
            elapsed = time.monotonic() - t0
        finally:
            watchdog._lock.release()
        assert elapsed < 5.0

    def test_memory_track_degrades(self):
        from paddle_tpu.observability import memory

        assert memory._lock.acquire(timeout=1.0)
        try:
            t0 = time.monotonic()
            memory.track("conclint-test", 128, kind="scratch")
            elapsed = time.monotonic() - t0
        finally:
            memory._lock.release()
        assert elapsed < 5.0
        # the wedged-lock visit dropped the entry (advisory ledger)
        assert all(h["name"] != "conclint-test"
                   for h in memory.top_holders(k=100))


# ---------------------------------------------------------------------------
# the tree itself: the CI conclint gate
# ---------------------------------------------------------------------------


class TestTreeIsClean(object):
    def test_package_lints_clean_at_info(self):
        pkg = os.path.join(_REPO, "paddle_tpu")
        diags = concurrency.lint_paths([pkg])
        assert diags == [], "\n".join(str(d) for d in diags)

    def test_rule_catalog_complete(self):
        assert set(concurrency.RULES) == {
            "C000", "C001", "C002", "C003", "C004", "C005", "C006"}
        for rule, (slug, severity) in concurrency.RULES.items():
            assert severity in ("info", "warning", "error"), rule
