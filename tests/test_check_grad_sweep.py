"""check_grad sweep over every hand-written vjp and masked/selective
lowering (VERDICT r2 item 7; reference model: unittests/op_test.py:400's
per-op check_grad coverage).

Targets: straight-through estimators (quantize, clip), dynamic-program
losses (warpctc, linear_chain_crf), flash attention (sdpa), Length-masked
sequence ops, and top-k / argmax-selective lowerings (top_k, maxout,
roi_pool). Inputs are chosen so the finite-difference window never
straddles a kink (clip bounds, argmax ties, huber delta); tolerances are
the harness defaults (max_relative_error=5e-3, delta=5e-3) unless noted.
"""

import numpy as np
import pytest

import paddle_tpu as fluid

from op_test import OpTest


def _t(op_type, inputs, out_shapes, attrs=None):
    """Grad-only OpTest: outputs only need correct SHAPES (check_grad uses
    the expected array for the random projection, not its values)."""
    t = OpTest()
    t.op_type = op_type
    t.inputs = inputs
    t.outputs = {k: np.zeros(v, "float32") for k, v in out_shapes.items()}
    t.attrs = dict(attrs or {})
    return t


# --- straight-through estimators ----------------------------------------
# Numeric differentiation of a rounding op sees a staircase, so the STE
# contract is checked ANALYTICALLY: the quantized output lives in the
# integer domain (round(x/scale * range)), and the straight-through grad
# is range/scale EVERYWHERE — unconditional pass-through of dout, exactly
# the reference grad kernel (quantize_ops.py _quantize docstring).
@pytest.mark.parametrize("op,extra_in,attrs", [
    ("fake_quantize_abs_max", {}, {"bit_length": 8}),
    ("fake_quantize_range_abs_max",
     {"InScale": np.asarray([0.9], "float32")},
     {"bit_length": 8, "window_size": 4, "is_test": False}),
], ids=["abs_max", "range_abs_max"])
def test_quantize_ste_grad_is_unconditional_passthrough(op, extra_in, attrs):
    x = np.random.RandomState(0).uniform(-1, 1, (3, 4)).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        block = main.global_block()
        xv = block.create_var(name="X", shape=x.shape, dtype="float32",
                              stop_gradient=False)
        feeds = {"X": x}
        ins = {"X": ["X"]}
        for slot, arr in extra_in.items():
            block.create_var(name=slot, shape=arr.shape,
                             dtype=str(arr.dtype))
            feeds[slot] = arr
            ins[slot] = [slot]
        block.create_var(name="Q", shape=None, dtype="float32")
        block.create_var(name="S", shape=None, dtype="float32")
        block.append_op(type=op, inputs=ins,
                        outputs={"Out": ["Q"], "OutScale": ["S"]},
                        attrs=attrs)
        loss = fluid.layers.reduce_sum(block.var("Q"))
        (g,) = fluid.calc_gradient(loss, [xv])
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        gv, sv = exe.run(main, feed=feeds, fetch_list=[g, "S"])
    qrange = float(2 ** (attrs["bit_length"] - 1) - 1)
    expected = np.full_like(x, qrange / float(np.ravel(sv)[0]))
    np.testing.assert_allclose(
        gv, expected, rtol=1e-6,
        err_msg="%s STE grad is not the unconditional range/scale "
                "pass-through" % op)


def test_clip_grad():
    # values placed > delta away from the +/-1 bounds: the window never
    # crosses a kink, inside-region grad 1, outside-region grad 0
    x = np.asarray([[-1.7, -0.6, -0.05], [0.3, 0.92, 1.8]], "float32")
    t = _t("clip", {"X": x}, {"Out": x.shape},
           {"min": -1.0, "max": 1.0})
    t.check_grad(["X"], "Out")


@pytest.mark.parametrize("scale", [0.4, 3.0], ids=["clipped", "passthru"])
def test_clip_by_norm_grad(scale):
    x = (np.random.RandomState(1).randn(2, 5) * scale).astype("float32")
    t = _t("clip_by_norm", {"X": x}, {"Out": x.shape}, {"max_norm": 1.0})
    t.check_grad(["X"], "Out")


# --- dynamic-program losses ---------------------------------------------
def test_warpctc_grad():
    rng = np.random.RandomState(2)
    B, T, V, L = 2, 5, 4, 2
    t = _t("warpctc", {
        "Logits": rng.randn(B, T, V).astype("float32"),
        "Label": rng.randint(1, V, (B, L)).astype("int32"),
        "LogitsLength": np.asarray([T, T - 1], "int32"),
        "LabelLength": np.asarray([L, L - 1], "int32"),
    }, {"Loss": (B, 1)}, {"blank": 0})
    # log-space DP in f32: fd cancellation noise dominates below ~2e-2
    t.check_grad(["Logits"], "Loss", max_relative_error=3e-2, delta=1e-2)


def test_linear_chain_crf_grad():
    rng = np.random.RandomState(3)
    B, T, K = 2, 4, 3
    t = _t("linear_chain_crf", {
        "Emission": rng.randn(B, T, K).astype("float32"),
        "Transition": (0.3 * rng.randn(K + 2, K)).astype("float32"),
        "Label": rng.randint(0, K, (B, T)).astype("int32"),
        "Length": np.asarray([T, T - 1], "int32"),
    }, {"LogLikelihood": (B, 1)})
    t.check_grad(["Emission", "Transition"], "LogLikelihood",
                 max_relative_error=1e-2)


# --- attention -----------------------------------------------------------
@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_sdpa_grad(causal):
    rng = np.random.RandomState(4)
    B, H, T, D = 1, 2, 4, 4
    t = _t("scaled_dot_product_attention", {
        "Q": rng.randn(B, H, T, D).astype("float32"),
        "K": rng.randn(B, H, T, D).astype("float32"),
        "V": rng.randn(B, H, T, D).astype("float32"),
    }, {"Out": (B, H, T, D)}, {"causal": causal})
    t.check_grad(["Q", "K", "V"], "Out", max_relative_error=1e-2)


# --- Length-masked sequence ops -----------------------------------------
@pytest.mark.parametrize("pooltype", ["AVERAGE", "SUM", "SQRT", "MAX"])
def test_sequence_pool_grad(pooltype):
    rng = np.random.RandomState(5)
    x = (rng.permutation(24).reshape(2, 4, 3) * 0.37).astype("float32")
    t = _t("sequence_pool",
           {"X": x, "Length": np.asarray([4, 2], "int32")},
           {"Out": (2, 3)}, {"pooltype": pooltype})
    t.check_grad(["X"], "Out")


def test_sequence_softmax_grad():
    rng = np.random.RandomState(6)
    t = _t("sequence_softmax",
           {"X": rng.randn(2, 5).astype("float32"),
            "Length": np.asarray([5, 3], "int32")},
           {"Out": (2, 5)})
    t.check_grad(["X"], "Out")


def test_sequence_conv_grad():
    rng = np.random.RandomState(7)
    B, T, D, ctx_len = 2, 5, 3, 3
    t = _t("sequence_conv", {
        "X": rng.randn(B, T, D).astype("float32"),
        "Filter": rng.randn(ctx_len * D, 4).astype("float32"),
        "Length": np.asarray([5, 4], "int32"),
    }, {"Out": (B, T, 4)},
        {"contextLength": ctx_len, "contextStart": -1, "contextStride": 1})
    t.check_grad(["X", "Filter"], "Out")


def test_sequence_expand_as_grad():
    rng = np.random.RandomState(8)
    t = _t("sequence_expand_as", {
        "X": rng.randn(2, 3).astype("float32"),
        "Y": rng.randn(2, 4, 3).astype("float32"),
    }, {"Out": (2, 4, 3)})
    t.check_grad(["X"], "Out", no_grad_set={"Y"})


# --- top-k / argmax-selective lowerings ----------------------------------
def test_top_k_grad():
    # distinct, well-separated values: the top-k set is stable in the
    # finite-difference window
    x = (np.arange(12, dtype="float32").reshape(2, 6) * 1.7) % 9.1
    t = _t("top_k", {"X": x}, {"Out": (2, 2)}, {"k": 2})
    t.check_grad(["X"], "Out")


def test_maxout_grad():
    x = (np.arange(24, dtype="float32").reshape(1, 4, 2, 3) * 3.1) % 7.3
    t = _t("maxout", {"X": x}, {"Out": (1, 2, 2, 3)}, {"groups": 2})
    t.check_grad(["X"], "Out")


def test_roi_pool_grad():
    x = (np.arange(32, dtype="float32").reshape(1, 2, 4, 4) * 2.3) % 11.0
    rois = np.asarray([[0.0, 0.0, 3.0, 3.0]], "float32")
    t = _t("roi_pool", {
        "X": x, "ROIs": rois,
        "RoisBatch": np.asarray([0], "int32"),
    }, {"Out": (1, 2, 2, 2)},
        {"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0})
    t.check_grad(["X"], "Out")


# --- windowed / padded reshapes ------------------------------------------
def test_im2sequence_grad():
    rng = np.random.RandomState(9)
    x = rng.randn(1, 2, 4, 4).astype("float32")
    t = _t("im2sequence", {"X": x}, {"Out": (4, 8)},
           {"kernels": [2, 2], "strides": [2, 2],
            "paddings": [0, 0, 0, 0]})
    t.check_grad(["X"], "Out")


def test_row_conv_grad():
    rng = np.random.RandomState(10)
    t = _t("row_conv", {
        "X": rng.randn(2, 5, 3).astype("float32"),
        "Filter": rng.randn(3, 3).astype("float32"),
    }, {"Out": (2, 5, 3)})
    t.check_grad(["X", "Filter"], "Out")


def test_pad_and_crop_grad():
    rng = np.random.RandomState(11)
    x = rng.randn(2, 3).astype("float32")
    t = _t("pad", {"X": x}, {"Out": (4, 6)},
           {"paddings": [1, 1, 2, 1], "pad_value": 0.5})
    t.check_grad(["X"], "Out")
    big = rng.randn(4, 6).astype("float32")
    t2 = _t("crop", {"X": big}, {"Out": (2, 3)},
            {"offsets": [1, 2], "shape": [2, 3]})
    t2.check_grad(["X"], "Out")


def test_prelu_grad():
    rng = np.random.RandomState(12)
    # keep values > delta away from the kink at 0
    x = rng.choice([-1.5, -0.7, 0.4, 1.2], (2, 4)).astype("float32")
    t = _t("prelu", {"X": x, "Alpha": np.asarray([0.25], "float32")},
           {"Out": (2, 4)}, {"mode": "all"})
    t.check_grad(["X", "Alpha"], "Out")


# --- piecewise losses (kink-aware inputs) --------------------------------
def test_huber_loss_grad():
    # residuals well inside (0.3) and outside (2.0) delta=1.0
    x = np.asarray([[0.0], [0.0], [1.0]], "float32")
    y = np.asarray([[0.3], [2.0], [-0.8]], "float32")
    t = _t("huber_loss", {"X": x, "Y": y}, {"Out": (3, 1)},
           {"delta": 1.0})
    t.check_grad(["X", "Y"], "Out")


def test_squared_l2_distance_grad():
    rng = np.random.RandomState(13)
    t = _t("squared_l2_distance", {
        "X": rng.randn(3, 4).astype("float32"),
        "Y": rng.randn(3, 4).astype("float32"),
    }, {"Out": (3, 1)})
    t.check_grad(["X", "Y"], "Out")


def test_rank_loss_grad():
    rng = np.random.RandomState(14)
    t = _t("rank_loss", {
        "Label": np.asarray([[1.0], [0.0], [1.0]], "float32"),
        "Left": rng.randn(3, 1).astype("float32"),
        "Right": rng.randn(3, 1).astype("float32"),
    }, {"Out": (3, 1)})
    t.check_grad(["Left", "Right"], "Out", no_grad_set={"Label"})


def test_margin_rank_loss_grad():
    # margins chosen so activated = margin - (x1 - x2) stays > delta
    # away from 0 (the relu kink)
    t = _t("margin_rank_loss", {
        "Label": np.asarray([[1.0], [1.0], [-1.0]], "float32"),
        "X1": np.asarray([[0.8], [-0.5], [0.6]], "float32"),
        "X2": np.asarray([[0.1], [0.4], [1.5]], "float32"),
    }, {"Out": (3, 1)}, {"margin": 0.1})
    t.check_grad(["X1", "X2"], "Out", no_grad_set={"Label"})


def test_hinge_loss_grad():
    # y*pred kept > delta away from the hinge at 1
    t = _t("hinge_loss", {
        "Logits": np.asarray([[0.3], [1.6], [-0.4]], "float32"),
        "Labels": np.asarray([[1.0], [1.0], [0.0]], "float32"),
    }, {"Loss": (3, 1)})
    t.check_grad(["Logits"], "Loss", no_grad_set={"Labels"})


def test_modified_huber_loss_grad():
    # y*pred in (-1, 1) quadratic region and < -1 linear region, away
    # from both kinks
    t = _t("modified_huber_loss", {
        "X": np.asarray([[0.3], [-1.8], [0.6]], "float32"),
        "Y": np.asarray([[1.0], [1.0], [0.0]], "float32"),
    }, {"Out": (3, 1)})
    t.check_grad(["X"], "Out", no_grad_set={"Y"})


# --- fused ops (graph_pattern fusion-pass targets) -----------------------
def test_fused_elemwise_activation_grad():
    rng = np.random.RandomState(11)
    x = rng.randn(3, 4).astype("float32") + 0.3  # keep relu off its kink
    y = rng.randn(3, 4).astype("float32") * 0.1
    t = _t("fused_elemwise_activation", {"X": x, "Y": y},
           {"Out": x.shape, "IntermediateOut": x.shape},
           {"functor_list": ["elementwise_add", "tanh"], "axis": -1})
    t.check_grad(["X", "Y"], "Out")


def test_fusion_lstm_grad():
    rng = np.random.RandomState(12)
    B, T, M, D = 2, 4, 3, 5
    t = _t("fusion_lstm", {
        "X": rng.randn(B, T, M).astype("float32"),
        "WeightX": rng.randn(M, 4 * D).astype("float32") * 0.3,
        "WeightH": rng.randn(D, 4 * D).astype("float32") * 0.3,
        "Bias": rng.randn(7 * D).astype("float32") * 0.1,
        "BiasX": rng.randn(4 * D).astype("float32") * 0.1,
    }, {"Hidden": (B, T, D), "Cell": (B, T, D)})
    t.check_grad(["X", "WeightX", "WeightH"], "Hidden",
                 max_relative_error=1e-2)


def test_fusion_gru_grad():
    rng = np.random.RandomState(13)
    B, T, M, D = 2, 4, 3, 5
    t = _t("fusion_gru", {
        "X": rng.randn(B, T, M).astype("float32"),
        "WeightX": rng.randn(M, 3 * D).astype("float32") * 0.3,
        "WeightH": rng.randn(D, 3 * D).astype("float32") * 0.3,
        "Bias": rng.randn(3 * D).astype("float32") * 0.1,
    }, {"Hidden": (B, T, D)})
    # f32 fd noise compounds through the recurrence; 2e-2 matches the
    # dynamic-rnn entries above
    t.check_grad(["X", "WeightX", "WeightH"], "Hidden",
                 max_relative_error=2e-2)


def test_fusion_seqconv_eltadd_relu_grad():
    rng = np.random.RandomState(14)
    B, T, D, M = 2, 6, 3, 4
    # keep pre-relu values away from zero so fd never crosses the kink
    t = _t("fusion_seqconv_eltadd_relu", {
        "X": rng.randn(B, T, D).astype("float32"),
        "Filter": rng.randn(3 * D, M).astype("float32") * 0.4,
        "Bias": np.full((M,), 1.5, "float32"),
    }, {"Out": (B, T, M)})
    t.check_grad(["X", "Filter"], "Out", max_relative_error=1e-2)
