"""Training-step observatory: the observe-don't-perturb contract
(OFF = silent, ON = bit-identical + zero fresh compiles), phase
coverage, the roofline/MFU join, starvation banking, the regression
detector naming the guilty phase, the bounded ring, and the
perf-ledger round trip."""

import json
import math
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core import exec_cache
from paddle_tpu.observability import step_profiler, telemetry
from paddle_tpu.resilience import chaos


@pytest.fixture(autouse=True)
def _quiet_profiler():
    """Profiler off + empty ring around every test; the process-global
    executable registry is purged so a structurally identical program
    from an earlier test can't hide a fresh compile from this one."""
    import paddle_tpu.executor as executor_mod

    executor_mod._shared_executables.clear()
    telemetry.enable(False)
    step_profiler.enable(False)
    step_profiler.reset()
    chaos.disable()
    yield
    step_profiler.enable(False)
    step_profiler.reset()
    chaos.disable()


def _build_mlp():
    unique_name.switch({})
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6])
        hid = fluid.layers.fc(x, size=8, act="relu")
        loss = fluid.layers.mean(fluid.layers.fc(hid, size=2))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def _feed(bs=3):
    return {"x": np.arange(bs * 6, dtype="float32").reshape(bs, 6) / 10.0}


def _leg(exe, main, startup, loss, singles=2, multi=8):
    """One schedule on a SHARED Executor with the run counter rewound:
    the step PRNG key folds the counter in, so identical counters replay
    identical init and step keys — legs compare executable for
    executable (the stepprof_smoke.py discipline, sized for pytest)."""
    exe._run_counter = 0
    exe.run(startup)
    out = []
    for _ in range(singles):
        out.append(exe.run(main, feed=_feed(), fetch_list=[loss])[0])
    out.append(
        exe.run_multi_step(main, multi, feed=_feed(), fetch_list=[loss])[0])
    return out


# -- the overhead contract ---------------------------------------------------

def test_off_is_silent_on_is_bit_identical_with_zero_fresh_compiles():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    _leg(exe, main, startup, loss)  # discarded: stabilizes scope-name keys

    off = _leg(exe, main, startup, loss)
    assert step_profiler.records() == []
    assert step_profiler.inflight() == []
    compiles_off = exec_cache.stats()["fresh_compiles"]

    step_profiler.enable(True)
    step_profiler.reset()
    try:
        on = _leg(exe, main, startup, loss)
    finally:
        step_profiler.enable(False)
    # the flag is deliberately NOT in core/fingerprint.TRACE_FLAGS:
    # flipping it can never bust a cache key
    assert exec_cache.stats()["fresh_compiles"] == compiles_off
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)
    assert step_profiler.records(), "profiled leg left no step records"
    assert step_profiler.inflight() == []


# -- coverage + the roofline join --------------------------------------------

def test_multi_step_record_covers_wall_and_joins_mfu():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    step_profiler.enable(True)
    try:
        exe.run(startup)
        exe.run_multi_step(main, 32, feed=_feed(), fetch_list=[loss])
    finally:
        step_profiler.enable(False)
    recs = [r for r in step_profiler.records()
            if not r.get("dispatch_only") and r["steps"] == 32]
    assert len(recs) == 1
    r = recs[0]
    assert set(r["phases"]) <= set(step_profiler.PHASES)
    assert r["phases"].get("dispatch", 0.0) > 0.0
    assert r["phases"].get("device", 0.0) > 0.0
    assert r["coverage"] >= 0.95, r
    assert r["step_s"] == pytest.approx(r["wall_s"] / 32)
    assert r["feed_bytes"] == _feed()["x"].nbytes
    assert r["fetch_bytes"] > 0
    # the one-shot cost join priced this executable: per-step FLOPs,
    # achieved-FLOP/s, achieved-MFU, all finite and positive
    assert r["flops_per_step"] > 0
    assert r["achieved_flops_per_sec"] > 0
    assert math.isfinite(r["achieved_mfu"]) and r["achieved_mfu"] > 0
    assert r["bound"] in ("compute", "bandwidth", "input", "host", "device")
    assert r["fingerprint"] in step_profiler.cost_table()


def test_cost_join_is_one_shot_per_executable():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    step_profiler.enable(True)
    try:
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=_feed(), fetch_list=[loss])
    finally:
        step_profiler.enable(False)
    table = step_profiler.cost_table()
    # recs[0] is the startup run (its own executable); the three train
    # steps share ONE fingerprint and price identically off the single
    # join
    train = [r for r in step_profiler.records()
             if not r.get("dispatch_only")][1:]
    assert len(train) == 3
    fps = {r["fingerprint"] for r in train}
    assert len(fps) == 1 and fps <= set(table)
    assert len({r["flops_per_step"] for r in train}) == 1
    assert all(r["flops_per_step"] > 0 for r in train)


# -- starvation banking ------------------------------------------------------

def test_input_wait_banked_to_the_calling_threads_next_step():
    step_profiler.enable(True)
    try:
        step_profiler.note_input_wait(0.05, site="test")
        sp = step_profiler.begin("t")
        assert sp.input_wait == pytest.approx(0.05)
        rec = step_profiler.finish(sp)
        assert rec["phases"]["input_wait"] == pytest.approx(0.05)
        assert rec["starvation_fraction"] > 0.0
        assert rec["bound"] == "input"
        # claimed exactly once: the next step starts clean
        assert step_profiler.begin("t").input_wait == 0.0
    finally:
        step_profiler.enable(False)


# -- the regression detector -------------------------------------------------

def test_detector_names_dispatch_on_injected_stall():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    step_profiler.enable(True)
    try:
        exe.run(startup)
        # baseline: enough identical steps for the rolling median+MAD
        # window to open (the detector is silent below _REG_MIN samples)
        for _ in range(step_profiler._REG_MIN + 2):
            exe.run(main, feed=_feed(), fetch_list=[loss])
        assert not any(r.get("regression")
                       for r in step_profiler.records())
        # one injected 0.25s stall INSIDE the dispatch bracket
        chaos.configure("slow@site=exec.dispatch,n=1,secs=0.25")
        rec = None
        exe.run(main, feed=_feed(), fetch_list=[loss])
        rec = [r for r in step_profiler.records()
               if r.get("regression")][-1]
    finally:
        chaos.disable()
        step_profiler.enable(False)
    v = rec["regression"]
    assert v["kind"] == "excursion"
    assert v["phase"] == "dispatch", v
    assert v["step_s"] > v["threshold_s"] > v["median_s"]
    assert v["phase_s"] > 0.2


def test_detector_rebases_after_sustained_drift():
    key = "drift-test"
    for _ in range(step_profiler._REG_MIN):
        with step_profiler._lock:
            step_profiler._detect_regression(key, 0.001, {"host": 0.001})
    kinds = []
    for _ in range(step_profiler._DRIFT_N + 1):
        with step_profiler._lock:
            v = step_profiler._detect_regression(key, 0.01, {"host": 0.01})
        kinds.append(v["kind"] if v else None)
    # excursions until the streak matures, ONE drift, then the rebased
    # baseline accepts the new regime (the +1th sample is healthy)
    assert kinds[:step_profiler._DRIFT_N - 1] == \
        ["excursion"] * (step_profiler._DRIFT_N - 1)
    assert kinds[step_profiler._DRIFT_N - 1] == "drift"
    assert kinds[step_profiler._DRIFT_N] is None


# -- the ring ----------------------------------------------------------------

def test_ring_is_bounded_and_snapshots_oldest_first():
    step_profiler.enable(True)
    try:
        for i in range(step_profiler.RING_CAP + 57):
            sp = step_profiler.begin("ring-%d" % i)
            step_profiler.finish(sp)
    finally:
        step_profiler.enable(False)
    recs = step_profiler.records()
    assert len(recs) == step_profiler.RING_CAP
    assert recs[0]["origin"] == "ring-57"
    assert recs[-1]["origin"] == "ring-%d" % (step_profiler.RING_CAP + 56)


def test_inflight_exposes_open_bracket_and_clears_on_finish():
    sp = step_profiler.begin("watchdog-target")
    sp.enter("dispatch")
    snap = step_profiler.inflight()
    assert len(snap) == 1
    assert snap[0]["origin"] == "watchdog-target"
    assert snap[0]["phase"] == "dispatch"
    step_profiler.finish(sp)
    assert step_profiler.inflight() == []


# -- the ledger round trip ---------------------------------------------------

def test_jsonl_flush_and_perf_ledger_round_trip(tmp_path):
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    step_profiler.enable(True)
    try:
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=_feed(), fetch_list=[loss])
    finally:
        step_profiler.enable(False)
    jsonl = tmp_path / "t.stepprof.jsonl"
    n = step_profiler.write_stepprof_jsonl(str(jsonl))
    assert n == len(step_profiler.records())
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert len(lines) == n

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import perf_ledger

    entry = perf_ledger.summarize_stepprof(lines)
    assert entry["records"] == 4  # startup + 3 train steps
    assert entry["phase_coverage"] >= 0.9
    assert entry["step_ms"]["p50"] > 0
    assert entry["regressions"] == 0
    assert math.isfinite(entry["achieved_mfu"])

    ledger = tmp_path / "ledger.jsonl"
    for label in ("a", "b"):
        perf_ledger.append_entry(str(ledger), {"stepprof": entry},
                                 label=label)
    assert len(perf_ledger.read_ledger(str(ledger))) == 2
    # identical trajectory points must gate clean (cmd_diff raises
    # SystemExit(1) on regression, returns on clean)
    perf_ledger.main(["diff", "--ledger", str(ledger)])

    # a slowed newest entry must FAIL the relative gate
    worse = dict(entry, step_ms={"p50": entry["step_ms"]["p50"] * 10,
                                 "p95": entry["step_ms"]["p95"] * 10})
    perf_ledger.append_entry(str(ledger), {"stepprof": worse}, label="c")
    with pytest.raises(SystemExit) as ex:
        perf_ledger.main(["diff", "--ledger", str(ledger)])
    assert ex.value.code == 1
