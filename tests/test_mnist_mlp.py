"""End-to-end slice: MNIST-style MLP trains to convergence on synthetic
data (book/test_recognize_digits.py parity, SURVEY.md §7 stage 2)."""

import numpy as np

import paddle_tpu as fluid


def _make_data(n=512, dim=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim).astype("float32") * 2.0
    labels = rng.randint(0, classes, size=n).astype("int64")
    x = centers[labels] + rng.randn(n, dim).astype("float32") * 0.5
    return x.astype("float32"), labels.reshape(n, 1)


def build_mlp(img_dim=64, classes=10):
    image = fluid.layers.data(name="img", shape=[img_dim], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    hidden = fluid.layers.fc(input=image, size=128, act="relu")
    hidden = fluid.layers.fc(input=hidden, size=64, act="relu")
    logits = fluid.layers.fc(input=hidden, size=classes, act=None)
    loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(input=logits, label=label)
    return image, label, avg_loss, acc


def test_mnist_mlp_converges():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        image, label, avg_loss, acc = build_mlp()
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(avg_loss)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    x, y = _make_data()
    bs = 64
    first_loss, last_loss, last_acc = None, None, None
    for epoch in range(6):
        for i in range(0, len(x), bs):
            loss_v, acc_v = exe.run(
                main,
                feed={"img": x[i : i + bs], "label": y[i : i + bs]},
                fetch_list=[avg_loss, acc],
            )
            if first_loss is None:
                first_loss = float(loss_v[0])
        last_loss, last_acc = float(loss_v[0]), float(acc_v[0])

    assert first_loss > last_loss, (first_loss, last_loss)
    assert last_loss < 0.5, last_loss
    assert last_acc > 0.85, last_acc


def test_program_cache_reused():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        image, label, avg_loss, _ = build_mlp()
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x, y = _make_data(n=128)
    exe.run(main, feed={"img": x[:64], "label": y[:64]}, fetch_list=[avg_loss])
    n_compiled = len(exe._cache)
    exe.run(main, feed={"img": x[64:], "label": y[64:]}, fetch_list=[avg_loss])
    assert len(exe._cache) == n_compiled  # same shapes -> cached executable


def test_infer_after_train():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        image, label, avg_loss, acc = build_mlp()
        test_program = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x, y = _make_data(n=256)
    for _ in range(40):
        exe.run(main, feed={"img": x, "label": y}, fetch_list=[avg_loss])
    acc_v, = exe.run(
        test_program, feed={"img": x, "label": y}, fetch_list=[acc]
    )
    assert float(acc_v[0]) > 0.9
