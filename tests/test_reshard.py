"""Checkpoint resharding tests: sharded checkpoint dialect, mesh-shape
round trips over the golden models' DERIVED plans, typed unsupported-
layout errors, and the offline inspector's shard verification.

The headline contract (ISSUE 9 acceptance): every golden model's derived
plan round-trips across mesh shapes 4 -> 2 -> 1 -> 4 with per-var sha256
equality on the reassembled host arrays — resharding is byte-lossless,
or it refuses loudly.
"""

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.elastic.reshard import (
    ReshardError,
    ShardedCheckpointManager,
    checkpoint_sharding,
    reassemble_checkpoint,
    reshard_checkpoint,
    shard_factors_for,
)
from paddle_tpu.parallel.sharding import derive_sharding
from paddle_tpu.resilience.checkpoint import (
    CheckpointManager,
    read_manifest,
    verify_checkpoint_dir,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _sha(arr):
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def _golden_state(name):
    """(program, {var: host array}) for one golden model, deterministic
    params (tests/golden_models.py discipline)."""
    import golden_models as gm

    with fluid.scope_guard(fluid.Scope()):
        pruned = gm.build_golden(name)[0]
        scope = fluid.global_scope()
        snap = {}
        for v in pruned.list_vars():
            if not getattr(v, "persistable", False):
                continue
            val = scope.get_value(v.name)
            if val is not None and hasattr(val, "shape"):
                snap[v.name] = np.asarray(val)
    return pruned, snap


def _round_trip(name, tmp_path):
    program, snap = _golden_state(name)
    want = {n: _sha(a) for n, a in snap.items()}
    plans = {w: derive_sharding(program, {"data": 1, "fsdp": w})
             for w in (4, 2, 1)}
    dirs = {w: str(tmp_path / ("w%d%s" % (w, tag)))
            for w, tag in ((4, ""), (2, ""), (1, ""))}
    dirs["4b"] = str(tmp_path / "w4b")
    ShardedCheckpointManager(dirs[4], plan=plans[4]).write_state(
        snap, step=0, serial=0)
    reshard_checkpoint(os.path.join(dirs[4], "checkpoint_0"), dirs[2],
                       plan=plans[2])
    reshard_checkpoint(os.path.join(dirs[2], "checkpoint_0"), dirs[1],
                       plan=plans[1])
    reshard_checkpoint(os.path.join(dirs[1], "checkpoint_0"), dirs["4b"],
                       plan=plans[4])
    out, manifest = reassemble_checkpoint(
        os.path.join(dirs["4b"], "checkpoint_0"))
    assert set(out) == set(snap)
    for n in out:
        assert _sha(out[n]) == want[n], (
            "%s: var %r bytes changed across 4->2->1->4" % (name, n))
    return plans, manifest


def test_golden_round_trip_mnist(tmp_path):
    plans, manifest = _round_trip("mnist", tmp_path)
    # the 4-way plan actually shards something, and the manifest names
    # the mesh it was written under
    assert shard_factors_for(plans[4])
    sharding = checkpoint_sharding(manifest)
    assert sharding["mesh_axes"] == {"data": 1, "fsdp": 4}
    assert any(f == 4 for f in sharding["factors"].values())


@pytest.mark.slow
@pytest.mark.parametrize("name", [
    "mnist", "resnet_cifar10", "vgg16", "googlenet", "se_resnext50",
    "alexnet", "stacked_lstm", "transformer", "machine_translation",
])
def test_golden_round_trip_every_model(name, tmp_path):
    """ISSUE 9 acceptance: EVERY golden model's derived plan survives
    the 4 -> 2 -> 1 -> 4 mesh walk byte-for-byte."""
    _round_trip(name, tmp_path)


def test_sharded_manager_writes_shard_files_and_restores(tmp_path):
    rng = np.random.RandomState(0)
    snap = {"big": rng.rand(8, 6).astype("float32"),
            "tiny": rng.rand(3).astype("float32")}
    d = str(tmp_path / "ck")
    m = ShardedCheckpointManager(d, factors={"big": 4},
                                 mesh_axes={"fsdp": 4})
    m.write_state(snap, rng={"base_seed": 7, "run_counter": 9},
                  step=5, serial=5)
    step_dir = os.path.join(d, "checkpoint_5")
    files = sorted(os.listdir(step_dir))
    assert "big.shard-00-of-04.npy" in files
    assert "big.shard-03-of-04.npy" in files
    assert "big.npy" not in files
    assert "tiny.npy" in files
    assert not verify_checkpoint_dir(step_dir)
    manifest = read_manifest(step_dir)
    meta = manifest["vars"]["big"]
    assert meta["factor"] == 4 and meta["shard_axis"] == 0
    assert sum(s["bytes"] for s in meta["shards"]) == meta["bytes"]
    assert manifest["rng"] == {"base_seed": 7, "run_counter": 9}

    # a PLAIN CheckpointManager restores the sharded dialect: scope gets
    # the reassembled full arrays (cross-dialect restore is what lets a
    # 1-device resume read a 4-way fleet checkpoint)
    with fluid.scope_guard(fluid.Scope()):
        plain = CheckpointManager(d, scope=fluid.global_scope())
        loaded = plain.restore()
        assert int(loaded["serial"]) == 5
        got = np.asarray(fluid.global_scope().get_value("big"))
        np.testing.assert_array_equal(got, snap["big"])


def test_io_load_checkpoint_reads_sharded_dialect(tmp_path):
    """fluid.io.load_checkpoint must reassemble elastic shard files —
    silently skipping a shard-file var would hand back a half-restored
    model."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16], stop_gradient=False)
        y = fluid.layers.fc(x, 64, bias_attr=False)
        fluid.layers.mean(y)
    w_name = main.global_block().all_parameters()[0].name
    rng = np.random.RandomState(4)
    want = rng.rand(16, 64).astype("float32")
    d = str(tmp_path / "ck")
    ShardedCheckpointManager(d, factors={w_name: 4}).write_state(
        {w_name: want}, step=0, serial=0)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        serial = fluid.io.load_checkpoint(exe, d, main_program=main)
        assert serial == 0
        got = np.asarray(fluid.global_scope().get_value(w_name))
    np.testing.assert_array_equal(got, want)


def test_shard_verification_catches_missing_and_byte_mismatch(tmp_path):
    rng = np.random.RandomState(1)
    snap = {"w": rng.rand(4, 4).astype("float32")}
    d = str(tmp_path / "ck")
    ShardedCheckpointManager(d, factors={"w": 2}).write_state(
        snap, step=0, serial=0)
    step_dir = os.path.join(d, "checkpoint_0")
    mpath = os.path.join(step_dir, "__manifest__.json")
    man = json.load(open(mpath))
    man["vars"]["w"]["shards"][1]["bytes"] -= 8
    json.dump(man, open(mpath, "w"))
    problems = verify_checkpoint_dir(step_dir)
    assert any("shard bytes" in p for p in problems), problems
    os.unlink(os.path.join(step_dir, "w.shard-00-of-02.npy"))
    problems = verify_checkpoint_dir(step_dir)
    assert any("missing file" in p for p in problems), problems
    with pytest.raises(ReshardError):
        reassemble_checkpoint(step_dir)


def test_unsupported_layouts_raise_typed_error_naming_the_var(tmp_path):
    """A tp column split (dim-1 shard) must refuse with the var's name
    — never silently replicate."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], stop_gradient=False)
        y = fluid.layers.fc(
            x, 16, param_attr=fluid.ParamAttr(name="colw"),
            bias_attr=False)
        fluid.layers.mean(y)
    # force a big enough param and a tp axis so the derived spec shards
    # the OUTPUT dim (column parallel: P(fsdp, tp))
    plan = derive_sharding(main, {"data": 1, "fsdp": 2, "tp": 2},
                           min_shard_numel=1)
    assert "tp" in str(plan.specs["colw"]) or any(
        "tp" in str(e) for e in plan.specs["colw"])
    with pytest.raises(ReshardError) as ei:
        shard_factors_for(plan)
    assert ei.value.var_name == "colw"
    assert "colw" in str(ei.value)

    with pytest.raises(ReshardError) as ei2:
        ShardedCheckpointManager(str(tmp_path / "ck"), plan=plan)
    assert ei2.value.var_name == "colw"


def test_factor_not_dividing_live_state_raises(tmp_path):
    m = ShardedCheckpointManager(str(tmp_path / "ck"), factors={"w": 3})
    with pytest.raises(ReshardError) as ei:
        m.write_state({"w": np.zeros((4, 2), "float32")}, step=0)
    assert ei.value.var_name == "w"


def test_reshard_checkpoint_rejects_factor_for_unknown_var(tmp_path):
    snap = {"w": np.zeros((4, 2), "float32")}
    src = str(tmp_path / "src")
    ShardedCheckpointManager(src).write_state(snap, step=0, serial=0)
    with pytest.raises(ReshardError) as ei:
        reshard_checkpoint(os.path.join(src, "checkpoint_0"),
                           str(tmp_path / "dst"), factors={"ghost": 2})
    assert ei.value.var_name == "ghost"


def test_reshard_preserves_rng_step_and_serial(tmp_path):
    rng = np.random.RandomState(2)
    snap = {"w": rng.rand(8, 2).astype("float32")}
    src = str(tmp_path / "src")
    ShardedCheckpointManager(src, factors={"w": 4}).write_state(
        snap, rng={"base_seed": 11, "run_counter": 23}, step=42, serial=42)
    dst = str(tmp_path / "dst")
    path = reshard_checkpoint(os.path.join(src, "checkpoint_42"), dst,
                              factors={"w": 2}, mesh_axes={"fsdp": 2})
    manifest = read_manifest(path)
    assert manifest["serial"] == 42 and manifest["step"] == 42
    assert manifest["rng"] == {"base_seed": 11, "run_counter": 23}
    assert checkpoint_sharding(manifest)["factors"] == {"w": 2}
    assert manifest["vars"]["w"]["factor"] == 2


def test_ckpt_inspect_prints_mesh_and_gates_shard_bytes(tmp_path):
    """Satellite: the offline inspector names the recorded mesh/factors
    and exits 2 on a shard-byte mismatch (jax-free diagnosis path)."""
    rng = np.random.RandomState(3)
    snap = {"w": rng.rand(8, 2).astype("float32")}
    d = str(tmp_path / "ck")
    ShardedCheckpointManager(
        d, factors={"w": 4}, mesh_axes={"data": 1, "fsdp": 4}).write_state(
        snap, step=0, serial=0)
    step_dir = os.path.join(d, "checkpoint_0")
    tool = os.path.join(REPO, "tools", "ckpt_inspect.py")
    r = subprocess.run([sys.executable, tool, step_dir, "--verify"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fsdp=4" in r.stdout and "w/4" in r.stdout
    assert "all digests match" in r.stdout
    mpath = os.path.join(step_dir, "__manifest__.json")
    man = json.load(open(mpath))
    man["vars"]["w"]["shards"][0]["bytes"] += 16
    json.dump(man, open(mpath, "w"))
    r2 = subprocess.run([sys.executable, tool, step_dir, "--verify"],
                        capture_output=True, text=True)
    assert r2.returncode == 2, r2.stdout + r2.stderr
    assert "shard bytes" in r2.stdout
