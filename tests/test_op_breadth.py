"""Breadth sweep part 1: op families that previously had no dedicated test.

Reference model: the per-op test files under
python/paddle/fluid/tests/unittests/ (op_test.py:131 OpTest, :400
check_grad) — one output-parity check against an independent numpy
mirror plus an analytic-vs-numeric gradient check per differentiable op.
Inputs are placed away from kinks (clip bounds, shrink thresholds,
argmax ties) so the finite-difference window never straddles a
non-smooth point; the numpy mirrors are written from the reference op
semantics (activation_op.cc, elementwise_op.h, reduce_op.h, ...), not
from this repo's lowerings.
"""

import numpy as np
import pytest

import paddle_tpu as fluid

from op_test import make_grad_test as _shapes, make_op_test as _t

_RNG = np.random.RandomState


def _away_from(rng, shape, kinks, margin=0.08, lo=-3.0, hi=3.0):
    """Uniform sample resampled until every element is > margin from
    every kink (finite differences use delta=5e-3, so 0.08 is safe)."""
    x = rng.uniform(lo, hi, shape)
    for _ in range(100):
        bad = np.zeros(x.shape, bool)
        for k in kinks:
            bad |= np.abs(x - k) < margin
        if not bad.any():
            break
        x[bad] = rng.uniform(lo, hi, int(bad.sum()))
    return x.astype("float32")


# --- activations ---------------------------------------------------------
def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_softplus(x):
    return np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0.0)


_ACTIVATIONS = {
    # name: (attrs, kinks, domain, numpy mirror)
    "logsigmoid": ({}, [], (-3, 3),
                   lambda x, a: np.minimum(x, 0) - np.log1p(np.exp(-np.abs(x)))),
    "tanh_shrink": ({}, [], (-3, 3), lambda x, a: x - np.tanh(x)),
    "sin": ({}, [], (-3, 3), lambda x, a: np.sin(x)),
    "reciprocal": ({}, [], (0.4, 3), lambda x, a: 1.0 / x),
    "softplus": ({}, [], (-3, 3), lambda x, a: _np_softplus(x)),
    "gelu": ({}, [], (-3, 3),
             lambda x, a: 0.5 * x * (1.0 + np.tanh(
                 np.sqrt(2.0 / np.pi) * (x + 0.044715 * x ** 3)))),
    "relu6": ({"threshold": 6.0}, [0.0, 6.0], (-3, 8),
              lambda x, a: np.clip(x, 0.0, 6.0)),
    "leaky_relu": ({"alpha": 0.1}, [0.0], (-3, 3),
                   lambda x, a: np.where(x >= 0, x, 0.1 * x)),
    "elu": ({"alpha": 1.0}, [0.0], (-3, 3),
            lambda x, a: np.where(x > 0, x, np.expm1(np.minimum(x, 0.0)))),
    "stanh": ({"scale_a": 2.0 / 3.0, "scale_b": 1.7159}, [], (-3, 3),
              lambda x, a: 1.7159 * np.tanh(x * 2.0 / 3.0)),
    "hard_sigmoid": ({"slope": 0.2, "offset": 0.5}, [-2.5, 2.5], (-4, 4),
                     lambda x, a: np.clip(0.2 * x + 0.5, 0.0, 1.0)),
    "thresholded_relu": ({"threshold": 1.0}, [1.0], (-3, 3),
                         lambda x, a: np.where(x > 1.0, x, 0.0)),
    "soft_relu": ({"threshold": 40.0}, [], (-3, 3),
                  lambda x, a: np.log1p(np.exp(np.clip(x, -40.0, 40.0)))),
    "brelu": ({"t_min": 0.0, "t_max": 24.0}, [0.0], (-3, 3),
              lambda x, a: np.clip(x, 0.0, 24.0)),
    "swish": ({"beta": 1.0}, [], (-3, 3), lambda x, a: x * _np_sigmoid(x)),
    "softshrink": ({"lambda": 0.5}, [-0.5, 0.5], (-3, 3),
                   lambda x, a: np.sign(x) * np.maximum(np.abs(x) - 0.5, 0)),
    "hard_shrink": ({"threshold": 0.5}, [-0.5, 0.5], (-3, 3),
                    lambda x, a: np.where(np.abs(x) > 0.5, x, 0.0)),
    "rsqrt": ({}, [], (0.4, 3), lambda x, a: 1.0 / np.sqrt(x)),
}


@pytest.mark.parametrize("name", sorted(_ACTIVATIONS), ids=sorted(_ACTIVATIONS))
def test_activation_output_and_grad(name):
    attrs, kinks, (lo, hi), mirror = _ACTIVATIONS[name]
    x = _away_from(_RNG(11), (3, 7), kinks, lo=lo, hi=hi)
    t = _t(name, {"X": x}, {"Out": mirror(x.astype("float64"), attrs)}, attrs)
    t.check_output(atol=1e-5, rtol=1e-4)
    t2 = _t(name, {"X": x}, {"Out": mirror(x.astype("float64"), attrs)}, attrs)
    t2.check_grad(["X"], "Out")


def test_log_softmax_output_and_grad():
    x = _RNG(12).randn(4, 6).astype("float32")
    x64 = x.astype("float64")
    expect = x64 - np.log(np.sum(np.exp(x64 - x64.max(-1, keepdims=True)),
                                 -1, keepdims=True)) - x64.max(-1, keepdims=True)
    t = _t("log_softmax", {"X": x}, {"Out": expect}, {"axis": -1})
    t.check_output()
    _t("log_softmax", {"X": x}, {"Out": expect},
       {"axis": -1}).check_grad(["X"], "Out", max_relative_error=1e-2)


# --- elementwise ---------------------------------------------------------
def test_elementwise_div_output_and_grad():
    rng = _RNG(13)
    x = rng.uniform(-2, 2, (3, 5)).astype("float32")
    y = _away_from(rng, (3, 5), [0.0], margin=0.5)
    t = _t("elementwise_div", {"X": x, "Y": y},
           {"Out": x.astype("float64") / y.astype("float64")})
    t.check_output()
    _shapes("elementwise_div", {"X": x, "Y": y},
            {"Out": (3, 5)}).check_grad(["X", "Y"], "Out")


@pytest.mark.parametrize("op,npf", [
    ("elementwise_max", np.maximum), ("elementwise_min", np.minimum),
], ids=["max", "min"])
def test_elementwise_minmax_output_and_grad(op, npf):
    rng = _RNG(14)
    x = rng.uniform(-2, 2, (3, 5)).astype("float32")
    # keep |x - y| > 0.2: the selection never flips inside the fd window
    y = x + np.where(rng.rand(3, 5) > 0.5, 1.0, -1.0).astype("float32") * \
        rng.uniform(0.2, 1.5, (3, 5)).astype("float32")
    t = _t(op, {"X": x, "Y": y}, {"Out": npf(x, y).astype("float64")})
    t.check_output()
    _shapes(op, {"X": x, "Y": y}, {"Out": (3, 5)}).check_grad(
        ["X", "Y"], "Out")


def test_elementwise_pow_output_and_grad():
    rng = _RNG(15)
    x = rng.uniform(0.3, 2.5, (3, 4)).astype("float32")
    y = rng.uniform(-2, 2, (3, 4)).astype("float32")
    t = _t("elementwise_pow", {"X": x, "Y": y},
           {"Out": np.power(x.astype("float64"), y.astype("float64"))})
    t.check_output()
    _shapes("elementwise_pow", {"X": x, "Y": y},
            {"Out": (3, 4)}).check_grad(["X", "Y"], "Out",
                                        max_relative_error=1e-2)


@pytest.mark.parametrize("op,npf", [
    ("elementwise_floordiv", lambda x, y: x // y),
    ("elementwise_mod", lambda x, y: x % y),
], ids=["floordiv", "mod"])
def test_elementwise_int_ops_output(op, npf):
    rng = _RNG(16)
    x = rng.randint(1, 50, (3, 5)).astype("int32")
    y = rng.randint(1, 7, (3, 5)).astype("int32")
    _t(op, {"X": x, "Y": y}, {"Out": npf(x, y)}).check_output()


def test_minus_output_and_grad():
    rng = _RNG(17)
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 4).astype("float32")
    t = _t("minus", {"X": x, "Y": y}, {"Out": (x - y).astype("float64")})
    t.check_output()
    _shapes("minus", {"X": x, "Y": y}, {"Out": (3, 4)}).check_grad(
        ["X", "Y"], "Out")


# --- reductions ----------------------------------------------------------
@pytest.mark.parametrize("op,npf", [
    ("reduce_max", np.max), ("reduce_min", np.min), ("reduce_prod", np.prod),
], ids=["max", "min", "prod"])
def test_reduce_output_and_grad(op, npf):
    rng = _RNG(18)
    # distinct, well-separated magnitudes: unique argmax/argmin per row,
    # and products stay O(1)
    x = (rng.permutation(24).reshape(4, 6) * 0.11 + 0.2).astype("float32")
    expect = npf(x.astype("float64"), axis=1)
    t = _t(op, {"X": x}, {"Out": expect}, {"dim": [1], "keep_dim": False})
    t.check_output()
    _shapes(op, {"X": x}, {"Out": (4,)},
            {"dim": [1], "keep_dim": False}).check_grad(
        ["X"], "Out", max_relative_error=1e-2)


# --- shape / movement ----------------------------------------------------
def test_reshape2_output_and_grad():
    x = _RNG(19).randn(3, 4).astype("float32")
    t = _t("reshape2", {"X": x}, {"Out": x.reshape(2, 6)}, {"shape": [2, 6]})
    t.check_output()
    _shapes("reshape2", {"X": x}, {"Out": (2, 6)},
            {"shape": [2, 6]}).check_grad(["X"], "Out")


@pytest.mark.parametrize("op", ["squeeze", "squeeze2"])
def test_squeeze_output_and_grad(op):
    x = _RNG(20).randn(3, 1, 4).astype("float32")
    t = _t(op, {"X": x}, {"Out": x.reshape(3, 4)}, {"axes": [1]})
    t.check_output()
    _shapes(op, {"X": x}, {"Out": (3, 4)}, {"axes": [1]}).check_grad(
        ["X"], "Out")


def test_transpose2_output_and_grad():
    x = _RNG(21).randn(2, 3, 4).astype("float32")
    t = _t("transpose2", {"X": x}, {"Out": x.transpose(1, 0, 2)},
           {"axis": [1, 0, 2]})
    t.check_output()
    _shapes("transpose2", {"X": x}, {"Out": (3, 2, 4)},
            {"axis": [1, 0, 2]}).check_grad(["X"], "Out")


def test_unstack_output_and_grad():
    x = _RNG(22).randn(3, 4).astype("float32")
    outs = [("y0", x[0]), ("y1", x[1]), ("y2", x[2])]
    t = _t("unstack", {"X": x}, {"Y": outs}, {"axis": 0, "num": 3})
    t.check_output()
    t2 = _t("unstack", {"X": x}, {"Y": outs}, {"axis": 0, "num": 3})
    t2.check_grad(["X"], "y1")


def test_scatter_output_and_grad():
    rng = _RNG(23)
    x = rng.randn(5, 3).astype("float32")
    ids = np.asarray([1, 3], "int32")
    upd = rng.randn(2, 3).astype("float32")
    expect = x.copy()
    expect[ids] = upd
    t = _t("scatter", {"X": x, "Ids": ids, "Updates": upd}, {"Out": expect},
           {"overwrite": True})
    t.check_output()
    _shapes("scatter", {"X": x, "Ids": ids, "Updates": upd},
            {"Out": (5, 3)}, {"overwrite": True}).check_grad(
        ["X", "Updates"], "Out")


def test_batched_gather_output_and_grad():
    rng = _RNG(24)
    x = rng.randn(2, 5, 3).astype("float32")
    idx = np.asarray([[0, 4, 2], [1, 1, 3]], "int32")
    expect = np.stack([x[b][idx[b]] for b in range(2)])
    t = _t("batched_gather", {"X": x, "Index": idx}, {"Out": expect})
    t.check_output()
    _shapes("batched_gather", {"X": x, "Index": idx},
            {"Out": (2, 3, 3)}).check_grad(["X"], "Out")


def test_where_select_output_and_grad():
    # Cond is a per-ROW selector [batch, 1]: the dense merge behind IfElse
    rng = _RNG(25)
    cond = (rng.rand(3, 1) > 0.5)
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 4).astype("float32")
    t = _t("where_select", {"Cond": cond, "X": x, "Y": y},
           {"Out": np.where(cond, x, y)})
    t.check_output()
    _shapes("where_select", {"Cond": cond, "X": x, "Y": y},
            {"Out": (3, 4)}).check_grad(["X", "Y"], "Out")


def test_pad2d_output_and_grad():
    x = _RNG(26).randn(2, 3, 4, 5).astype("float32")
    pads = [1, 2, 0, 1]  # top, bottom, left, right
    expect = np.pad(x, ((0, 0), (0, 0), (1, 2), (0, 1)), constant_values=0.5)
    t = _t("pad2d", {"X": x}, {"Out": expect},
           {"paddings": pads, "mode": "constant", "pad_value": 0.5})
    t.check_output()
    _shapes("pad2d", {"X": x}, {"Out": (2, 3, 7, 6)},
            {"paddings": pads, "mode": "constant",
             "pad_value": 0.5}).check_grad(["X"], "Out")


@pytest.mark.parametrize("mode", ["reflect", "edge"])
def test_pad2d_modes_output(mode):
    x = _RNG(27).randn(1, 2, 4, 4).astype("float32")
    np_mode = {"reflect": "reflect", "edge": "edge"}[mode]
    expect = np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 1)), mode=np_mode)
    _t("pad2d", {"X": x}, {"Out": expect},
       {"paddings": [1, 1, 2, 1], "mode": mode}).check_output()


def test_label_smooth_output_and_grad():
    x = np.eye(4, 6, dtype="float32")
    eps = 0.1
    expect = (1 - eps) * x + eps / 6.0
    t = _t("label_smooth", {"X": x}, {"Out": expect}, {"epsilon": eps})
    t.check_output()
    _shapes("label_smooth", {"X": x}, {"Out": (4, 6)},
            {"epsilon": eps}).check_grad(["X"], "Out")


def test_add_position_encoding_grad():
    x = _RNG(28).randn(2, 5, 8).astype("float32")
    _shapes("add_position_encoding", {"X": x}, {"Out": (2, 5, 8)},
            {"alpha": 1.0, "beta": 1.0}).check_grad(["X"], "Out")


def test_fill_zeros_like_output():
    x = _RNG(29).randn(3, 4).astype("float32")
    _t("fill_zeros_like", {"X": x}, {"Out": np.zeros_like(x)}).check_output()


def test_fill_constant_batch_size_like_output():
    x = np.zeros((5, 3), "float32")
    expect = np.full((5, 7), 2.5, "float32")
    _t("fill_constant_batch_size_like", {"Input": x}, {"Out": expect},
       {"shape": [1, 7], "value": 2.5, "dtype": "float32",
        "input_dim_idx": 0, "output_dim_idx": 0}).check_output()


def test_assign_value_output():
    vals = [0.5, -1.5, 2.0, 3.25, 0.0, -7.0]
    expect = np.asarray(vals, "float32").reshape(2, 3)
    _t("assign_value", {}, {"Out": expect},
       {"shape": [2, 3], "dtype": "float32", "values": vals}).check_output()


def test_arg_min_output():
    x = np.asarray([[3.0, 1.0, 2.0], [0.5, 4.0, -1.0]], "float32")
    _t("arg_min", {"X": x}, {"Out": np.argmin(x, 1)},
       {"axis": 1}).check_output()


# --- losses --------------------------------------------------------------
def test_bce_loss_output_and_grad():
    rng = _RNG(30)
    x = rng.uniform(0.05, 0.95, (4, 3)).astype("float32")
    label = (rng.rand(4, 3) > 0.5).astype("float32")
    x64, l64 = x.astype("float64"), label.astype("float64")
    expect = -(l64 * np.log(x64) + (1 - l64) * np.log(1 - x64))
    t = _t("bce_loss", {"X": x, "Label": label}, {"Out": expect})
    t.check_output()
    _shapes("bce_loss", {"X": x, "Label": label},
            {"Out": (4, 3)}).check_grad(["X"], "Out")


def test_log_loss_output_and_grad():
    rng = _RNG(31)
    p = rng.uniform(0.1, 0.9, (6, 1)).astype("float32")
    label = (rng.rand(6, 1) > 0.5).astype("float32")
    eps = 1e-4
    p64, l64 = p.astype("float64"), label.astype("float64")
    expect = -l64 * np.log(p64 + eps) - (1 - l64) * np.log(1 - p64 + eps)
    t = _t("log_loss", {"Predicted": p, "Labels": label}, {"Loss": expect},
           {"epsilon": eps})
    t.check_output()
    _shapes("log_loss", {"Predicted": p, "Labels": label},
            {"Loss": (6, 1)}, {"epsilon": eps}).check_grad(
        ["Predicted"], "Loss")


def test_kldiv_loss_grad():
    rng = _RNG(32)
    x = np.log(rng.dirichlet(np.ones(5), 4)).astype("float32")
    target = rng.dirichlet(np.ones(5), 4).astype("float32")
    _shapes("kldiv_loss", {"X": x, "Target": target}, {"Loss": ()},
            {"reduction": "mean"}).check_grad(["X"], "Loss")


def test_smooth_l1_loss_grad():
    rng = _RNG(33)
    x = rng.randn(4, 3).astype("float32")
    # |x - y| kept away from the quadratic/linear switch at 1/sigma^2 = 1
    d = np.where(rng.rand(4, 3) > 0.5,
                 rng.uniform(0.2, 0.8, (4, 3)),
                 rng.uniform(1.2, 1.8, (4, 3))).astype("float32")
    y = (x + d * np.where(rng.rand(4, 3) > 0.5, 1, -1)).astype("float32")
    iw = np.ones((4, 3), "float32")
    t = _shapes("smooth_l1_loss",
                {"X": x, "Y": y, "InsideWeight": iw, "OutsideWeight": iw},
                {"Out": (4, 1)}, {"sigma": 1.0})
    t.check_grad(["X", "Y"], "Out")


def test_sigmoid_cross_entropy_with_logits_output_and_grad():
    rng = _RNG(34)
    x = rng.randn(4, 5).astype("float32")
    label = rng.uniform(0, 1, (4, 5)).astype("float32")
    x64, l64 = x.astype("float64"), label.astype("float64")
    expect = np.maximum(x64, 0) - x64 * l64 + np.log1p(np.exp(-np.abs(x64)))
    t = _t("sigmoid_cross_entropy_with_logits", {"X": x, "Label": label},
           {"Out": expect}, {"ignore_index": -100})
    t.check_output()
    _shapes("sigmoid_cross_entropy_with_logits", {"X": x, "Label": label},
            {"Out": (4, 5)}, {"ignore_index": -100}).check_grad(["X"], "Out")


def test_squared_l2_norm_output_and_grad():
    x = _RNG(35).randn(3, 4).astype("float32")
    t = _t("squared_l2_norm", {"X": x},
           {"Out": np.sum(x.astype("float64") ** 2)})
    t.check_output()
    _shapes("squared_l2_norm", {"X": x}, {"Out": ()}).check_grad(
        ["X"], "Out")


def test_l1_norm_output_and_grad():
    x = _away_from(_RNG(36), (3, 4), [0.0], margin=0.2)
    t = _t("l1_norm", {"X": x}, {"Out": np.sum(np.abs(x))})
    t.check_output()
    _shapes("l1_norm", {"X": x}, {"Out": ()}).check_grad(["X"], "Out")


def test_l2_normalize_rows_unit_norm_and_grad():
    x = _RNG(37).randn(4, 6).astype("float32") + 0.5
    t = _shapes("l2_normalize", {"X": x}, {"Out": (4, 6)},
                {"axis": -1, "epsilon": 1e-10})
    main = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    (out,) = exe.run(main, feed=t._feed, fetch_list=["Out"])
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1), np.ones(4), rtol=1e-5)
    _shapes("l2_normalize", {"X": x}, {"Out": (4, 6)},
            {"axis": -1, "epsilon": 1e-10}).check_grad(
        ["X"], "Out", max_relative_error=1e-2)


# --- norms ---------------------------------------------------------------
def test_group_norm_grad():
    rng = _RNG(38)
    x = rng.randn(2, 4, 3, 3).astype("float32")
    scale = (1.0 + 0.1 * rng.randn(4)).astype("float32")
    bias = (0.1 * rng.randn(4)).astype("float32")
    t = _shapes("group_norm", {"X": x, "Scale": scale, "Bias": bias},
                {"Y": (2, 4, 3, 3)}, {"groups": 2, "epsilon": 1e-5})
    t.check_grad(["X", "Scale", "Bias"], "Y", max_relative_error=1e-2)


def test_lrn_grad():
    x = _RNG(39).randn(2, 7, 3, 3).astype("float32")
    t = _shapes("lrn", {"X": x}, {"Out": (2, 7, 3, 3)},
                {"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75})
    t.check_grad(["X"], "Out")


# --- comparisons / logicals ---------------------------------------------
@pytest.mark.parametrize("op,npf", [
    ("greater_than", np.greater), ("greater_equal", np.greater_equal),
    ("less_equal", np.less_equal), ("not_equal", np.not_equal),
], ids=["gt", "ge", "le", "ne"])
def test_compare_output(op, npf):
    rng = _RNG(40)
    x = rng.randint(0, 4, (3, 5)).astype("float32")
    y = rng.randint(0, 4, (3, 5)).astype("float32")
    _t(op, {"X": x, "Y": y}, {"Out": npf(x, y)}).check_output()


@pytest.mark.parametrize("op,npf", [
    ("logical_and", np.logical_and), ("logical_or", np.logical_or),
    ("logical_xor", np.logical_xor),
], ids=["and", "or", "xor"])
def test_logical_binary_output(op, npf):
    rng = _RNG(41)
    x = rng.rand(3, 4) > 0.5
    y = rng.rand(3, 4) > 0.5
    _t(op, {"X": x, "Y": y}, {"Out": npf(x, y)}).check_output()


def test_logical_not_output():
    x = _RNG(42).rand(3, 4) > 0.5
    _t("logical_not", {"X": x}, {"Out": np.logical_not(x)}).check_output()


def test_isinf_output():
    x = np.asarray([[1.0, np.inf], [-np.inf, 0.0]], "float32")
    _t("isinf", {"X": x}, {"Out": np.asarray(True)}).check_output()


def test_is_empty_output():
    x = np.ones((2, 3), "float32")
    _t("is_empty", {"X": x}, {"Out": np.asarray(False)}).check_output()


def test_one_hot_output():
    x = np.asarray([[0], [3], [1]], "int64")
    expect = np.zeros((3, 5), "float32")
    expect[np.arange(3), x.ravel()] = 1.0
    _t("one_hot", {"X": x}, {"Out": expect}, {"depth": 5}).check_output()


def test_dynamic_update_slice_output_and_grad():
    rng = _RNG(80)
    x = rng.randn(5, 3).astype("float32")
    u = rng.randn(1, 3).astype("float32")
    idx = np.asarray([2], "int64")
    expect = x.copy()
    expect[2] = u[0]
    t = _t("dynamic_update_slice", {"X": x, "Update": u, "Index": idx},
           {"Out": expect}, {"axis": 0})
    t.check_output()
    _shapes("dynamic_update_slice", {"X": x, "Update": u, "Index": idx},
            {"Out": (5, 3)}, {"axis": 0}).check_grad(
        ["X", "Update"], "Out")


def test_reduce_dim_out_of_range_errors():
    """Cross-engine fuzz finding (r5): an out-of-range reduce dim was
    silently wrapped modulo rank onto a DIFFERENT axis by the XLA
    lowering while the C++ interpreter refused. Both engines must now
    reject it; negative python-style dims stay legal."""
    x = _RNG(41).randn(2, 3).astype("float32")
    t = _shapes("reduce_sum", {"X": x}, {"Out": (3,)}, {"dim": [2]})
    main = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(Exception, match="out of range"):
        exe.run(main, feed=t._feed, fetch_list=[])
    # negative dim still works
    t2 = _shapes("reduce_sum", {"X": x}, {"Out": (2,)}, {"dim": [-1]})
    t2.check_grad(["X"], "Out")
