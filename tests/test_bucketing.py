"""bucket_by_length reader decorator: the bucketed-padding strategy that
bounds XLA recompiles for variable-length data (SURVEY.md §5.7 /
§7 hard part (a); the LoD-free answer to the reference's ragged batching).
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.reader.decorator import bucket_by_length


def _var_len_reader(n, seed=0, lo=3, hi=70):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(lo, hi))
            seq = rng.randint(1, 100, (length,)).astype("int64")
            label = int(rng.randint(0, 2))
            yield seq, label
    return reader


def test_bucketing_bounds_shapes_and_pads_correctly():
    bucketed = bucket_by_length(
        _var_len_reader(200), key=lambda s: len(s[0]),
        bucket_boundaries=[16, 32, 64], batch_size=8)
    widths = set()
    for seqs, labels, lengths in bucketed():
        widths.add(seqs.shape[1])
        assert seqs.shape[0] == labels.shape[0] == lengths.shape[0] <= 8
        for row, n in zip(seqs, lengths):
            assert (row[:n] > 0).all()      # payload intact
            assert (row[n:] == 0).all()     # padded with pad_value
            assert seqs.shape[1] >= n
    # at most one shape per bucket (3 boundaries + overflow)
    assert len(widths) <= 4
    assert widths <= {16, 32, 64, 128}


def test_bucketing_overflow_bucket_width_is_quantized():
    # overflow widths are quantized to multiples of the last boundary:
    # bounded shape churn, not one shape per distinct batch maximum
    bucketed = bucket_by_length(
        _var_len_reader(60, lo=65, hi=90), key=lambda s: len(s[0]),
        bucket_boundaries=[16, 32, 64], batch_size=4)
    widths = {seqs.shape[1] for seqs, _, _ in bucketed()}
    assert widths == {128}  # every batch max in (64, 128]


def test_bucketing_seq2seq_pad_fields():
    """Two variable-length fields (src, tgt) bucketed by their max: both
    padded to the bucket width from their own lengths."""
    def reader():
        rng = np.random.RandomState(7)
        for _ in range(40):
            src = rng.randint(1, 9, (int(rng.randint(3, 30)),))
            tgt = rng.randint(1, 9, (int(rng.randint(3, 30)),))
            yield src, tgt

    bucketed = bucket_by_length(
        reader, key=lambda s: max(len(s[0]), len(s[1])),
        bucket_boundaries=[8, 16, 32], batch_size=4, pad_fields=[0, 1])
    n_batches = 0
    for src, tgt, lengths in bucketed():
        n_batches += 1
        assert src.shape == tgt.shape
        assert src.shape[1] in (8, 16, 32)
        assert (lengths <= src.shape[1]).all()
    assert n_batches > 0


def test_bucketing_ragged_unpadded_field_raises_clearly():
    def reader():
        yield np.arange(3), np.arange(5)
        yield np.arange(3), np.arange(9)

    bucketed = bucket_by_length(
        reader, key=lambda s: len(s[0]),
        bucket_boundaries=[4], batch_size=2, pad_fields=[0])
    with np.testing.assert_raises_regex(ValueError, "pad_fields"):
        list(bucketed())


def test_bucketing_max_length_cap():
    bucketed = bucket_by_length(
        _var_len_reader(10, lo=60, hi=70), key=lambda s: len(s[0]),
        bucket_boundaries=[16], batch_size=2, max_length=50)
    with np.testing.assert_raises_regex(ValueError, "max_length"):
        list(bucketed())


def test_bucketing_bounds_executor_compiles():
    """The point of the exercise: a 200-sample variable-length stream
    trains through the Executor with at most one compile per bucket."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        seq = fluid.layers.data(name="seq", shape=[-1], dtype="int64")
        length = fluid.layers.data(name="len", shape=[1], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(seq, size=[100, 8])
        pooled = fluid.layers.sequence_pool(emb, "average", length=length)
        logits = fluid.layers.fc(pooled, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    compiles_before = len(exe._cache)  # startup's own executable

    bucketed = bucket_by_length(
        _var_len_reader(200), key=lambda s: len(s[0]),
        bucket_boundaries=[16, 32, 64], batch_size=8, drop_last=True)
    losses = []
    for seqs, labels, lengths in bucketed():
        lv, = exe.run(main, feed={
            "seq": seqs,
            "len": lengths.reshape(-1, 1),
            "label": np.asarray(labels).reshape(-1, 1),
        }, fetch_list=[loss])
        losses.append(float(np.ravel(lv)[0]))
    assert all(np.isfinite(losses))
    # one executable per distinct feed-shape set = one per bucket
    assert len(exe._cache) - compiles_before <= 4


def test_reader_creators(tmp_path):
    """reader.creator: np_array rows, text_file lines, recordio samples
    through the native reader (creator.py parity)."""
    from paddle_tpu.reader import creator
    from paddle_tpu.recordio_writer import convert_reader_to_recordio_file
    from paddle_tpu import native
    import pytest

    arr = np.arange(12).reshape(4, 3)
    assert [r.tolist() for r in creator.np_array(arr)()] == arr.tolist()

    p = tmp_path / "lines.txt"
    p.write_text("alpha\nbeta\ngamma\n")
    assert list(creator.text_file(str(p))()) == ["alpha", "beta", "gamma"]

    if not native.available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.RandomState(1)
    samples = [(rng.rand(3).astype("float32"), np.int64(i))
               for i in range(5)]
    rio = str(tmp_path / "data.recordio")
    convert_reader_to_recordio_file(rio, lambda: iter(samples))
    got = list(creator.recordio(rio)())
    assert len(got) == 5
    for (x, y), (gx, gy) in zip(samples, got):
        np.testing.assert_allclose(gx, x)
        assert int(gy) == int(y)
