"""Book-style end-to-end train tests (tests/book/test_{fit_a_line,word2vec,
recommender_system}.py parity, SURVEY.md §4): full layers->optimizer->
Executor loops on synthetic data with convergence thresholds, plus the
save/load_inference_model round-trip fit_a_line exercises."""

import numpy as np

import paddle_tpu as fluid


def test_fit_a_line(tmp_path):
    """Linear regression (uci_housing shape): SGD drives MSE well down and
    the saved inference model reproduces predictions."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        y_predict = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.square_error_cost(input=y_predict, label=y)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype("float32")
    losses = []
    for _ in range(120):
        xb = rng.randn(32, 13).astype("float32")
        yb = xb @ w_true + 0.1 * rng.randn(32, 1).astype("float32")
        (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                        fetch_list=[avg_cost])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < 0.25, losses[::20]

    # save_inference_model -> load_inference_model round trip.
    path = str(tmp_path / "fit_a_line.model")
    fluid.io.save_inference_model(path, ["x"], [y_predict], exe,
                                  main_program=main)
    xb = rng.randn(8, 13).astype("float32")
    (want,) = exe.run(main, feed={"x": xb, "y": np.zeros((8, 1), "float32")},
                      fetch_list=[y_predict])
    infer_prog, feed_names, fetch_vars = fluid.io.load_inference_model(
        path, exe
    )
    (got,) = exe.run(infer_prog, feed={feed_names[0]: xb},
                     fetch_list=fetch_vars)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_word2vec_ngram():
    """N-gram LM (book chapter 4): 4 context embeddings -> concat -> hidden
    -> softmax. Synthetic deterministic-ish text must be learnable."""
    dict_size, emb_dim, hidden = 40, 16, 64
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = [
            fluid.layers.data(name="w%d" % i, shape=[1], dtype="int64")
            for i in range(4)
        ]
        label = fluid.layers.data(name="next", shape=[1], dtype="int64")
        embs = [
            fluid.layers.embedding(
                input=w, size=[dict_size, emb_dim],
                param_attr=fluid.ParamAttr(name="shared_w"),
            )
            for w in words
        ]
        concat = fluid.layers.concat(
            [fluid.layers.reshape(e, shape=[-1, emb_dim]) for e in embs],
            axis=1,
        )
        hid = fluid.layers.fc(input=concat, size=hidden, act="sigmoid")
        predict = fluid.layers.fc(input=hid, size=dict_size, act="softmax")
        cost = fluid.layers.mean(
            fluid.layers.cross_entropy(input=predict, label=label)
        )
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    # Synthetic corpus: next word deterministically follows the first
    # context word (a learnable bigram structure).
    rng = np.random.RandomState(1)
    succ = rng.permutation(dict_size)
    losses = []
    for _ in range(150):
        ctx = rng.randint(0, dict_size, (64, 4)).astype("int64")
        nxt = succ[ctx[:, 0]].astype("int64")
        feed = {"w%d" % i: ctx[:, i:i + 1] for i in range(4)}
        feed["next"] = nxt.reshape(-1, 1)
        (lv,) = exe.run(main, feed=feed, fetch_list=[cost])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.7, losses[::30]
    # The shared embedding is a single parameter (4 lookups, one table).
    params = [p.name for p in main.global_block().all_parameters()]
    assert params.count("shared_w") == 1


def test_recommender_system():
    """Dual-tower user/movie model with cos_sim rating head (book ch. 5)."""
    n_users, n_movies, n_cats = 50, 80, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = fluid.layers.data(name="user_id", shape=[1], dtype="int64")
        ujob = fluid.layers.data(name="job_id", shape=[1], dtype="int64")
        mid = fluid.layers.data(name="movie_id", shape=[1], dtype="int64")
        mcat = fluid.layers.data(name="category_id", shape=[1],
                                 dtype="int64")
        score = fluid.layers.data(name="score", shape=[1], dtype="float32")

        def tower(ids, sizes):
            feats = []
            for inp, size in zip(ids, sizes):
                emb = fluid.layers.embedding(input=inp, size=[size, 16])
                feats.append(fluid.layers.reshape(emb, shape=[-1, 16]))
            return fluid.layers.fc(input=feats, size=32, act="tanh")

        usr = tower([uid, ujob], [n_users, n_cats])
        mov = tower([mid, mcat], [n_movies, n_cats])
        sim = fluid.layers.cos_sim(X=usr, Y=mov)
        predict = fluid.layers.scale(sim, scale=5.0)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=predict, label=score)
        )
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(2)
    # Rating = affinity of (user mod 8) vs (movie mod 8) buckets.
    affinity = rng.rand(8, 8).astype("float32") * 5
    losses = []
    for _ in range(150):
        u = rng.randint(0, n_users, (64, 1)).astype("int64")
        m = rng.randint(0, n_movies, (64, 1)).astype("int64")
        feed = {
            "user_id": u,
            "job_id": (u % n_cats).astype("int64"),
            "movie_id": m,
            "category_id": (m % n_cats).astype("int64"),
            "score": affinity[u.ravel() % 8, m.ravel() % 8].reshape(-1, 1),
        }
        (lv,) = exe.run(main, feed=feed, fetch_list=[cost])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.5, losses[::30]


def test_label_semantic_roles_bilstm_crf():
    """BiLSTM + linear_chain_crf tagging (book ch. 7 capability): CRF NLL
    falls and Viterbi decoding recovers most tags on a learnable synthetic
    tagging rule."""
    vocab, n_tags, T, D = 30, 5, 8, 24
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        word = fluid.layers.data(name="word", shape=[T], dtype="int64")
        target = fluid.layers.data(name="target", shape=[T], dtype="int64")
        length = fluid.layers.data(name="length", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=word, size=[vocab, D])
        proj = fluid.layers.fc(input=emb, size=D * 4, num_flatten_dims=2,
                               bias_attr=False)
        fwd, _ = fluid.layers.dynamic_lstm(
            input=proj, size=D * 4, length=length, use_peepholes=False
        )
        emission = fluid.layers.fc(input=fwd, size=n_tags,
                                   num_flatten_dims=2)
        crf_cost = fluid.layers.linear_chain_crf(
            emission, target, length=length,
            param_attr=fluid.ParamAttr(name="crfw"),
        )
        avg_cost = fluid.layers.mean(crf_cost)
        fluid.optimizer.Adam(5e-3).minimize(avg_cost)
        decoded = fluid.layers.crf_decoding(
            emission, param_attr=fluid.ParamAttr(name="crfw"),
            length=length,
        )

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(6)

    def batch(bs):
        lens = rng.randint(3, T, (bs,))
        w = rng.randint(0, vocab, (bs, T))
        tags = (w % n_tags).astype("int64")  # tag derivable from word
        for i, ln in enumerate(lens):
            w[i, ln:] = 0
            tags[i, ln:] = 0
        return (
            w.astype("int64"), tags,
            lens.reshape(-1, 1).astype("int64"),
        )

    losses = []
    for _ in range(150):
        w, tg, ln = batch(16)
        (lv,) = exe.run(
            main, feed={"word": w, "target": tg, "length": ln},
            fetch_list=[avg_cost],
        )
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.5, losses[::30]

    w, tg, ln = batch(32)
    (path,) = exe.run(
        main, feed={"word": w, "target": tg, "length": ln},
        fetch_list=[decoded],
    )
    path = np.asarray(path)
    mask = np.arange(T)[None, :] < ln
    acc = (path == tg)[mask].mean()
    assert acc > 0.8, acc
