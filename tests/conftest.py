"""Test harness config: force an 8-device virtual CPU mesh BEFORE jax
import so multi-device/GSPMD tests run without TPU hardware (SURVEY.md §4:
dist-parity tests via multi-device CPU XLA)."""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# The axon TPU plugin prepends itself to jax_platforms regardless of the env
# var; pin the backend explicitly before any computation initializes it.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Give every test fresh default programs, scope and name counters."""
    import paddle_tpu as fluid
    from paddle_tpu import framework, unique_name
    from paddle_tpu.core.scope import Scope
    import paddle_tpu.executor as executor_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch({})
    executor_mod._global_scope = Scope()
    executor_mod._scope_stack = [executor_mod._global_scope]
    np.random.seed(42)
    yield
