"""Test harness config: force an 8-device virtual CPU mesh BEFORE jax
import so multi-device/GSPMD tests run without TPU hardware (SURVEY.md §4:
dist-parity tests via multi-device CPU XLA)."""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# The axon TPU plugin prepends itself to jax_platforms regardless of the env
# var; pin the backend explicitly before any computation initializes it.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    # "slow": excluded from the time-budgeted tier-1 run (-m 'not slow');
    # still executed by tools/run_ci.sh's python stage, which runs the
    # whole suite unfiltered
    config.addinivalue_line(
        "markers", "slow: long-running (subprocess-spawning) tests "
        "excluded from the tier-1 budget")


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Give every test fresh default programs, scope and name counters."""
    import paddle_tpu as fluid
    from paddle_tpu import framework, unique_name
    from paddle_tpu.core.scope import Scope
    import paddle_tpu.executor as executor_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch({})
    executor_mod._global_scope = Scope()
    executor_mod._scope_stack = [executor_mod._global_scope]
    np.random.seed(42)
    yield


_NATIVE_BUILD_RESULT = {}


def build_native_binary(name):
    """Locate a native/build binary, running the cmake build AT MOST once
    per session and only when first asked (never at collection time).
    Returns the path or None when the toolchain is unavailable. Shared by
    every test that drives a native executable."""
    import subprocess

    if name in _NATIVE_BUILD_RESULT:
        return _NATIVE_BUILD_RESULT[name]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "native", "build", name)
    if not os.path.exists(path):
        try:
            subprocess.run(
                ["cmake", "-S", os.path.join(root, "native"), "-B",
                 os.path.join(root, "native", "build"), "-G", "Ninja"],
                check=True, capture_output=True)
            subprocess.run(
                ["cmake", "--build", os.path.join(root, "native", "build")],
                check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pass
    _NATIVE_BUILD_RESULT[name] = path if os.path.exists(path) else None
    return _NATIVE_BUILD_RESULT[name]
