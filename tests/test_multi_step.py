"""Compiled multi-step loop tests (SURVEY §7 hard part (c)): K steps in
one lax.scan executable must match K sequential Executor.run calls."""

import numpy as np

import paddle_tpu as fluid


def _build_sgd_program(seed=3):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], stop_gradient=False)
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feed(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(8, 4).astype("float32")
    return {"x": x, "y": (x.sum(1, keepdims=True)).astype("float32")}


def test_multi_step_matches_sequential_runs():
    feed = _feed()
    k = 5

    main, startup, loss = _build_sgd_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope_a = fluid.core.scope.Scope()
    with fluid.scope_guard(scope_a):
        exe.run(startup)
        init = {n: np.array(scope_a.get_value(n))
                for n in scope_a.local_var_names()}
        seq_losses = [
            float(np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0]).reshape(-1)[0])
            for _ in range(k)
        ]
        w_name = [n for n in init if n.endswith("w_0")][0]
        w_seq = np.asarray(scope_a.get_value(w_name))

    # same program + identical initial weights, one scanned executable
    scope_b = fluid.core.scope.Scope()
    for n, v in init.items():
        scope_b.set_value(n, v)
    with fluid.scope_guard(scope_b):
        out = exe.run_multi_step(main, k, feed=feed, fetch_list=[loss])
        w_multi = np.asarray(scope_b.get_value(w_name))

    # identical deterministic math -> identical trained weights
    np.testing.assert_allclose(w_multi, w_seq, rtol=1e-5, atol=1e-6)
    # default fetch mode returns the LAST step's loss
    last = float(np.asarray(out[0]).reshape(-1)[0])
    np.testing.assert_allclose(last, seq_losses[-1], rtol=1e-5)


def test_multi_step_stacked_fetches_trajectory():
    feed = _feed(1)
    k = 4
    main, startup, loss = _build_sgd_program(seed=9)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.scope.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (traj,) = exe.run_multi_step(main, k, feed=feed, fetch_list=[loss],
                                     stack_fetches=True)
    traj = np.asarray(traj).reshape(k)
    assert np.isfinite(traj).all()
    # SGD on a fixed batch: strictly decreasing loss trajectory
    assert (np.diff(traj) < 0).all(), traj


def test_multi_step_with_in_graph_reader():
    """No feeds at all: input comes from the in-graph random reader, the
    bench.py configuration."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x, y = fluid.layers.random_data_generator(
            shapes=[[8, 4], [8, 1]], dtypes=["float32", "float32"])
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.scope.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (traj,) = exe.run_multi_step(main, 6, fetch_list=[loss],
                                     stack_fetches=True)
    traj = np.asarray(traj).reshape(6)
    assert np.isfinite(traj).all()
    # random batches differ step to step: check the steps actually ran
    assert len(set(np.round(traj, 6))) > 1
