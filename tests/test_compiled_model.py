"""AOT compiled-model deployment (save/load_compiled_inference_model):
the artifact is a serialized XLA executable with the parameters baked
in — no program IR, parameter files, or tracing at the serving site.

Reference analogy: inference/api/api_impl.cc loads an optimized
ProgramDesc + params; the TPU-native form skips the IR entirely and
ships the compiled computation (jax.export serialization).
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _build_cnn():
    img = fluid.layers.data("img", [1, 16, 16])
    c = fluid.nets.simple_img_conv_pool(
        img, filter_size=3, num_filters=4, pool_size=2, pool_stride=2,
        act="relu")
    out = fluid.layers.fc(c, size=5, act="softmax")
    return img, out


def test_compiled_model_roundtrip(tmp_path):
    rng = np.random.RandomState(3)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        img, out = _build_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"img": rng.rand(2, 1, 16, 16).astype("float32")}
    (want,) = exe.run(main.clone(for_test=True), feed=feed,
                      fetch_list=[out])

    path = str(tmp_path / "aot")
    fluid.io.save_compiled_inference_model(
        path, ["img"], [out], exe,
        feed_shapes={"img": ((2, 1, 16, 16), "float32")},
        main_program=main)

    # load in a scope WITHOUT the params: the artifact must be
    # self-contained (constants baked at export)
    with fluid.scope_guard(fluid.executor.Scope()):
        model = fluid.io.load_compiled_inference_model(path)
        got = model.run(feed)
    assert model.fetch_names == [out.name]
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want),
                               rtol=1e-5, atol=1e-6)

    # AOT executables are shape-specialized: a wrong batch errors cleanly
    with pytest.raises(ValueError, match="shape-specialized"):
        model.run({"img": rng.rand(3, 1, 16, 16).astype("float32")})
    with pytest.raises(KeyError):
        model.run({})


def test_compiled_model_exports_for_tpu(tmp_path):
    """Cross-platform export: a CPU host emits an artifact whose
    lowering targets the TPU platform (the deploy story: compile on the
    build machine, serve on the accelerator host)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img, out = _build_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / "aot_tpu")
    fluid.io.save_compiled_inference_model(
        path, ["img"], [out], exe,
        feed_shapes={"img": ((1, 1, 16, 16), "float32")},
        main_program=main, platforms=("tpu",))
    model = fluid.io.load_compiled_inference_model(path)
    assert model.platforms == ["tpu"]
    # calling on CPU must fail loudly, not silently run the wrong target
    with pytest.raises(Exception):
        model.run({"img": np.zeros((1, 1, 16, 16), "float32")})


def test_compiled_model_requires_params_in_scope(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img, out = _build_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    # startup NOT run: params missing from scope
    with fluid.scope_guard(fluid.executor.Scope()):
        # surfaced either by the explicit pre-check (param known to the
        # scope but valueless) or by the lowerer at trace time (param
        # entirely absent) — both are RuntimeError
        with pytest.raises(RuntimeError,
                           match="not in scope|uninitialized variable"):
            fluid.io.save_compiled_inference_model(
                str(tmp_path / "x"), ["img"], [out], exe,
                feed_shapes={"img": ((1, 1, 16, 16), "float32")},
                main_program=main)
