"""Detection op/layer tests against brute-force numpy oracles.

Reference test strategy: tests/unittests/test_{bipartite_match,multiclass_nms,
anchor_generator,density_prior_box,roi_pool,roi_align,rpn_target_assign,
detection_map,polygon_box_transform}_op.py and test_detection.py — each op is
checked against an independent host-side implementation, then an SSD-style
loss is trained end-to-end on synthetic boxes.
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(build_fn, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    outs = exe.run(main, feed=feed, fetch_list=list(fetches))
    return [np.asarray(o) for o in outs]


def _np_iou(a, b):
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


def _rand_boxes(rng, n, scale=1.0):
    xy = rng.uniform(0, 0.7 * scale, (n, 2))
    wh = rng.uniform(0.1 * scale, 0.3 * scale, (n, 2))
    return np.concatenate([xy, xy + wh], axis=1).astype("float32")


# ---------------------------------------------------------------------------
# bipartite_match
# ---------------------------------------------------------------------------


def _np_bipartite(dist, match_type, thr):
    g, p = dist.shape
    d = dist.copy()
    row_valid = d.max(axis=1) > 0
    d[~row_valid] = -1.0
    midx = np.full(p, -1, np.int32)
    mdist = np.zeros(p, np.float32)
    work = d.copy()
    for _ in range(min(g, p)):
        k = np.argmax(work)
        r, c = k // p, k % p
        if work[r, c] <= 0:
            break
        midx[c] = r
        mdist[c] = work[r, c]
        work[r, :] = -1
        work[:, c] = -1
    if match_type == "per_prediction":
        best = d.max(axis=0)
        best_row = d.argmax(axis=0)
        for c in range(p):
            if midx[c] < 0 and best[c] >= thr:
                midx[c] = best_row[c]
                mdist[c] = best[c]
    return midx, mdist


@pytest.mark.parametrize("match_type", ["bipartite", "per_prediction"])
def test_bipartite_match_matches_numpy(match_type):
    rng = np.random.RandomState(7)
    n, g, p = 2, 3, 8
    gt = np.stack([_rand_boxes(rng, g) for _ in range(n)])
    gt[1, 2] = 0.0  # padded gt row
    priors = _rand_boxes(rng, p)
    dist = np.stack([_np_iou(gt[i], priors) for i in range(n)])

    def build():
        d = fluid.layers.data("dist", [g, p], append_batch_size=True)
        mi, md = fluid.layers.bipartite_match(d, match_type, 0.3)
        return mi, md

    mi, md = _run(build, {"dist": dist.astype("float32")})
    for i in range(n):
        emi, emd = _np_bipartite(dist[i], match_type, 0.3)
        np.testing.assert_array_equal(mi[i], emi)
        np.testing.assert_allclose(md[i], emd, rtol=1e-5)


# ---------------------------------------------------------------------------
# target_assign
# ---------------------------------------------------------------------------


def test_target_assign_gathers_matched_rows():
    rng = np.random.RandomState(3)
    n, g, p, k = 2, 3, 5, 4
    x = rng.randn(n, g, k).astype("float32")
    midx = np.array([[0, -1, 2, 1, -1], [2, 2, -1, 0, 1]], np.int32)

    def build():
        xv = fluid.layers.data("x", [g, k])
        mv = fluid.layers.data("m", [p], dtype="int32")
        out, w = fluid.layers.target_assign(xv, mv, mismatch_value=0)
        return out, w

    out, w = _run(build, {"x": x, "m": midx})
    for i in range(n):
        for j in range(p):
            if midx[i, j] >= 0:
                np.testing.assert_allclose(out[i, j], x[i, midx[i, j]], rtol=1e-6)
                assert w[i, j, 0] == 1.0
            else:
                np.testing.assert_array_equal(out[i, j], np.zeros(k))
                assert w[i, j, 0] == 0.0


# ---------------------------------------------------------------------------
# multiclass NMS
# ---------------------------------------------------------------------------


def _np_nms(boxes, scores, score_thr, nms_thr, top_k):
    order = np.argsort(-scores)[:top_k]
    keep = []
    for i in order:
        if scores[i] <= score_thr:
            continue
        ok = True
        for j in keep:
            if _np_iou(boxes[i:i + 1], boxes[j:j + 1])[0, 0] > nms_thr:
                ok = False
                break
        if ok:
            keep.append(i)
    return keep


def test_multiclass_nms_matches_numpy():
    rng = np.random.RandomState(11)
    n, c, p = 2, 3, 12
    boxes = np.stack([_rand_boxes(rng, p) for _ in range(n)])
    scores = rng.uniform(0, 1, (n, c, p)).astype("float32")

    def build():
        bv = fluid.layers.data("b", [p, 4])
        sv = fluid.layers.data("s", [c, p])
        out, count = fluid.layers.multiclass_nms(
            bv, sv, background_label=0, score_threshold=0.3,
            nms_top_k=10, nms_threshold=0.4, keep_top_k=6)
        return out, count

    out, count = _run(build, {"b": boxes, "s": scores})
    for i in range(n):
        expected = []
        for cls in range(1, c):
            for j in _np_nms(boxes[i], scores[i, cls], 0.3, 0.4, 10):
                expected.append((cls, scores[i, cls, j], j))
        expected.sort(key=lambda t: -t[1])
        expected = expected[:6]
        assert count[i] == len(expected)
        got = out[i][out[i][:, 0] >= 0]
        assert got.shape[0] == len(expected)
        for row, (cls, sc, j) in zip(got, expected):
            assert int(row[0]) == cls
            np.testing.assert_allclose(row[1], sc, rtol=1e-5)
            np.testing.assert_allclose(row[2:6], boxes[i, j], rtol=1e-5)


# ---------------------------------------------------------------------------
# anchor / density prior generators
# ---------------------------------------------------------------------------


def test_anchor_generator_matches_reference_formula():
    def build():
        feat = fluid.layers.data("feat", [8, 2, 2], append_batch_size=True)
        anchors, variances = fluid.layers.anchor_generator(
            feat, anchor_sizes=[32.0, 64.0], aspect_ratios=[0.5, 1.0],
            variance=[0.1, 0.1, 0.2, 0.2], stride=[16.0, 16.0], offset=0.5)
        return anchors, variances

    a, v = _run(build, {"feat": np.zeros((1, 8, 2, 2), "float32")})
    assert a.shape == (2, 2, 4, 4) and v.shape == (2, 2, 4, 4)
    # anchor (h=0, w=0, ratio=0.5, size=32): reference anchor_generator_op.h
    sw = sh = 16.0
    x_ctr = 0.5 * (sw - 1)
    y_ctr = 0.5 * (sh - 1)
    base_w = round(np.sqrt(sw * sh / 0.5))
    base_h = round(base_w * 0.5)
    aw = (32.0 / sw) * base_w
    ah = (32.0 / sh) * base_h
    np.testing.assert_allclose(
        a[0, 0, 0],
        [x_ctr - 0.5 * (aw - 1), y_ctr - 0.5 * (ah - 1),
         x_ctr + 0.5 * (aw - 1), y_ctr + 0.5 * (ah - 1)],
        rtol=1e-5,
    )
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2], rtol=1e-6)


def test_density_prior_box_counts_and_range():
    def build():
        feat = fluid.layers.data("feat", [4, 4, 4], append_batch_size=True)
        img = fluid.layers.data("img", [3, 32, 32], append_batch_size=True)
        boxes, variances = fluid.layers.density_prior_box(
            feat, img, densities=[2, 1], fixed_sizes=[8.0, 16.0],
            fixed_ratios=[1.0], clip=True)
        return boxes, variances

    b, v = _run(build, {
        "feat": np.zeros((1, 4, 4, 4), "float32"),
        "img": np.zeros((1, 3, 32, 32), "float32"),
    })
    # densities [2,1] with one ratio -> 2*2 + 1*1 = 5 priors per cell
    assert b.shape == (4, 4, 5, 4)
    assert (b >= 0).all() and (b <= 1).all()
    assert v.shape == b.shape


# ---------------------------------------------------------------------------
# ROI ops
# ---------------------------------------------------------------------------


def test_roi_pool_matches_numpy():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 3, 8, 8).astype("float32")
    rois = np.array([[0, 0, 7, 7], [2, 2, 6, 6], [1, 0, 5, 3]], "float32")
    batch = np.array([0, 1, 1], "int32")
    ph = pw = 2

    def build():
        xv = fluid.layers.data("x", [3, 8, 8])
        rv = fluid.layers.data("r", [4], append_batch_size=True)
        bv = fluid.layers.data("bi", [], dtype="int32", append_batch_size=True)
        out = fluid.layers.roi_pool(xv, rv, ph, pw, 1.0, rois_batch=bv)
        return (out,)

    (out,) = _run(build, {"x": x, "r": rois, "bi": batch})
    # numpy oracle (roi_pool_op.cc quantized bins)
    for r in range(3):
        x1, y1, x2, y2 = np.round(rois[r]).astype(int)
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        for c in range(3):
            for i in range(ph):
                for j in range(pw):
                    hs = int(np.floor(i * rh / ph)) + y1
                    he = int(np.ceil((i + 1) * rh / ph)) + y1
                    ws = int(np.floor(j * rw / pw)) + x1
                    we = int(np.ceil((j + 1) * rw / pw)) + x1
                    hs, he = np.clip([hs, he], 0, 8)
                    ws, we = np.clip([ws, we], 0, 8)
                    patch = x[batch[r], c, hs:he, ws:we]
                    exp = patch.max() if patch.size else 0.0
                    np.testing.assert_allclose(out[r, c, i, j], exp, rtol=1e-5)


def test_roi_align_shape_and_grad():
    rng = np.random.RandomState(9)
    x = rng.randn(1, 2, 8, 8).astype("float32")
    rois = np.array([[0.5, 0.5, 6.5, 6.5], [2, 2, 5, 5]], "float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [2, 8, 8], stop_gradient=False)
        rv = fluid.layers.data("r", [4], append_batch_size=True)
        out = fluid.layers.roi_align(xv, rv, 3, 3, 1.0, sampling_ratio=2)
        loss = fluid.layers.mean(out)
        grads = fluid.backward.calc_gradient(loss, [xv])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    o, g = exe.run(main, feed={"x": x, "r": rois}, fetch_list=[out, grads[0]])
    assert np.asarray(o).shape == (2, 2, 3, 3)
    g = np.asarray(g)
    assert g.shape == x.shape and np.abs(g).sum() > 0


# ---------------------------------------------------------------------------
# RPN target assign + generate_proposals
# ---------------------------------------------------------------------------


def test_rpn_target_assign_labels_and_counts():
    rng = np.random.RandomState(21)
    a, n, g, s = 32, 2, 3, 16
    anchors = (_rand_boxes(rng, a, scale=30.0)).astype("float32")
    gt = np.stack([_rand_boxes(rng, g, scale=30.0) for _ in range(n)])
    gt[0, 2] = 0.0  # padding
    im_info = np.tile(np.array([[40.0, 40.0, 1.0]], "float32"), (n, 1))
    bbox_pred = rng.randn(n, a, 4).astype("float32")
    cls_logits = rng.randn(n, a, 1).astype("float32")

    def build():
        av = fluid.layers.data("a", [a, 4], append_batch_size=False)
        gv = fluid.layers.data("g", [g, 4])
        iv = fluid.layers.data("im", [3])
        bp = fluid.layers.data("bp", [a, 4])
        cl = fluid.layers.data("cl", [a, 1])
        outs = fluid.layers.rpn_target_assign(
            bp, cl, av, None, gv, im_info=iv, rpn_batch_size_per_im=s,
            rpn_straddle_thresh=-1.0, rpn_fg_fraction=0.5,
            rpn_positive_overlap=0.6, rpn_negative_overlap=0.3,
            use_random=False)
        return outs

    logits, locs, tlabel, tbbox, bw, lw = _run(build, {
        "a": anchors, "g": gt, "im": im_info,
        "bp": bbox_pred, "cl": cls_logits,
    })
    n_fg = s // 2
    cap = n_fg + s  # fg slots + full-minibatch negative capacity
    assert logits.shape == (n, cap, 1)
    assert locs.shape == (n, n_fg, 4)
    assert tlabel.shape == (n, cap) and lw.shape == (n, cap)
    assert tbbox.shape == (n, n_fg, 4) and bw.shape == (n, n_fg, 4)
    for i in range(n):
        valid = lw[i] > 0
        # positives come first; labels are 1/0; weights mask padding
        assert set(np.unique(tlabel[i][valid])) <= {0, 1}
        # every gt with nonzero box should create >= 1 positive (best-anchor rule)
        n_valid_gt = int((gt[i].max(axis=1) > 0).sum())
        num_pos = tlabel[i][valid].sum()
        assert num_pos >= min(n_valid_gt, 1)
        # with plentiful anchors the minibatch is filled: pos + neg == S
        assert valid.sum() == s


def test_rpn_target_assign_background_only_image():
    """All-padding gt: every inside anchor is a negative candidate and the
    minibatch is filled with background samples (reference behavior)."""
    rng = np.random.RandomState(4)
    a, g, s = 24, 2, 8
    anchors = _rand_boxes(rng, a, scale=30.0)
    gt = np.zeros((1, g, 4), "float32")
    im_info = np.array([[40.0, 40.0, 1.0]], "float32")

    def build():
        av = fluid.layers.data("a", [a, 4], append_batch_size=False)
        gv = fluid.layers.data("g", [g, 4])
        iv = fluid.layers.data("im", [3])
        bp = fluid.layers.data("bp", [a, 4])
        cl = fluid.layers.data("cl", [a, 1])
        return fluid.layers.rpn_target_assign(
            bp, cl, av, None, gv, im_info=iv, rpn_batch_size_per_im=s,
            rpn_straddle_thresh=-1.0, use_random=False)

    outs = _run(build, {
        "a": anchors, "g": gt, "im": im_info,
        "bp": rng.randn(1, a, 4).astype("float32"),
        "cl": rng.randn(1, a, 1).astype("float32"),
    })
    tlabel, lw = outs[2], outs[5]
    valid = lw[0] > 0
    assert valid.sum() == s  # full minibatch of negatives
    assert (tlabel[0][valid] == 0).all()


def test_detection_map_ignores_difficult_when_not_evaluated():
    # det 0 hits a difficult gt -> ignored (not FP); det 1 hits normal gt
    det = np.zeros((1, 2, 6), "float32")
    det[0, 0] = [1, 0.9, 0.5, 0.5, 0.8, 0.8]  # on difficult gt
    det[0, 1] = [1, 0.8, 0.1, 0.1, 0.4, 0.4]  # on normal gt
    gt_label = np.array([[1, 1]], "int32")
    gt_box = np.zeros((1, 2, 4), "float32")
    gt_box[0, 0] = [0.1, 0.1, 0.4, 0.4]  # normal
    gt_box[0, 1] = [0.5, 0.5, 0.8, 0.8]  # difficult
    difficult = np.array([[0.0, 1.0]], "float32")

    def build():
        dv = fluid.layers.data("d", [2, 6])
        lv = fluid.layers.data("l", [2], dtype="int32")
        bv = fluid.layers.data("b", [2, 4])
        fv = fluid.layers.data("f", [2])
        m = fluid.layers.detection_map(dv, lv, bv, gt_difficult=fv,
                                       class_num=2,
                                       evaluate_difficult=False)
        return (m,)

    (m,) = _run(build, {"d": det, "l": gt_label, "b": gt_box, "f": difficult})
    # difficult det ignored; remaining det is a clean TP on the 1 countable
    # gt -> AP 1.0 (were the difficult hit counted as FP, AP would be 0.5)
    np.testing.assert_allclose(m, 1.0, atol=1e-5)


def test_generate_proposals_runs_and_clips():
    rng = np.random.RandomState(2)
    n, a, h, w = 1, 3, 4, 4
    scores = rng.uniform(0, 1, (n, a, h, w)).astype("float32")
    deltas = (0.1 * rng.randn(n, a * 4, h, w)).astype("float32")
    im_info = np.array([[32.0, 32.0, 1.0]], "float32")

    def build():
        sv = fluid.layers.data("s", [a, h, w])
        dv = fluid.layers.data("d", [a * 4, h, w])
        iv = fluid.layers.data("im", [3])
        feat = fluid.layers.data("feat", [8, h, w])
        anchors, variances = fluid.layers.anchor_generator(
            feat, anchor_sizes=[8.0], aspect_ratios=[0.5, 1.0, 2.0],
            stride=[8.0, 8.0])
        rois, probs, count = fluid.layers.generate_proposals(
            sv, dv, iv, anchors, variances, pre_nms_top_n=24,
            post_nms_top_n=8, nms_thresh=0.7, min_size=1.0)
        return rois, probs, count

    rois, probs, count = _run(build, {
        "s": scores, "d": deltas, "im": im_info,
        "feat": np.zeros((1, 8, h, w), "float32"),
    })
    assert rois.shape[0] == 1 and rois.shape[2] == 4
    assert 0 < count[0] <= 8
    k = count[0]
    assert (rois[0, :k, 0::2] >= 0).all() and (rois[0, :k, 0::2] <= 31).all()
    assert (rois[0, :k, 1::2] >= 0).all() and (rois[0, :k, 1::2] <= 31).all()
    # probs sorted descending among valid
    p = probs[0, :k]
    assert (np.diff(p) <= 1e-6).all()


# ---------------------------------------------------------------------------
# detection_map
# ---------------------------------------------------------------------------


def test_detection_map_perfect_and_mixed():
    # image 0: one gt of class 1, detection hits it -> AP 1.0
    det = np.zeros((1, 3, 6), "float32")
    det[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]  # IoU 1 with gt
    det[0, 1] = [1, 0.5, 0.6, 0.6, 0.9, 0.9]  # miss (FP)
    det[0, 2] = [-1, 0, 0, 0, 0, 0]  # padding
    gt_label = np.array([[1, -1]], "int32")
    gt_box = np.zeros((1, 2, 4), "float32")
    gt_box[0, 0] = [0.1, 0.1, 0.4, 0.4]

    def build():
        dv = fluid.layers.data("d", [3, 6])
        lv = fluid.layers.data("l", [2], dtype="int32")
        bv = fluid.layers.data("b", [2, 4])
        m = fluid.layers.detection_map(dv, lv, bv, class_num=2,
                                       overlap_threshold=0.5)
        return (m,)

    (m,) = _run(build, {"d": det, "l": gt_label, "b": gt_box})
    # one TP at rank 0 (p=1, r=1), one FP at rank 1: integral AP = 1.0
    np.testing.assert_allclose(m, 1.0, atol=1e-5)


# ---------------------------------------------------------------------------
# polygon_box_transform
# ---------------------------------------------------------------------------


def test_polygon_box_transform_formula():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 4, 3, 3).astype("float32")

    def build():
        xv = fluid.layers.data("x", [4, 3, 3])
        return (fluid.layers.polygon_box_transform(xv),)

    (out,) = _run(build, {"x": x})
    jj = np.arange(3)[None, :]
    ii = np.arange(3)[:, None]
    for c in range(4):
        exp = (jj * 4.0 - x[0, c]) if c % 2 == 0 else (ii * 4.0 - x[0, c])
        np.testing.assert_allclose(out[0, c], exp, rtol=1e-5)


# ---------------------------------------------------------------------------
# SSD end-to-end: multi_box_head + ssd_loss trains on synthetic boxes
# ---------------------------------------------------------------------------


def test_ssd_loss_trains_on_synthetic_boxes():
    rng = np.random.RandomState(42)
    num_classes, g = 3, 2

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [3, 32, 32], stop_gradient=False)
        gt_box = fluid.layers.data("gt_box", [g, 4])
        gt_label = fluid.layers.data("gt_label", [g], dtype="int32")
        c1 = fluid.layers.conv2d(img, 8, 3, stride=2, padding=1, act="relu")
        c2 = fluid.layers.conv2d(c1, 8, 3, stride=2, padding=1, act="relu")
        c3 = fluid.layers.conv2d(c2, 8, 3, stride=2, padding=1, act="relu")
        loc, conf, boxes, variances = fluid.layers.multi_box_head(
            inputs=[c2, c3], image=img, base_size=32,
            num_classes=num_classes, aspect_ratios=[[1.0], [1.0]],
            min_sizes=[8.0, 16.0], max_sizes=[16.0, 24.0], flip=False)
        loss = fluid.layers.ssd_loss(loc, conf, gt_box, gt_label,
                                     boxes, variances)
        avg = fluid.layers.mean(loss)
        opt = fluid.optimizer.Adam(learning_rate=5e-3)
        opt.minimize(avg)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def batch():
        imgs = rng.rand(4, 3, 32, 32).astype("float32")
        gb = np.stack([_rand_boxes(rng, g) for _ in range(4)])
        gl = rng.randint(1, num_classes, (4, g)).astype("int32")
        return {"img": imgs, "gt_box": gb.astype("float32"), "gt_label": gl}

    losses = []
    for _ in range(15):
        (lv,) = exe.run(main, feed=batch(), fetch_list=[avg])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_detection_output_inference_path():
    rng = np.random.RandomState(8)
    n, p, c = 2, 6, 3
    loc = (0.05 * rng.randn(n, p, 4)).astype("float32")
    scores = rng.randn(n, p, c).astype("float32")
    priors = _rand_boxes(rng, p)
    pvar = np.tile(np.array([0.1, 0.1, 0.2, 0.2], "float32"), (p, 1))

    def build():
        lv = fluid.layers.data("loc", [p, 4])
        sv = fluid.layers.data("sc", [p, c])
        pb = fluid.layers.data("pb", [p, 4], append_batch_size=False)
        pv = fluid.layers.data("pv", [p, 4], append_batch_size=False)
        out = fluid.layers.detection_output(
            lv, sv, pb, pv, nms_threshold=0.45, score_threshold=0.01,
            nms_top_k=6, keep_top_k=4)
        return (out,)

    (out,) = _run(build, {"loc": loc, "sc": scores, "pb": priors, "pv": pvar})
    assert out.shape == (n, 4, 6)
    valid = out[out[:, :, 0] >= 0]
    assert (valid[:, 1] >= 0).all() and (valid[:, 1] <= 1).all()


def test_metrics_detection_map_accumulates_and_matches_op():
    from paddle_tpu.metrics import DetectionMAP

    det = np.zeros((1, 3, 6), "float32")
    det[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    det[0, 1] = [1, 0.5, 0.6, 0.6, 0.9, 0.9]
    det[0, 2] = [-1, 0, 0, 0, 0, 0]
    gt_label = np.array([[1, -1]], "int32")
    gt_box = np.zeros((1, 2, 4), "float32")
    gt_box[0, 0] = [0.1, 0.1, 0.4, 0.4]

    m = DetectionMAP(class_num=2)
    m.update(det, gt_label, gt_box)
    # single batch == the in-graph op's verdict (1.0, see op test above)
    np.testing.assert_allclose(m.eval(), 1.0, atol=1e-6)

    # second batch: one pure miss halves per-class precision tail but the
    # integral AP only integrates at recall increases -> stays 1.0 until
    # an actual hit ranks below a miss
    det2 = np.zeros((1, 1, 6), "float32")
    det2[0, 0] = [1, 0.95, 0.5, 0.5, 0.9, 0.9]  # miss (top-ranked FP)
    gt2 = np.array([[1]], "int32")
    gb2 = np.array([[[0.0, 0.0, 0.2, 0.2]]], "float32")
    m.update(det2, gt2, gb2)
    v = m.eval()
    assert 0.0 < v < 1.0
    m.reset()
    assert m.eval() == 0.0


def test_generate_proposal_labels_sampling():
    rng = np.random.RandomState(13)
    r, g, bs = 20, 3, 8
    gt = np.stack([_rand_boxes(rng, g, scale=30.0)])
    gt[0, 2] = 0.0  # padding
    gt_cls = np.array([[1, 2, -1]], "int32")
    # candidate rois: jittered copies of the gts + random junk
    rois = np.concatenate([
        gt[0, :2] + 0.8 * rng.randn(2, 4).astype("float32"),
        _rand_boxes(rng, r - 2, scale=30.0),
    ])

    def build():
        rv = fluid.layers.data("r", [r, 4], append_batch_size=False)
        cv = fluid.layers.data("c", [g], dtype="int32")
        gv = fluid.layers.data("g", [g, 4])
        return fluid.layers.generate_proposal_labels(
            rv, cv, None, gv, batch_size_per_im=bs, fg_fraction=0.25,
            class_nums=3, use_random=False)

    rois_o, labels, targets, inw, outw, rw = _run(build, {
        "r": rois, "c": gt_cls, "g": gt})
    n_fg = 2  # round(8 * 0.25)
    cap = n_fg + bs
    assert rois_o.shape == (1, cap, 4)
    assert labels.shape == (1, cap)
    assert targets.shape == (1, cap, 12)  # 4 * class_nums
    valid = rw[0] > 0
    # gt boxes join the pool, so >=1 fg with the right class labels
    fg_labels = labels[0][:n_fg][valid[:n_fg]]
    assert (fg_labels > 0).all() and set(fg_labels) <= {1, 2}
    # regression targets only on the matched class columns of fg rows
    for i in range(n_fg):
        if not valid[i]:
            continue
        cls = labels[0, i]
        cols = slice(4 * cls, 4 * cls + 4)
        assert inw[0, i, cols].sum() == 4.0
        other = np.delete(inw[0, i], np.r_[cols])
        assert other.sum() == 0.0
    # background rows: label 0, no regression
    bg = labels[0][n_fg:][valid[n_fg:]]
    assert (bg == 0).all()
    assert inw[0, n_fg:][valid[n_fg:]].sum() == 0.0


def test_roi_perspective_transform_identity_and_warp():
    rng = np.random.RandomState(2)
    x = rng.rand(1, 2, 8, 8).astype("float32")
    # axis-aligned quad == the whole image: output resamples the image grid
    quad = np.array([[0, 0, 7, 0, 7, 7, 0, 7]], "float32")

    def build():
        xv = fluid.layers.data("x", [2, 8, 8])
        rv = fluid.layers.data("q", [8], append_batch_size=True)
        out = fluid.layers.roi_perspective_transform(xv, rv, 8, 8, 1.0)
        return (out,)

    (out,) = _run(build, {"x": x, "q": quad})
    assert out.shape == (1, 2, 8, 8)
    np.testing.assert_allclose(out[0], x[0], rtol=1e-4, atol=1e-4)

    # half-size output = downsampled content, still finite and in-range
    def build2():
        xv = fluid.layers.data("x", [2, 8, 8])
        rv = fluid.layers.data("q", [8], append_batch_size=True)
        out = fluid.layers.roi_perspective_transform(xv, rv, 4, 4, 1.0)
        return (out,)

    (out2,) = _run(build2, {"x": x, "q": quad})
    assert out2.shape == (1, 2, 4, 4)
    assert np.isfinite(out2).all()
    assert out2.min() >= x.min() - 1e-5 and out2.max() <= x.max() + 1e-5


def test_generate_proposal_labels_small_pool_and_crowd():
    """Pool smaller than the sample capacity must pad, not crash; crowd
    gt rows are excluded from sampling."""
    rng = np.random.RandomState(17)
    r, g, bs = 3, 2, 8  # pool (r + g) << n_fg + bs
    gt = np.stack([_rand_boxes(rng, g, scale=30.0)])
    gt_cls = np.array([[1, 2]], "int32")
    is_crowd = np.array([[0, 1]], "int32")  # second gt is crowd
    rois = gt[0] + 0.5 * rng.randn(g, 4).astype("float32")
    rois = np.concatenate([rois, _rand_boxes(rng, r - g, scale=30.0)])

    def build():
        rv = fluid.layers.data("r", [r, 4], append_batch_size=False)
        cv = fluid.layers.data("c", [g], dtype="int32")
        gv = fluid.layers.data("g", [g, 4])
        ic = fluid.layers.data("ic", [g], dtype="int32")
        return fluid.layers.generate_proposal_labels(
            rv, cv, ic, gv, batch_size_per_im=bs, fg_fraction=0.25,
            class_nums=3, use_random=False)

    rois_o, labels, targets, inw, outw, rw = _run(build, {
        "r": rois, "c": gt_cls, "g": gt, "ic": is_crowd})
    n_fg = 2
    assert rois_o.shape == (1, n_fg + bs, 4)  # fixed capacity held
    valid = rw[0] > 0
    # crowd class (2) never appears as a foreground label
    assert 2 not in set(labels[0][valid].tolist())


def test_ssd_model_zoo_trains_and_evals():
    """models/ssd.py book-style check: loss falls on synthetic boxes and
    the eval head (NMS + mAP) runs on the test clone."""
    from paddle_tpu.models import ssd

    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, feeds, extras = ssd.build(img_shape=(3, 64, 64), class_num=3,
                                        max_gt=2, nms_keep_top_k=10)
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(3e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def batch(n=4):
        xy = rng.uniform(0, 0.6, (n, 2, 2))
        wh = rng.uniform(0.15, 0.35, (n, 2, 2))
        gb = np.concatenate([xy, xy + wh], -1).astype("float32")
        return {"image": rng.rand(n, 3, 64, 64).astype("float32"),
                "gt_box": gb,
                "gt_label": rng.randint(1, 3, (n, 2)).astype("int32")}

    losses = []
    for _ in range(12):
        (lv,) = exe.run(main, feed=batch(), fetch_list=[loss])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])

    out, m = exe.run(test_prog, feed=batch(),
                     fetch_list=[extras["nmsed_out"], extras["map_eval"]])
    out = np.asarray(out)
    assert out.shape[2] == 6
    assert 0.0 <= float(np.ravel(np.asarray(m))[0]) <= 1.0


def test_mine_hard_examples_hard_example_mining_type():
    """mining_type='hard_example' caps negatives at sample_size instead of
    neg_pos_ratio * num_pos (mine_hard_examples_op.cc)."""
    cls_loss = np.array([[5.0, 4.0, 3.0, 2.0, 1.0, 0.5]], "float32")
    midx = np.array([[0, -1, -1, -1, -1, -1]], np.int32)  # 1 positive
    mdist = np.zeros((1, 6), "float32")

    def build(mining_type, sample_size):
        def b():
            cl = fluid.layers.data("cl", [6])
            mi = fluid.layers.data("mi", [6], dtype="int32")
            md = fluid.layers.data("md", [6])
            from paddle_tpu.layer_helper import LayerHelper

            helper = LayerHelper("mine_hard_examples")
            neg = helper.create_variable_for_type_inference(
                "float32", stop_gradient=True)
            upd = helper.create_variable_for_type_inference(
                "int32", stop_gradient=True)
            helper.append_op(
                type="mine_hard_examples",
                inputs={"ClsLoss": [cl],
                        "MatchIndices": [mi],
                        "MatchDist": [md]},
                outputs={"NegMask": [neg], "UpdatedMatchIndices": [upd]},
                attrs={"neg_pos_ratio": 3.0, "neg_dist_threshold": 0.5,
                       "mining_type": mining_type,
                       "sample_size": sample_size},
            )
            return (neg,)

        return b

    feed = {"cl": cls_loss, "mi": midx, "md": mdist}
    (neg_ratio,) = _run(build("max_negative", 0), feed)
    assert neg_ratio[0].sum() == 3  # 3 * num_pos, highest-loss first
    np.testing.assert_array_equal(neg_ratio[0], [0, 1, 1, 1, 0, 0])
    (neg_hard,) = _run(build("hard_example", 2), feed)
    assert neg_hard[0].sum() == 2  # capped by sample_size
    np.testing.assert_array_equal(neg_hard[0], [0, 1, 1, 0, 0, 0])


def test_ssd_trains_data_parallel_on_mesh():
    """SSD loss (matching + mining + NMS-free train path) compiles and
    trains under GSPMD over the 8-device mesh — the whole detection
    machinery is SPMD-safe."""
    from paddle_tpu.models import ssd
    from paddle_tpu.parallel_executor import ParallelExecutor

    rng = np.random.RandomState(5)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        loss, _, _ = ssd.build(img_shape=(3, 32, 32), class_num=3, max_gt=2)
        fluid.optimizer.Adam(3e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                          use_tpu=False)
    assert pe.device_count == 8

    def batch(n=16):  # divisible by 8
        xy = rng.uniform(0, 0.6, (n, 2, 2))
        wh = rng.uniform(0.15, 0.35, (n, 2, 2))
        gb = np.concatenate([xy, xy + wh], -1).astype("float32")
        return {"image": rng.rand(n, 3, 32, 32).astype("float32"),
                "gt_box": gb,
                "gt_label": rng.randint(1, 3, (n, 2)).astype("int32")}

    losses = []
    for _ in range(6):
        (lv,) = pe.run(fetch_list=[loss], feed=batch())
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
