"""Golden diagnostics for the static-analysis subsystem (PR 3).

Each verifier rule and each lint rule gets a minimal bad Program that
must trigger it (asserting the rule id and location) and a clean twin
that must not; every registry model verifies clean at level="error"; and
a deliberately cache-busting program trips the retrace-hazard linter AND
the recompile explainer stamps the same rule id on its event.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis, unique_name
from paddle_tpu.analysis import ProgramVerifyError
from paddle_tpu.analysis import lint as lint_mod
from paddle_tpu.analysis import liveness as liveness_mod
from paddle_tpu.analysis import verify as verify_mod
from paddle_tpu.framework import Operator, Parameter
from paddle_tpu.observability import explain


def _rules(diags):
    return sorted({d.rule for d in diags})


def _empty_prog():
    return fluid.Program()


def _simple_chain():
    """a (data) -> relu -> t -> relu -> out; verifies clean."""
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name="a", shape=(2, 3), dtype="float32", is_data=True)
    b.create_var(name="t", shape=(2, 3), dtype="float32")
    b.create_var(name="out", shape=(2, 3), dtype="float32")
    b.append_op("relu", inputs={"X": ["a"]}, outputs={"Out": ["t"]})
    b.append_op("relu", inputs={"X": ["t"]}, outputs={"Out": ["out"]})
    return prog


def _verify_all(prog, **kw):
    """Collect every diagnostic without raising."""
    return verify_mod.verify(prog, **kw)


# ---------------------------------------------------------------------------
# verifier rules: one bad program + one clean twin each
# ---------------------------------------------------------------------------


class TestVerifierRules(object):
    def test_clean_program_has_no_diagnostics(self):
        assert _verify_all(_simple_chain(), fetch_names=["out"]) == []

    def test_v001_undefined_input(self):
        prog = _simple_chain()
        prog.global_block().append_op(
            "relu", inputs={"X": ["nope"]}, outputs={"Out": ["t"]},
            infer_shape=False)
        with pytest.raises(ProgramVerifyError) as ei:
            prog.verify()
        d = [x for x in ei.value.diagnostics if x.rule == "V001"][0]
        assert d.block_idx == 0 and d.op_idx == 2
        assert "nope" in d.var_names

    def test_v002_use_before_write(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="x", shape=(2,), dtype="float32")
        b.create_var(name="y", shape=(2,), dtype="float32")
        # reads x before the fill that produces it
        b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["y"]},
                    infer_shape=False)
        b.append_op("fill_constant", outputs={"Out": ["x"]},
                    attrs={"shape": [2], "dtype": "float32", "value": 1.0})
        diags = _verify_all(prog)
        d = [x for x in diags if x.rule == "V002"][0]
        assert d.op_idx == 0 and "x" in d.var_names
        # clean twin: producer first
        prog2 = fluid.Program()
        b2 = prog2.global_block()
        b2.create_var(name="x", shape=(2,), dtype="float32")
        b2.create_var(name="y", shape=(2,), dtype="float32")
        b2.append_op("fill_constant", outputs={"Out": ["x"]},
                     attrs={"shape": [2], "dtype": "float32", "value": 1.0})
        b2.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["y"]})
        assert "V002" not in _rules(_verify_all(prog2))

    def test_v002_feed_names_count_as_written(self):
        prog = fluid.Program()
        b = prog.global_block()
        # not marked is_data (a pserver-style runtime feed)
        b.create_var(name="g", shape=(2,), dtype="float32")
        b.create_var(name="o", shape=(2,), dtype="float32")
        b.append_op("relu", inputs={"X": ["g"]}, outputs={"Out": ["o"]})
        assert "V002" in _rules(_verify_all(prog))
        assert "V002" not in _rules(_verify_all(prog, feed_names=["g"]))

    def test_v003_dangling_and_unwritten_fetch(self):
        prog = _simple_chain()
        prog.global_block().create_var(
            name="never_written", shape=(1,), dtype="float32")
        diags = _verify_all(
            prog, fetch_names=["missing", "never_written", "out"])
        v3 = [d for d in diags if d.rule == "V003"]
        assert sorted(n for d in v3 for n in d.var_names) == [
            "missing", "never_written"]

    def test_v004_duplicate_output(self):
        prog = _simple_chain()
        prog.global_block().append_op(
            "dropout", inputs={"X": ["t"]},
            outputs={"Out": ["o2"], "Mask": ["o2"]}, infer_shape=False)
        prog.global_block().create_var(
            name="o2", shape=(2, 3), dtype="float32")
        diags = _verify_all(prog)
        d = [x for x in diags if x.rule == "V004"][0]
        assert d.op_type == "dropout" and "o2" in d.var_names

    def test_v005_overwritten_before_read(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="a", shape=(2,), dtype="float32", is_data=True)
        b.create_var(name="t", shape=(2,), dtype="float32")
        b.append_op("relu", inputs={"X": ["a"]}, outputs={"Out": ["t"]})
        b.append_op("sigmoid", inputs={"X": ["a"]}, outputs={"Out": ["t"]})
        diags = _verify_all(prog)
        assert "V005" in _rules(diags)
        # clean twin: the first write is read before the second write
        prog2 = fluid.Program()
        b2 = prog2.global_block()
        b2.create_var(name="a", shape=(2,), dtype="float32", is_data=True)
        b2.create_var(name="t", shape=(2,), dtype="float32")
        b2.create_var(name="u", shape=(2,), dtype="float32")
        b2.append_op("relu", inputs={"X": ["a"]}, outputs={"Out": ["t"]})
        b2.append_op("relu", inputs={"X": ["t"]}, outputs={"Out": ["u"]})
        b2.append_op("sigmoid", inputs={"X": ["a"]},
                     outputs={"Out": ["t"]})
        assert "V005" not in _rules(_verify_all(prog2))

    def test_v006_unknown_op(self):
        prog = _simple_chain()
        b = prog.global_block()
        op = Operator.__new__(Operator)  # the deserialization path
        op.block, op.type = b, "no_such_op"
        op.inputs, op.outputs, op.attrs = {}, {}, {}
        b.ops.append(op)
        diags = _verify_all(prog)
        d = [x for x in diags if x.rule == "V006"][0]
        assert d.op_idx == 2 and d.op_type == "no_such_op"

    def test_v007_unknown_slot(self):
        prog = _simple_chain()
        prog.global_block().append_op(
            "relu", inputs={"X": ["t"], "Bogus": ["a"]},
            outputs={"Out": ["out"]}, infer_shape=False)
        diags = _verify_all(prog)
        assert any(d.rule == "V007" and "Bogus" in d.message
                   for d in diags)

    def test_v008_slot_arity(self):
        prog = _simple_chain()
        prog.global_block().append_op(
            "relu", inputs={"X": ["a", "t"]}, outputs={"Out": ["out"]},
            infer_shape=False)
        diags = _verify_all(prog)
        assert any(d.rule == "V008" and d.op_idx == 2 for d in diags)

    def test_v009_bad_dtype(self):
        prog = _simple_chain()
        prog.global_block().vars["t"].dtype = "float37"
        diags = _verify_all(prog)
        assert any(d.rule == "V009" and "t" in d.var_names for d in diags)

    def test_v010_v011_unknown_shape(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="u", shape=None, dtype="float32", is_data=True)
        b.create_var(name="o", shape=None, dtype="float32")
        b.append_op("relu", inputs={"X": ["u"]}, outputs={"Out": ["o"]})
        b.append_op("sigmoid", inputs={"X": ["o"]}, outputs={"Out": ["o"]},
                    infer_shape=False)
        diags = _verify_all(prog)
        assert "V010" in _rules(diags) and "V011" in _rules(diags)
        # feed shapes resolve the deferral -> clean
        diags = _verify_all(prog, feed_shapes={"u": (2, 3)})
        assert "V010" not in _rules(diags)
        assert "V011" not in _rules(diags)
        assert b.vars["o"].shape == (2, 3)

    def test_v012_orphaned_grad(self):
        prog = _simple_chain()
        prog.global_block().create_var(
            name="w@GRAD", shape=(2,), dtype="float32")
        diags = _verify_all(prog)
        assert any(d.rule == "V012" and "w@GRAD" in d.var_names
                   for d in diags)

    def test_v013_param_not_persistable(self):
        prog = _simple_chain()
        p = prog.global_block().create_parameter(
            "w", shape=[2], dtype="float32")
        p.persistable = False
        diags = _verify_all(prog)
        assert any(d.rule == "V013" for d in diags)

    def test_v014_v015_subblock_invariants(self):
        prog = _simple_chain()
        sub = prog.create_block()
        prog.rollback()
        p = Parameter(sub, "sub_w", (2,), "float32")
        sub.vars["sub_w"] = p
        sub.create_var(name="sub_state", shape=(2,), dtype="float32",
                       persistable=True)
        diags = _verify_all(prog)
        assert any(d.rule == "V014" and d.block_idx == 1 for d in diags)
        assert any(d.rule == "V015" and "sub_state" in d.var_names
                   for d in diags)

    def test_v016_bad_sub_block(self):
        prog = _simple_chain()
        prog.global_block().append_op(
            "relu", inputs={"X": ["t"]}, outputs={"Out": ["out"]},
            attrs={"sub_block": 99}, infer_shape=False)
        diags = _verify_all(prog)
        assert any(d.rule == "V016" and d.op_idx == 2 for d in diags)

    def test_suppress_and_level_gate(self):
        prog = _simple_chain()
        prog.global_block().append_op(
            "relu", inputs={"X": ["nope"]}, outputs={"Out": ["t"]},
            infer_shape=False)
        assert "V001" not in _rules(
            verify_mod.verify(prog, suppress=("V001",)))
        assert "V001" not in _rules(
            verify_mod.verify(prog, suppress=("undefined-input",)))
        # level=None collects without raising
        diags = prog.verify(level=None)
        assert "V001" in _rules(diags)

    def test_control_flow_models_verify_clean(self):
        """StaticRNN sub-block implicit inputs (rnn_step_in / rnn_mem are
        written by the scan machinery, not by ops) must not trip V002."""
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4, 8], dtype="float32")
            from paddle_tpu.layers.control_flow import StaticRNN

            rnn = StaticRNN()
            with rnn.step():
                x_t = rnn.step_input(x)
                h_prev = rnn.memory(shape=[-1, 8], batch_ref=x)
                h = fluid.layers.elementwise_add(x_t, h_prev)
                rnn.update_memory(h_prev, h)
                rnn.step_output(h)
            out = rnn()
        diags = main.verify(level="error", fetch_names=[out.name])
        assert "V002" not in _rules(diags)
        assert "V001" not in _rules(diags)


# ---------------------------------------------------------------------------
# lint rules
# ---------------------------------------------------------------------------


class TestLintRules(object):
    def test_l001_dynamic_feed_shapes(self):
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            fluid.layers.data("ids", shape=[-1], dtype="int64")  # (-1,-1)
            fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        diags = lint_mod.lint(prog)
        dyn = [d for d in diags if d.rule == "L001"
               and d.severity == "warning"]
        assert any("ids" in d.var_names for d in dyn)
        # static-feed program only gets the info-level batch-dim note
        assert not any("img" in d.var_names and d.severity == "warning"
                       for d in diags)

    def test_l002_literal_scalar_attrs(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="p", shape=(2,), dtype="float32",
                     persistable=True)
        b.create_var(name="g", shape=(2,), dtype="float32", is_data=True)
        # hand-rolled sgd with no LearningRate var and a baked literal
        b.append_op("sgd", inputs={"Param": ["p"], "Grad": ["g"]},
                    outputs={"ParamOut": ["p"]},
                    attrs={"learning_rate": 0.1}, infer_shape=False)
        diags = lint_mod.lint(prog)
        assert [d for d in diags if d.rule == "L002"]
        # the Optimizer classes route the rate through a var: clean
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(input=x, size=3)
            loss = fluid.layers.reduce_mean(y)
            fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
        assert "L002" not in _rules(lint_mod.lint(main))

    def test_l003_nondeterministic_names(self):
        with unique_name.guard():
            for _ in range(5):
                unique_name.generate("fc")  # simulate earlier builds
            prog = fluid.Program()
            with fluid.program_guard(prog, fluid.Program()):
                x = fluid.layers.data("x", shape=[4], dtype="float32")
                fluid.layers.fc(input=x, size=3)
        diags = [d for d in lint_mod.lint(prog) if d.rule == "L003"]
        assert diags and "unique_name.guard" in diags[0].hint
        # fresh counters: clean
        with unique_name.guard():
            prog2 = fluid.Program()
            with fluid.program_guard(prog2, fluid.Program()):
                x = fluid.layers.data("x", shape=[4], dtype="float32")
                fluid.layers.fc(input=x, size=3)
        assert "L003" not in _rules(lint_mod.lint(prog2))

    def test_l004_fetch_churn_from_events(self):
        explain.reset()
        base = {"program": "f" * 64, "feed_specs": (), "scope_signature":
                frozenset(), "flags": (), "device": "cpu:0",
                "mode": "single"}
        explain.record_compile(dict(base, fetch_names=("a",)))
        explain.record_compile(dict(base, fetch_names=("b",)))
        explain.record_compile(dict(base, fetch_names=("c",)))
        evs = explain.events()
        assert evs[-1]["changed"] == ["fetch_names"]
        assert evs[-1]["lint_rule"] == "L004"
        diags = lint_mod.lint_events(min_count=2)
        assert [d for d in diags if d.rule == "L004"]
        explain.reset()


# ---------------------------------------------------------------------------
# acceptance: cache-busting program -> linter AND explainer agree
# ---------------------------------------------------------------------------


def test_cache_busting_program_trips_linter_and_explainer():
    explain.reset()
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[4, -1], dtype="float32")
        out = fluid.layers.relu(x)
    # static lint predicts the retrace hazard
    diags = [d for d in lint_mod.lint(prog)
             if d.rule == "L001" and d.severity == "warning"]
    assert diags and "x" in diags[0].var_names
    # ... and running with churning shapes produces explainer events
    # naming the SAME rule id
    exe = fluid.Executor(fluid.CPUPlace())
    for width in (3, 5):
        exe.run(prog,
                feed={"x": np.zeros((2, 4, width), dtype="float32")},
                fetch_list=[out])
    evs = explain.events()
    assert len(evs) >= 2
    assert "feed_specs" in evs[-1]["changed"]
    assert evs[-1]["lint_rule"] == "L001"
    assert [d for d in lint_mod.lint_events(min_count=1)
            if d.rule == "L001"]
    explain.reset()


# ---------------------------------------------------------------------------
# every registry model verifies clean at level="error"
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(__import__(
    "golden_models").GOLDEN_MODELS))
def test_golden_models_verify_clean(name):
    from golden_models import GOLDEN_MODELS

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feed_names, fetch, _feed = GOLDEN_MODELS[name]()
    fetch_name = fetch.name if hasattr(fetch, "name") else str(fetch)
    # raises ProgramVerifyError on any error-severity diagnostic
    main.verify(level="error", fetch_names=[fetch_name],
                feed_names=list(feed_names))
    startup.verify(level="error")


# ---------------------------------------------------------------------------
# liveness
# ---------------------------------------------------------------------------


class TestLiveness(object):
    def test_dead_op_detection_and_ranges(self):
        prog = _simple_chain()
        b = prog.global_block()
        b.create_var(name="orphan", shape=(2, 3), dtype="float32")
        b.append_op("relu", inputs={"X": ["a"]},
                    outputs={"Out": ["orphan"]})
        info = liveness_mod.analyze(prog, fetch_names=["out"])
        bl = info.block(0)
        assert bl.dead_ops == [2]
        assert info.dead_op_count == 1
        # a: block input (def None), read by ops 0 and 2
        assert bl.live_ranges["a"] == (None, 2)
        # t: defined by op 0, last read by op 1
        assert bl.live_ranges["t"] == (0, 1)
        # out: escapes (fetched) -> last_use == n_ops
        assert bl.live_ranges["out"] == (1, bl.n_ops)

    def test_persistable_writes_are_live(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="state", shape=(2,), dtype="float32",
                     persistable=True)
        b.append_op("fill_constant", outputs={"Out": ["state"]},
                    attrs={"shape": [2], "dtype": "float32", "value": 0.0})
        info = liveness_mod.analyze(prog)
        assert info.block(0).dead_ops == []

    def test_memory_optimize_counts_live_grad_ops(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(input=x, size=3)
            loss = fluid.layers.reduce_mean(y)
            fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
        n = fluid.memory_optimize(main)
        assert n > 0
        assert main._remat is True


# ---------------------------------------------------------------------------
# deferred shape inference (satellite) + executor/flag integration
# ---------------------------------------------------------------------------


class TestDeferredShapes(object):
    def test_infer_shape_false_is_deferred_then_resolved(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="u", shape=None, dtype="float32", is_data=True)
        b.create_var(name="o", shape=None, dtype="float32")
        b.append_op("relu", inputs={"X": ["u"]}, outputs={"Out": ["o"]},
                    infer_shape=False)
        assert b.vars["o"].shape is None
        failures = prog.infer_deferred_shapes(feed_shapes={"u": (2, 5)})
        assert failures == []
        assert b.vars["o"].shape == (2, 5)
        assert prog._deferred_infer == []

    def test_executor_resolves_deferred_shapes_from_feeds(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="u", shape=None, dtype="float32", is_data=True)
        b.create_var(name="o", shape=None, dtype="float32")
        b.append_op("relu", inputs={"X": ["u"]}, outputs={"Out": ["o"]},
                    infer_shape=False)
        exe = fluid.Executor(fluid.CPUPlace())
        x = np.array([[-1.0, 2.0]], dtype="float32")
        (res,) = exe.run(prog, feed={"u": x}, fetch_list=["o"])
        np.testing.assert_allclose(res, np.maximum(x, 0.0))
        assert b.vars["o"].shape == (1, 2)

    def test_verify_flag_gates_executor(self):
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="a", shape=(2,), dtype="float32", is_data=True)
        b.create_var(name="o", shape=(2,), dtype="float32")
        b.append_op("relu", inputs={"X": ["missing_input"]},
                    outputs={"Out": ["o"]}, infer_shape=False)
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.flags.set_flag("verify_program", True)
        try:
            with pytest.raises(ProgramVerifyError):
                exe.run(prog, feed={"a": np.zeros(2, "float32")},
                        fetch_list=["o"])
        finally:
            fluid.flags.set_flag("verify_program", False)

    def test_transpiler_hook_verifies_output(self):
        from paddle_tpu.transpiler import GradientMergeTranspiler

        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(input=x, size=3)
            loss = fluid.layers.reduce_mean(y)
            fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
        fluid.flags.set_flag("verify_program", True)
        try:
            # a healthy transpile passes the post-transpile verifier
            GradientMergeTranspiler().transpile(main, k_steps=2)
        finally:
            fluid.flags.set_flag("verify_program", False)


# ---------------------------------------------------------------------------
# debugger rendering of diagnostics
# ---------------------------------------------------------------------------


class TestDebuggerRendering(object):
    def _flagged(self):
        prog = _simple_chain()
        prog.global_block().append_op(
            "relu", inputs={"X": ["nope"]}, outputs={"Out": ["t"]},
            infer_shape=False)
        return prog, prog.verify(level=None)

    def test_program_to_code_marks_flagged_ops(self):
        from paddle_tpu import debugger

        prog, diags = self._flagged()
        code = debugger.program_to_code(prog, diagnostics=diags)
        flagged = [ln for ln in code.splitlines() if ln.startswith(" !")]
        assert flagged and "V001" in flagged[0]
        # attrs are part of the dump
        assert "{" in flagged[0]
        clean = debugger.program_to_code(prog)
        assert not [ln for ln in clean.splitlines()
                    if ln.startswith(" !")]

    def test_graphviz_colors_diagnostics_red(self, tmp_path):
        from paddle_tpu import debugger

        prog, diags = self._flagged()
        dot = debugger.draw_block_graphviz(
            prog.global_block(), path=str(tmp_path / "g.dot"),
            diagnostics=diags)
        assert "#ff9d9d" in dot and "V001" in dot
        dot_clean = debugger.draw_block_graphviz(
            prog.global_block(), path=str(tmp_path / "g2.dot"))
        assert "#ff9d9d" not in dot_clean


# ---------------------------------------------------------------------------
# plint CLI
# ---------------------------------------------------------------------------


def test_plint_cli_over_saved_model(tmp_path):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import plint

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(input=x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(
        str(tmp_path / "model"), ["x"], [y], exe, main_program=main)
    assert plint.main([str(tmp_path / "model")]) == 0
    # corrupt the saved graph: dangling input -> nonzero exit
    from paddle_tpu.core.program_bin import (
        deserialize_program,
        serialize_program,
    )

    with open(str(tmp_path / "model" / "__model__"), "rb") as f:
        prog = deserialize_program(f.read())
    prog.global_block().ops[0].inputs["X"] = ["gone"]
    with open(str(tmp_path / "model" / "__model__"), "wb") as f:
        f.write(serialize_program(prog))
    assert plint.main([str(tmp_path / "model")]) == 1
