"""Differential fuzz: the XLA engine vs the C++ reference interpreter.

VERDICT r4 Next #3: the r3 window-attr bug (C++ SDPA silently ignoring
``window``) proved that fixed-input goldens don't cover attr space —
every attr added to a Python lowering must be mirrored or explicitly
rejected by the C++ engine, and nothing systematically checked that.

This harness generates seeded random programs over the op families the
C++ interpreter dispatches (native/src/interp.h), with randomized
shapes AND attrs including the known corner attrs (window, kv_group,
is_reverse, padding_idx, ceil_mode, use_peepholes, keep_dim, axis...).
For every program, both engines run the same program bytes over the
same scope:

* outputs agree within f32 tolerance  -> pass, or
* the C++ engine refuses EXPLICITLY (nonzero rc + message)  -> pass
  (an honest capability boundary), or
* anything else — silent wrong numbers, a crash, a missing output —
  -> the test fails with the seed, so the case replays exactly.

Reference analog: the op_test.py check_output discipline
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:131),
turned cross-engine instead of cross-device.

Env knobs: PTPU_FUZZ_N (default 200 cases), PTPU_FUZZ_SEED (base seed,
default 20260801).
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native

N_CASES = int(os.environ.get("PTPU_FUZZ_N", "200"))
BASE_SEED = int(os.environ.get("PTPU_FUZZ_SEED", "20260801"))

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="native toolchain unavailable: %s" % native.last_error())


# set by _run_case before invoking the drawn case: multiplexed
# families (several ops behind one case) use it to ROUND-ROBIN their op
# menu across seeds instead of an independent random draw, so a default
# 200-case run spreads over the menu deterministically; the per-op CI
# guarantee comes from test_fuzz_every_multiplexed_op below, which
# forces every (family, op) pair once.
_CURRENT_SEED = [0]


class CppRefusal(Exception):
    """The C++ engine declined the program with an explicit message."""


def run_cpp(program, scope, feed, fetch_name):
    """Drive native/src/interp.h directly on the program bytes (the
    run_native_reference path minus the save/load round-trip)."""
    from paddle_tpu.core.program_bin import serialize_program

    lib = native.get_lib()
    blob = serialize_program(program)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    if not prog:
        raise CppRefusal(native.last_error())
    try:
        ns = native.NativeScope()
        for name in scope.local_var_names():
            val = scope.get_value(name)
            if val is not None:
                arr = np.asarray(val)
                if arr.dtype == np.float64:
                    arr = arr.astype(np.float32)
                ns.set(name, arr)
        for name, val in feed.items():
            arr = np.asarray(val)
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        if rc != 0:
            raise CppRefusal(native.last_error())
        out = ns.get(fetch_name)
        if out is None:
            raise AssertionError(
                "C++ engine returned rc=0 but fetch %r is missing "
                "(silent failure)" % fetch_name)
        return out
    finally:
        lib.ptpu_program_destroy(prog)


# --------------------------------------------------------------- helpers

def _data(name, shape, dtype="float32"):
    return fluid.layers.data(name=name, shape=list(shape[1:]), dtype=dtype)


def _feedval(rng, shape, dtype="float32", low=-1.0, high=1.0):
    if dtype == "int64":
        return rng.randint(0, 8, shape).astype("int64")
    return rng.uniform(low, high, shape).astype("float32")


# ---------------------------------------------------------- case builders
# Each builder: (rng) -> (fetch_var, feed_dict). Called inside a
# program_guard. Shapes stay tiny: the point is attr/op coverage, not
# throughput.

def case_elementwise(rng):
    op = rng.choice(["elementwise_add", "elementwise_sub",
                     "elementwise_mul", "elementwise_div",
                     "elementwise_max", "elementwise_min"])
    nd = int(rng.randint(2, 5))
    shape = tuple(int(rng.randint(1, 5)) for _ in range(nd))
    x = _data("x", shape)
    fx = _feedval(rng, shape)
    fy = _feedval(rng, shape)
    if op == "elementwise_div":
        fy = np.abs(fy) + 0.5
    y = _data("y", shape)
    out = getattr(fluid.layers, op)(x, y)
    return out, {"x": fx, "y": fy}


def case_act_chain(rng):
    shape = (int(rng.randint(1, 4)), int(rng.randint(2, 9)))
    x = _data("x", shape)
    v = x
    for _ in range(int(rng.randint(1, 4))):
        act = rng.choice(["relu", "tanh", "sigmoid", "scale", "softmax",
                          "log_softmax"])
        if act == "scale":
            v = fluid.layers.scale(v, scale=float(rng.uniform(0.5, 2.0)),
                                   bias=float(rng.uniform(-1, 1)))
        else:
            v = getattr(fluid.layers, act)(v)
    return v, {"x": _feedval(rng, shape)}


def case_matmul(rng):
    m, k, n = (int(rng.randint(1, 7)) for _ in range(3))
    x = _data("x", (2, m, k))  # leading batch folded by mul's num_flatten
    y = _data("y", (2, k, n))
    x2 = fluid.layers.reshape(x, [-1, k])
    y2 = fluid.layers.reshape(y, [k, -1])
    out = fluid.layers.mul(x2, y2)
    return out, {"x": _feedval(rng, (2, m, k)), "y": _feedval(rng, (2, k, n))}


def case_fc(rng):
    bs, d = int(rng.randint(1, 5)), int(rng.randint(2, 9))
    size = int(rng.randint(2, 9))
    act = rng.choice([None, "relu", "tanh", "sigmoid"])
    x = _data("x", (bs, d))
    out = fluid.layers.fc(x, size=size, act=None if act is None else str(act))
    return out, {"x": _feedval(rng, (bs, d))}


def case_conv(rng):
    cin = int(rng.choice([1, 2, 3, 4]))
    cout_mult = int(rng.randint(1, 4))
    groups = int(rng.choice([1, 1, 1, cin]))
    cout = cout_mult * max(1, groups)
    hw = int(rng.randint(5, 11))
    k = int(rng.choice([1, 3, 5]))
    stride = int(rng.choice([1, 2]))
    pad = int(rng.choice([0, 1, 2]))
    x = _data("x", (2, cin, hw, hw))
    v = fluid.layers.conv2d(x, num_filters=cout, filter_size=k,
                            stride=stride, padding=pad, groups=groups,
                            act=None)
    if rng.rand() < 0.4:
        v = fluid.layers.batch_norm(v, is_test=True)
    if rng.rand() < 0.4:
        v = fluid.layers.relu(v)
    return v, {"x": _feedval(rng, (2, cin, hw, hw))}


def case_conv_transpose(rng):
    cin = int(rng.randint(1, 4))
    cout = int(rng.randint(1, 4))
    hw = int(rng.randint(4, 8))
    k = int(rng.choice([2, 3, 4]))
    stride = int(rng.choice([1, 2]))
    pad = int(rng.choice([0, 1]))
    x = _data("x", (2, cin, hw, hw))
    v = fluid.layers.conv2d_transpose(x, num_filters=cout, filter_size=k,
                                      stride=stride, padding=pad)
    return v, {"x": _feedval(rng, (2, cin, hw, hw))}


def case_pool(rng):
    c = int(rng.randint(1, 4))
    hw = int(rng.randint(4, 10))
    x = _data("x", (2, c, hw, hw))
    v = fluid.layers.pool2d(
        x,
        pool_size=int(rng.choice([2, 3])),
        pool_type=str(rng.choice(["max", "avg"])),
        pool_stride=int(rng.choice([1, 2])),
        pool_padding=int(rng.choice([0, 1])),
        ceil_mode=bool(rng.rand() < 0.3),   # corner attr (r5: now a
        # PARITY corner — both engines implement ceil_mode)
        global_pooling=bool(rng.rand() < 0.2),
    )
    return v, {"x": _feedval(rng, (2, c, hw, hw))}


def case_norm(rng):
    which = rng.choice(["layer_norm", "lrn"])
    if which == "layer_norm":
        shape = (2, int(rng.randint(2, 6)), int(rng.randint(2, 6)))
        x = _data("x", shape)
        v = fluid.layers.layer_norm(
            x, begin_norm_axis=int(rng.choice([1, 2])))
    else:
        c = int(rng.randint(2, 8))
        shape = (2, c, 4, 4)
        x = _data("x", shape)
        # even n is the ADVICE r4 window-bias corner
        v = fluid.layers.lrn(x, n=int(rng.choice([3, 4, 5])))
    return v, {"x": _feedval(rng, shape)}


def case_reduce(rng):
    nd = int(rng.randint(2, 5))
    shape = tuple(int(rng.randint(1, 5)) for _ in range(nd))
    x = _data("x", shape)
    op = rng.choice(["reduce_sum", "reduce_mean"])
    if rng.rand() < 0.25:
        # reduce_all path (dim=None): the attr is a BOOL — a missed
        # kBool arm in the C++ geometry once silently reduced dim 0
        # instead (caught by the MT golden, now pinned here)
        v = getattr(fluid.layers, op)(x, dim=None)
        return v, {"x": _feedval(rng, shape)}
    dims = sorted(rng.choice(nd, size=int(rng.randint(1, nd)),
                             replace=False).tolist())
    v = getattr(fluid.layers, op)(
        x, dim=[int(d) for d in dims], keep_dim=bool(rng.rand() < 0.5))
    return v, {"x": _feedval(rng, shape)}


def case_shape_ops(rng):
    which = rng.choice(["transpose", "reshape", "flatten", "concat",
                        "split", "sum"])
    if which == "transpose":
        nd = int(rng.randint(2, 5))
        shape = tuple(int(rng.randint(1, 5)) for _ in range(nd))
        perm = rng.permutation(nd).tolist()
        x = _data("x", shape)
        v = fluid.layers.transpose(x, perm=[int(p) for p in perm])
        return v, {"x": _feedval(rng, shape)}
    if which == "reshape":
        shape = (2, int(rng.randint(2, 5)), int(rng.randint(2, 5)))
        x = _data("x", shape)
        n = int(np.prod(shape))
        v = fluid.layers.reshape(x, shape=[n // shape[0], shape[0]])
        return v, {"x": _feedval(rng, shape)}
    if which == "flatten":
        shape = (2, 3, int(rng.randint(2, 5)), 2)
        x = _data("x", shape)
        v = fluid.layers.flatten(x, axis=int(rng.choice([1, 2, 3])))
        return v, {"x": _feedval(rng, shape)}
    if which == "concat":
        axis = int(rng.choice([0, 1]))
        a = (2, int(rng.randint(2, 5)))
        b = list(a)
        b[axis] = int(rng.randint(1, 4))
        x = _data("x", a)
        y = _data("y", tuple(b))
        v = fluid.layers.concat([x, y], axis=axis)
        return v, {"x": _feedval(rng, a), "y": _feedval(rng, tuple(b))}
    if which == "split":
        n = int(rng.choice([2, 3]))
        shape = (2, n * int(rng.randint(1, 4)))
        x = _data("x", shape)
        parts = fluid.layers.split(x, num_or_sections=n, dim=1)
        v = parts[int(rng.randint(0, n))]
        return v, {"x": _feedval(rng, shape)}
    shape = (2, int(rng.randint(2, 5)))
    x = _data("x", shape)
    y = _data("y", shape)
    v = fluid.layers.sum([x, y])
    return v, {"x": _feedval(rng, shape), "y": _feedval(rng, shape)}


def case_embedding(rng):
    vocab, dim = int(rng.randint(4, 12)), int(rng.randint(2, 6))
    bs, seq = 2, int(rng.randint(1, 5))
    padding_idx = rng.choice([None, 0, vocab - 1])  # corner attr
    ids = _data("ids", (bs, seq), dtype="int64")
    emb = fluid.layers.embedding(
        ids, size=[vocab, dim],
        padding_idx=None if padding_idx is None else int(padding_idx))
    # lookup_table squeezes a trailing singleton id dim: seq==1 yields a
    # rank-2 emb, so reduce over the LAST axis, not a hardcoded one
    # (the hardcoded dim=[2] variant exposed a real lowering bug: see
    # reduce_axes' out-of-range validation)
    v = fluid.layers.reduce_sum(emb, dim=[-1])
    feed_ids = rng.randint(0, vocab, (bs, seq)).astype("int64")
    return v, {"ids": feed_ids}


def case_xent(rng):
    bs, nc = int(rng.randint(2, 5)), int(rng.randint(2, 8))
    logits = _data("x", (bs, nc))
    label = _data("label", (bs, 1), dtype="int64")
    if rng.rand() < 0.5:
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    else:
        prob = fluid.layers.softmax(logits)
        loss = fluid.layers.cross_entropy(input=prob, label=label)
    v = fluid.layers.mean(loss)
    return v, {"x": _feedval(rng, (bs, nc)),
               "label": rng.randint(0, nc, (bs, 1)).astype("int64")}


def case_topk(rng):
    bs, n = int(rng.randint(1, 4)), int(rng.randint(3, 9))
    k = int(rng.randint(1, n + 1))
    x = _data("x", (bs, n))
    vals, _idx = fluid.layers.topk(x, k=k)
    return vals, {"x": _feedval(rng, (bs, n))}


def case_sdpa(rng):
    b, t, d = 2, int(rng.choice([4, 6, 8])), int(rng.choice([4, 8]))
    h = int(rng.choice([2, 4]))
    kv_group = int(rng.choice([1, 1, 2]))   # corner attr
    kvh = h // kv_group
    causal = bool(rng.rand() < 0.5)
    window = int(rng.choice([0, 0, max(1, t // 2)]))   # corner attr
    q = _data("q", (b, h, t, d))
    k = _data("k", (b, kvh, t, d))
    v = _data("v", (b, kvh, t, d))
    out = fluid.layers.scaled_dot_product_attention(
        q, k, v, causal=causal, kv_group=kv_group, window=window,
        impl="reference")
    out = fluid.layers.reduce_mean(out, dim=[3])
    return out, {"q": _feedval(rng, (b, h, t, d)),
                 "k": _feedval(rng, (b, kvh, t, d)),
                 "v": _feedval(rng, (b, kvh, t, d))}


def case_gru(rng):
    size = int(rng.choice([2, 3, 4]))
    bs, t = 2, int(rng.randint(2, 6))
    is_reverse = bool(rng.rand() < 0.5)   # corner attr
    x = _data("x", (bs, t, 3 * size))
    kwargs = {}
    feed = {"x": _feedval(rng, (bs, t, 3 * size))}
    if rng.rand() < 0.5:
        length = _data("len", (bs, 1), dtype="int64")
        kwargs["length"] = length
        feed["len"] = rng.randint(1, t + 1, (bs, 1)).astype("int64")
    v = fluid.layers.dynamic_gru(x, size=size, is_reverse=is_reverse,
                                 **kwargs)
    v = fluid.layers.reduce_mean(v, dim=[2])
    return v, feed


def case_lstm(rng):
    hidden = int(rng.choice([2, 3]))
    bs, t = 2, int(rng.randint(2, 6))
    x = _data("x", (bs, t, 4 * hidden))
    kwargs = {}
    feed = {"x": _feedval(rng, (bs, t, 4 * hidden))}
    if rng.rand() < 0.5:
        length = _data("len", (bs, 1), dtype="int64")
        kwargs["length"] = length
        feed["len"] = rng.randint(1, t + 1, (bs, 1)).astype("int64")
    h, _c = fluid.layers.dynamic_lstm(
        x, size=4 * hidden,
        use_peepholes=bool(rng.rand() < 0.5),
        is_reverse=bool(rng.rand() < 0.5), **kwargs)
    v = fluid.layers.reduce_mean(h, dim=[2])
    return v, feed


def case_cast_chain(rng):
    shape = (2, int(rng.randint(2, 6)))
    x = _data("x", shape)
    v = fluid.layers.cast(fluid.layers.scale(x, scale=4.0), "int32")
    v = fluid.layers.cast(v, "float32")
    return v, {"x": _feedval(rng, shape)}


def case_moe_ffn(rng):
    """The interpreter's newest and most intricate kernel (r5): Switch
    routing with capacity queues — fuzz expert count, top_k, fractional
    capacity factors (the f32-vs-double truncation corner), activation,
    and the optional token mask."""
    b, t = 2, int(rng.randint(2, 5))
    d = int(rng.choice([4, 8]))
    experts = int(rng.choice([2, 3, 4]))
    top_k = int(rng.randint(1, min(3, experts) + 1))
    cap = float(rng.choice([0.7, 1.0, 1.25, 2.0]))
    act = str(rng.choice(["gelu", "relu", "tanh", "sigmoid"]))
    x = _data("x", (b, t, d))
    feed = {"x": _feedval(rng, (b, t, d))}
    kwargs = {}
    if rng.rand() < 0.4:
        mask = _data("mask", (b, t))
        kwargs["mask"] = mask
        feed["mask"] = rng.randint(0, 2, (b, t)).astype("float32")
    y, aux = fluid.layers.moe_ffn(
        x, num_experts=experts, d_hidden=int(rng.choice([4, 8])),
        top_k=top_k, capacity_factor=cap, act=act, **kwargs)
    out = fluid.layers.elementwise_add(
        fluid.layers.reduce_mean(y, dim=[2]),
        fluid.layers.expand(
            fluid.layers.reshape(aux, shape=[1, 1]),
            expand_times=[b, t]))
    return out, feed


UNARY_OPS = [
    "exp", "log", "sqrt", "rsqrt", "abs", "square", "reciprocal",
    "floor", "ceil", "round", "sign", "softplus", "softsign",
    "tanh_shrink", "logsigmoid", "gelu", "sin", "cos", "leaky_relu",
    "elu", "relu6", "pow", "stanh", "hard_sigmoid",
    "thresholded_relu", "soft_relu", "brelu", "swish", "softshrink",
    "hard_shrink"]


def case_unary(rng, which=None):
    """The r5 C++ unary/activation batch: every op maps to a scalar
    function of (x, attrs); random attrs hit the parameterized ones
    through the generated layer wrappers (which pass attr kwargs
    straight through to the op)."""
    shape = (2, int(rng.randint(2, 7)))
    if which is None:
        which = UNARY_OPS[_CURRENT_SEED[0] % len(UNARY_OPS)]
    x = _data("x", shape)
    fx = _feedval(rng, shape, low=-2.0, high=2.0)
    if which in ("log", "sqrt", "rsqrt"):
        fx = np.abs(fx) + 0.1
    if which == "reciprocal":
        fx = np.sign(fx) * (np.abs(fx) + 0.3)
    attrs = {}
    if which == "leaky_relu":
        attrs["alpha"] = float(rng.uniform(0.01, 0.3))
    elif which == "elu":
        attrs["alpha"] = float(rng.uniform(0.5, 2.0))
    elif which == "pow":
        attrs["factor"] = float(rng.choice([2.0, 3.0, 0.5]))
        fx = np.abs(fx) + 0.1
    elif which in ("thresholded_relu", "hard_shrink", "soft_relu"):
        attrs["threshold"] = float(rng.uniform(0.2, 1.5))
    elif which == "softshrink":
        attrs["lambda"] = float(rng.uniform(0.1, 1.0))
    elif which == "brelu":
        attrs["t_min"] = float(rng.uniform(-1.0, 0.0))
        attrs["t_max"] = float(rng.uniform(0.5, 2.0))
    elif which == "swish":
        attrs["beta"] = float(rng.uniform(0.5, 2.0))
    elif which == "stanh":
        attrs["scale_a"] = float(rng.uniform(0.4, 1.0))
        attrs["scale_b"] = float(rng.uniform(1.0, 2.0))
    elif which == "hard_sigmoid":
        attrs["slope"] = float(rng.uniform(0.1, 0.4))
        attrs["offset"] = float(rng.uniform(0.3, 0.7))
    elif which == "relu6":
        attrs["threshold"] = float(rng.uniform(3.0, 8.0))
    layer = getattr(fluid.layers, which)
    v = layer(x, **attrs)
    # the attrs must actually land on the op (a wrapper silently
    # dropping kwargs would turn this family into defaults-only)
    if attrs:
        op = fluid.default_main_program().global_block().ops[-1]
        for k, val in attrs.items():
            got = op.attrs.get(k)
            assert got is not None and abs(float(got) - val) < 1e-6, (
                "layer wrapper dropped attr %r for %s" % (k, which))
    return v, {"x": fx}


INDEXING_OPS = [
    "slice", "gather", "stack", "pad", "one_hot", "matmul", "clip", "cumsum", "elementwise_pow"]


def case_indexing(rng, which=None):
    """r5 C++ batch 2: slice/gather/stack/pad/one_hot/matmul/clip/
    cumsum/elementwise_pow with randomized attrs."""
    if which is None:
        which = INDEXING_OPS[_CURRENT_SEED[0] % len(INDEXING_OPS)]
    if which == "slice":
        shape = (3, int(rng.randint(3, 7)), int(rng.randint(3, 7)))
        x = _data("x", shape)
        ax = int(rng.choice([1, 2]))
        st = int(rng.randint(0, shape[ax] - 1))
        en = int(rng.randint(st + 1, shape[ax] + 1))
        if rng.rand() < 0.3:
            st, en = st - shape[ax], en - shape[ax]  # negative indexing
            if en == 0:
                en = shape[ax]  # slice(st, 0) would be empty
        v = fluid.layers.slice(x, axes=[ax], starts=[st], ends=[en])
        return v, {"x": _feedval(rng, shape)}
    if which == "gather":
        rows, d = int(rng.randint(3, 8)), int(rng.randint(2, 5))
        k = int(rng.randint(1, 6))
        x = _data("x", (rows, d))
        idx = _data("idx", (k,), dtype="int64")
        v = fluid.layers.gather(x, idx)
        return v, {"x": _feedval(rng, (rows, d)),
                   "idx": rng.randint(0, rows, (k,)).astype("int64")}
    if which == "stack":
        shape = (2, int(rng.randint(2, 5)))
        xs = [_data("x%d" % i, shape) for i in range(int(rng.randint(2, 4)))]
        axis = int(rng.choice([0, 1, -1]))
        v = fluid.layers.stack(xs, axis=axis)
        return v, {"x%d" % i: _feedval(rng, shape)
                   for i in range(len(xs))}
    if which == "pad":
        shape = (2, int(rng.randint(2, 5)), int(rng.randint(2, 5)))
        x = _data("x", shape)
        pads = [int(rng.randint(0, 3)) for _ in range(6)]
        v = fluid.layers.pad(x, paddings=pads,
                             pad_value=float(rng.uniform(-1, 1)))
        return v, {"x": _feedval(rng, shape)}
    if which == "one_hot":
        bs, depth = int(rng.randint(2, 5)), int(rng.randint(3, 9))
        ids = _data("ids", (bs, 1), dtype="int64")
        v = fluid.layers.one_hot(ids, depth=depth)
        return v, {"ids": rng.randint(0, depth, (bs, 1)).astype("int64")}
    if which == "matmul":
        b = 2
        m, k, n = (int(rng.randint(1, 5)) for _ in range(3))
        tx, ty = bool(rng.rand() < 0.5), bool(rng.rand() < 0.5)
        # independent per-side batching covers the mixed-rank broadcast
        # paths (3D x 2D and 2D x 3D) RunMatmul implements
        bx, by = bool(rng.rand() < 0.5), bool(rng.rand() < 0.5)
        xs = ((b,) if bx else ()) + ((k, m) if tx else (m, k))
        ys = ((b,) if by else ()) + ((n, k) if ty else (k, n))
        x = _data("x", xs if bx else (1,) + xs)
        y = _data("y", ys if by else (1,) + ys)
        if not bx:
            x = fluid.layers.reshape(x, list(xs))
        if not by:
            y = fluid.layers.reshape(y, list(ys))
        v = fluid.layers.matmul(x, y, transpose_x=tx, transpose_y=ty,
                                alpha=float(rng.choice([1.0, 0.5, 2.0])))
        feed = {"x": _feedval(rng, xs if bx else (1,) + xs),
                "y": _feedval(rng, ys if by else (1,) + ys)}
        return v, feed
    if which == "clip":
        shape = (2, int(rng.randint(2, 7)))
        x = _data("x", shape)
        lo = float(rng.uniform(-1.0, 0.0))
        v = fluid.layers.clip(x, min=lo, max=float(rng.uniform(0.0, 1.0)))
        return v, {"x": _feedval(rng, shape, low=-2.0, high=2.0)}
    if which == "cumsum":
        shape = (2, int(rng.randint(2, 6)), int(rng.randint(2, 5)))
        x = _data("x", shape)
        v = fluid.layers.cumsum(
            x, axis=int(rng.choice([1, 2, -1])),
            exclusive=bool(rng.rand() < 0.5),
            reverse=bool(rng.rand() < 0.5))
        return v, {"x": _feedval(rng, shape)}
    shape = (2, int(rng.randint(2, 5)))
    x = _data("x", shape)
    y = _data("y", shape)
    v = fluid.layers.elementwise_pow(x, y)
    return v, {"x": np.abs(_feedval(rng, shape)) + 0.2,
               "y": _feedval(rng, shape, low=-2.0, high=2.0)}


MISC_OPS = [
    "scatter", "argmax", "assign", "shape", "prelu", "fill_zeros_like"]


def case_misc(rng, which=None):
    """r5 C++ batch 3: scatter/argmax/assign/shape/prelu."""
    if which is None:
        which = MISC_OPS[_CURRENT_SEED[0] % len(MISC_OPS)]
    if which == "scatter":
        rows, d = int(rng.randint(3, 7)), int(rng.randint(2, 5))
        k = int(rng.randint(1, rows + 1))
        x = _data("x", (rows, d))
        # distinct ids: overwrite-mode result is order-dependent on
        # duplicates (XLA .at[].set picks one arbitrarily)
        ids_val = rng.permutation(rows)[:k].astype("int64")
        ids = _data("ids", (k,), dtype="int64")
        upd = _data("upd", (k, d))
        v = fluid.layers.scatter(x, ids, upd,
                                 overwrite=bool(rng.rand() < 0.5))
        return v, {"x": _feedval(rng, (rows, d)), "ids": ids_val,
                   "upd": _feedval(rng, (k, d))}
    if which == "argmax":
        shape = (2, int(rng.randint(2, 6)), int(rng.randint(2, 5)))
        x = _data("x", shape)
        v = fluid.layers.argmax(x, axis=int(rng.choice([1, 2, -1])))
        v = fluid.layers.cast(v, "float32")
        return v, {"x": _feedval(rng, shape)}
    if which == "assign":
        shape = (2, int(rng.randint(2, 5)))
        x = _data("x", shape)
        v = fluid.layers.assign(fluid.layers.scale(x, scale=2.0))
        return v, {"x": _feedval(rng, shape)}
    if which == "shape":
        shape = (2, int(rng.randint(2, 6)), int(rng.randint(2, 4)))
        x = _data("x", shape)
        v = fluid.layers.cast(fluid.layers.shape(x), "float32")
        return v, {"x": _feedval(rng, shape)}
    if which == "fill_zeros_like":
        shape = (2, int(rng.randint(2, 5)))
        x = _data("x", shape)
        from paddle_tpu.layer_helper import LayerHelper
        helper = LayerHelper("fill_zeros_like")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="fill_zeros_like", inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs={})
        v = fluid.layers.elementwise_add(out, x)
        return v, {"x": _feedval(rng, shape)}
    c = int(rng.randint(2, 5))
    shape = (2, c, 3, 3)
    mode = str(rng.choice(["all", "channel", "element"]))
    x = _data("x", shape)
    v = fluid.layers.prelu(x, mode=mode)
    return v, {"x": _feedval(rng, shape, low=-2.0, high=2.0)}


NORMS_OPS = [
    "group_norm", "sequence_softmax", "l2_normalize", "huber_loss", "log_loss", "maxout"]


def case_norms_losses(rng, which=None):
    """r5 C++ batch 4: group_norm / sequence_softmax / l2_normalize /
    huber_loss / log_loss / maxout."""
    if which is None:
        which = NORMS_OPS[_CURRENT_SEED[0] % len(NORMS_OPS)]
    if which == "group_norm":
        groups = int(rng.choice([1, 2, 4]))
        c = groups * int(rng.randint(1, 4))
        shape = (2, c, 3, 3)
        x = _data("x", shape)
        v = fluid.layers.group_norm(x, groups=groups)
        return v, {"x": _feedval(rng, shape)}
    if which == "sequence_softmax":
        b, t = 2, int(rng.randint(2, 7))
        x = _data("x", (b, t))
        feed = {"x": _feedval(rng, (b, t))}
        kwargs = {}
        if rng.rand() < 0.6:
            length = _data("len", (b, 1), dtype="int64")
            kwargs["length"] = length
            feed["len"] = rng.randint(0, t + 1, (b, 1)).astype("int64")
        v = fluid.layers.sequence_softmax(x, **kwargs)
        return v, feed
    if which == "l2_normalize":
        shape = (2, int(rng.randint(2, 6)), int(rng.randint(2, 4)))
        x = _data("x", shape)
        v = fluid.layers.l2_normalize(x, axis=int(rng.choice([1, 2, -1])))
        return v, {"x": _feedval(rng, shape)}
    if which in ("huber_loss", "log_loss"):
        shape = (3, int(rng.randint(1, 4)))
        x = _data("x", shape)
        y = _data("y", shape)
        if which == "huber_loss":
            v = fluid.layers.huber_loss(x, y,
                                        delta=float(rng.uniform(0.3, 2.0)))
            return v, {"x": _feedval(rng, shape, low=-2, high=2),
                       "y": _feedval(rng, shape, low=-2, high=2)}
        v = fluid.layers.log_loss(x, y)
        return v, {"x": rng.uniform(0.05, 0.95, shape).astype("float32"),
                   "y": rng.randint(0, 2, shape).astype("float32")}
    groups = int(rng.choice([2, 3]))
    c = groups * int(rng.randint(1, 4))
    shape = (2, c, 3, 3)
    x = _data("x", shape)
    v = fluid.layers.maxout(x, groups=groups)
    return v, {"x": _feedval(rng, shape)}


def case_sequence_mask(rng):
    bs = int(rng.randint(1, 4))
    maxlen = int(rng.randint(2, 7))
    length = _data("len", (bs,), dtype="int64")
    v = fluid.layers.sequence_mask(length, maxlen=maxlen, dtype="float32")
    return v, {"len": rng.randint(0, maxlen + 1, (bs,)).astype("int64")}


CASES = [
    case_elementwise, case_act_chain, case_matmul, case_fc, case_conv,
    case_conv_transpose, case_pool, case_norm, case_reduce,
    case_shape_ops, case_embedding, case_xent, case_topk, case_sdpa,
    case_gru, case_lstm, case_cast_chain, case_sequence_mask,
    case_moe_ffn, case_unary, case_indexing, case_misc,
    case_norms_losses,
]


def _run_case(seed):
    """Returns ("match"|"refused", detail)."""
    rng = np.random.RandomState(seed)
    _CURRENT_SEED[0] = seed
    case = CASES[int(rng.randint(len(CASES)))]
    scope = fluid.executor.Scope()
    with fluid.scope_guard(scope):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = seed
        startup.random_seed = seed
        with fluid.program_guard(main, startup):
            fetch, feed = case(rng)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (got_xla,) = exe.run(main, feed=feed, fetch_list=[fetch])
        try:
            got_cpp = run_cpp(main, scope, feed, fetch.name)
        except CppRefusal as e:
            return "refused", "%s: %s" % (case.__name__, e)
    got_xla = np.asarray(got_xla)
    got_cpp = np.asarray(got_cpp)
    assert got_xla.shape == tuple(got_cpp.shape), (
        "engine shape divergence in %s (seed %d): xla %s vs cpp %s"
        % (case.__name__, seed, got_xla.shape, got_cpp.shape))
    np.testing.assert_allclose(
        got_cpp.astype(np.float64), got_xla.astype(np.float64),
        rtol=1e-3, atol=1e-4,
        err_msg="silent engine divergence in %s (seed %d)"
                % (case.__name__, seed))
    return "match", case.__name__


@pytest.mark.parametrize("seed", range(BASE_SEED, BASE_SEED + N_CASES))
def test_diff_fuzz(seed):
    _OUTCOMES[seed] = _run_case(seed)


MULTIPLEXED = [
    (case_unary, UNARY_OPS),
    (case_indexing, INDEXING_OPS),
    (case_misc, MISC_OPS),
    (case_norms_losses, NORMS_OPS),
]


@pytest.mark.parametrize(
    "case,which",
    [(c, w) for c, menu in MULTIPLEXED for w in menu],
    ids=["%s-%s" % (c.__name__.replace("case_", ""), w)
         for c, menu in MULTIPLEXED for w in menu])
def test_fuzz_every_multiplexed_op(case, which):
    """Families that multiplex several ops behind one case would leave
    individual ops unexercised at the default case count (review
    finding); this forces every (family, op) pair through both engines
    once per CI run."""
    rng = np.random.RandomState(77001)
    scope = fluid.executor.Scope()
    with fluid.scope_guard(scope):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            fetch, feed = case(rng, which=which)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (got_xla,) = exe.run(main, feed=feed, fetch_list=[fetch])
        try:
            got_cpp = run_cpp(main, scope, feed, fetch.name)
        except CppRefusal:
            return  # explicit refusal is an honest boundary
    got_xla = np.asarray(got_xla)
    got_cpp = np.asarray(got_cpp)
    assert got_xla.shape == tuple(got_cpp.shape), (case.__name__, which)
    np.testing.assert_allclose(
        got_cpp.astype(np.float64), got_xla.astype(np.float64),
        rtol=1e-3, atol=1e-4,
        err_msg="silent engine divergence in %s op %s"
                % (case.__name__, which))


def test_fuzz_covers_every_family():
    """Selection-only check (no engines run): across the seed range the
    fuzz actually executes, every case family must be drawn at least
    once — otherwise an attr corner (e.g. the sdpa window that
    motivated this harness) could silently drop out of coverage."""
    if N_CASES < 100:
        pytest.skip("reduced PTPU_FUZZ_N slice: full family coverage "
                    "is only asserted for the default-size run")
    drawn = set()
    for seed in range(BASE_SEED, BASE_SEED + N_CASES):
        rng = np.random.RandomState(seed)
        drawn.add(CASES[int(rng.randint(len(CASES)))].__name__)
    missing = {c.__name__ for c in CASES} - drawn
    assert not missing, (
        "case families never drawn in the executed seed range: %r"
        % missing)


# outcomes recorded by the parametrized runs, so the vacuity check
# below doesn't pay for a second pass over the same seeds
_OUTCOMES = {}


def test_fuzz_exercises_comparisons():
    """The harness is only meaningful if most cases actually compare
    outputs — a C++ engine that refused everything would vacuously
    pass the per-seed tests. Uses the outcomes the parametrized pass
    already recorded; falls back to running a slice when invoked alone
    (e.g. -k selection)."""
    outcomes = dict(_OUTCOMES)
    if len(outcomes) < min(N_CASES, 30):
        for seed in range(BASE_SEED, BASE_SEED + min(N_CASES, 60)):
            if seed not in outcomes:
                outcomes[seed] = _run_case(seed)
    n = len(outcomes)
    matched = sum(1 for kind, _ in outcomes.values() if kind == "match")
    refused = [d for kind, d in outcomes.values() if kind == "refused"]
    assert matched >= int(0.6 * n), (
        "only %d/%d fuzz cases produced comparable outputs; refusals: %r"
        % (matched, n, refused[:10]))
