"""Sharding transpiler tests (parallel/sharding.py + analysis rule S001).

Covers the per-op derivation rules (matmul column/row parallel, embedding
vocab sharding, conv out-channel fsdp, norm-stat replication), tag
propagation and conflict resolution (explicit reshard points), the S001
validation surface (each trigger + a clean twin), override precedence,
and golden-model parity: the transformer block trains tensor-parallel on
the 8-virtual-device CPU mesh with ZERO hand-written layout entries and
its losses match the single-device run; the fsdp path shows per-device
param+opt_state ledger bytes <= 1/4 of the replicated run on a 4-way
axis.
"""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.analysis.diagnostics import ProgramVerifyError
from paddle_tpu.analysis.shard_check import check_sharding
from paddle_tpu.parallel.sharding import (
    DerivedShardingPolicy,
    derive_sharding,
    plan_shard_factors,
)
from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

MESH = {"data": 2, "fsdp": 2, "tp": 2}


def _param_name(plan_or_program, base):
    """create_parameter suffixes names ('w' -> 'w.w_0'): resolve the
    real name against a plan's specs or a program's global block."""
    names = (plan_or_program.specs if hasattr(plan_or_program, "specs")
             else plan_or_program.global_block().vars)
    return next(n for n in names if n == base or n.startswith(base + "."))


def _mlp_program(din=64, dh=128, nclass=8, seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[din])
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=dh, act="relu",
                            param_attr=fluid.ParamAttr(name="w1"),
                            bias_attr=False)
        logits = fluid.layers.fc(h, size=nclass,
                                 param_attr=fluid.ParamAttr(name="w2"),
                                 bias_attr=False)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _tp_program(seed=13):
    """The driver's Megatron TP block, sized so every TP weight clears
    the numel threshold (d_model=32, d_ff=64)."""
    import __graft_entry__

    return __graft_entry__.build_tp_block_program(
        seed=seed, d_model=32, d_ff=64, nclass=8)


# -- per-op derivation rules -------------------------------------------------

def test_matmul_column_parallel_by_default():
    main, _s, _l = _mlp_program()
    plan = derive_sharding(main, MESH, feed_shapes={"x": (16, 64)})
    # first matmul: activation not tp-sharded -> column parallel
    assert plan.specs["w1"] == ("fsdp", "tp")
    # w2 consumes w1's tp-tagged output -> row parallel (psum over tp)
    assert plan.specs["w2"] == ("tp", "fsdp")
    assert plan.collective_bytes.get("tp", 0) > 0
    assert plan.collective_bytes.get("data", 0) > 0


def test_small_param_replicates_with_note():
    main, _s, _l = _mlp_program(din=8, dh=16, nclass=4)
    plan = derive_sharding(main, MESH, feed_shapes={"x": (16, 8)})
    assert plan.specs["w1"] == ()
    assert "threshold" in plan.notes["w1"]


def test_non_divisible_dim_degrades_that_axis_only():
    # rows 65 % fsdp(2) != 0 -> row entry None, tp cols still shard
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[65])
        w = fluid.layers.create_parameter([65, 64], "float32", name="wodd")
        y = fluid.layers.mul(x, w)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, {"data": 2, "fsdp": 2, "tp": 2},
                           feed_shapes={"x": (8, 65)})
    wodd = _param_name(plan, "wodd")
    assert plan.specs[wodd] == (None, "tp")
    assert "does not divide" in plan.notes[wodd]


def test_embedding_vocab_sharded_over_fsdp_x_tp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[64, 32],
            param_attr=fluid.ParamAttr(name="emb.w"))
        loss = fluid.layers.mean(emb)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, MESH, feed_shapes={"ids": (16, 1)})
    assert plan.specs["emb.w"] == (("fsdp", "tp"), None)


def test_embedding_vocab_degrades_one_axis_at_a_time():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[66, 32],  # 66 % 4 != 0 but 66 % 2 == 0
            param_attr=fluid.ParamAttr(name="emb.w"))
        loss = fluid.layers.mean(emb)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, MESH, feed_shapes={"ids": (16, 1)})
    assert plan.specs["emb.w"] == (("fsdp",), None)


def test_conv_filter_fsdp_and_norm_stats_replicated():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 16, 16])
        c = fluid.layers.conv2d(img, num_filters=128, filter_size=3,
                                param_attr=fluid.ParamAttr(name="conv.w"),
                                bias_attr=False)
        c = fluid.layers.batch_norm(c)
        c = fluid.layers.pool2d(c, pool_size=2, pool_stride=2,
                                pool_type="max")
        flat = fluid.layers.reshape(c, [-1, 128 * 7 * 7])
        logits = fluid.layers.fc(flat, size=4,
                                 param_attr=fluid.ParamAttr(name="head.w"))
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, MESH,
                           feed_shapes={"img": (16, 3, 16, 16),
                                        "label": (16, 1)})
    assert plan.specs["conv.w"] == ("fsdp", None, None, None)
    # BN scale/bias/stats replicate with the documented note
    bn_params = [n for n in plan.specs
                 if "batch_norm" in n and plan.kinds[n] == "param"]
    assert bn_params
    for n in bn_params:
        assert plan.specs[n] == (), n
    # activations stay batch-sharded through conv/bn/pool/reshape
    flat_like = [n for n, s in plan.specs.items()
                 if plan.kinds[n] == "activation" and s
                 and s[0] == ("data", "fsdp")]
    assert flat_like


# -- propagation + conflict resolution ---------------------------------------

def test_batch_tag_dropped_when_transpose_moves_dim0():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 8])
        t = fluid.layers.transpose(x, [1, 0, 2])
        loss = fluid.layers.mean(t)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, MESH, feed_shapes={"x": (16, 4, 8)})
    # the transposed output must NOT be batch-annotated
    t_specs = [s for n, s in plan.specs.items()
               if plan.kinds[n] == "activation" and "transpose" in n]
    for s in t_specs:
        assert not (s and s[0] == ("data", "fsdp")), s


def test_conflict_inserts_reshard_point_not_silent_replication():
    """A tp-partial activation flowing into a loss reduction resolves as
    an explicit reshard point at the producer, while the column-parallel
    weight KEEPS its derived spec."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64])
        w = fluid.layers.create_parameter([64, 32], "float32", name="wcol")
        y = fluid.layers.mul(x, w)       # column-parallel -> tp-tagged out
        loss = fluid.layers.mean(y)      # no tp story -> conflict
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, MESH, feed_shapes={"x": (16, 64)})
    wcol = _param_name(plan, "wcol")
    assert plan.specs[wcol] == ("fsdp", "tp")
    assert plan.reshard_points, "conflict must surface as a reshard point"
    rp = plan.reshard_points[0]
    assert rp["op_type"] == "mean"
    v = main.global_block()._find_var_recursive(rp["var"])
    assert getattr(v, "reshard_spec", None) is not None


def test_overrides_take_precedence_and_are_noted():
    main, _s, _l = _mlp_program()
    plan = derive_sharding(main, MESH, overrides={"w1": (None, "tp")},
                           feed_shapes={"x": (16, 64)})
    assert plan.specs["w1"] == (None, "tp")
    assert "override" in plan.notes["w1"]


def test_feed_override_honored_on_derived_path():
    """Overrides win for feeds too (the legacy policy honored them; so
    must the derived plan): forcing a feed replicated sticks."""
    main, _s, _l = _mlp_program()
    plan = derive_sharding(main, MESH, overrides={"x": (None, None)},
                           feed_shapes={"x": (16, 64)})
    assert plan.specs["x"] == (None, None)
    assert "override" in plan.notes["x"]


def test_rederivation_clears_stale_annotations():
    """Deriving plan B must not leave plan A's stamps on vars B never
    touches (core/lowering.py would apply the stale reshard spec)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64])
        w = fluid.layers.create_parameter([64, 32], "float32", name="wc")
        y = fluid.layers.mul(x, w)
        loss = fluid.layers.mean(y)  # tp conflict -> reshard point
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan_a = derive_sharding(main, MESH, feed_shapes={"x": (16, 64)})
    assert plan_a.reshard_points
    rv = main.global_block()._find_var_recursive(
        plan_a.reshard_points[0]["var"])
    assert getattr(rv, "reshard_spec", None) is not None
    # plan B: tp-free mesh -> no conflict, no reshard point, stamp gone
    plan_b = derive_sharding(main, {"data": 2, "fsdp": 4},
                             feed_shapes={"x": (16, 64)})
    assert not plan_b.reshard_points
    assert getattr(rv, "reshard_spec", None) is None


def test_accumulators_inherit_param_layout():
    import __graft_entry__

    main, _s, _l = __graft_entry__.build_tp_block_program(
        d_model=32, d_ff=64, nclass=8)
    plan = derive_sharding(main, MESH,
                           feed_shapes={"x": (16, 8, 32), "label": (16, 1)})
    assert plan.specs["tp_qkv.w"] == ("fsdp", "tp")
    assert plan.specs.get("tp_qkv.w_moment1_0") == ("fsdp", "tp")
    assert "inherits" in plan.notes["tp_qkv.w_moment1_0"]
    # shard factors feed the memory plan: 2 (fsdp) * 2 (tp) = 4-way
    factors = plan_shard_factors(plan)
    assert factors["tp_qkv.w"] == 4


def test_feed_not_divisible_falls_back_with_note():
    main, _s, _l = _mlp_program()
    plan = derive_sharding(main, MESH, feed_shapes={"x": (6, 64)})
    assert plan.specs["x"] == ()
    assert "not divisible" in plan.notes["x"]


def test_memory_plan_divides_by_shard_factor():
    main, _s, loss = _mlp_program()
    feed_shapes = {"x": (16, 64), "label": (16, 1)}
    plan = derive_sharding(main, MESH, feed_shapes=feed_shapes)
    whole = main.memory_plan(feed_shapes=feed_shapes,
                             fetch_names=[loss.name])
    sharded = main.memory_plan(feed_shapes=feed_shapes,
                               fetch_names=[loss.name],
                               shard_factors=plan_shard_factors(plan))
    assert sharded.peak_bytes < whole.peak_bytes


# -- S001: bad spec surface --------------------------------------------------

def test_s001_unknown_var():
    main, _s, _l = _mlp_program()
    diags = check_sharding(main, MESH, {"nope.w": ("fsdp", None)})
    assert [d.rule for d in diags] == ["S001"]
    assert "unknown var" in diags[0].message


def test_s001_rank_excess():
    main, _s, _l = _mlp_program()
    diags = check_sharding(main, MESH, {"w1": ("fsdp", None, "tp")})
    assert [d.rule for d in diags] == ["S001"]
    assert "rank" in diags[0].message


def test_s001_unknown_axis():
    main, _s, _l = _mlp_program()
    diags = check_sharding(main, MESH, {"w1": (None, "model")})
    assert [d.rule for d in diags] == ["S001"]
    assert "absent from" in diags[0].message


def test_s001_non_divisible():
    main, _s, _l = _mlp_program(dh=127)
    diags = check_sharding(main, MESH, {"w1": (None, "tp")})
    assert [d.rule for d in diags] == ["S001"]
    assert "not divisible" in diags[0].message


def test_s001_malformed_spec():
    main, _s, _l = _mlp_program()
    diags = check_sharding(main, MESH, {"w1": (0, 1)})
    assert [d.rule for d in diags] == ["S001"]


def test_s001_clean_twin_is_silent():
    main, _s, _l = _mlp_program()
    assert check_sharding(main, MESH, {"w1": ("fsdp", "tp"),
                                       "w2": (None, None)}) == []


def test_derive_sharding_raises_on_bad_override():
    main, _s, _l = _mlp_program()
    with pytest.raises(ProgramVerifyError) as ei:
        derive_sharding(main, MESH, overrides={"w1": (None, "model")})
    assert "S001" in str(ei.value)


def test_parallel_executor_rejects_bad_override_at_transpile_time():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    main, startup, loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                          use_tpu=False, fsdp=2, tp=2,
                          sharding_overrides={"w1": ("fsdp", None, "tp")})
    x = np.random.RandomState(0).randn(16, 64).astype("float32")
    y = np.zeros((16, 1), dtype="int64")
    with pytest.raises(ProgramVerifyError) as ei:
        pe.run(fetch_list=[loss], feed={"x": x, "label": y})
    assert "S001" in str(ei.value)


# -- the derived plan is inspectable without running it ----------------------

def test_program_to_code_shows_partition_specs():
    from paddle_tpu import debugger

    main, _s, _l = _mlp_program()
    derive_sharding(main, MESH, feed_shapes={"x": (16, 64)})
    code = debugger.program_to_code(main)
    assert "@P(fsdp, tp)" in code
    assert "@P((data,fsdp), None)" in code  # the batch-sharded feed


def test_graphviz_labels_partition_specs(tmp_path):
    from paddle_tpu import debugger

    main, _s, _l = _mlp_program()
    derive_sharding(main, MESH, feed_shapes={"x": (16, 64)})
    dot = debugger.draw_block_graphviz(
        main.global_block(), path=str(tmp_path / "g.dot"))
    assert "P(fsdp, tp)" in dot


def test_dump_sharding_plan_accepts_derived_plan():
    import io

    from paddle_tpu import debugger

    main, _s, _l = _mlp_program()
    plan = derive_sharding(main, MESH, feed_shapes={"x": (16, 64)})
    buf = io.StringIO()
    debugger.dump_sharding_plan(plan, file=buf)
    text = buf.getvalue()
    assert "w1" in text and "P(fsdp, tp)" in text


# -- golden-model parity on the 8-device CPU mesh ----------------------------

def _run_single(build, feeds, loss_getter=None, steps=4):
    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = []
    for i in range(steps):
        lv, = exe.run(main, feed=feeds[i], fetch_list=[loss])
        out.append(float(np.ravel(np.asarray(lv))[0]))
    return out


def _run_derived(build, feeds, steps=4, **pe_kwargs):
    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                          use_tpu=False, **pe_kwargs)
    out = []
    for i in range(steps):
        lv, = pe.run(fetch_list=[loss], feed=feeds[i])
        out.append(float(np.ravel(np.asarray(lv))[0]))
    return pe, out


def test_transformer_tp_parity_zero_overrides():
    """The acceptance bar: tensor-parallel training of the transformer
    block with NO hand-written tp_layout — the plan is fully derived —
    matching single-device losses step for step."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.RandomState(5)
    feeds = [{"x": rng.randn(16, 8, 32).astype("float32"),
              "label": rng.randint(0, 8, (16, 1)).astype("int64")}
             for _ in range(4)]
    single = _run_single(_tp_program, feeds)
    pe, par = _run_derived(_tp_program, feeds, fsdp=2, tp=2)
    np.testing.assert_allclose(single, par, atol=1e-4, rtol=1e-4)
    # the TP weights really span the mesh with the derived Megatron specs
    qkv = fluid.global_scope().get_value("tp_qkv.w")
    assert tuple(qkv.sharding.spec) == ("fsdp", "tp")
    out_w = fluid.global_scope().get_value("tp_attn_out.w")
    assert tuple(out_w.sharding.spec) == ("tp", "fsdp")
    # and the executor exposes the plan it compiled with
    plan = pe.sharding_plan()
    assert plan is not None and plan.sharded_params()


def test_conv_model_fsdp_parity_under_reduce():
    """BuildStrategy.Reduce now means 'fsdp over the derived plan': a
    conv+bn model still matches the single-device run step for step."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    def build(seed=11):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = seed
        startup.random_seed = seed
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[3, 8, 8])
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            c = fluid.layers.conv2d(img, num_filters=16, filter_size=3,
                                    act="relu")
            c = fluid.layers.pool2d(c, pool_size=2, pool_stride=2,
                                    pool_type="avg")
            flat = fluid.layers.reshape(c, [-1, 16 * 3 * 3])
            logits = fluid.layers.fc(flat, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(3)
    feeds = [{"img": rng.randn(16, 3, 8, 8).astype("float32"),
              "label": rng.randint(0, 4, (16, 1)).astype("int64")}
             for _ in range(4)]
    single = _run_single(build, feeds)
    bs = BuildStrategy()
    bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
    pe, par = _run_derived(build, feeds, build_strategy=bs)
    assert "fsdp" in pe.mesh.shape  # Reduce maps to the planning mesh
    np.testing.assert_allclose(single, par, atol=1e-4, rtol=1e-4)


def test_fsdp_ledger_bytes_quarter_of_replicated():
    """The measured half of the acceptance bar: on a 4-way fsdp axis the
    per-device param+opt_state ledger bytes are <= 1/4 of the replicated
    run's (plus the replicated crumbs: tiny biases, scalar state)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from paddle_tpu.observability import memory, telemetry

    rng = np.random.RandomState(9)
    feeds = [{"x": rng.randn(16, 64).astype("float32"),
              "label": rng.randint(0, 8, (16, 1)).astype("int64")}
             for _ in range(2)]

    def per_device_state_bytes(**pe_kwargs):
        telemetry.enable(True)
        memory.enable(True)
        memory.reset()
        try:
            _pe, _losses = _run_derived(_mlp_program, feeds, steps=2,
                                        **pe_kwargs)
            by_dev = {d: b for d, b in memory.live_by_device().items()
                      if d != "mesh"}  # feeds/fetches ride the mesh label
            assert by_dev, "state must be booked per device"
            return max(by_dev.values())
        finally:
            memory.reset()
            memory.enable(False)
            telemetry.enable(False)

    replicated = per_device_state_bytes()  # AllReduce: params replicate
    bs = BuildStrategy()
    bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
    sharded = per_device_state_bytes(build_strategy=bs, fsdp=4, tp=1)
    assert sharded <= replicated / 4 * 1.10, (sharded, replicated)


def test_derived_policy_plan_interface():
    main, _s, _l = _mlp_program()
    import jax as _jax
    from paddle_tpu.parallel.mesh import build_mesh

    if len(_jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = build_mesh(num_devices=8, data=2, fsdp=2, tp=2)
    plan = derive_sharding(main, mesh, feed_shapes={"x": (16, 64)})
    policy = DerivedShardingPolicy(mesh, plan)
    assert "fsdp" in str(policy.state_sharding("w1").spec)
    assert str(policy.state_sharding("unknown_scalar").spec) == str(
        policy.replicated().spec)
    # concrete non-divisible batch at run time falls back to replication
    assert policy.feed_sharding("x", shape=(6, 64)).is_fully_replicated
    table = policy.plan()
    assert table["w1"][0] == "P(fsdp, tp)"


def test_pipeline_stages_rejects_planning_mesh():
    """pipeline x fsdp/tp is not wired (pipe-axis composition): asking
    for both must fail loudly, not silently drop the planning mesh."""
    main, startup, loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(NotImplementedError, match="pipeline_stages"):
        ParallelExecutor(loss_name=loss.name, main_program=main,
                         use_tpu=False, pipeline_stages=2, tp=2)


def test_propagate_op_param_is_never_silently_replicated():
    """A big param consumed by an elementwise (propagate) op must get
    the generic rule — fsdp dim-0 shard or a plan.notes entry — never
    an un-noted replication."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64])
        pos = fluid.layers.create_parameter([16, 64], "float32",
                                            name="pos_big")
        y = fluid.layers.elementwise_add(x, pos)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan = derive_sharding(main, MESH, feed_shapes={"x": (16, 64)})
    name = _param_name(plan, "pos_big")
    assert plan.specs[name] == ("fsdp", None)
    # and the tiny twin still replicates, with the audit note
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x = fluid.layers.data(name="x", shape=[8])
        b = fluid.layers.create_parameter([8], "float32", name="tiny_b")
        y = fluid.layers.elementwise_add(x, b)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    plan2 = derive_sharding(main2, MESH, feed_shapes={"x": (16, 8)})
    tiny = _param_name(plan2, "tiny_b")
    assert plan2.specs[tiny] == ()
    assert "threshold" in plan2.notes[tiny]


def test_sharding_plan_reflects_compiled_executable():
    """After a run, the no-arg sharding_plan() is the plan the compiled
    executable actually used — not a fresh divergent derivation."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.RandomState(11)
    feeds = [{"x": rng.randn(16, 64).astype("float32"),
              "label": rng.randint(0, 8, (16, 1)).astype("int64")}
             for _ in range(2)]
    pe, _ = _run_derived(_mlp_program, feeds, steps=2, fsdp=2, tp=2)
    plan = pe.sharding_plan()
    assert plan is pe._active_plan
    assert plan is pe.sharding_plan()  # stable across calls, no re-derive
    # a what-if derivation with explicit feeds is still available and
    # does not clobber the compiled answer
    what_if = pe.sharding_plan(feed_shapes={"x": (32, 64)})
    assert what_if is not plan
    assert pe.sharding_plan() is plan
