"""Multi-process trainer over a DERIVED sharding plan (the PR 7
residual: ``num_trainers>1`` planning meshes, proven): 2 jax.distributed
processes x 4 virtual CPU devices = a global ``(data=2, fsdp=1, tp=4)``
planning mesh whose ``data`` axis crosses the process boundary (the DCN
stand-in) while the derived Megatron tp splits stay intra-process (the
ICI stand-in) — and NOT ONE hand-written layout entry: the sharding
transpiler derives every PartitionSpec from the op graph.

Spawned by test_dist_multiproc.py with the PADDLE_* env cluster surface;
the single-process parity reference runs the SAME program over the same
planning mesh built from 8 local devices.
"""

import json
import os
import sys

GLOBAL_BATCH = 16
STEPS = 4
TP_AXIS = 4


def global_batch_for(step, seq=8, nclass=8, d_model=32):
    """The step's GLOBAL batch, a pure function of the step index —
    every trainer slices its rows from the same arrays, and the
    single-device parity reference feeds them whole."""
    import numpy as np

    rng = np.random.RandomState(300 + step)
    return {
        "x": rng.randn(GLOBAL_BATCH, seq, d_model).astype(np.float32),
        "label": rng.randint(0, nclass,
                             (GLOBAL_BATCH, 1)).astype(np.int64),
    }


def run_derived_trainer(num_trainers, trainer_id):
    import numpy as np

    import paddle_tpu as fluid
    import __graft_entry__ as graft
    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    # d_model=32/d_ff=64: big enough that the Megatron weights clear the
    # transpiler's numel threshold (the test_sharding discipline)
    seq, nclass, d_model = 8, 8, 32
    main, startup, loss = graft.build_tp_block_program(
        seq=seq, nclass=nclass, d_model=d_model, d_ff=64)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    import jax

    if jax.local_device_count() != 8 // num_trainers or len(
            jax.devices()) != 8:
        raise RuntimeError(
            "derived-plan parity needs %d local devices (8 global), found "
            "%d local / %d global"
            % (8 // num_trainers, jax.local_device_count(),
               len(jax.devices())))
    bs = BuildStrategy()
    bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
    # fsdp=1, tp=4 -> data axis = 8/(1*4) = 2, laid across the two
    # processes (jax.devices() orders process 0's devices first); the
    # transpiler derives the full plan — no sharding_overrides, no
    # hand-replaced mesh
    pe = ParallelExecutor(
        loss_name=loss.name,
        main_program=main,
        build_strategy=bs,
        use_tpu=False,
        fsdp=1,
        tp=TP_AXIS,
        num_trainers=num_trainers,
        trainer_id=trainer_id,
    )
    plan = pe.sharding_plan()
    sharded = plan.sharded_params()
    if not sharded:
        raise RuntimeError("derived plan sharded nothing: %r" % plan)

    shard = GLOBAL_BATCH // num_trainers
    lo, hi = trainer_id * shard, (trainer_id + 1) * shard
    losses = []
    for step in range(STEPS):
        batch = global_batch_for(step, seq=seq, nclass=nclass,
                                 d_model=d_model)
        feed = {k: v[lo:hi] for k, v in batch.items()}
        lv, = pe.run(fetch_list=[loss], feed=feed)
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    return losses, sharded


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nprocs = int(os.environ["PADDLE_TRAINERS_NUM"])
    coord = os.environ["PADDLE_COORDINATOR"]
    out_file = os.environ["DIST_OUT_FILE"]
    local_devices = 8 // nprocs
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=%d" % local_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.parallel.mesh import init_distributed

    if nprocs > 1:
        init_distributed(
            coordinator_address=coord, num_processes=nprocs,
            process_id=rank)
    losses, sharded = run_derived_trainer(nprocs, rank)
    with open(out_file, "w") as f:
        json.dump({"rank": rank, "losses": losses, "sharded": sharded}, f)
    print("derived trainer %d done: %s" % (rank, losses), flush=True)


if __name__ == "__main__":
    sys.exit(main())
