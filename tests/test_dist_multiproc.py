"""Multi-process data-parallel parity tests — the TestDistBase pattern
(/root/reference/python/paddle/fluid/tests/unittests/test_dist_base.py:183,
check_with_place :377-410): launch real trainer subprocesses on localhost,
then assert the distributed loss trajectory matches local training.

Here the cluster bootstrap is jax.distributed.initialize (the gen_nccl_id
equivalent, parallel/mesh.py init_distributed) and the collective backend
is XLA/Gloo over the 2-process x 4-virtual-CPU-device mesh.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_cluster(nprocs, tmp_path, reduce_strategy="all_reduce",
                   script="dist_trainer_mlp.py", extra_env=None,
                   per_rank_env=None):
    """Start nprocs trainer processes; returns (procs, out_files).
    extra_env applies to every rank; per_rank_env maps rank -> dict."""
    port = _free_port()
    procs, out_files = [], []
    for rank in range(nprocs):
        out = str(tmp_path / ("trainer_%d.json" % rank))
        out_files.append(out)
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
        }
        env.update(
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS_NUM=str(nprocs),
            PADDLE_COORDINATOR="127.0.0.1:%d" % port,
            DIST_OUT_FILE=out,
            DIST_REDUCE=reduce_strategy,
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        env.update(extra_env or {})
        env.update((per_rank_env or {}).get(rank, {}))
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.join(HERE, script)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    return procs, out_files


def _launch_cluster(nprocs, tmp_path, reduce_strategy="all_reduce",
                    script="dist_trainer_mlp.py"):
    procs, out_files = _spawn_cluster(nprocs, tmp_path, reduce_strategy,
                                      script)
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o.decode(errors="replace")[-2000:]
    results = []
    for f in out_files:
        with open(f) as fh:
            results.append(json.load(fh))
    return results


@pytest.mark.parametrize("reduce_strategy", ["all_reduce", "reduce"])
def test_two_process_dp_matches_local(tmp_path, reduce_strategy):
    import dist_trainer_mlp as m

    local_losses = m.run_trainer(1, 0, reduce_strategy)
    results = _launch_cluster(2, tmp_path, reduce_strategy)
    assert {r["rank"] for r in results} == {0, 1}
    for r in results:
        np.testing.assert_allclose(
            r["losses"], local_losses, rtol=1e-4, atol=1e-4,
            err_msg="dist loss diverged from local (rank %d)" % r["rank"],
        )
    # losses must actually move (training happened)
    assert local_losses[-1] != local_losses[0]


def test_two_process_tensor_parallel_matches_single_process(tmp_path):
    """Multi-host TP x DP: 2 processes x 4 devices on a (data=2, model=4)
    mesh — the data axis crosses the process boundary (DCN stand-in), TP
    collectives stay intra-process (ICI stand-in). Loss trajectory must
    match the same program on the same mesh built from 8 local devices."""
    import dist_trainer_tp as t

    local_losses = t.run_tp_trainer(1, 0)
    results = _launch_cluster(2, tmp_path, reduce_strategy="reduce",
                              script="dist_trainer_tp.py")
    assert {r["rank"] for r in results} == {0, 1}
    for r in results:
        np.testing.assert_allclose(
            r["losses"], local_losses, rtol=1e-4, atol=1e-4,
            err_msg="tp-dist loss diverged (rank %d)" % r["rank"],
        )
    assert local_losses[-1] != local_losses[0]


@pytest.mark.slow
def test_derived_plan_num_trainers_mesh_matches_single_device():
    """PR 7 residual, the CPU-mesh leg: the SAME derived-plan trainer the
    2-process test spawns, run as its single-process reference over the
    full global (data=2, fsdp=1, tp=4) planning mesh — the data axis is
    the one that crosses hosts under num_trainers>1 — must reproduce a
    plain single-device run of the same program, with the transpiler
    (zero hand-written layout entries) sharding the Megatron weights."""
    import dist_trainer_derived as d
    import __graft_entry__ as graft

    mesh_losses, sharded = d.run_derived_trainer(1, 0)
    assert any("tp_" in n for n in sharded), sharded

    import paddle_tpu as fluid

    main, startup, loss = graft.build_tp_block_program(
        seq=8, nclass=8, d_model=32, d_ff=64)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ref = []
    for step in range(d.STEPS):
        feed = d.global_batch_for(step, seq=8, nclass=8, d_model=32)
        lv, = exe.run(main, feed=feed, fetch_list=[loss])
        ref.append(float(np.ravel(np.asarray(lv))[0]))
    np.testing.assert_allclose(
        mesh_losses, ref, rtol=1e-4, atol=1e-4,
        err_msg="derived plan on the (data x fsdp x tp) planning mesh "
        "diverged from the single-device run")
    assert ref[-1] != ref[0]


@pytest.mark.slow
def test_two_process_derived_plan_matches_single_process(tmp_path):
    """PR 7 residual, the cross-process leg: a DERIVED sharding plan
    (zero hand-written layout entries) drives multi-host parity. 2
    processes x 4 devices on the (data=2, fsdp=1, tp=4) planning mesh —
    the data axis crosses the process boundary, the transpiler's
    Megatron splits stay local — must reproduce the single-process
    8-device run, and the plan must shard the same weights in every
    process. Skips on jax builds whose CPU backend cannot run
    multi-process computations (the same limitation the other 2-process
    tests hit there)."""
    import dist_trainer_derived as d

    local_losses, local_sharded = d.run_derived_trainer(1, 0)
    procs, out_files = _spawn_cluster(2, tmp_path,
                                      reduce_strategy="reduce",
                                      script="dist_trainer_derived.py")
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for p, o in zip(procs, outs):
        text = o.decode(errors="replace")
        if "Multiprocess computations aren't implemented" in text:
            for q in procs:
                q.kill()
            pytest.skip("this jax CPU backend cannot run multi-process "
                        "computations")
        assert p.returncode == 0, text[-2000:]
    results = []
    for f in out_files:
        with open(f) as fh:
            results.append(json.load(fh))
    assert {r["rank"] for r in results} == {0, 1}
    for r in results:
        np.testing.assert_allclose(
            r["losses"], local_losses, rtol=1e-4, atol=1e-4,
            err_msg="derived-plan dist loss diverged (rank %d)"
            % r["rank"],
        )
        # the derivation ran in every process and sharded the Megatron
        # weights — identical plan with no overrides anywhere
        assert r["sharded"] == local_sharded
    assert any("tp_" in n for n in local_sharded), local_sharded
    assert local_losses[-1] != local_losses[0]


def test_num_trainers_validation():
    import paddle_tpu as fluid
    from paddle_tpu.parallel_executor import ParallelExecutor

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.fc(input=x, size=2))
    with pytest.raises(RuntimeError, match="num_trainers"):
        ParallelExecutor(
            loss_name=loss.name, main_program=main, use_tpu=False,
            num_trainers=2, trainer_id=0,
        )


def test_sharding_fallback_is_logged_and_planned(caplog):
    import logging

    import jax
    from paddle_tpu.parallel.mesh import ShardingPolicy, build_mesh

    mesh = build_mesh(num_devices=8, data=4, model=2)
    policy = ShardingPolicy(
        mesh,
        strategy="reduce",
        state_shapes={"odd": (7, 2048), "big": (8, 2048), "tiny": (8, 4)},
        model_sharded_vars={"odd"},
    )
    with caplog.at_level(logging.INFO, logger="paddle_tpu.parallel"):
        shardings = {n: policy.state_sharding(n)
                     for n in ("odd", "big", "tiny")}
    assert "odd" in caplog.text and "replicated" in caplog.text
    plan = policy.plan()
    assert plan["odd"][1] == "fallback"
    assert plan["big"][1] == "" and "data" in plan["big"][0]
    assert plan["tiny"][1] == "fallback"
    # dump goes through the debugger surface
    import io

    from paddle_tpu import debugger

    buf = io.StringIO()
    debugger.dump_sharding_plan(policy, file=buf)
    assert "odd" in buf.getvalue() and "fallback" in buf.getvalue()


def test_worker_death_fails_fast_then_elastic_restart_recovers(tmp_path):
    """Failure path (VERDICT r4 Next #8). Phase A: one trainer hard-dies
    mid-step (os._exit, a kill -9 stand-in); the survivor must error out
    PROMPTLY — bounded by the configured heartbeat timeout measured from
    the peer's death, not a hang — with a diagnosable message naming the
    dead peer (the ExceptionHolder role, reference
    framework/details/exception_holder.h). Phase B: the master itself
    dies WITHOUT a flush (kill -9 semantics: the throttled snapshot is
    all that survives); a restarted master recovers it and the restarted
    run finishes the pass — lost leases and unflushed finishes are
    re-dispatched, the documented at-least-once/bounded-staleness
    contract (go/master/service.go:313 role)."""
    import time

    # ---- phase A: kill one worker mid-step, survivor fails fast
    procs, _outs = _spawn_cluster(
        2, tmp_path,
        extra_env={"DIST_STEPS": "1000",          # >> the kill step
                   "PADDLE_HEARTBEAT_TIMEOUT": "10"},
        per_rank_env={1: {"DIST_DIE_AT_STEP": "3"}},
    )
    out1, _ = procs[1].communicate(timeout=120)
    assert procs[1].returncode == 42, out1.decode(errors="replace")[-800:]
    t_death = time.time()   # promptness is measured from the DEATH
    out0, _ = procs[0].communicate(timeout=120)
    detect_s = time.time() - t_death
    text0 = out0.decode(errors="replace")
    assert procs[0].returncode not in (0, None), (
        "survivor exited clean despite a dead peer:\n" + text0[-800:])
    assert detect_s < 60, (
        "survivor took %.0fs after the peer died to fail (heartbeat 10s)"
        % detect_s)
    assert ("heartbeat timeout" in text0 or "has failed" in text0
            or "crashed" in text0), (
        "survivor's failure is not diagnosable:\n" + text0[-1200:])

    # ---- phase B: master kill -9, restarted run recovers the snapshot
    from paddle_tpu.distributed.master import (
        MasterClient, MasterService, task_reader)

    snap = str(tmp_path / "master.snap")
    chunks = ["c%d" % i for i in range(6)]
    # huge throttle window: only structural writes (set_dataset) reach
    # disk, so the crash deterministically loses the lease AND the
    # finish below — the worst case the staleness contract allows
    s1 = MasterService(timeout_s=0.3, failure_max=5, snapshot_path=snap,
                       snapshot_interval_s=1000.0)
    s1.set_dataset(chunks)
    addr1 = s1.serve()
    doomed = MasterClient(addr1)
    t_done = doomed.get_task()
    t_lost = doomed.get_task()
    assert t_done and t_lost
    doomed.task_finished(t_done.task_id)
    doomed.close()
    # kill -9 the master: drop the in-memory state without close()'s
    # forced flush; the on-disk snapshot is the set_dataset one
    crash_state = open(snap).read()
    s1.close()
    with open(snap, "w") as f:
        f.write(crash_state)

    s2 = MasterService(timeout_s=0.3, failure_max=5, snapshot_path=snap)
    addr2 = s2.serve()
    seen = []
    c = MasterClient(addr2)

    def load_chunk(chunk):
        seen.append(chunk)
        yield np.float32(1.0)

    reader = task_reader(c, load_chunk, poll_s=0.1, max_polls=200)
    for _ in reader():      # one full pass completes the interrupted one
        pass
    c.close()
    s2.close()
    # at-least-once: EVERY chunk re-dispatches (the finish was lost with
    # the crash — that is the documented bounded-staleness trade), each
    # exactly once within the recovered pass
    assert sorted(seen) == sorted(chunks), (
        "recovered pass mismatch: seen=%r" % (seen,))
