"""Device-side input prefetch (PyReader double buffer).

Reference capability: buffered_reader.h:27 — overlap the host->device
copy of the next batch with compute on the current one. Contract under
test: start(place=...) makes next_feed() hand back DEVICE arrays (the
transfer was issued ahead of time), training consumes them unchanged,
EOF/reset semantics survive, and results match the unbuffered path.
"""

import numpy as np

import jax

import paddle_tpu as fluid
from paddle_tpu.reader.queue import EOFException


def _samples(n=24, seed=3):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        x = rng.rand(8, 4).astype("float32")
        yield x, x.sum(1, keepdims=True).astype("float32")


def _build():
    from paddle_tpu import unique_name

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    startup.random_seed = 9
    with fluid.program_guard(main, startup):
        reader = fluid.layers.py_reader(
            capacity=8, shapes=[[-1, 4], [-1, 1]],
            dtypes=["float32", "float32"], use_double_buffer=True)
        xv, yv = fluid.layers.read_file(reader)
        xv.stop_gradient = False
        pred = fluid.layers.fc(xv, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, yv))
        fluid.optimizer.SGD(0.05).minimize(loss)
    return main, startup, reader, loss


def _drain(exe, main, reader, loss):
    losses = []
    while True:
        try:
            feed = reader.next_feed()
        except EOFException:
            break
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_prefetch_hands_back_device_arrays_and_trains():
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, reader, loss = _build()
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(startup)
        reader.decorate_paddle_reader(lambda: _samples())
        reader.start(place=place)
        feed = reader.next_feed()
        for v in feed.values():
            assert isinstance(v, jax.Array), type(v)
            assert v.sharding.device_set == {place.jax_device()}
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv).reshape(-1)[0]))
        losses = _drain(exe, main, reader, loss)
        assert len(losses) == 23  # 24 batches, first consumed above


def test_prefetch_matches_unbuffered_losses():
    results = {}
    for buffered in (False, True):
        with fluid.scope_guard(fluid.executor.Scope()):
            main, startup, reader, loss = _build()
            place = fluid.CPUPlace()
            exe = fluid.Executor(place)
            exe.run(startup)
            reader.decorate_paddle_reader(lambda: _samples())
            reader.start(place=place if buffered else None)
            results[buffered] = _drain(exe, main, reader, loss)
    np.testing.assert_allclose(results[True], results[False],
                               rtol=1e-6, atol=1e-7)


def test_prefetch_reset_and_restart():
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, reader, loss = _build()
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(startup)
        reader.decorate_paddle_reader(lambda: _samples())
        reader.start(place=place)
        reader.next_feed()
        reader.reset()  # mid-stream: prefetch thread must not leak/hang
        assert reader._prefetch_q is None
        reader.start(place=place)
        losses = _drain(exe, main, reader, loss)
        assert len(losses) == 24  # full fresh pass after restart


def test_prefetch_surfaces_reader_errors():
    import pytest

    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup, reader, loss = _build()
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        exe.run(startup)

        def bad():
            yield from _samples(2)
            raise RuntimeError("source exploded")

        reader.decorate_paddle_reader(bad)
        reader.start(place=place)
        with pytest.raises((RuntimeError, EOFException)) as exc_info:
            for _ in range(10):
                feed = reader.next_feed()
                exe.run(main, feed=feed, fetch_list=[loss])
        if exc_info.type is RuntimeError:
            assert "py_reader source failed" in str(exc_info.value)
