"""Mixture-of-Experts FFN (ops/moe_ops.py): routing semantics vs a numpy
mirror, capacity overflow, top-2 combination, training, and expert
parallelism over the 8-device mesh.

The reference framework has no MoE (SURVEY.md §5.7-adjacent: like
long-context, this is TPU-native scope beyond the reference); the test
model is the Switch Transformer formulation — top-k gating, fixed
per-expert capacity, load-balancing auxiliary loss.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel_executor import (
    BuildStrategy,
    ParallelExecutor,
)
from paddle_tpu.parallel.mesh import build_mesh


def _build(e=4, h=8, d=6, top_k=1, cap=4.0, act="identity", seed=3):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [5, d])
        out, aux = fluid.layers.moe_ffn(
            x, num_experts=e, d_hidden=h, top_k=top_k,
            capacity_factor=cap, act=act, name="moe")
        loss = fluid.layers.mean(out)
    return main, startup, x, out, aux, loss


def _params(scope, prefix="moe"):
    names = sorted(n for n in scope.local_var_names()
                   if n.startswith(prefix) and ".w_" in n)
    return [np.asarray(scope.get_value(n)) for n in names]


def _np_moe(xv, gate_w, w1, b1, w2, b2, top_k, capacity, act=lambda v: v):
    """Numpy mirror of the Switch routing (token order, queue positions)."""
    n, d = xv.reshape(-1, xv.shape[-1]).shape
    xf = xv.reshape(-1, d).astype(np.float64)
    logits = xf @ gate_w.astype(np.float64)
    ex = np.exp(logits - logits.max(-1, keepdims=True))
    probs = ex / ex.sum(-1, keepdims=True)
    e = gate_w.shape[1]
    out = np.zeros_like(xf)
    counts = np.zeros(e, int)
    # route k times; earlier routes' assignments advance each queue
    chosen = [[] for _ in range(n)]
    for route in range(top_k):
        for i in range(n):
            p = probs[i].copy()
            p[chosen[i]] = 0.0
            sel = int(np.argmax(p))
            gate = p[sel]
            pos = counts[sel]
            counts[sel] += 1
            chosen[i].append(sel)
            if pos < capacity:
                hdn = act(xf[i] @ w1[sel].astype(np.float64)
                          + b1[sel].astype(np.float64))
                y = hdn @ w2[sel].astype(np.float64) + b2[sel].astype(
                    np.float64)
                out[i] += gate * y
    if top_k > 1:
        # mirror the renormalization: divide by sum of selected gates
        for i in range(n):
            tot = sum(probs[i][s] for s in chosen[i][:top_k])
            out[i] = out[i] / (tot + 1e-9) if tot > 0 else out[i]
    return out.reshape(xv.shape)


@pytest.mark.parametrize("cap", [8.0, 0.6], ids=["roomy", "dropping"])
@pytest.mark.parametrize("top_k", [1, 2], ids=["top1", "top2"])
def test_moe_matches_numpy_mirror(top_k, cap):
    """Both capacity regimes: roomy (no drops) and dropping (overflow
    tokens lose routes; pre-drop gate renormalization per Switch)."""
    e, h, d = 4, 8, 6
    main, startup, x, out, aux, _ = _build(e=e, h=h, d=d, top_k=top_k,
                                           cap=cap)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    gate_w, w1, b1, w2, b2 = _params(scope)
    assert gate_w.shape == (d, e) and w1.shape == (e, d, h)
    xv = np.random.RandomState(5).randn(3, 5, d).astype("float32")
    n_tok = 3 * 5
    capacity = max(1, int(cap * n_tok * top_k / e))
    (ov,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    expect = _np_moe(xv, gate_w, w1, b1, w2, b2, top_k, capacity)
    np.testing.assert_allclose(np.asarray(ov), expect, atol=1e-4,
                               rtol=1e-3)


def test_moe_capacity_drops_overflow():
    """Force every token onto expert 0 with capacity 1: exactly one token
    gets an output, the rest are dropped to zero (Switch overflow rule)."""
    e, h, d = 4, 8, 6
    main, startup, x, out, aux, _ = _build(e=e, h=h, d=d, cap=1e-9)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    gate_name = [n for n in scope.local_var_names()
                 if n.startswith("moe") and ".w_0" in n][0]
    gw = np.zeros((d, e), "float32")
    gw[:, 0] = 5.0  # softmax -> expert 0 for every token
    scope.set_value(gate_name, gw)
    # positive features: x @ gw stays positive, so expert 0 always wins
    xv = (0.1 + np.abs(
        np.random.RandomState(6).randn(2, 5, d))).astype("float32")
    (ov,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    ov = np.asarray(ov).reshape(-1, d)
    nonzero = np.abs(ov).sum(-1) > 1e-7
    assert nonzero.sum() == 1, nonzero  # capacity max(1, ...) = 1
    assert nonzero[0]  # token order: the first token wins the slot


def test_moe_aux_loss_prefers_balance():
    """The load-balancing loss is minimized at uniform routing and must
    see routing collapse at FULL strength even when capacity drops most
    of the collapsed tokens (f comes from pre-drop router assignments:
    switch_transformer paper eq. 4; a post-drop f would saturate at the
    capacity cap and stop penalizing exactly when pressure is needed)."""
    e, h, d = 4, 8, 8
    main, startup, x, out, aux, _ = _build(e=e, h=h, d=d, cap=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    gate_name = [n for n in scope.local_var_names()
                 if n.startswith("moe") and ".w_0" in n][0]
    xv = np.eye(8, d, dtype="float32")[None].repeat(2, 0)

    collapsed = np.zeros((d, e), "float32")
    collapsed[:, 2] = 4.0
    scope.set_value(gate_name, collapsed)
    (aux_collapsed,) = exe.run(main, feed={"x": xv}, fetch_list=[aux])

    balanced = np.zeros((d, e), "float32")
    for j in range(d):
        balanced[j, j % e] = 4.0  # distinct one-hot rows -> spread
    scope.set_value(gate_name, balanced)
    (aux_balanced,) = exe.run(main, feed={"x": xv}, fetch_list=[aux])
    a_col = float(np.ravel(aux_collapsed)[0])
    a_bal = float(np.ravel(aux_balanced)[0])
    assert a_bal < a_col
    # full collapse onto one expert scores ~E (here 4), not the ~1.0 a
    # post-capacity-drop f would report
    assert a_col > 0.5 * e, a_col


def test_moe_trains_with_aux():
    """End-to-end: MoE block + aux loss trains a toy regression."""
    d = 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4, d])
        t = fluid.layers.data("t", [4, d])
        y, aux = fluid.layers.moe_ffn(x, num_experts=4, d_hidden=16,
                                      top_k=2, act="gelu", name="m2")
        err = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(y, t)))
        loss = err + 0.01 * fluid.layers.mean(aux)
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(10)
    xv = rng.randn(8, 4, d).astype("float32")
    tv = np.tanh(xv) * 0.5
    losses = []
    for _ in range(60):
        (lv,) = exe.run(main, feed={"x": xv, "t": tv}, fetch_list=[err])
        losses.append(float(np.ravel(lv)[0]))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


def test_moe_expert_parallel_parity():
    """EP over the mesh: expert weights sharded on dim 0 across the
    'model' axis; per-step losses match the single-device run."""
    e, h, d = 4, 8, 6
    main, startup, x, out, aux, loss = _build(e=e, h=h, d=d, cap=8.0,
                                              act="gelu", seed=11)
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(0.05).minimize(loss)
    xv = np.random.RandomState(12).randn(8, 5, d).astype("float32")

    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        single = []
        for _ in range(3):
            (lv,) = exe.run(main, feed={"x": xv}, fetch_list=[loss])
            single.append(float(np.ravel(lv)[0]))

    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.global_scope()
        wnames = [n for n in scope.local_var_names() if n.startswith("moe")]
        overrides = {}
        for n in wnames:
            nd = np.asarray(scope.get_value(n)).ndim
            if nd == 3:  # [E, D, H] / [E, H, D] expert stacks
                overrides[n] = ("model",) + (None,) * (nd - 1)
            elif nd == 2 and np.asarray(
                    scope.get_value(n)).shape[0] == e:  # [E, ...] biases
                overrides[n] = ("model",) + (None,) * (nd - 1)
        pe = ParallelExecutor(
            loss_name=loss.name, main_program=main, use_tpu=False,
            sharding_overrides=overrides)
        pe.mesh = build_mesh(num_devices=8, data=2, model=4)
        par = []
        for _ in range(3):
            (lv,) = pe.run(fetch_list=[loss], feed={"x": xv})
            par.append(float(np.mean(np.asarray(lv))))
    np.testing.assert_allclose(single, par, atol=1e-4, rtol=1e-4)


def test_moe_named_param_attr_creates_distinct_params():
    """A user-supplied ParamAttr(name=...) must yield five distinct
    parameters (suffixed), not five aliases of one var."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4, 6])
        out, _ = fluid.layers.moe_ffn(
            x, num_experts=2, d_hidden=8,
            param_attr=fluid.ParamAttr(name="named_moe"))
    params = sorted(p.name for p in main.global_block().all_parameters()
                    if p.name.startswith("named_moe"))
    assert params == ["named_moe_b1", "named_moe_b2", "named_moe_gate",
                      "named_moe_w1", "named_moe_w2"], params
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.zeros((2, 4, 6), "float32")
    (ov,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    assert np.asarray(ov).shape == (2, 4, 6)


def test_switch_transformer_model_trains():
    """models/switch_transformer: MoE encoder classifier learns a
    separable toy task (first-token parity decides the class)."""
    from paddle_tpu.models import switch_transformer

    vocab, seq = 20, 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    startup.random_seed = 21
    with fluid.program_guard(main, startup):
        loss, feeds, extras = switch_transformer.build(
            vocab_size=vocab, max_length=seq, n_layer=2, n_head=2,
            d_model=16, d_inner=32, num_experts=4, top_k=1,
            moe_every=2, num_classes=2)
        fluid.optimizer.Adam(3e-3).minimize(loss)
    assert extras["aux_loss"] is not None  # one MoE layer present
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(22)
    losses = []
    for _ in range(90):
        w = rng.randint(1, vocab, (16, seq)).astype("int64")
        y = (w[:, :1] % 2).astype("int64")
        (lv,) = exe.run(
            main,
            feed={"word": w, "seq_len": np.full((16, 1), seq, "int64"),
                  "label": y},
            fetch_list=[extras["ce_loss"]])
        losses.append(float(np.ravel(lv)[0]))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


def test_moe_mask_keeps_padding_out_of_routing():
    """With a token mask: padded tokens get zero output, consume no
    expert capacity (a real token still gets its slot even when pads
    would have filled the queue first), and the aux statistics run over
    valid tokens only."""
    e, h, d = 2, 8, 6
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6, d])
        m = fluid.layers.data("m", [6])
        out, aux = fluid.layers.moe_ffn(
            x, num_experts=e, d_hidden=h, capacity_factor=0.5, mask=m,
            name="mk")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(30)
    xv = rng.randn(1, 6, d).astype("float32")
    # only the LAST token is real; with pads routing, the capacity-0.5
    # queues (capacity max(1, 0.5*6/2)=1) would be full before it
    mv = np.zeros((1, 6), "float32")
    mv[0, -1] = 1.0
    ov, av = exe.run(main, feed={"x": xv, "m": mv},
                     fetch_list=[out, aux])
    ov = np.asarray(ov)[0]
    assert (np.abs(ov[:-1]).sum(-1) < 1e-7).all()  # pads: zero output
    assert np.abs(ov[-1]).sum() > 1e-4  # the real token was served
    # aux over the single valid token: f is one-hot -> aux = E * p_e <= E
    assert 0.0 < float(np.ravel(av)[0]) <= e + 1e-5
