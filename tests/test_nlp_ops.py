"""CRF / CTC / edit-distance / chunk_eval / nce / hsigmoid / sequence-family
tests against brute-force numpy references (reference: tests/unittests/
test_{linear_chain_crf,crf_decoding,warpctc,edit_distance,chunk_eval,nce,
hsigmoid,sequence_*}_op.py)."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid


def _run_single_op(build_fn, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    outs = exe.run(main, feed=feed, fetch_list=list(fetches))
    return [np.asarray(o) for o in outs]


# ---------------------------------------------------------------------------
# CRF
# ---------------------------------------------------------------------------


def _crf_brute_force(x, trans, lens):
    """Enumerate all paths: returns (nll per row, best path per row)."""
    B, T, K = x.shape
    a, b, w = trans[0], trans[1], trans[2:]
    nlls, paths = [], []
    for i in range(B):
        L = lens[i]
        scores = {}
        for path in itertools.product(range(K), repeat=L):
            s = a[path[0]] + b[path[-1]] + sum(
                x[i, t, path[t]] for t in range(L)
            )
            s += sum(w[path[t - 1], path[t]] for t in range(1, L))
            scores[path] = s
        vals = np.array(list(scores.values()))
        m = vals.max()
        log_z = m + np.log(np.exp(vals - m).sum())
        best = max(scores, key=scores.get)
        # NLL of the gold path is computed by the caller; return log_z.
        nlls.append(log_z)
        paths.append(list(best) + [0] * (T - L))
    return np.array(nlls), np.array(paths)


def test_linear_chain_crf_matches_brute_force():
    rng = np.random.RandomState(0)
    B, T, K = 3, 4, 3
    x = rng.randn(B, T, K).astype("float32")
    trans = (0.5 * rng.randn(K + 2, K)).astype("float32")
    lens = np.array([4, 2, 3])
    label = rng.randint(0, K, (B, T)).astype("int64")

    def build():
        em = fluid.layers.data(name="em", shape=[T, K], dtype="float32")
        lb = fluid.layers.data(name="lb", shape=[T], dtype="int64")
        ln = fluid.layers.data(name="ln", shape=[1], dtype="int64")
        crf = fluid.layers.linear_chain_crf(
            em, lb, length=ln,
            param_attr=fluid.ParamAttr(
                name="crf_w",
                initializer=fluid.initializer.NumpyArrayInitializer(trans),
            ),
        )
        return [crf]

    (nll,) = _run_single_op(
        build,
        {"em": x, "lb": label, "ln": lens.reshape(-1, 1).astype("int64")},
    )
    log_z, _ = _crf_brute_force(x, trans, lens)
    a, b, w = trans[0], trans[1], trans[2:]
    for i in range(B):
        L = lens[i]
        path = label[i, :L]
        gold = a[path[0]] + b[path[-1]] + x[i, np.arange(L), path].sum()
        gold += sum(w[path[t - 1], path[t]] for t in range(1, L))
        np.testing.assert_allclose(
            nll[i, 0], log_z[i] - gold, rtol=2e-4, atol=2e-4
        )


def test_crf_decoding_matches_brute_force():
    rng = np.random.RandomState(1)
    B, T, K = 3, 4, 3
    x = rng.randn(B, T, K).astype("float32")
    trans = (0.5 * rng.randn(K + 2, K)).astype("float32")
    lens = np.array([4, 2, 3])

    def build():
        em = fluid.layers.data(name="em", shape=[T, K], dtype="float32")
        ln = fluid.layers.data(name="ln", shape=[1], dtype="int64")
        path = fluid.layers.crf_decoding(
            em,
            param_attr=fluid.ParamAttr(
                name="crf_w",
                initializer=fluid.initializer.NumpyArrayInitializer(trans),
            ),
            length=ln,
        )
        return [path]

    (path,) = _run_single_op(
        build, {"em": x, "ln": lens.reshape(-1, 1).astype("int64")}
    )
    _, want = _crf_brute_force(x, trans, lens)
    np.testing.assert_array_equal(path, want)


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------


def _ctc_brute_force(logits, label, t_len, l_len, blank=0):
    """Sum probability over all alignments whose collapse equals label."""
    lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))

    def collapse(path):
        out, prev = [], -1
        for p in path:
            if p != prev and p != blank:
                out.append(p)
            prev = p
        return tuple(out)

    B = logits.shape[0]
    out = []
    for i in range(B):
        T = t_len[i]
        V = logits.shape[2]
        want = tuple(label[i, : l_len[i]])
        total = -np.inf
        for path in itertools.product(range(V), repeat=T):
            if collapse(path) != want:
                continue
            s = sum(lp[i, t, path[t]] for t in range(T))
            total = np.logaddexp(total, s)
        out.append(-total)
    return np.array(out)


def test_warpctc_matches_brute_force():
    rng = np.random.RandomState(2)
    B, T, V, L = 2, 4, 3, 2
    logits = rng.randn(B, T, V).astype("float32")
    label = rng.randint(1, V, (B, L)).astype("int64")
    t_len = np.array([4, 3])
    l_len = np.array([2, 1])

    def build():
        lg = fluid.layers.data(name="lg", shape=[T, V], dtype="float32")
        lb = fluid.layers.data(name="lb", shape=[L], dtype="int64")
        tl = fluid.layers.data(name="tl", shape=[1], dtype="int64")
        ll = fluid.layers.data(name="ll", shape=[1], dtype="int64")
        loss = fluid.layers.warpctc(
            lg, lb, blank=0, input_length=tl, label_length=ll
        )
        return [loss]

    (loss,) = _run_single_op(
        build,
        {
            "lg": logits,
            "lb": label,
            "tl": t_len.reshape(-1, 1).astype("int64"),
            "ll": l_len.reshape(-1, 1).astype("int64"),
        },
    )
    want = _ctc_brute_force(logits, label, t_len, l_len)
    np.testing.assert_allclose(loss[:, 0], want, rtol=2e-4, atol=2e-4)


def test_ctc_greedy_decoder():
    # probs argmax path: [b, 1, 1, b, 2] -> collapse -> [1, 2]
    probs = np.zeros((1, 5, 3), "float32")
    hot = [0, 1, 1, 0, 2]
    probs[0, np.arange(5), hot] = 5.0

    def build():
        p = fluid.layers.data(name="p", shape=[5, 3], dtype="float32")
        out, out_len = fluid.layers.ctc_greedy_decoder(p, blank=0)
        return [out, out_len]

    out, out_len = _run_single_op(build, {"p": probs})
    assert out_len[0, 0] == 2
    np.testing.assert_array_equal(out[0, :2], [1, 2])


def test_edit_distance():
    # kitten -> sitting = 3; abc -> abc = 0 (with padding + lengths).
    def enc(s, T):
        v = [ord(c) for c in s] + [0] * (T - len(s))
        return v

    hyps = np.array([enc("kitten", 8), enc("abc", 8)], "int64")
    refs = np.array([enc("sitting", 8), enc("abc", 8)], "int64")
    hl = np.array([[6], [3]], "int64")
    rl = np.array([[7], [3]], "int64")

    def build():
        h = fluid.layers.data(name="h", shape=[8], dtype="int64")
        r = fluid.layers.data(name="r", shape=[8], dtype="int64")
        hlen = fluid.layers.data(name="hl", shape=[1], dtype="int64")
        rlen = fluid.layers.data(name="rl", shape=[1], dtype="int64")
        d, n = fluid.layers.edit_distance(
            h, r, normalized=False, input_length=hlen, label_length=rlen
        )
        return [d, n]

    d, n = _run_single_op(
        build, {"h": hyps, "r": refs, "hl": hl, "rl": rl}
    )
    np.testing.assert_allclose(d[:, 0], [3.0, 0.0])
    assert n[0] == 2


def test_chunk_eval_iob():
    # IOB, 2 chunk types. tag = type*2 + {0:B, 1:I}; O = 4.
    # label:  B0 I0 O  B1 I1   (chunks: [0,1] type0, [3,4] type1)
    # pred:   B0 I0 O  B1 O    (chunks: [0,1] type0, [3,3] type1)
    label = np.array([[0, 1, 4, 2, 3]], "int64")
    pred = np.array([[0, 1, 4, 2, 4]], "int64")

    def build():
        p = fluid.layers.data(name="p", shape=[5], dtype="int64")
        l = fluid.layers.data(name="l", shape=[5], dtype="int64")
        return list(
            fluid.layers.chunk_eval(
                p, l, chunk_scheme="IOB", num_chunk_types=2
            )
        )

    prec, rec, f1, ni, nl, nc = _run_single_op(
        build, {"p": pred, "l": label}
    )
    assert nl[0] == 2 and ni[0] == 2 and nc[0] == 1
    np.testing.assert_allclose(prec[0], 0.5)
    np.testing.assert_allclose(rec[0], 0.5)


# ---------------------------------------------------------------------------
# Sampled softmax family
# ---------------------------------------------------------------------------


def test_hsigmoid_is_normalized_distribution():
    """exp(-cost(label=c)) over all c must sum to 1: the binary tree's leaf
    probabilities partition the class space."""
    rng = np.random.RandomState(3)
    num_classes, D = 6, 8
    x = np.tile(rng.randn(1, D).astype("float32"), (num_classes, 1))
    labels = np.arange(num_classes).reshape(-1, 1).astype("int64")

    def build():
        xin = fluid.layers.data(name="x", shape=[D], dtype="float32")
        lb = fluid.layers.data(name="lb", shape=[1], dtype="int64")
        cost = fluid.layers.hsigmoid(xin, lb, num_classes)
        return [cost]

    (cost,) = _run_single_op(build, {"x": x, "lb": labels})
    probs = np.exp(-cost[:, 0])
    np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-5)


def test_nce_trains():
    rng = np.random.RandomState(4)
    dict_size, D = 30, 16
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[D], dtype="float32")
        lb = fluid.layers.data(name="lb", shape=[1], dtype="int64")
        cost = fluid.layers.nce(
            x, lb, num_total_classes=dict_size, num_neg_samples=5
        )
        loss = fluid.layers.mean(cost)
        fluid.optimizer.Adam(1e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # Learnable structure: class = argmax of first dict_size dims pattern.
    proto = rng.randn(dict_size, D).astype("float32")
    losses = []
    for _ in range(60):
        y = rng.randint(0, dict_size, (32,))
        xb = proto[y] + 0.1 * rng.randn(32, D).astype("float32")
        (lv,) = exe.run(
            main, feed={"x": xb, "lb": y.reshape(-1, 1).astype("int64")},
            fetch_list=[loss],
        )
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.8, losses[::10]


# ---------------------------------------------------------------------------
# Sequence family semantics
# ---------------------------------------------------------------------------


def test_sequence_concat():
    x = np.array([[1, 2, 0], [3, 0, 0]], "int64").astype("float32")
    y = np.array([[7, 8], [9, 0]], "float32")
    lx = np.array([[2], [1]], "int64")
    ly = np.array([[2], [1]], "int64")

    def build():
        xv = fluid.layers.data(name="x", shape=[3], dtype="float32")
        yv = fluid.layers.data(name="y", shape=[2], dtype="float32")
        lxv = fluid.layers.data(name="lx", shape=[1], dtype="int64")
        lyv = fluid.layers.data(name="ly", shape=[1], dtype="int64")
        out = fluid.layers.sequence_concat([xv, yv], lengths=[lxv, lyv])
        return [out]

    (out,) = _run_single_op(
        build, {"x": x, "y": y, "lx": lx, "ly": ly}
    )
    np.testing.assert_allclose(out[0], [1, 2, 7, 8, 0])
    np.testing.assert_allclose(out[1], [3, 9, 0, 0, 0])


def test_sequence_erase_and_enumerate():
    x = np.array([[2, 5, 2, 7, 0]], "int64")
    lens = np.array([[4]], "int64")

    def build():
        xv = fluid.layers.data(name="x", shape=[5], dtype="int64")
        lv = fluid.layers.data(name="l", shape=[1], dtype="int64")
        erased, n = fluid.layers.sequence_erase(xv, tokens=[2], length=lv)
        enum = fluid.layers.sequence_enumerate(xv, win_size=2, length=lv)
        return [erased, n, enum]

    erased, n, enum = _run_single_op(build, {"x": x, "l": lens})
    assert n[0, 0] == 2
    np.testing.assert_array_equal(erased[0, :2], [5, 7])
    np.testing.assert_array_equal(enum[0, 0], [2, 5])
    np.testing.assert_array_equal(enum[0, 3], [7, 0])  # padded tail


def test_sequence_slice_and_pad_unpad():
    x = np.arange(12, dtype="float32").reshape(1, 6, 2)
    off = np.array([[2]], "int64")
    ln = np.array([[3]], "int64")

    def build():
        xv = fluid.layers.data(name="x", shape=[6, 2], dtype="float32")
        ov = fluid.layers.data(name="o", shape=[1], dtype="int64")
        lv = fluid.layers.data(name="l", shape=[1], dtype="int64")
        sl = fluid.layers.sequence_slice(xv, ov, lv)
        unp = fluid.layers.sequence_unpad(xv, lv)
        return [sl, unp]

    sl, unp = _run_single_op(build, {"x": x, "o": off, "l": ln})
    np.testing.assert_allclose(sl[0, :3], x[0, 2:5])
    assert (sl[0, 3:] == 0).all()
    np.testing.assert_allclose(unp[0, :3], x[0, :3])
    assert (unp[0, 3:] == 0).all()


def test_sequence_conv_matches_numpy():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 5, 3).astype("float32")
    w = rng.randn(9, 4).astype("float32")  # ctx_len 3 * D 3 -> 4

    def build():
        xv = fluid.layers.data(name="x", shape=[5, 3], dtype="float32")
        out = fluid.layers.sequence_conv(
            xv, num_filters=4, filter_size=3, bias_attr=False,
            param_attr=fluid.ParamAttr(
                name="sc_w",
                initializer=fluid.initializer.NumpyArrayInitializer(w),
            ),
        )
        return [out]

    (out,) = _run_single_op(build, {"x": x})
    # numpy reference: context [-1, 0, 1] stacked then projected.
    padded = np.pad(x, ((0, 0), (1, 1), (0, 0)))
    stacked = np.concatenate(
        [padded[:, 0:5], padded[:, 1:6], padded[:, 2:7]], axis=2
    )
    want = stacked @ w
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)
