"""Cross-request KV reuse tests (the PR 12 serving layer): fork groups
over the refcounted page pool, group-pooled cross-attention K/V, the
prefix cache + chunked prefill, and copy-on-write — all pinned at the
BIT level:

* an ``admit_group(n=N)`` greedy member's tokens are bit-identical to
  a solo ``admit()`` of the same source;
* sampled members match a per-member seeded UNSHARED replay (same
  slots => same ``(seed, slot, position)`` PRNG streams);
* a prefix-cache hit decodes bit-identical to a cold suffix prefill;
* a post-dispatch admission fault rolls back with the table row
  repointed at the trash page FIRST, so a recycled page can never
  receive the stale row's writes (the chaos regression for the PR 11
  rollback bug);
* ``generate()``'s deferred-request ordering is pinned (deque
  semantics);
* cross K/V pool bytes scale with ``num_groups``, not ``num_slots``.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import exec_cache
from paddle_tpu.kernels import paged_attention as pa
from paddle_tpu.resilience.chaos import ChaosTransientError
from paddle_tpu.serving.generation import (
    NoFreeGroupError,
    NoFreePageError,
    Sampler,
    SlotDecodeSession,
)

VOCAB, SEQ, D = 24, 8, 32
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=2,
           n_head=2, d_inner=64)


@pytest.fixture(scope="module")
def trained(request):
    """One tiny trained 2-layer transformer (2 layers so per-layer
    pools, prefill writes and COW copies are all exercised past layer
    0) + the dense-decoder greedy oracle."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 31
    startup.random_seed = 31
    from paddle_tpu.executor import global_scope
    from paddle_tpu.models import transformer

    scope = global_scope()
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, max_length=SEQ,
            d_model=D, **CFG)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(32)
    for _ in range(25):
        src = rng.randint(3, VOCAB, (16, SEQ)).astype("int64")
        trg = np.full_like(src, 1)
        trg[:, 1:] = src[:, :-1]
        exe.run(main, feed={
            "src_word": src,
            "src_len": np.full((16, 1), SEQ, "int64"),
            "trg_word": trg,
            "trg_len": np.full((16, 1), SEQ, "int64"),
            "label": src,
        }, fetch_list=[loss])
    src = rng.randint(3, VOCAB, (4, SEQ)).astype("int64")
    src_len = np.asarray([[SEQ], [SEQ - 2], [SEQ], [3]], "int64")
    dense = SlotDecodeSession(exe, num_slots=4, max_length=SEQ,
                              d_model=D, scope=scope, **CFG)
    want = dense.generate(src, src_len)
    return {"exe": exe, "scope": scope, "src": src, "src_len": src_len,
            "want": want}


def _paged(trained, **kw):
    args = dict(num_slots=4, max_length=SEQ, d_model=D, paged=True,
                page_size=4, steps=2, scope=trained["scope"])
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


def test_group_greedy_member_bit_identical_to_solo_admit(trained):
    """Acceptance: one encoder forward + a shared cross K/V row + a
    shared (then COW'd) page set changes NOTHING about a greedy
    member's tokens vs a solo admission of the same source — and the
    solo path itself still equals the dense oracle."""
    sess = _paged(trained)
    solo = sess.generate(trained["src"][:1], trained["src_len"][:1])
    np.testing.assert_array_equal(solo, trained["want"][:1])
    group = sess.generate_best_of(trained["src"][0], 3,
                                  src_len=trained["src_len"][0])
    for row in group:
        np.testing.assert_array_equal(row, solo[0])
    assert sess.pages_in_use == 0 and sess.free_groups == 4


def test_group_sampled_members_match_unshared_replay(trained):
    """Sampled members share encoder/cross/pages yet reproduce a
    per-member UNSHARED replay bit-for-bit: group members land in the
    same slots consecutive solo admissions would, so the
    (seed, slot, position) streams line up; sharing must not perturb a
    single sampled bit."""
    smp = Sampler(strategy="top_k", top_k=4, temperature=0.9, seed=7)
    shared = _paged(trained, sampler=smp)
    got = shared.generate_best_of(trained["src"][0], 3,
                                  src_len=trained["src_len"][0])
    # members DO diverge (the sampler is per-slot), else the test is
    # vacuous
    assert not (np.array_equal(got[0], got[1])
                and np.array_equal(got[1], got[2]))
    unshared = _paged(trained, sampler=smp)
    s = [unshared.admit(trained["src"][0], trained["src_len"][0])
         for _ in range(3)]
    outs = {}
    while len(outs) < 3:
        outs.update(unshared.step())
    np.testing.assert_array_equal(
        got, np.stack([outs[i] for i in s]))


def test_prefix_cache_hit_bit_identical_and_skips_prefill(trained):
    """A prefix-cache hit provisions full pages by REFERENCE and
    decodes bit-identical to the cold suffix prefill that created
    them; stats/gauges record the reuse, cached pages outlive the
    slots, and clear_prefix_cache() drains the pool to zero."""
    sess = _paged(trained, prefix_cache_pages=8)
    pfx = [int(t) for t in trained["src"][0][:5]]  # 5 forced + bos = 6
    cold = sess.generate_best_of(trained["src"][0], 1, src_len=SEQ,
                                 prefix_tokens=pfx)
    st = sess.prefix_cache_stats()
    assert st["lookups"] == 1 and st["hits"] == 0
    assert sess.cached_pages == 1  # one FULL page (4 of 5 positions)
    hit = sess.generate_best_of(trained["src"][0], 1, src_len=SEQ,
                                prefix_tokens=pfx)
    np.testing.assert_array_equal(hit, cold)
    st = sess.prefix_cache_stats()
    assert st["hits"] == 1 and st["hit_rate"] == 0.5
    assert st["tokens_saved"] == 4  # one full page provisioned by ref
    # forced rows actually lead the output
    assert (cold[0][:6] == [1] + pfx).all()
    # a LONGER prefix extending the cached one reuses its full page
    pfx2 = pfx + [int(trained["src"][0][5])]
    sess.generate_best_of(trained["src"][0], 1, src_len=SEQ,
                          prefix_tokens=pfx2)
    st = sess.prefix_cache_stats()
    assert st["hits"] == 2 and st["tokens_saved"] == 8
    # a different SOURCE must miss (prefix K/V depends on cross attn)
    sess.generate_best_of(trained["src"][2], 1, src_len=SEQ,
                          prefix_tokens=pfx)
    assert sess.prefix_cache_stats()["hits"] == 2
    # cached pages persist after every slot drained; clear() frees them
    assert sess.free_slots == 4 and sess.pages_in_use > 0
    assert sess.pages_in_use == sess.cached_pages
    sess.clear_prefix_cache()
    assert sess.pages_in_use == 0
    from paddle_tpu.observability import REGISTRY

    text = REGISTRY.to_prometheus()
    assert "paddle_tpu_serving_prefix_hit_rate" in text
    assert "paddle_tpu_serving_prefill_tokens_saved_total" in text


def test_prefix_fork_shares_pages_until_cow_and_conserves(trained):
    """A best-of-N fork over a forced prefix: members share the prefix
    pages (kv_pages_shared / dedup gauges go live), each member's
    first write copy-on-writes the partial tail, tokens equal the
    unshared replay, and the drained pool conserves every page. A
    second wave through the warm session adds ZERO fresh compiles
    (join/prefill/copy are fixed-shape executables)."""
    smp = Sampler(strategy="temperature", temperature=0.8, seed=11)
    sess = _paged(trained, sampler=smp, prefix_cache_pages=8)
    pfx = [int(t) for t in trained["src"][0][:5]]
    shared_seen = []
    orig_run = sess._exe.run

    def spy(prog, **kw):
        shared_seen.append(sess.shared_pages)
        return orig_run(prog, **kw)

    sess._exe = type("E", (), {
        "run": staticmethod(spy),
        "run_multi_step": staticmethod(sess._exe.run_multi_step)})()
    got = sess.generate_best_of(trained["src"][0], 3, src_len=SEQ,
                                prefix_tokens=pfx)
    assert max(shared_seen) > 0, "fork never actually shared a page"
    # unshared replay (cache off => three cold prefills)
    solo = _paged(trained, sampler=smp)
    s = [solo.admit(trained["src"][0], SEQ, prefix_tokens=pfx)
         for _ in range(3)]
    outs = {}
    while len(outs) < 3:
        outs.update(solo.step())
    np.testing.assert_array_equal(got, np.stack([outs[i] for i in s]))
    # conservation at drain: only cache refs remain, then none
    assert sess.pages_in_use == sess.cached_pages
    assert sess.shared_pages == 0
    before = exec_cache.stats()["fresh_compiles"]
    # wave 2 members land in whatever slots the free stack now leads
    # with (slot-keyed PRNG => legitimately different samples); the
    # invariant is the EXECUTABLE SET: zero fresh compiles warm
    sess.generate_best_of(trained["src"][0], 3, src_len=SEQ,
                          prefix_tokens=pfx)
    assert exec_cache.stats()["fresh_compiles"] == before, \
        "warm fork/prefix wave paid fresh compiles"
    sess.clear_prefix_cache()
    assert sess.pages_in_use == 0 and sess.free_pages == sess._P - 1


def test_admit_failure_rollback_repoints_before_freeing(trained):
    """Chaos regression for the admission rollback: a fault raised
    AFTER the admit dispatch committed device-side (the worst case —
    the device row points at the rolled-back pages and the slot's
    done flag is 0) must repoint the table row at the trash page
    BEFORE the pages return to the free list. Otherwise the next
    admission recycles those pages while the stale, still-stepping
    row keeps writing into them — and the re-admitted sequence's
    tokens silently corrupt."""
    sess = _paged(trained, num_pages=1 + 2 * pa.pages_for(SEQ, 4))
    orig_exe = sess._exe
    state = {"armed": True}

    class _PostDispatchFault(object):
        def run(self, prog, **kw):
            out = orig_exe.run(prog, **kw)
            if state["armed"] and prog is sess._admit_prog:
                state["armed"] = False
                raise ChaosTransientError(
                    "chaos: post-dispatch admit fault")
            return out

        def run_multi_step(self, *a, **kw):
            return orig_exe.run_multi_step(*a, **kw)

    sess._exe = _PostDispatchFault()
    free_pages = sess.free_pages
    with pytest.raises(ChaosTransientError):
        sess.admit(trained["src"][0], trained["src_len"][0])
    # rollback left every count unchanged
    assert sess.free_slots == 4 and sess.free_pages == free_pages
    assert sess.free_groups == 4 and sess._reserved_pages == 0
    # the poisoned slot's device row now points at the trash page, so
    # admissions that RECYCLE its pages decode clean while the stale
    # row keeps stepping on device
    out = sess.generate(trained["src"][1:3], trained["src_len"][1:3])
    np.testing.assert_array_equal(out, trained["want"][1:3])
    assert sess.pages_in_use == 0


def test_cow_failure_leaks_destination_instead_of_freeing(trained):
    """A copy_prog dispatch that fails AFTER possibly committing must
    LEAK the destination page, not free it: if the dispatch committed,
    the device row points at it, and recycling it would corrupt the
    next owner. The leak also shrinks the admission capacity bound so
    provisioning still can never fail mid-flight."""
    smp = Sampler(strategy="temperature", temperature=0.8, seed=19)
    # prefix of 3 forced tokens: the first write (pos 3) lands inside
    # the shared tail page => one COW per non-final member
    sess = _paged(trained, sampler=smp)
    pfx = [int(t) for t in trained["src"][0][:3]]
    slots = sess.admit_group(trained["src"][0], 2, src_len=SEQ,
                             prefix_tokens=pfx)
    orig_exe = sess._exe
    state = {"armed": True}

    class _PostDispatchCopyFault(object):
        def run(self, prog, **kw):
            out = orig_exe.run(prog, **kw)
            # COW rides the coalesced bucket-ladder programs now (one
            # dispatch per step window), not the per-pair copy_prog
            if state["armed"] and prog in sess._cow_progs.values():
                state["armed"] = False
                raise ChaosTransientError(
                    "chaos: post-dispatch copy fault")
            return out

        def run_multi_step(self, *a, **kw):
            return orig_exe.run_multi_step(*a, **kw)

    sess._exe = _PostDispatchCopyFault()
    in_use = sess.pages_in_use
    with pytest.raises(ChaosTransientError):
        sess.step()
    # the destination page stays allocated (leaked), the host row
    # restored the shared source, and capacity shrank by the leak
    assert sess._leaked_pages == 1
    assert sess.pages_in_use == in_use + 1
    assert sess.shared_pages > 0  # src_pg still shared in the row
    sess._exe = orig_exe
    # the session still decodes: the retried dispatch COWs afresh and
    # both members finish with uncorrupted streams (== unshared replay)
    outs = {}
    while len(outs) < 2:
        outs.update(sess.step())
    solo = _paged(trained, sampler=smp)
    s = [solo.admit(trained["src"][0], SEQ, prefix_tokens=pfx)
         for _ in range(2)]
    want = {}
    while len(want) < 2:
        want.update(solo.step())
    for got_slot, want_slot in zip(slots, s):
        np.testing.assert_array_equal(outs[got_slot], want[want_slot])
    # drain leaves exactly the leaked page allocated, and the shrunk
    # reservation bound still admits and drains cleanly (the leaked
    # page is never handed out again)
    assert sess.pages_in_use == 1 and sess._reserved_pages == 0
    worst = pa.pages_for(SEQ, 4)
    assert (sess._P - 1 - sess._leaked_pages) // worst >= 1
    sess.generate(trained["src"][:1], trained["src_len"][:1])
    assert sess.pages_in_use == 1 and sess.free_slots == 4


def test_generate_deferred_request_ordering_pinned(trained):
    """generate() serves requests strictly in row order even when the
    pool defers admissions (deque popleft/appendleft — the O(B^2)
    list shuffle is gone, the ordering contract stays)."""
    # pool covers ONE sequence at a time: every admission but the
    # in-flight one defers
    sess = _paged(trained, num_pages=1 + pa.pages_for(SEQ, 4))
    order = []
    orig_admit = sess.admit

    def spy_admit(src, src_len=None, **kw):
        slot = orig_admit(src, src_len, **kw)  # deferred retries raise
        for i in range(len(trained["src"])):
            if np.array_equal(np.ravel(src), trained["src"][i]):
                order.append(i)
                break
        return slot

    sess.admit = spy_admit
    out = sess.generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(out, trained["want"])
    assert order == [0, 1, 2, 3], \
        "deferred requests were reordered: %r" % order


def test_cross_kv_pool_scales_with_groups_not_slots(trained):
    """The cross-attention K/V pool is [G, H, T, dh]: sizing groups
    below slots shrinks the live scope buffers (the HBM ledger counts
    them once, at group size), and grid_accounting models the same
    contract. Group exhaustion is a typed reject and generate()
    defers through it."""
    sess = _paged(trained, num_groups=2)
    kc = np.asarray(trained["scope"].get_value("pgd_kcross_0"))
    assert kc.shape == (2, 2, SEQ, D // 2)  # [G, H, T, dh], G=2 < S=4
    acc = pa.grid_accounting([SEQ] * 4, 4, 2, D // 2, SEQ,
                             num_groups=2, n_layer=2)
    assert acc["cross_hbm_bytes"] == 2 * 2 * 2 * 2 * SEQ * (D // 2) * 4
    assert acc["cross_hbm_bytes"] * 2 == acc["cross_dense_hbm_bytes"]
    # one fork pair + one solo fill both groups (3 of 4 slots)...
    a = sess.admit_group(trained["src"][0], 2,
                         src_len=trained["src_len"][0])
    b = sess.admit(trained["src"][2], trained["src_len"][2])
    assert sess.free_groups == 0 and sess.free_slots == 1
    # ...and a third SOURCE is a typed reject (a slot is still free —
    # it's the group pool that's exhausted) until a group drains
    with pytest.raises(NoFreeGroupError):
        sess.admit(trained["src"][1], trained["src_len"][1])
    outs = {}
    while len(outs) < 3:
        outs.update(sess.step())
    for slot in a:
        np.testing.assert_array_equal(outs[slot], trained["want"][0])
    np.testing.assert_array_equal(outs[b], trained["want"][2])
    assert sess.free_groups == 2
    # generate() defers through group exhaustion and stays ordered
    out = sess.generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(out, trained["want"])


def test_pool_reservation_respects_group_size(trained):
    """admit_group reserves n x worst-case pages up front: a pool
    sized for one sequence rejects a fork pair atomically (no partial
    group ever lands), and counts are untouched by the reject."""
    sess = _paged(trained, num_pages=1 + pa.pages_for(SEQ, 4))
    with pytest.raises(NoFreePageError):
        sess.admit_group(trained["src"][0], 2)
    assert sess.free_slots == 4 and sess.free_groups == 4
    assert sess._reserved_pages == 0 and sess.pages_in_use == 0
    # a solo admission still fits and decodes clean
    out = sess.generate(trained["src"][:1], trained["src_len"][:1])
    np.testing.assert_array_equal(out, trained["want"][:1])
